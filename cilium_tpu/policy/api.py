"""Policy rule AST — the declarative policy language.

Mirrors the reference's rule model (reference: pkg/policy/api/{rule,ingress,
egress,l4,l7,http,kafka,selector,entity,cidr}.go): a Rule selects endpoints
via an EndpointSelector and carries ingress/egress sections whose members
(L3 selectors, L4 ports, L7 rules) must all match.  ``sanitize`` validates
and normalizes in place, as the reference's Rule.Sanitize does.
"""

from __future__ import annotations

import ipaddress
import re
from dataclasses import dataclass, field, replace
from typing import Iterable, Optional

from ..labels import (
    ID_NAME_ALL,
    ID_NAME_HOST,
    ID_NAME_INIT,
    ID_NAME_UNMANAGED,
    ID_NAME_WORLD,
    SOURCE_ANY,
    SOURCE_CILIUM_GENERATED,
    SOURCE_K8S,
    SOURCE_RESERVED,
    Label,
    LabelArray,
    PATH_DELIMITER,
    get_extended_key_from,
)
from ..labels.cidr import ip_string_to_label

# ---------------------------------------------------------------------------
# L4 protocol

PROTO_TCP = "TCP"
PROTO_UDP = "UDP"
PROTO_ANY = "ANY"

_PROTO_NUM = {PROTO_TCP: 6, PROTO_UDP: 17, PROTO_ANY: 0, "": 0}


class PolicyValidationError(ValueError):
    """Raised by sanitize on an invalid rule (reference: rule_validation.go)."""


def parse_l4_proto(proto: str) -> str:
    if proto == "":
        return PROTO_ANY
    p = proto.upper()
    if p in (PROTO_TCP, PROTO_UDP, PROTO_ANY):
        return p
    raise PolicyValidationError(f"invalid protocol {proto!r}, must be tcp/udp/any")


def proto_number(proto: str) -> int:
    return _PROTO_NUM.get(proto, 0)


# ---------------------------------------------------------------------------
# Endpoint selectors (k8s LabelSelector semantics over extended keys)

OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"


@dataclass(frozen=True)
class SelectorRequirement:
    """One matchExpressions entry (k8s LabelSelectorRequirement)."""

    key: str  # extended key, e.g. "any.role"
    operator: str
    values: tuple[str, ...] = ()

    def matches(self, lbls: LabelArray) -> bool:
        if self.operator == OP_IN:
            return lbls.get(self.key) in self.values
        if self.operator == OP_NOT_IN:
            # k8s semantics: matches if key absent OR value not in set.
            v = lbls.get(self.key)
            return v is None or v not in self.values
        if self.operator == OP_EXISTS:
            return lbls.has(self.key)
        if self.operator == OP_DOES_NOT_EXIST:
            return not lbls.has(self.key)
        return False

    def validate(self) -> None:
        if self.operator in (OP_IN, OP_NOT_IN) and not self.values:
            raise PolicyValidationError(
                f"operator {self.operator} requires values for key {self.key}"
            )
        if self.operator in (OP_EXISTS, OP_DOES_NOT_EXIST) and self.values:
            raise PolicyValidationError(
                f"operator {self.operator} forbids values for key {self.key}"
            )
        if self.operator not in (OP_IN, OP_NOT_IN, OP_EXISTS, OP_DOES_NOT_EXIST):
            raise PolicyValidationError(f"invalid selector operator {self.operator!r}")


@dataclass(frozen=True)
class EndpointSelector:
    """k8s-LabelSelector wrapper keyed by extended label keys
    (reference: pkg/policy/api/selector.go:34).

    match_labels keys are stored in extended form (``source.key``); bare
    keys are normalized with the ``any`` source at construction.
    """

    match_labels: tuple[tuple[str, str], ...] = ()
    match_expressions: tuple[SelectorRequirement, ...] = ()

    @staticmethod
    def from_dict(
        match_labels: dict[str, str] | None = None,
        match_expressions: Iterable[SelectorRequirement] = (),
    ) -> "EndpointSelector":
        ml = tuple(
            sorted(
                (get_extended_key_from(k), v)
                for k, v in (match_labels or {}).items()
            )
        )
        me = tuple(
            replace(r, key=get_extended_key_from(r.key)) for r in match_expressions
        )
        return EndpointSelector(match_labels=ml, match_expressions=me)

    @staticmethod
    def from_labels(*lbls: Label) -> "EndpointSelector":
        """reference: pkg/policy/api/selector.go NewESFromLabels."""
        return EndpointSelector(
            match_labels=tuple(
                sorted((l.extended_key, l.value) for l in lbls)
            )
        )

    def matches(self, lbls: LabelArray) -> bool:
        """reference: pkg/policy/api/selector.go:279-306 — the reserved
        ``all`` label key short-circuits to True."""
        all_key = SOURCE_RESERVED + PATH_DELIMITER + ID_NAME_ALL
        for k, v in self.match_labels:
            if k == all_key:
                return True
        for k, v in self.match_labels:
            got = lbls.get(k)
            if got != v:
                return False
        for req in self.match_expressions:
            if not req.matches(lbls):
                return False
        return True

    def is_wildcard(self) -> bool:
        return not self.match_labels and not self.match_expressions

    def with_requirements(
        self, reqs: Iterable[SelectorRequirement]
    ) -> "EndpointSelector":
        """Append extra requirements (used to fold FromRequires/ToRequires
        into the selector, reference: pkg/policy/rule.go:236-249)."""
        reqs = tuple(reqs)
        if not reqs:
            return self
        return EndpointSelector(
            match_labels=self.match_labels,
            match_expressions=self.match_expressions + reqs,
        )

    def to_requirements(self) -> tuple[SelectorRequirement, ...]:
        """reference: selector.go ConvertToLabelSelectorRequirementSlice."""
        out = list(self.match_expressions)
        for k, v in self.match_labels:
            out.append(SelectorRequirement(key=k, operator=OP_IN, values=(v,)))
        return tuple(out)

    def has_key(self, ext_key: str) -> bool:
        return any(k == ext_key for k, _ in self.match_labels) or any(
            r.key == ext_key for r in self.match_expressions
        )

    def has_key_prefix(self, prefix: str) -> bool:
        return any(k.startswith(prefix) for k, _ in self.match_labels) or any(
            r.key.startswith(prefix) for r in self.match_expressions
        )

    def validate(self) -> None:
        for r in self.match_expressions:
            r.validate()

    def __str__(self) -> str:
        parts = [f"{k}={v}" for k, v in self.match_labels]
        parts += [
            f"{r.key} {r.operator.lower()} {list(r.values)}"
            for r in self.match_expressions
        ]
        return "&".join(parts) if parts else "<wildcard>"


WILDCARD_SELECTOR = EndpointSelector()


def _reserved_selector(name: str) -> EndpointSelector:
    return EndpointSelector.from_labels(Label(key=name, source=SOURCE_RESERVED))


RESERVED_ENDPOINT_SELECTORS = {
    ID_NAME_HOST: _reserved_selector(ID_NAME_HOST),
    ID_NAME_WORLD: _reserved_selector(ID_NAME_WORLD),
}

# ---------------------------------------------------------------------------
# Entities (reference: pkg/policy/api/entity.go)

ENTITY_ALL = "all"
ENTITY_WORLD = "world"
ENTITY_CLUSTER = "cluster"
ENTITY_HOST = "host"
ENTITY_INIT = "init"

POLICY_LABEL_CLUSTER = "io.cilium.k8s.policy.cluster"

ENTITY_SELECTOR_MAPPING: dict[str, tuple[EndpointSelector, ...]] = {
    ENTITY_ALL: (WILDCARD_SELECTOR,),
    ENTITY_WORLD: (_reserved_selector(ID_NAME_WORLD),),
    ENTITY_HOST: (_reserved_selector(ID_NAME_HOST),),
    ENTITY_INIT: (_reserved_selector(ID_NAME_INIT),),
    # Populated by init_entities (depends on cluster name).
    ENTITY_CLUSTER: (),
}


def init_entities(cluster_name: str) -> None:
    """reference: entity.go InitEntities."""
    ENTITY_SELECTOR_MAPPING[ENTITY_CLUSTER] = (
        _reserved_selector(ID_NAME_HOST),
        _reserved_selector(ID_NAME_INIT),
        _reserved_selector(ID_NAME_UNMANAGED),
        EndpointSelector.from_labels(
            Label(key=POLICY_LABEL_CLUSTER, value=cluster_name, source=SOURCE_K8S)
        ),
    )


def entities_to_selectors(entities: Iterable[str]) -> list[EndpointSelector]:
    out: list[EndpointSelector] = []
    for e in entities:
        out.extend(ENTITY_SELECTOR_MAPPING.get(e, ()))
    return out


# ---------------------------------------------------------------------------
# CIDR (reference: pkg/policy/api/cidr.go)

CIDR_MATCH_ALL = ("0.0.0.0/0", "::/0")


@dataclass(frozen=True)
class CIDRRule:
    cidr: str
    except_cidrs: tuple[str, ...] = ()
    generated: bool = False

    def sanitize(self) -> int:
        """Validate; returns the prefix length (reference:
        rule_validation.go CIDRRule.sanitize)."""
        try:
            net = ipaddress.ip_network(self.cidr, strict=False)
        except ValueError as e:
            raise PolicyValidationError(f"unable to parse CIDRRule {self.cidr!r}: {e}")
        for p in self.except_cidrs:
            try:
                exc = ipaddress.ip_network(p, strict=False)
            except ValueError as e:
                raise PolicyValidationError(str(e))
            if exc.version != net.version or not (
                int(net.network_address)
                <= int(exc.network_address)
                <= int(net.broadcast_address)
            ):
                raise PolicyValidationError(
                    f"allow CIDR prefix {self.cidr} does not contain "
                    f"exclude CIDR prefix {p}"
                )
        return net.prefixlen


def sanitize_cidr(cidr: str) -> int:
    """Validate a bare CIDR or IP string; returns prefix length
    (reference: rule_validation.go CIDR.sanitize)."""
    if not cidr:
        raise PolicyValidationError("IP must be specified")
    try:
        net = ipaddress.ip_network(cidr, strict=False)
        return net.prefixlen
    except ValueError:
        try:
            ipaddress.ip_address(cidr)
            return 0
        except ValueError as e:
            raise PolicyValidationError(f"unable to parse CIDR: {e}")


def compute_resultant_cidr_set(rules: Iterable[CIDRRule]) -> list[str]:
    """Expand CIDRRules into a minimal covering set of CIDRs with the
    exceptions carved out (reference: api/cidr.go ComputeResultantCIDRSet)."""
    out: list[str] = []
    for r in rules:
        allow = ipaddress.ip_network(r.cidr, strict=False)
        nets = [allow]
        for exc_s in r.except_cidrs:
            exc = ipaddress.ip_network(exc_s, strict=False)
            nxt = []
            for n in nets:
                if exc.version == n.version and exc.subnet_of(n):
                    nxt.extend(n.address_exclude(exc))
                elif exc.version == n.version and n.subnet_of(exc):
                    continue  # fully removed
                else:
                    nxt.append(n)
            nets = nxt
        out.extend(str(n) for n in sorted(nets, key=lambda n: (int(n.network_address), n.prefixlen)))
    return out


def cidrs_to_selectors(cidrs: Iterable[str]) -> list[EndpointSelector]:
    """CIDR strings -> cidr-label selectors; the all-match prefix also adds
    reserved:world once (reference: api/cidr.go GetAsEndpointSelectors)."""
    out: list[EndpointSelector] = []
    world_added = False
    for c in cidrs:
        if c in CIDR_MATCH_ALL and not world_added:
            world_added = True
            out.append(RESERVED_ENDPOINT_SELECTORS[ID_NAME_WORLD])
        lbl = ip_string_to_label(c)
        if lbl is not None:
            out.append(EndpointSelector.from_labels(lbl))
    return out


def cidr_rules_to_selectors(rules: Iterable[CIDRRule]) -> list[EndpointSelector]:
    return cidrs_to_selectors(compute_resultant_cidr_set(rules))


# ---------------------------------------------------------------------------
# L7 rules

@dataclass
class PortRuleHTTP:
    """HTTP constraint; fields are POSIX-extended regexes
    (reference: pkg/policy/api/http.go:28)."""

    path: str = ""
    method: str = ""
    host: str = ""
    headers: tuple[str, ...] = ()

    def sanitize(self) -> None:
        from ..regex import ParseError, compile_pattern

        for pat in (self.path, self.method):
            if pat:
                try:
                    compile_pattern(pat)
                except ParseError as e:
                    raise PolicyValidationError(f"invalid regex {pat!r}: {e}")

    def key(self):
        return (self.path, self.method, self.host, tuple(self.headers))


# Kafka API keys (reference: pkg/policy/api/kafka.go:153-190).
KAFKA_API_KEY_MAP: dict[str, int] = {
    "produce": 0, "fetch": 1, "offsets": 2, "metadata": 3, "leaderandisr": 4,
    "stopreplica": 5, "updatemetadata": 6, "controlledshutdown": 7,
    "offsetcommit": 8, "offsetfetch": 9, "findcoordinator": 10, "joingroup": 11,
    "heartbeat": 12, "leavegroup": 13, "syncgroup": 14, "describegroups": 15,
    "listgroups": 16, "saslhandshake": 17, "apiversions": 18, "createtopics": 19,
    "deletetopics": 20, "deleterecords": 21, "initproducerid": 22,
    "offsetforleaderepoch": 23, "addpartitionstotxn": 24, "addoffsetstotxn": 25,
    "endtxn": 26, "writetxnmarkers": 27, "txnoffsetcommit": 28,
    "describeacls": 29, "createacls": 30, "deleteacls": 31,
    "describeconfigs": 32, "alterconfigs": 33,
}
KAFKA_REVERSE_API_KEY_MAP = {v: k for k, v in KAFKA_API_KEY_MAP.items()}

KAFKA_ROLE_PRODUCE = "produce"
KAFKA_ROLE_CONSUME = "consume"

# Role expansions (reference: kafka.go:274-291): produce needs
# produce+metadata+apiversions; consume needs the full consumer-group set.
KAFKA_PRODUCE_KEYS = (0, 3, 18)
KAFKA_CONSUME_KEYS = (1, 2, 3, 8, 9, 10, 11, 12, 13, 14, 18)

KAFKA_MAX_TOPIC_LEN = 255
# The reference's pattern is a Go *raw* string (kafka.go:244), so its `\\`
# is a regex-escaped literal backslash: backslashes ARE accepted there, and
# this port preserves that exact behavior.
_KAFKA_TOPIC_RE = re.compile(r"^[a-zA-Z0-9._\-\\]+$")

# API keys whose requests carry topics — the behavioral set the matcher
# uses (reference: pkg/kafka/policy.go:27 isTopicAPIKey; note kafka.go's
# constant block also lists FindCoordinator/JoinGroup, but isTopicAPIKey,
# which decides verdicts, does not).
KAFKA_TOPIC_API_KEYS = frozenset(
    [0, 1, 2, 3, 4, 5, 6, 8, 9, 19, 20, 21, 23, 24, 27, 28, 34, 35, 37]
)


@dataclass
class PortRuleKafka:
    """Kafka constraint (reference: pkg/policy/api/kafka.go:26)."""

    role: str = ""
    api_key: str = ""
    api_version: str = ""
    client_id: str = ""
    topic: str = ""

    # Private, filled by sanitize.
    api_keys_int: tuple[int, ...] = field(default=(), compare=False)
    api_version_int: Optional[int] = field(default=None, compare=False)

    def sanitize(self) -> None:
        if self.api_key and self.role:
            raise PolicyValidationError(
                f"cannot set both Role {self.role!r} and APIKey {self.api_key!r}"
            )
        if self.api_key:
            n = KAFKA_API_KEY_MAP.get(self.api_key.lower())
            if n is None:
                raise PolicyValidationError(f"invalid Kafka APIKey {self.api_key!r}")
            self.api_keys_int = (n,)
        if self.role:
            role = self.role.lower()
            if role == KAFKA_ROLE_PRODUCE:
                self.api_keys_int = KAFKA_PRODUCE_KEYS
            elif role == KAFKA_ROLE_CONSUME:
                self.api_keys_int = KAFKA_CONSUME_KEYS
            else:
                raise PolicyValidationError(f"invalid Kafka role {self.role!r}")
        if self.api_version:
            try:
                self.api_version_int = int(self.api_version)
            except ValueError:
                raise PolicyValidationError(
                    f"invalid Kafka APIVersion {self.api_version!r}"
                )
        if self.topic:
            if len(self.topic) > KAFKA_MAX_TOPIC_LEN:
                raise PolicyValidationError(
                    f"kafka topic exceeds maximum len of {KAFKA_MAX_TOPIC_LEN}"
                )
            if not _KAFKA_TOPIC_RE.match(self.topic):
                raise PolicyValidationError(
                    f"invalid Kafka topic name {self.topic!r}"
                )

    def check_api_key_role(self, kind: int) -> bool:
        """reference: kafka.go CheckAPIKeyRole — empty set is a wildcard."""
        return not self.api_keys_int or kind in self.api_keys_int

    def get_api_version(self) -> tuple[int, bool]:
        if self.api_version_int is None:
            return 0, True
        return self.api_version_int, False

    def key(self):
        return (self.role, self.api_key, self.api_version, self.client_id, self.topic)


class PortRuleL7(dict):
    """Generic key/value L7 rule (reference: pkg/policy/api/l7.go:24)."""

    def sanitize(self) -> None:
        for k in self:
            if k == "":
                raise PolicyValidationError("empty key not allowed")

    def key(self):
        return tuple(sorted(self.items()))


@dataclass
class L7Rules:
    """Union of L7 rule types; exactly one kind may be set
    (reference: pkg/policy/api/l4.go:65)."""

    http: list[PortRuleHTTP] = field(default_factory=list)
    kafka: list[PortRuleKafka] = field(default_factory=list)
    l7proto: str = ""
    l7: list[PortRuleL7] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.http) + len(self.kafka) + len(self.l7)

    def is_empty(self) -> bool:
        return not self.http and not self.kafka and not self.l7 and not self.l7proto

    def sanitize(self) -> None:
        n_types = 0
        if self.http:
            n_types += 1
            for h in self.http:
                h.sanitize()
        if self.kafka:
            n_types += 1
            for k in self.kafka:
                k.sanitize()
        if self.l7 and not self.l7proto:
            raise PolicyValidationError(
                "'l7' may only be specified when a 'l7proto' is also specified"
            )
        if self.l7proto:
            n_types += 1
            for r in self.l7:
                r.sanitize()
        if n_types > 1:
            raise PolicyValidationError(
                "multiple L7 protocol rule types specified in single rule"
            )


# ---------------------------------------------------------------------------
# L4 port rules

MAX_PORTS = 40
MAX_CIDR_PREFIX_LENGTHS = 40


@dataclass(frozen=True)
class PortProtocol:
    port: str
    protocol: str = ""

    def sanitize(self) -> "PortProtocol":
        if not self.port:
            raise PolicyValidationError("port must be specified")
        try:
            p = int(self.port, 0)
        except ValueError as e:
            raise PolicyValidationError(f"unable to parse port: {e}")
        if p == 0:
            raise PolicyValidationError("port cannot be 0")
        if not 0 < p <= 65535:
            raise PolicyValidationError(f"port out of range: {p}")
        return PortProtocol(port=self.port, protocol=parse_l4_proto(self.protocol))


@dataclass
class PortRule:
    ports: list[PortProtocol] = field(default_factory=list)
    rules: Optional[L7Rules] = None

    def sanitize(self) -> None:
        if len(self.ports) > MAX_PORTS:
            raise PolicyValidationError(f"too many ports, the max is {MAX_PORTS}")
        have_l7 = self.rules is not None and not self.rules.is_empty()
        for i, pp in enumerate(self.ports):
            self.ports[i] = pp.sanitize()
            if have_l7 and self.ports[i].protocol != PROTO_TCP:
                raise PolicyValidationError(
                    "L7 rules can only apply exclusively to TCP, "
                    f"not {self.ports[i].protocol}"
                )
        if have_l7:
            self.rules.sanitize()


# ---------------------------------------------------------------------------
# Ingress / egress rules

@dataclass
class Service:
    """ToServices reference (reference: pkg/policy/api/service.go)."""

    k8s_service_name: str = ""
    k8s_service_namespace: str = ""
    k8s_service_selector: Optional[EndpointSelector] = None


@dataclass
class FQDNSelector:
    """ToFQDNs entry (reference: pkg/policy/api/fqdn.go)."""

    match_name: str = ""

    def sanitize(self) -> None:
        if not self.match_name:
            raise PolicyValidationError("FQDNSelector.match_name must be set")


@dataclass
class IngressRule:
    """reference: pkg/policy/api/ingress.go:35."""

    from_endpoints: list[EndpointSelector] = field(default_factory=list)
    from_requires: list[EndpointSelector] = field(default_factory=list)
    to_ports: list[PortRule] = field(default_factory=list)
    from_cidr: list[str] = field(default_factory=list)
    from_cidr_set: list[CIDRRule] = field(default_factory=list)
    from_entities: list[str] = field(default_factory=list)

    def get_source_endpoint_selectors(self) -> list[EndpointSelector]:
        """All L3 source selectors (reference: ingress.go:111-116)."""
        res = list(self.from_endpoints)
        res += entities_to_selectors(self.from_entities)
        res += cidrs_to_selectors(self.from_cidr)
        res += cidr_rules_to_selectors(self.from_cidr_set)
        return res

    def is_label_based(self) -> bool:
        return not (self.from_requires or self.from_cidr or self.from_cidr_set)

    def sanitize(self) -> None:
        l3 = {
            "FromEndpoints": len(self.from_endpoints),
            "FromCIDR": len(self.from_cidr),
            "FromCIDRSet": len(self.from_cidr_set),
            "FromEntities": len(self.from_entities),
        }
        l3_dependent_l4 = {"FromEndpoints": True, "FromCIDR": False,
                           "FromCIDRSet": False, "FromEntities": True}
        _check_l3_members(l3, l3_dependent_l4, len(self.to_ports))
        for es in self.from_endpoints + self.from_requires:
            es.validate()
        for pr in self.to_ports:
            pr.sanitize()
        prefix_lengths = set()
        for c in self.from_cidr:
            prefix_lengths.add(sanitize_cidr(c))
        for cr in self.from_cidr_set:
            prefix_lengths.add(cr.sanitize())
        for e in self.from_entities:
            if e not in ENTITY_SELECTOR_MAPPING:
                raise PolicyValidationError(f"unsupported entity: {e}")
        if len(prefix_lengths) > MAX_CIDR_PREFIX_LENGTHS:
            raise PolicyValidationError(
                f"too many ingress CIDR prefix lengths "
                f"{len(prefix_lengths)}/{MAX_CIDR_PREFIX_LENGTHS}"
            )


@dataclass
class EgressRule:
    """reference: pkg/policy/api/egress.go:28."""

    to_endpoints: list[EndpointSelector] = field(default_factory=list)
    to_requires: list[EndpointSelector] = field(default_factory=list)
    to_ports: list[PortRule] = field(default_factory=list)
    to_cidr: list[str] = field(default_factory=list)
    to_cidr_set: list[CIDRRule] = field(default_factory=list)
    to_entities: list[str] = field(default_factory=list)
    to_services: list[Service] = field(default_factory=list)
    to_fqdns: list[FQDNSelector] = field(default_factory=list)

    def get_destination_endpoint_selectors(self) -> list[EndpointSelector]:
        res = list(self.to_endpoints)
        res += entities_to_selectors(self.to_entities)
        res += cidrs_to_selectors(self.to_cidr)
        res += cidr_rules_to_selectors(self.to_cidr_set)
        return res

    def is_label_based(self) -> bool:
        return not (
            self.to_requires or self.to_cidr or self.to_cidr_set or self.to_services
        )

    def sanitize(self) -> None:
        l3 = {
            "ToCIDR": len(self.to_cidr),
            "ToCIDRSet": len(self.to_cidr_set),
            "ToEndpoints": len(self.to_endpoints),
            "ToEntities": len(self.to_entities),
            "ToServices": len(self.to_services),
            "ToFQDNs": len(self.to_fqdns),
        }
        l3_dependent_l4 = {k: True for k in l3}
        _check_l3_members(l3, l3_dependent_l4, len(self.to_ports))
        for es in self.to_endpoints + self.to_requires:
            es.validate()
        for pr in self.to_ports:
            pr.sanitize()
        prefix_lengths = set()
        for c in self.to_cidr:
            prefix_lengths.add(sanitize_cidr(c))
        for cr in self.to_cidr_set:
            prefix_lengths.add(cr.sanitize())
        for e in self.to_entities:
            if e not in ENTITY_SELECTOR_MAPPING:
                raise PolicyValidationError(f"unsupported entity: {e}")
        for f in self.to_fqdns:
            f.sanitize()
        if len(prefix_lengths) > MAX_CIDR_PREFIX_LENGTHS:
            raise PolicyValidationError(
                f"too many egress CIDR prefix lengths "
                f"{len(prefix_lengths)}/{MAX_CIDR_PREFIX_LENGTHS}"
            )


def _check_l3_members(
    l3: dict[str, int], l3_dependent_l4: dict[str, bool], n_ports: int
) -> None:
    """Mutually-exclusive L3 member check (reference: rule_validation.go:71-95)."""
    present = [k for k, v in l3.items() if v > 0]
    for i, m1 in enumerate(present):
        for m2 in present[i + 1:]:
            raise PolicyValidationError(
                f"combining {m1} and {m2} is not supported yet"
            )
    for m in present:
        if n_ports > 0 and not l3_dependent_l4[m]:
            raise PolicyValidationError(
                f"combining {m} and ToPorts is not supported yet"
            )


# ---------------------------------------------------------------------------
# Rule

@dataclass
class Rule:
    """reference: pkg/policy/api/rule.go:32."""

    endpoint_selector: Optional[EndpointSelector] = None
    ingress: list[IngressRule] = field(default_factory=list)
    egress: list[EgressRule] = field(default_factory=list)
    labels: LabelArray = field(default_factory=LabelArray)
    description: str = ""

    def sanitize(self) -> None:
        """reference: rule_validation.go Rule.Sanitize."""
        for lbl in self.labels:
            if lbl.source == SOURCE_CILIUM_GENERATED:
                raise PolicyValidationError(
                    "rule labels cannot have cilium-generated source"
                )
        if self.endpoint_selector is None:
            raise PolicyValidationError("rule cannot have nil EndpointSelector")
        self.endpoint_selector.validate()
        for i in self.ingress:
            i.sanitize()
        for e in self.egress:
            e.sanitize()

    def get_cidr_prefixes(self) -> list[str]:
        """All CIDR prefixes referenced by this rule
        (reference: pkg/policy/cidr.go GetCIDRPrefixes)."""
        out: list[str] = []
        for i in self.ingress:
            out += [str(ipaddress.ip_network(c, strict=False)) for c in i.from_cidr]
            out += [
                str(ipaddress.ip_network(r.cidr, strict=False))
                for r in i.from_cidr_set
            ]
        for e in self.egress:
            out += [str(ipaddress.ip_network(c, strict=False)) for c in e.to_cidr]
            out += [
                str(ipaddress.ip_network(r.cidr, strict=False))
                for r in e.to_cidr_set
            ]
        return out
