"""Policy enforcement mode (reference: pkg/policy/config.go)."""

from __future__ import annotations

import threading

# Enforcement modes (reference: pkg/option — DefaultEnforcement etc.).
DEFAULT_ENFORCEMENT = "default"
ALWAYS_ENFORCE = "always"
NEVER_ENFORCE = "never"

_mutex = threading.Lock()
_policy_enabled = DEFAULT_ENFORCEMENT


def set_policy_enabled(val: str) -> None:
    global _policy_enabled
    with _mutex:
        _policy_enabled = val.lower()


def get_policy_enabled() -> str:
    with _mutex:
        return _policy_enabled
