"""JSON (de)serialization of policy rules.

The wire format matches the reference's JSON rule schema (reference:
pkg/policy/api JSON tags, e.g. examples/policies/*.json), so existing policy
documents written for the reference import unchanged.
"""

from __future__ import annotations

import json
from typing import Any

from ..labels import SOURCE_UNSPEC, Label, LabelArray, get_cilium_key_from, parse_label
from .api import (
    CIDRRule,
    EgressRule,
    EndpointSelector,
    FQDNSelector,
    IngressRule,
    L7Rules,
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    OP_NOT_IN,
    PortProtocol,
    PortRule,
    PortRuleHTTP,
    PortRuleKafka,
    PortRuleL7,
    Rule,
    SelectorRequirement,
    Service,
)


def selector_from_dict(d: dict) -> EndpointSelector:
    reqs = [
        SelectorRequirement(
            key=e["key"],
            operator=e["operator"],
            values=tuple(e.get("values", ())),
        )
        for e in d.get("matchExpressions", [])
    ]
    return EndpointSelector.from_dict(d.get("matchLabels", {}), reqs)


def selector_to_dict(s: EndpointSelector) -> dict:
    # Emit keys in cilium "source:key" form so re-parsing re-extends them
    # (reference: selector.go MarshalJSON via GetCiliumKeyFrom).
    out: dict[str, Any] = {}
    if s.match_labels:
        out["matchLabels"] = {get_cilium_key_from(k): v for k, v in s.match_labels}
    if s.match_expressions:
        out["matchExpressions"] = [
            {"key": get_cilium_key_from(r.key), "operator": r.operator,
             **({"values": list(r.values)} if r.values else {})}
            for r in s.match_expressions
        ]
    return out


def _port_rule_from_dict(d: dict) -> PortRule:
    rules = None
    rd = d.get("rules")
    if rd:
        rules = L7Rules(
            http=[
                PortRuleHTTP(
                    path=h.get("path", ""),
                    method=h.get("method", ""),
                    host=h.get("host", ""),
                    headers=tuple(h.get("headers", ())),
                )
                for h in rd.get("http", [])
            ],
            kafka=[
                PortRuleKafka(
                    role=k.get("role", ""),
                    api_key=k.get("apiKey", ""),
                    api_version=k.get("apiVersion", ""),
                    client_id=k.get("clientID", ""),
                    topic=k.get("topic", ""),
                )
                for k in rd.get("kafka", [])
            ],
            l7proto=rd.get("l7proto", ""),
            l7=[PortRuleL7(e) for e in rd.get("l7", [])],
        )
    return PortRule(
        ports=[
            PortProtocol(port=p["port"], protocol=p.get("protocol", ""))
            for p in d.get("ports", [])
        ],
        rules=rules,
    )


def _port_rule_to_dict(pr: PortRule) -> dict:
    out: dict[str, Any] = {
        "ports": [
            {"port": p.port, **({"protocol": p.protocol} if p.protocol else {})}
            for p in pr.ports
        ]
    }
    if pr.rules is not None:
        rd: dict[str, Any] = {}
        if pr.rules.http:
            rd["http"] = [
                {
                    **({"path": h.path} if h.path else {}),
                    **({"method": h.method} if h.method else {}),
                    **({"host": h.host} if h.host else {}),
                    **({"headers": list(h.headers)} if h.headers else {}),
                }
                for h in pr.rules.http
            ]
        if pr.rules.kafka:
            rd["kafka"] = [
                {
                    **({"role": k.role} if k.role else {}),
                    **({"apiKey": k.api_key} if k.api_key else {}),
                    **({"apiVersion": k.api_version} if k.api_version else {}),
                    **({"clientID": k.client_id} if k.client_id else {}),
                    **({"topic": k.topic} if k.topic else {}),
                }
                for k in pr.rules.kafka
            ]
        if pr.rules.l7proto:
            rd["l7proto"] = pr.rules.l7proto
            rd["l7"] = [dict(e) for e in pr.rules.l7]
        out["rules"] = rd
    return out


def _cidr_rule_from(d) -> CIDRRule:
    if isinstance(d, str):
        return CIDRRule(cidr=d)
    return CIDRRule(cidr=d["cidr"], except_cidrs=tuple(d.get("except", ())))


def rule_from_dict(d: dict) -> Rule:
    ingress = [
        IngressRule(
            from_endpoints=[
                selector_from_dict(s) for s in i.get("fromEndpoints", [])
            ],
            from_requires=[
                selector_from_dict(s) for s in i.get("fromRequires", [])
            ],
            to_ports=[_port_rule_from_dict(p) for p in i.get("toPorts", [])],
            from_cidr=list(i.get("fromCIDR", [])),
            from_cidr_set=[_cidr_rule_from(c) for c in i.get("fromCIDRSet", [])],
            from_entities=list(i.get("fromEntities", [])),
        )
        for i in d.get("ingress", [])
    ]
    egress = [
        EgressRule(
            to_endpoints=[selector_from_dict(s) for s in e.get("toEndpoints", [])],
            to_requires=[selector_from_dict(s) for s in e.get("toRequires", [])],
            to_ports=[_port_rule_from_dict(p) for p in e.get("toPorts", [])],
            to_cidr=list(e.get("toCIDR", [])),
            to_cidr_set=[_cidr_rule_from(c) for c in e.get("toCIDRSet", [])],
            to_entities=list(e.get("toEntities", [])),
            to_services=[
                Service(
                    k8s_service_name=s.get("k8sService", {}).get("serviceName", ""),
                    k8s_service_namespace=s.get("k8sService", {}).get("namespace", ""),
                )
                for s in e.get("toServices", [])
            ],
            to_fqdns=[
                FQDNSelector(match_name=f.get("matchName", ""))
                for f in e.get("toFQDNs", [])
            ],
        )
        for e in d.get("egress", [])
    ]
    return Rule(
        endpoint_selector=selector_from_dict(d.get("endpointSelector", {})),
        ingress=ingress,
        egress=egress,
        labels=LabelArray(_label_from(s) for s in d.get("labels", [])),
        description=d.get("description", ""),
    )


def _label_from(v) -> Label:
    """Labels appear either as ``source:key=value`` strings or as the
    reference's Label object form {key, value, source} (the format the
    examples/policies corpus uses)."""
    if isinstance(v, str):
        return parse_label(v)
    return Label(
        key=v.get("key") or "",
        value=v.get("value") or "",
        source=v.get("source") or SOURCE_UNSPEC,
    )


def rules_from_json(text: str) -> list[Rule]:
    data = json.loads(text)
    if isinstance(data, dict):
        data = [data]
    return [rule_from_dict(d) for d in data]


def rule_to_dict(r: Rule) -> dict:
    out: dict[str, Any] = {
        "endpointSelector": selector_to_dict(r.endpoint_selector)
    }
    if r.ingress:
        out["ingress"] = []
        for i in r.ingress:
            d: dict[str, Any] = {}
            if i.from_endpoints:
                d["fromEndpoints"] = [selector_to_dict(s) for s in i.from_endpoints]
            if i.from_requires:
                d["fromRequires"] = [selector_to_dict(s) for s in i.from_requires]
            if i.to_ports:
                d["toPorts"] = [_port_rule_to_dict(p) for p in i.to_ports]
            if i.from_cidr:
                d["fromCIDR"] = list(i.from_cidr)
            if i.from_cidr_set:
                d["fromCIDRSet"] = [
                    {"cidr": c.cidr,
                     **({"except": list(c.except_cidrs)} if c.except_cidrs else {})}
                    for c in i.from_cidr_set
                ]
            if i.from_entities:
                d["fromEntities"] = list(i.from_entities)
            out["ingress"].append(d)
    if r.egress:
        out["egress"] = []
        for e in r.egress:
            d = {}
            if e.to_endpoints:
                d["toEndpoints"] = [selector_to_dict(s) for s in e.to_endpoints]
            if e.to_requires:
                d["toRequires"] = [selector_to_dict(s) for s in e.to_requires]
            if e.to_ports:
                d["toPorts"] = [_port_rule_to_dict(p) for p in e.to_ports]
            if e.to_cidr:
                d["toCIDR"] = list(e.to_cidr)
            if e.to_cidr_set:
                d["toCIDRSet"] = [
                    {"cidr": c.cidr,
                     **({"except": list(c.except_cidrs)} if c.except_cidrs else {})}
                    for c in e.to_cidr_set
                ]
            if e.to_entities:
                d["toEntities"] = list(e.to_entities)
            if e.to_services:
                d["toServices"] = [
                    {"k8sService": {
                        **({"serviceName": s.k8s_service_name}
                           if s.k8s_service_name else {}),
                        **({"namespace": s.k8s_service_namespace}
                           if s.k8s_service_namespace else {}),
                    }}
                    for s in e.to_services
                ]
            if e.to_fqdns:
                d["toFQDNs"] = [{"matchName": f.match_name} for f in e.to_fqdns]
            out["egress"].append(d)
    if r.labels:
        out["labels"] = [str(l) for l in r.labels]
    if r.description:
        out["description"] = r.description
    return out


def rules_to_json(rules: list[Rule]) -> str:
    return json.dumps([rule_to_dict(r) for r in rules], indent=2)
