"""L3 (CIDR) policy resolution result.

reference: pkg/policy/l3.go.  The CIDRPolicy tracks allowed prefixes and the
set of distinct prefix lengths; ``to_lpm_data`` (the reference's ToBPFData)
yields the longest-to-shortest prefix-length lists the array-LPM datapath op
consumes (cilium_tpu.ops.lpm).
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass, field

from ..labels import LabelArray
from .api import MAX_CIDR_PREFIX_LENGTHS, PolicyValidationError


def get_default_prefix_lengths() -> tuple[list[int], list[int]]:
    """Prefix lengths always present (host, world); longest first
    (reference: l3.go:50-57)."""
    return [128, 0], [32, 0]


@dataclass
class CIDRPolicyMapRule:
    prefix: str
    derived_from_rules: list[LabelArray] = field(default_factory=list)


class CIDRPolicyMap:
    """Allowed prefixes keyed "address/prefixlen" + per-family prefix-length
    counts (reference: l3.go:40)."""

    def __init__(self) -> None:
        self.map: dict[str, CIDRPolicyMapRule] = {}
        self.ipv6_prefix_count: dict[int, int] = {}
        self.ipv4_prefix_count: dict[int, int] = {}

    def insert(self, cidr: str, rule_labels: LabelArray) -> int:
        """Insert; returns 1 if newly added (reference: l3.go:60-98)."""
        try:
            net = ipaddress.ip_network(cidr, strict=False)
        except ValueError:
            try:
                addr = ipaddress.ip_address(cidr)
            except ValueError:
                return 0
            net = ipaddress.ip_network(f"{addr}/{addr.max_prefixlen}")
        key = f"{net.network_address}/{net.prefixlen}"
        existing = self.map.get(key)
        if existing is None:
            self.map[key] = CIDRPolicyMapRule(
                prefix=key, derived_from_rules=[rule_labels]
            )
            counts = (
                self.ipv4_prefix_count if net.version == 4 else self.ipv6_prefix_count
            )
            counts[net.prefixlen] = counts.get(net.prefixlen, 0) + 1
            return 1
        existing.derived_from_rules.append(rule_labels)
        return 0


class CIDRPolicy:
    """reference: l3.go:105."""

    def __init__(self) -> None:
        self.ingress = CIDRPolicyMap()
        self.egress = CIDRPolicyMap()
        s6, s4 = get_default_prefix_lengths()
        for m in (self.ingress, self.egress):
            for p in s6:
                m.ipv6_prefix_count.setdefault(p, 0)
            for p in s4:
                m.ipv4_prefix_count.setdefault(p, 0)

    def to_lpm_data(self) -> tuple[list[int], list[int]]:
        """Distinct prefix lengths longest-first, (v6, v4)
        (reference: l3.go:146-170 ToBPFData)."""
        s6: set[int] = set()
        s4: set[int] = set()
        for m in (self.ingress, self.egress):
            s6.update(m.ipv6_prefix_count)
            s4.update(m.ipv4_prefix_count)
        return sorted(s6, reverse=True), sorted(s4, reverse=True)

    def validate(self) -> None:
        """reference: l3.go:200."""
        for name, m in (("ingress", self.ingress), ("egress", self.egress)):
            for fam, counts in (
                ("IPv6", m.ipv6_prefix_count),
                ("IPv4", m.ipv4_prefix_count),
            ):
                if len(counts) > MAX_CIDR_PREFIX_LENGTHS:
                    raise PolicyValidationError(
                        f"too many {name} {fam} CIDR prefix lengths "
                        f"{len(counts)}/{MAX_CIDR_PREFIX_LENGTHS}"
                    )

    def get_model(self) -> dict:
        return {
            "ingress": [
                {"rule": v.prefix,
                 "derived_from_rules": [l.get_model() for l in v.derived_from_rules]}
                for v in self.ingress.map.values()
            ],
            "egress": [
                {"rule": v.prefix,
                 "derived_from_rules": [l.get_model() for l in v.derived_from_rules]}
                for v in self.egress.map.values()
            ],
        }
