"""Policy engine: rule AST, revisioned repository, L4/L7/CIDR resolution.

The TPU-native equivalent of the reference's pkg/policy + pkg/policy/api:
declarative label/identity rules compiled into (a) packed L4 policy-map
entries (cilium_tpu.maps.policymap), and (b) NFA transition tables for L7
rules (cilium_tpu.models.*) evaluated in batch on device.
"""

from .api import (
    CIDRRule,
    EgressRule,
    EndpointSelector,
    FQDNSelector,
    IngressRule,
    L7Rules,
    PROTO_ANY,
    PROTO_TCP,
    PROTO_UDP,
    PolicyValidationError,
    PortProtocol,
    PortRule,
    PortRuleHTTP,
    PortRuleKafka,
    PortRuleL7,
    Rule,
    SelectorRequirement,
    Service,
    WILDCARD_SELECTOR,
    init_entities,
)
from .config import (
    ALWAYS_ENFORCE,
    DEFAULT_ENFORCEMENT,
    NEVER_ENFORCE,
    get_policy_enabled,
    set_policy_enabled,
)
from .l3 import CIDRPolicy, CIDRPolicyMap, get_default_prefix_lengths
from .l4 import (
    L4Filter,
    L4Policy,
    L4PolicyMap,
    L7DataMap,
    PARSER_TYPE_HTTP,
    PARSER_TYPE_KAFKA,
    PARSER_TYPE_NONE,
)
from .proxyid import parse_proxy_id, proxy_id
from .repository import PolicyMergeError, Repository, TraceState
from .search import Decision, DPort, SearchContext, Tracing
from .serialize import rule_from_dict, rules_from_json, rules_to_json

__all__ = [
    "ALWAYS_ENFORCE",
    "CIDRPolicy",
    "CIDRPolicyMap",
    "CIDRRule",
    "DEFAULT_ENFORCEMENT",
    "DPort",
    "Decision",
    "EgressRule",
    "EndpointSelector",
    "FQDNSelector",
    "IngressRule",
    "L4Filter",
    "L4Policy",
    "L4PolicyMap",
    "L7DataMap",
    "L7Rules",
    "NEVER_ENFORCE",
    "PARSER_TYPE_HTTP",
    "PARSER_TYPE_KAFKA",
    "PARSER_TYPE_NONE",
    "PROTO_ANY",
    "PROTO_TCP",
    "PROTO_UDP",
    "PolicyMergeError",
    "PolicyValidationError",
    "PortProtocol",
    "PortRule",
    "PortRuleHTTP",
    "PortRuleKafka",
    "PortRuleL7",
    "Repository",
    "Rule",
    "SearchContext",
    "SelectorRequirement",
    "Service",
    "TraceState",
    "Tracing",
    "WILDCARD_SELECTOR",
    "get_default_prefix_lengths",
    "get_policy_enabled",
    "init_entities",
    "parse_proxy_id",
    "proxy_id",
    "rule_from_dict",
    "rules_from_json",
    "rules_to_json",
    "set_policy_enabled",
]
