"""Clustermesh: merge remote clusters' state into the local caches.

reference: pkg/clustermesh/{clustermesh.go,remote_cluster.go} — the agent
watches a config directory where each file names a remote cluster and
carries its kvstore client config; per cluster it connects and merges
nodes and ipcache entries (identities share one global id space across
the mesh).  Here a remote cluster config is a JSON file
``{"address": "host:port"}`` pointing at that cluster's KvstoreServer;
removing the file disconnects and purges everything learned from it.
"""

from __future__ import annotations

import json
import logging
import os
import threading

from .ipcache import IP_IDENTITIES_PATH, IPIdentityCache
from .kvstore import EventType, NetBackend
from .node import NODES_PATH, Node
from .utils.controller import ControllerManager, ControllerParams

log = logging.getLogger(__name__)


class RemoteCluster:
    """One connected remote cluster (reference: remote_cluster.go)."""

    def __init__(self, name: str, address: str, cache: IPIdentityCache) -> None:
        self.name = name
        self.address = address
        self.cache = cache
        self.backend = NetBackend(address)
        self.nodes: dict[str, Node] = {}
        self._learned_ips: set[str] = set()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._watch(f"{IP_IDENTITIES_PATH}/{name}/", self._ip_event)
        self._watch(f"{NODES_PATH}/{name}/", self._node_event)

    def _watch(self, prefix: str, handler) -> None:
        w = self.backend.list_and_watch(f"mesh-{self.name}", prefix)

        def run() -> None:
            while not self._stop.is_set():
                ev = w.next_event(timeout=0.2)
                if ev is None or ev.typ == EventType.LIST_DONE:
                    continue
                try:
                    handler(prefix, ev)
                except Exception:  # noqa: BLE001
                    log.exception("clustermesh %s event failed", self.name)
            w.stop()

        t = threading.Thread(
            target=run, daemon=True, name=f"mesh-{self.name}"
        )
        t.start()
        self._threads.append(t)

    def _ip_event(self, prefix: str, ev) -> None:
        ip = ev.key[len(prefix):]
        if ev.typ == EventType.DELETE:
            self.cache.delete(ip)
            self._learned_ips.discard(ip)
            return
        data = json.loads(ev.value.decode())
        self.cache.upsert(
            data.get("IP", ip), data.get("ID", 0),
            data.get("TunnelEndpoint", 0), data.get("HostIP", ""),
        )
        self._learned_ips.add(data.get("IP", ip))

    def _node_event(self, prefix: str, ev) -> None:
        name = ev.key[len(prefix):]
        if ev.typ == EventType.DELETE:
            self.nodes.pop(name, None)
            return
        self.nodes[name] = Node.from_dict(json.loads(ev.value.decode()))

    def status(self) -> dict:
        return {
            "name": self.name,
            "address": self.address,
            "connected": self.backend.ping(),
            "nodes": len(self.nodes),
            "ips": len(self._learned_ips),
        }

    def close(self) -> None:
        """Disconnect and purge everything learned from this cluster
        (reference: remote_cluster.go onRemove)."""
        self._stop.set()
        for ip in sorted(self._learned_ips):
            self.cache.delete(ip)
        self._learned_ips.clear()
        self.nodes.clear()
        self.backend.close()


class ClusterMesh:
    """Config-dir watcher wiring RemoteClusters (clustermesh.go:NewClusterMesh)."""

    def __init__(self, config_dir: str, cache: IPIdentityCache,
                 controllers: ControllerManager | None = None,
                 interval: float = 0.2) -> None:
        self.config_dir = config_dir
        self.cache = cache
        self.clusters: dict[str, RemoteCluster] = {}
        self._mutex = threading.Lock()
        self._controllers = controllers or ControllerManager()
        self._own_controllers = controllers is None
        os.makedirs(config_dir, exist_ok=True)
        self._controllers.update_controller(
            "clustermesh-config",
            ControllerParams(do_func=self.sync, run_interval=interval),
        )

    def sync(self) -> None:
        """Reconcile connected clusters against the config dir."""
        want: dict[str, str] = {}
        for fn in sorted(os.listdir(self.config_dir)):
            path = os.path.join(self.config_dir, fn)
            if not os.path.isfile(path):
                continue
            try:
                with open(path) as f:
                    want[fn] = json.load(f)["address"]
            except (ValueError, KeyError, OSError):
                log.warning("bad clustermesh config %s", path)
        with self._mutex:
            for name in list(self.clusters):
                cluster = self.clusters[name]
                if name not in want or cluster.address != want[name]:
                    self.clusters.pop(name).close()
                elif not cluster.backend.ping():
                    # Connection died (remote store restart): drop and
                    # reconnect on this pass (reference: remote clusters
                    # reconnect with backoff, remote_cluster.go).
                    self.clusters.pop(name).close()
            for name, address in want.items():
                if name not in self.clusters:
                    try:
                        self.clusters[name] = RemoteCluster(
                            name, address, self.cache
                        )
                    except OSError as e:
                        log.warning(
                            "clustermesh %s unreachable: %s", name, e
                        )

    def status(self) -> list[dict]:
        with self._mutex:
            return [c.status() for c in self.clusters.values()]

    def num_connected(self) -> int:
        with self._mutex:
            return sum(1 for c in self.clusters.values())

    def close(self) -> None:
        if self._own_controllers:
            self._controllers.remove_all()
        else:
            self._controllers.remove_controller("clustermesh-config")
        with self._mutex:
            for c in self.clusters.values():
                c.close()
            self.clusters.clear()
