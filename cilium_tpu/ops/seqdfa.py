"""Sequence-axis parallel DFA search: shard the BYTES, not the flows.

The long-context strategy for this framework.  The reference's closest
analog is streaming frame reassembly (proxylib's MORE contract,
SURVEY §5 long-context) — but on TPU a single very wide frame
(a 32KB HTTP head is the worst case the in-process engine tiers for)
forces ``ops/dfa.py`` through tens of thousands of SEQUENTIAL scan
steps on one device.  Sequence parallelism fixes the wall-clock the
same way ring attention fixes attention over long sequences: split the
byte axis across the mesh and replace the sequential dependency with an
associative combine.

The construction (the classic parallel-prefix automaton):

1. **Absorbing accepts.**  Sticky acceptance ("accepted if ANY prefix
   hit an accept state") is folded into the automaton by making accept
   states absorbing — then acceptance is a property of the FINAL state
   only, and the whole span becomes one function composition.
2. **Chunk folding.**  A byte ``b`` is a state map δ_b: S→S; a chunk of
   bytes composes to one map.  Each device folds its local slice with
   the same one-hot-matmul step the serial scan uses, but carries the
   full [S, S] permutation-like matrix instead of one state row:
   ``P' = P @ D_c`` (batched over [F, R], MXU-friendly, no gathers).
   Inactive positions (outside a flow's span) multiply by identity.
3. **Associative combine.**  The per-chunk maps (tiny: [F, R, S, S]
   int8) are matmul-composed across the sequence axis — log-depth in
   theory; with n_devices ≤ 8 chunks a serial fold of the gathered
   summaries costs nanoseconds and keeps the collective to ONE
   all_gather over ICI.

Per-device work is O(F·R·S³/D) per byte-slice versus the serial scan's
O(F·R·S²·C) over ALL bytes — with the per-pattern S ≈ 16 ≈ C these are
the same cost class, so wall-clock scales ~1/D with device count.

Bit-exactness: composed-map acceptance equals the serial sticky scan by
construction (absorbing accepts ⊆ accept_final); fuzz-checked against
ops/dfa.py in tests/test_seqdfa.py on an 8-device mesh.
"""

from __future__ import annotations

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..regex.dfa import DfaTables
from .dfa import DeviceDfa, byte_class_onehot, device_dfa

SEQ_AXIS = "seq"


def make_absorbing(tables: DfaTables) -> DfaTables:
    """Accept states become self-loops on every byte class, so sticky
    acceptance reduces to final-state acceptance (step 1 above)."""
    delta = tables.delta.copy()
    ri, si = np.nonzero(tables.accept)
    delta[ri, si, :] = si[:, None]
    return replace(tables, delta=delta)


def device_dfa_absorbing(tables: DfaTables) -> DeviceDfa:
    return device_dfa(make_absorbing(tables))


def _fold_chunk(dfa: DeviceDfa, data, t0, span_start, span_end,
                vary_axis: str | None = None):
    """Fold data[f, :] (positions t0..t0+Lc) into state maps
    [F, R, S, S] one-hot: map[f, r, s0, :] = state reached from s0."""
    f, lc = data.shape
    r, s, c = dfa.n_patterns, dfa.n_states, dfa.n_classes
    eye = jnp.eye(s, dtype=jnp.int8)
    p0 = jnp.broadcast_to(eye[None, None, :, :], (f, r, s, s)).astype(jnp.int8)
    if vary_axis is not None:
        # Inside shard_map the scan carry becomes device-varying (each
        # device folds its own byte slice); the initial carry must be
        # marked varying too or jax's manual-axes check rejects the scan.
        if hasattr(jax.lax, "pcast"):
            p0 = jax.lax.pcast(p0, (vary_axis,), to="varying")
        elif hasattr(jax.lax, "pvary"):  # older jax
            p0 = jax.lax.pvary(p0, (vary_axis,))
    # delta as [R, C, S, S]: for class c, D[r, c, s, t] = 1 iff δ(s,c)=t,
    # derived from the integer-id table (padded states map to 0 but are
    # never selected: composition starts from the identity and final
    # application selects real start states only).
    delta_sc = (
        dfa.delta_id.transpose(0, 2, 1)[:, :, :, None]
        == jnp.arange(s, dtype=jnp.int32)[None, None, None, :]
    ).astype(jnp.int8)

    def step(p, inputs):
        byte_col, t = inputs  # [F], scalar-per-flow position
        cls1h = byte_class_onehot(dfa, byte_col)  # [F, C]
        # Per-flow transition matrix for this byte: [F, R, S, S]
        d_t = jnp.einsum(
            "fc,rcst->frst", cls1h, delta_sc,
            preferred_element_type=jnp.int32,
        ).astype(jnp.int8)
        nxt = jnp.einsum(
            "frsu,frut->frst", p, d_t, preferred_element_type=jnp.int32
        )
        nxt = (nxt > 0).astype(jnp.int8)
        active = (t >= span_start) & (t < span_end)  # [F]
        return jnp.where(active[:, None, None, None], nxt, p), None

    ts = t0 + jnp.arange(lc, dtype=jnp.int32)
    p, _ = jax.lax.scan(step, p0, (data.T, ts), unroll=8)
    return p


def _compose(p1, p2):
    """(p2 ∘ p1): apply p1 first.  [..., S, S] one-hot matmul."""
    out = jnp.einsum(
        "...su,...ut->...st", p1, p2, preferred_element_type=jnp.int32
    )
    return (out > 0).astype(jnp.int8)


def _apply_start_accept(dfa: DeviceDfa, pmap):
    """Start state through the composed map; accept_final membership
    (absorbing accepts make sticky == final)."""
    final_state = jnp.einsum(
        "rs,frst->frt", dfa.start_1h, pmap,
        preferred_element_type=jnp.int32,
    ).astype(jnp.int8)
    return (
        jnp.einsum(
            "frt,rt->fr", final_state, dfa.accept_final_mask,
            preferred_element_type=jnp.int32,
        )
        > 0
    )


def seqdfa_search_batch(
    dfa_abs: DeviceDfa, data, lengths, n_chunks: int = 1
):
    """Single-device reference of the chunked formulation: fold
    n_chunks sub-spans independently, compose, accept.  Exists so the
    sharded path's math is testable without a mesh."""
    f, width = data.shape
    lengths = jnp.asarray(lengths, jnp.int32)
    assert width % n_chunks == 0
    lc = width // n_chunks
    pmap = None
    for k in range(n_chunks):
        pk = _fold_chunk(
            dfa_abs, data[:, k * lc : (k + 1) * lc],
            jnp.int32(k * lc), jnp.zeros_like(lengths), lengths,
        )
        pmap = pk if pmap is None else _compose(pmap, pk)
    return _apply_start_accept(dfa_abs, pmap)


def seqdfa_search_sharded(dfa_abs: DeviceDfa, data, lengths, mesh: Mesh):
    """Sequence-sharded search over ``mesh``'s SEQ_AXIS: each device
    folds its byte slice, one all_gather moves the [S, S] summaries
    over ICI, and every device composes + accepts (replicated result).

    ``data`` is [F, W] with W divisible by the seq axis size; flows may
    simultaneously shard on a flow axis if the mesh has one."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    n_seq = mesh.shape[SEQ_AXIS]
    f, width = data.shape
    if width % n_seq != 0:
        raise ValueError(f"width {width} not divisible by seq axis {n_seq}")
    lc = width // n_seq
    lengths = jnp.asarray(lengths, jnp.int32)

    if f % n_seq != 0:
        raise ValueError(f"flow count {f} not divisible by seq axis {n_seq}")
    fb = f // n_seq

    def local(data_slice, lengths_full):
        # Which chunk this device holds follows from its axis index.
        k = jax.lax.axis_index(SEQ_AXIS)
        p = _fold_chunk(
            dfa_abs, data_slice, k * lc,
            jnp.zeros_like(lengths_full), lengths_full,
            vary_axis=SEQ_AXIS,
        )
        # [D, F, R, S, S] — tiny; ONE collective over the seq axis.
        all_p = jax.lax.all_gather(p, SEQ_AXIS)

        def body(i, acc):
            return _compose(acc, all_p[i])

        pmap = jax.lax.fori_loop(1, n_seq, body, all_p[0])
        out = _apply_start_accept(dfa_abs, pmap)  # [F, R], full batch
        # Every device holds the full composed map; emit only this
        # device's flow block so the output spec shards cleanly over
        # the same axis (concatenation rebuilds [F, R]).
        return jax.lax.dynamic_slice_in_dim(out, k * fb, fb, axis=0)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, SEQ_AXIS), P(None)),
        out_specs=P(SEQ_AXIS, None),
    )(data, lengths)
