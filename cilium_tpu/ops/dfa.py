"""Batched per-pattern DFA evaluation on TPU.

The scale-out sibling of ops/nfa.py.  The dense union NFA advances a
[F, S_total] state set with an O(S_total²·C) matmul per byte; at
hundred-rule scale S_total is thousands and the delta is HBM-hostile.
But the union automaton is block-diagonal — patterns never share states
— and each pattern determinizes to a TINY DFA (regex/dfa.py), whose
next state is a SCALAR.  The per-byte step therefore needs no S×S
transition algebra at all:

  state:   [F, R, S] one-hot int8 (deterministic => exactly one bit)
  cls1h:   [F, C]    range compares (classes are unions of byte runs)
  row:     [F, R, C] = state @ delta_id[R, S, C]   (row select, MXU)
  nxt_id:  [F, R]    = Σ_c row·cls1h               (class select, VPU)
  state':  [F, R, S] = (nxt_id == iota_S)          (one-hot rebuild)

Work per byte is O(F·R·S·C) — S× less than the one-hot-delta matmul
this replaced and S_total/S·S× less than the dense NFA — with tables a
few KB.  No gathers anywhere: TPU gathers do not vectorize (a
gather-based scan measured ~10k flows/s; take_along_axis variants cost
~0.4s per 500k-flow pass).

Acceptance is a mask reduction (state ⋅ accept_mask), sticky across
steps like the NFA op.  API mirrors ops/nfa.py; bit-identical by
construction from the same CompiledPattern NFAs (tests/test_dfa_op.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..regex.dfa import DfaTables


@jax.tree_util.register_pytree_node_class
@dataclass
class DeviceDfa:
    """Packed per-pattern DFA tables resident on device.

    ``delta_id`` holds the transition TARGET ID (not a one-hot): a
    deterministic automaton's next state is a scalar, so the step
    contracts the one-hot state against an integer-valued table —
    O(S·C) MACs per (flow, pattern, byte) instead of the one-hot
    delta's O(S²·C), a 48× compute and ~50× HBM-traffic saving at
    S=48/C=19 (measured 3× wall on the 500k-flow stress replay)."""

    # Byte classes as unions of ranges: cls c contains byte b iff
    # lo[c,k] <= b <= hi[c,k] for some k.  The range compare form costs
    # ~C*K byte-ops per flow-byte instead of materializing a [F, 256]
    # one-hot (16MB per scan step at F=64k) for the classmap matmul.
    cls_lo: jax.Array  # [C, K] int32 (padded rows have lo > hi)
    cls_hi: jax.Array  # [C, K] int32
    delta_id: jax.Array  # [R, S, C] int8 — next-state id per (state, class)
    start_1h: jax.Array  # [R, S] int8
    accept_mask: jax.Array  # [R, S] int8 — sticky accept states
    accept_final_mask: jax.Array  # [R, S] int8 — accept | accept-via-END
    n_states: int
    n_classes: int
    n_patterns: int

    def tree_flatten(self):
        leaves = (
            self.cls_lo,
            self.cls_hi,
            self.delta_id,
            self.start_1h,
            self.accept_mask,
            self.accept_final_mask,
        )
        return leaves, (self.n_states, self.n_classes, self.n_patterns)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, *aux)


def device_dfa(tables: DfaTables) -> DeviceDfa:
    """Upload packed host tables to the device."""
    from ..regex.dfa import DfaBlowupError

    r, s, c = tables.n_patterns, tables.n_states, tables.n_classes
    if s > 128:  # ids 0..s-1 must fit int8
        # DfaBlowupError (not ValueError) so compile_automaton's 'auto'
        # path falls back to the dense NFA instead of failing the build.
        raise DfaBlowupError(
            f"DFA state id must fit int8 (got {s} states)"
        )
    # Byte classes as maximal runs of the 256-entry classmap.
    runs: list[list[tuple[int, int]]] = [[] for _ in range(c)]
    start_b = 0
    for b in range(1, 257):
        if b == 256 or tables.classmap[b] != tables.classmap[start_b]:
            runs[int(tables.classmap[start_b])].append((start_b, b - 1))
            start_b = b
    k = max(1, max(len(rr) for rr in runs))
    cls_lo = np.full((c, k), 1, np.int32)  # lo>hi: empty padding
    cls_hi = np.zeros((c, k), np.int32)
    for ci, rr in enumerate(runs):
        for ki, (lo, hi) in enumerate(rr):
            cls_lo[ci, ki] = lo
            cls_hi[ci, ki] = hi
    # Padded states/patterns keep delta_id=0: the one-hot state vector
    # never activates them, so their targets are never selected.
    delta_id = tables.delta.astype(np.int8)  # [R, S, C]
    start_1h = np.zeros((r, s), np.int8)
    start_1h[np.arange(r), tables.start] = 1
    return DeviceDfa(
        cls_lo=jnp.asarray(cls_lo),
        cls_hi=jnp.asarray(cls_hi),
        delta_id=jnp.asarray(delta_id),
        start_1h=jnp.asarray(start_1h),
        accept_mask=jnp.asarray(tables.accept.astype(np.int8)),
        accept_final_mask=jnp.asarray(tables.accept_final.astype(np.int8)),
        n_states=s,
        n_classes=c,
        n_patterns=r,
    )


def byte_class_onehot(dfa: DeviceDfa, byte_col: jax.Array) -> jax.Array:
    """[F] bytes -> [F, C] one-hot byte classes (shared by the serial
    scan and the sequence-sharded fold so the two paths cannot drift).
    Range-compare form: classes are unions of byte runs, so membership
    is a handful of [F] compares instead of a [F, 256] one-hot matmul
    (which cost 16MB of traffic per scan step at F=64k — measured 3.5x
    slower end to end on the r2d2 search)."""
    b = jnp.asarray(byte_col, jnp.int32)[:, None, None]  # [F, 1, 1]
    in_run = (b >= dfa.cls_lo[None, :, :]) & (b <= dfa.cls_hi[None, :, :])
    return jnp.any(in_run, axis=2).astype(jnp.int8)  # [F, C]


def _accepts(state: jax.Array, mask: jax.Array) -> jax.Array:
    """[F, R] bool: the one-hot state is in the mask."""
    return (
        jnp.einsum(
            "frs,rs->fr", state, mask, preferred_element_type=jnp.int32
        )
        > 0
    )


def _dfa_scan(dfa: DeviceDfa, data, span_start, span_end):
    f = data.shape[0]
    r, s, c = dfa.n_patterns, dfa.n_states, dfa.n_classes

    state0 = jnp.broadcast_to(dfa.start_1h[None, :, :], (f, r, s)).astype(
        jnp.int8
    )
    accepted0 = _accepts(state0, dfa.accept_mask)

    data_t = data.T  # [L, F]

    iota_s = jnp.arange(s, dtype=jnp.int32)

    def step(carry, inputs):
        state, accepted = carry
        byte_col, t = inputs  # [F]
        cls1h = byte_class_onehot(dfa, byte_col)  # [F, C]
        # Row select: row[f, r, c] = delta_id[r, cur_state(f,r), c]
        # — one-hot state × integer table, O(S·C) MACs per (f, r).
        row = jax.lax.dot_general(
            state,
            dfa.delta_id,
            (((2,), (1,)), ((1,), (0,))),
            preferred_element_type=jnp.int32,
        ).transpose(1, 0, 2)  # [F, R, C]
        # Class select (VPU): nxt_id[f, r] = row[f, r, cls(byte_f)].
        nxt_id = (row * cls1h[:, None, :].astype(jnp.int32)).sum(
            axis=2
        )  # [F, R]
        nxt = (nxt_id[:, :, None] == iota_s).astype(jnp.int8)  # [F, R, S]
        active = (t >= span_start) & (t < span_end)  # [F]
        state = jnp.where(active[:, None, None], nxt, state)
        accepted = accepted | _accepts(state, dfa.accept_mask)
        return (state, accepted), None

    length = data.shape[1]
    ts = jnp.arange(length, dtype=jnp.int32)
    # unroll: each step is a handful of SMALL kernels (the per-policy
    # tables are tiny), so an un-unrolled scan is launch-latency-bound;
    # unrolling lets XLA fuse across byte positions.
    (state, accepted), _ = jax.lax.scan(
        step, (state0, accepted0), (data_t, ts), unroll=8
    )
    final_acc = _accepts(state, dfa.accept_final_mask)
    return accepted | final_acc  # [F, R] bool


@jax.jit
def dfa_search_spans(
    dfa: DeviceDfa, data: jax.Array, span_start: jax.Array, span_end: jax.Array
) -> jax.Array:
    """Search each pattern within ``data[f, span_start[f]:span_end[f]]``;
    same contract as ops.nfa.nfa_search_spans."""
    return _dfa_scan(dfa, data, span_start, span_end)


@jax.jit
def dfa_search_batch(
    dfa: DeviceDfa, data: jax.Array, lengths: jax.Array
) -> jax.Array:
    """Search each pattern in ``data[f, :lengths[f]]``; [F, R] bool."""
    zeros = jnp.zeros_like(lengths)
    return _dfa_scan(dfa, data, zeros, lengths)
