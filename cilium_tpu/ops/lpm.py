"""Batched longest-prefix-match over packed prefix arrays.

The TPU-native replacement for the reference's LPM structures — the XDP
prefilter's BPF_MAP_TYPE_LPM_TRIE + /32 hash pair (reference:
bpf/bpf_xdp.c:44-90), the per-prefix-length cidrmap emulation (reference:
pkg/maps/cidrmap), and the ipcache LPM (reference: pkg/maps/ipcache) — as
one masked-compare sweep: for F query addresses against N prefixes,
``matched[f, n] = (addr[f] & mask[n]) == net[n]``, and the winner is the
matched row with the longest prefix.  No trie, no pointer chasing: a dense
[F, N] compare the VPU streams through, exactly the "per-length masked
compare" strategy the reference uses on pre-LPM kernels
(pkg/policy/l3.go:50 GetDefaultPrefixLengths ordering, longest first).

IPv4 addresses are a single uint32 lane; IPv6 uses four uint32 lanes.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


def _mask32(prefix_len: np.ndarray, word: int, v6: bool) -> np.ndarray:
    """Per-word network mask for word index ``word`` given prefix lengths."""
    base = prefix_len - 32 * word
    bits = np.clip(base, 0, 32)
    # (0xFFFFFFFF << (32-bits)) & 0xFFFFFFFF, with bits==0 -> 0
    full = np.uint64(0xFFFFFFFF)
    m = (full << (np.uint64(32) - bits.astype(np.uint64))) & full
    m = np.where(bits == 0, np.uint64(0), m)
    return m.astype(np.uint32)


@jax.tree_util.register_pytree_node_class
@dataclass
class DeviceLpm:
    """Packed prefix table on device.

    words:  [W][N] int32 — network address words (W=1 for v4, 4 for v6),
            already masked.
    masks:  [W][N] int32 — per-word masks.
    plen:   [N] int32 — prefix lengths (winner = max among matches).
    values: [N] int32 — value per prefix (identity, flags, ...).
    valid:  [N] bool.
    """

    words: tuple
    masks: tuple
    plen: jax.Array
    values: jax.Array
    valid: jax.Array

    def tree_flatten(self):
        return ((self.words, self.masks, self.plen, self.values, self.valid), None)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


def _ip_words(net: ipaddress._BaseNetwork, n_words: int) -> list[int]:
    x = int(net.network_address)
    total_bits = 32 * n_words
    return [(x >> (total_bits - 32 * (w + 1))) & 0xFFFFFFFF for w in range(n_words)]


def build_lpm(
    prefixes: list[tuple[str, int]], v6: bool = False, pad_to: int | None = None
) -> DeviceLpm:
    """Build a device LPM table from (cidr_string, value) pairs."""
    n_words = 4 if v6 else 1
    nets = []
    vals = []
    for cidr, value in prefixes:
        net = ipaddress.ip_network(cidr, strict=False)
        if (net.version == 6) != v6:
            raise ValueError(f"address family mismatch for {cidr}")
        nets.append(net)
        vals.append(value)
    n = len(nets)
    size = pad_to if pad_to is not None else max(n, 1)
    if size < n:
        raise ValueError(f"pad_to {size} < table size {n}")
    plen = np.zeros((size,), np.int64)
    values = np.zeros((size,), np.int32)
    valid = np.zeros((size,), bool)
    words = np.zeros((n_words, size), np.uint32)
    for i, net in enumerate(nets):
        plen[i] = net.prefixlen
        values[i] = vals[i]
        valid[i] = True
        for w, word in enumerate(_ip_words(net, n_words)):
            words[w, i] = word
    masks = np.stack([_mask32(plen, w, v6) for w in range(n_words)])
    words = words & masks  # normalize: host bits cleared
    return DeviceLpm(
        words=tuple(jnp.asarray(words[w].view(np.int32)) for w in range(n_words)),
        masks=tuple(jnp.asarray(masks[w].view(np.int32)) for w in range(n_words)),
        plen=jnp.asarray(plen.astype(np.int32)),
        values=jnp.asarray(values),
        valid=jnp.asarray(valid),
    )


def lpm_lookup(
    lpm: DeviceLpm, *addr_words: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Longest-prefix match for F addresses given as W [F] int32 word arrays.

    Returns (found [F] bool, value [F] int32, prefix_len [F] int32).
    """
    f = addr_words[0].shape[0]
    matched = lpm.valid[None, :]  # [F, N]
    for w, aw in enumerate(addr_words):
        masked = jnp.bitwise_and(aw[:, None], lpm.masks[w][None, :])
        matched = matched & (masked == lpm.words[w][None, :])
    # Longest prefix wins: score = plen+1 for matches, 0 otherwise.
    # Gather-free selection (TPU gathers serialize): the best score is
    # a max-reduce; the winning row's value is a masked max over the
    # rows attaining it (tables MAY contain duplicate equal-length
    # prefixes — any of their values is a valid answer, matching the
    # argmax tie-break contract).
    score = jnp.where(matched, lpm.plen[None, :] + 1, 0)
    best_score = jnp.max(score, axis=1)  # [F]
    found = best_score > 0
    at_best = matched & (score == best_score[:, None])  # [F, N]
    value = jnp.max(
        jnp.where(at_best, lpm.values[None, :], jnp.iinfo(jnp.int32).min),
        axis=1,
    )
    value = jnp.where(found, value, 0)
    plen_out = jnp.where(found, best_score - 1, -1)
    return found, value, plen_out


def ipv4_to_words(ips) -> tuple[np.ndarray]:
    """Host helper: array/list of IPv4 strings or ints -> ([F] int32,)."""
    out = np.zeros((len(ips),), np.uint32)
    for i, ip in enumerate(ips):
        if isinstance(ip, str):
            ip = int(ipaddress.IPv4Address(ip))
        out[i] = ip
    return (out.view(np.int32),)


def ipv6_to_words(ips) -> tuple[np.ndarray, ...]:
    """Host helper: array/list of IPv6 strings or ints -> 4x [F] int32."""
    words = np.zeros((4, len(ips)), np.uint32)
    for i, ip in enumerate(ips):
        if isinstance(ip, str):
            ip = int(ipaddress.IPv6Address(ip))
        for w in range(4):
            words[w, i] = (ip >> (128 - 32 * (w + 1))) & 0xFFFFFFFF
    return tuple(words[w].view(np.int32) for w in range(4))


def prefilter_check_batch(lpm: DeviceLpm, *addr_words) -> jax.Array:
    """XDP prefilter verdict: True = drop (source address in a deny prefix)
    (reference: bpf/bpf_xdp.c:97-121 check_v4)."""
    found, _, _ = lpm_lookup(lpm, *addr_words)
    return found
