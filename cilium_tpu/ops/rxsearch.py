"""Automaton compile + search dispatch shared by the protocol models.

Every protocol model (r2d2/http/cassandra/memcached) needs "compile
these regex patterns to a device automaton, then search spans with it".
Two device automata exist:

- ``DeviceDfa`` (ops/dfa.py): per-pattern determinized blocks advanced
  with an integer-id row-select — O(S·C) MACs per (flow, pattern, byte).
  The default: ~12× the dense NFA's throughput (r2d2 measured 1.9M/s →
  23M/s verdicts on the same rule set).
- ``DeviceNfa`` (ops/nfa.py): the dense union-NFA matmul — O(S²·C) per
  byte, but immune to determinization blowup.  The fallback when a
  pattern's DFA explodes (``DfaBlowupError``).

(reference: the per-rule compiled std::regex walk this replaces,
envoy/cilium_network_policy.h:50-76.)
"""

from __future__ import annotations

import jax

from ..regex import compile_patterns
from ..regex.dfa import DfaBlowupError, compile_pattern_dfas
from .dfa import DeviceDfa, device_dfa, dfa_search_batch, dfa_search_spans
from .nfa import DeviceNfa, device_nfa, nfa_search_batch, nfa_search_spans

__all__ = [
    "compile_automaton",
    "automaton_search_spans",
    "automaton_search_batch",
    "DeviceDfa",
    "DeviceNfa",
]


def compile_automaton(
    patterns: list[str], backend: str = "auto"
) -> DeviceDfa | DeviceNfa | None:
    """Compile patterns to the requested device automaton; None when
    the list is empty.  ``auto`` = DFA with NFA fallback on blowup."""
    if not patterns:
        return None
    if backend in ("auto", "dfa"):
        try:
            return device_dfa(compile_pattern_dfas(patterns))
        except DfaBlowupError:
            if backend == "dfa":
                raise
    return device_nfa(compile_patterns(patterns))


def automaton_search_spans(tab, data, span_start, span_end) -> jax.Array:
    """[F, R] bool: pattern r matches data[f, span_start:span_end]."""
    fn = dfa_search_spans if isinstance(tab, DeviceDfa) else nfa_search_spans
    return fn(tab, data, span_start, span_end)


def automaton_search_batch(tab, data, lengths) -> jax.Array:
    """[F, R] bool: pattern r matches data[f, :lengths[f]]."""
    fn = dfa_search_batch if isinstance(tab, DeviceDfa) else nfa_search_batch
    return fn(tab, data, lengths)
