"""Batched exact-match lookup into packed key tables.

The TPU replacement for per-packet BPF hash-map lookups (reference:
bpf/lib/policy.h:47 map_lookup_elem on POLICY_MAP): instead of one hash
probe per packet, F flows look up N table entries in one data-parallel
broadcast compare.  For the rule-table sizes policy maps reach (hundreds to
a few thousand entries) an [F, N] compare is a single fused VPU pass and
beats hash emulation on TPU, which has no efficient scatter/probe loop.

Keys are column arrays (struct-of-arrays) so each field compare vectorizes;
the table is padded to a fixed shape for jit stability.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


def u32_to_i32(a):
    """uint32 bit pattern -> int32 lanes.  Device tables carry
    addresses and other full-range uint32 values as int32 bit patterns
    so entries >= 2^31 compare bit-exact; every pack/oracle site must
    use this one conversion."""
    arr = np.asarray(a, np.int64)
    return (arr & 0xFFFFFFFF).astype(np.uint32).view(np.int32)


@jax.tree_util.register_pytree_node_class
@dataclass
class DeviceTable:
    """Packed column-oriented lookup table resident on device.

    cols: tuple of [N] int32 arrays, one per key field.
    values: [N, V] int32 value columns.
    valid: [N] bool — padding rows are invalid.
    """

    cols: tuple
    values: jax.Array
    valid: jax.Array

    def tree_flatten(self):
        return ((self.cols, self.values, self.valid), None)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


def pack_table(
    keys: np.ndarray, values: np.ndarray, pad_to: int | None = None
) -> DeviceTable:
    """Build a DeviceTable from [N, K] int key rows and [N, V] int values."""
    keys = np.asarray(keys, dtype=np.int32)
    values = np.asarray(values, dtype=np.int32)
    if keys.ndim != 2:
        raise ValueError("keys must be [N, K]")
    n, k = keys.shape
    size = pad_to if pad_to is not None else max(n, 1)
    if size < n:
        raise ValueError(f"pad_to {size} < table size {n}")
    pk = np.zeros((size, k), dtype=np.int32)
    pv = np.zeros((size, values.shape[1] if values.ndim == 2 else 1), np.int32)
    valid = np.zeros((size,), dtype=bool)
    pk[:n] = keys
    if n:
        pv[:n] = values.reshape(n, -1)
    valid[:n] = True
    return DeviceTable(
        cols=tuple(jnp.asarray(pk[:, i]) for i in range(k)),
        values=jnp.asarray(pv),
        valid=jnp.asarray(valid),
    )


def exact_lookup(table: DeviceTable, *query_cols) -> tuple[jax.Array, jax.Array]:
    """Look up F queries (one [F] int32 array per key field).

    Returns (found [F] bool, values [F, V] int32; zeros when not found).
    First matching row wins (tables are deduplicated on build).
    """
    if len(query_cols) != len(table.cols):
        raise ValueError(
            f"query has {len(query_cols)} columns, table has "
            f"{len(table.cols)} — every key field must be matched"
        )
    matched = table.valid[None, :]  # [F, N]
    for col, q in zip(table.cols, query_cols):
        matched = matched & (col[None, :] == q[:, None])
    found = jnp.any(matched, axis=1)
    # Row extraction as ONE matmul (match rows are unique after build
    # dedup, so the sum IS the matched row; zero when unmatched) — TPU
    # gathers serialize, the [F,N]x[N,V] dot rides the MXU.
    vals = jax.lax.dot_general(
        matched.astype(jnp.int8),
        table.values,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return found, vals
