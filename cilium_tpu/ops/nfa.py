"""Batched multi-pattern NFA evaluation on TPU.

Replaces the reference's per-request sequential rule matching — the proxylib
rule walk (reference: proxylib/proxylib/policymap.go:91-111) and Envoy's
per-rule ``std::regex_search`` (reference: envoy/cilium_network_policy.h:50-76)
— with one data-parallel scan that advances *all* flows' NFA state sets one
input byte at a time.

Formulation (MXU-friendly):
  state:  [F, S]  0/1 int8 — per-flow NFA state set
  delta:  [C, S, S] packed per byte-class; stored flat as [S, C*S] so the
          per-byte step is ONE matmul:
              proj   = state @ delta_flat          # [F, C*S], int32 accum
              proj   = proj.reshape(F, C, S)
              counts = select proj rows by each flow's byte class (one-hot
                       multiply-reduce; no gather)
              state' = counts > 0
  Acceptance is sticky: accepted[f, r] |= any(state & accept[r]) each step,
  computed as a second small matmul against accept^T.

Anchor handling (virtual BEGIN/END symbols) is folded into the tables at
compile time (see cilium_tpu.regex.nfa), so the scan runs exactly
``max_len`` steps regardless of anchors.

Cost: F*S*C*S MACs per byte position.  Byte-class compression keeps C small
(single-digit for typical policy rule sets), and S pads to the MXU tile.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..regex.tables import NfaTables


@jax.tree_util.register_pytree_node_class
@dataclass
class DeviceNfa:
    """Packed NFA tables resident on device."""

    delta_flat: jax.Array  # [S, C*S] int8
    classmap: jax.Array  # [256] int32
    start: jax.Array  # [S] int8
    accept_t: jax.Array  # [S, R] int8
    accept_final_t: jax.Array  # [S, R] int8
    n_classes: int
    n_states: int
    n_patterns: int

    def tree_flatten(self):
        leaves = (
            self.delta_flat,
            self.classmap,
            self.start,
            self.accept_t,
            self.accept_final_t,
        )
        aux = (self.n_classes, self.n_states, self.n_patterns)
        return leaves, aux

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, *aux)


def device_nfa(tables: NfaTables) -> DeviceNfa:
    """Upload packed host tables to the device."""
    s, c = tables.n_states, tables.n_classes
    # [C, S, S] -> [S, C, S] -> [S, C*S]: row s holds, for each class, the
    # outgoing-state row, so state @ delta_flat projects through EVERY class
    # at once and the per-flow class selection happens afterwards.
    delta_flat = np.ascontiguousarray(
        tables.delta.transpose(1, 0, 2).reshape(s, c * s)
    ).astype(np.int8)
    return DeviceNfa(
        delta_flat=jnp.asarray(delta_flat),
        classmap=jnp.asarray(tables.classmap, dtype=jnp.int32),
        start=jnp.asarray(tables.start, dtype=jnp.int8),
        accept_t=jnp.asarray(tables.accept.T, dtype=jnp.int8),
        accept_final_t=jnp.asarray(tables.accept_final.T, dtype=jnp.int8),
        n_classes=c,
        n_states=s,
        n_patterns=tables.n_patterns,
    )


def _nfa_scan(nfa: DeviceNfa, data: jax.Array, span_start: jax.Array, span_end: jax.Array):
    f = data.shape[0]
    s, c, r = nfa.n_states, nfa.n_classes, nfa.n_patterns

    state0 = jnp.broadcast_to(nfa.start, (f, s))
    accepted0 = (
        jax.lax.dot_general(
            state0,
            nfa.accept_t,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        > 0
    )

    data_t = data.T  # [L, F] — scan over byte positions

    def step(carry, inputs):
        state, accepted = carry
        byte_col, t = inputs
        cls = nfa.classmap[byte_col]  # [F]
        proj = jax.lax.dot_general(
            state,
            nfa.delta_flat,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [F, C*S]
        proj = proj.reshape(f, c, s)
        onehot = (cls[:, None] == jnp.arange(c, dtype=jnp.int32)[None, :]).astype(
            jnp.int32
        )  # [F, C]
        counts = jnp.sum(proj * onehot[:, :, None], axis=1)  # [F, S]
        nxt = (counts > 0).astype(jnp.int8)
        active = (t >= span_start) & (t < span_end)  # [F]
        state = jnp.where(active[:, None], nxt, state)
        acc_now = (
            jax.lax.dot_general(
                state,
                nfa.accept_t,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            > 0
        )
        accepted = accepted | acc_now
        return (state, accepted), None

    length = data.shape[1]
    ts = jnp.arange(length, dtype=jnp.int32)
    (state, accepted), _ = jax.lax.scan(step, (state0.astype(jnp.int8), accepted0), (data_t, ts))
    final_acc = (
        jax.lax.dot_general(
            state,
            nfa.accept_final_t,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        > 0
    )
    return accepted | final_acc  # [F, R] bool


@partial(jax.jit, static_argnames=())
def nfa_search_spans(
    nfa: DeviceNfa, data: jax.Array, span_start: jax.Array, span_end: jax.Array
) -> jax.Array:
    """Search each pattern within ``data[f, span_start[f]:span_end[f]]``.

    data: [F, L] uint8 (padded); span bounds: [F] int32.
    Returns [F, R] bool: pattern r matches somewhere in flow f's span.
    Empty spans (start >= end) match patterns that match the empty string.
    """
    return _nfa_scan(nfa, data, span_start, span_end)


@jax.jit
def nfa_search_batch(nfa: DeviceNfa, data: jax.Array, lengths: jax.Array) -> jax.Array:
    """Search each pattern in ``data[f, :lengths[f]]``; returns [F, R] bool."""
    zeros = jnp.zeros_like(lengths)
    return _nfa_scan(nfa, data, zeros, lengths)
