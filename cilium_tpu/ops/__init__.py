"""Device ops: the JAX/XLA compute path of the framework.

Every op here is a pure function over fixed-shape arrays, jit-safe, and
batched on the leading "flows" axis so it shards data-parallel over the mesh
(``cilium_tpu.parallel``).  These replace the reference's per-packet /
per-request scalar hot loops:

- ``nfa``          — batched multi-pattern regex-NFA evaluation
                     (replaces proxylib rule walks + Envoy std::regex,
                     reference: proxylib/proxylib/policymap.go:91,
                     envoy/cilium_network_policy.h:50-76)
- ``lpm``          — batched longest-prefix-match over packed CIDR arrays
                     (replaces the XDP LPM trie, reference: bpf/bpf_xdp.c:44-90)
- ``policy_table`` — batched L4 policy-map lookups
                     (replaces bpf/lib/policy.h:47 __policy_can_access)
- ``bytescan``     — fixed-width byte-parallel field extraction primitives
                     (delimiter finding, field splits) used by the protocol
                     tokenizers in ``cilium_tpu.models``
"""
