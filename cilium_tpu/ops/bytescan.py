"""Byte-parallel field extraction primitives.

The reference's parsers scan byte streams sequentially per request
(reference: proxylib/r2d2/r2d2parser.go:151-167 splits on "\\r\\n" and " ").
On TPU the same extraction is a handful of vectorized reductions over the
whole [flows, bytes] batch at once; everything here is jit-safe with static
shapes.

Positions are int32; "not found" is encoded as ``length`` (one past the
span), which composes directly with span-based ops downstream.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def first_occurrence(data: jax.Array, lengths: jax.Array, byte: int) -> jax.Array:
    """Index of the first ``byte`` within each flow's valid span, or
    ``lengths[f]`` if absent.  data: [F, L] uint8, lengths: [F] int32."""
    f, l = data.shape
    pos = jnp.arange(l, dtype=jnp.int32)[None, :]
    valid = pos < lengths[:, None]
    hit = (data == jnp.uint8(byte)) & valid
    return jnp.min(jnp.where(hit, pos, lengths[:, None]), axis=1)


def first_subsequence2(
    data: jax.Array, lengths: jax.Array, b0: int, b1: int
) -> jax.Array:
    """Index of the first two-byte sequence ``b0 b1`` (e.g. CRLF) fully
    inside each flow's valid span, or ``lengths[f]`` if absent."""
    f, l = data.shape
    pos = jnp.arange(l, dtype=jnp.int32)[None, :]
    nxt = jnp.concatenate(
        [data[:, 1:], jnp.zeros((f, 1), dtype=data.dtype)], axis=1
    )
    valid = (pos + 1) < lengths[:, None]
    hit = (data == jnp.uint8(b0)) & (nxt == jnp.uint8(b1)) & valid
    return jnp.min(jnp.where(hit, pos, lengths[:, None]), axis=1)


def count_byte(data: jax.Array, lengths: jax.Array, byte: int) -> jax.Array:
    """Occurrences of ``byte`` within each flow's valid span -> [F] int32."""
    f, l = data.shape
    pos = jnp.arange(l, dtype=jnp.int32)[None, :]
    valid = pos < lengths[:, None]
    return jnp.sum(((data == jnp.uint8(byte)) & valid).astype(jnp.int32), axis=1)


def window_at(data: jax.Array, start: jax.Array, n: int) -> jax.Array:
    """Per-flow window ``data[f, start[f]:start[f]+n]`` (zeros past the
    row end).

    Two formulations, selected by the tracing backend:
    - TPU: a barrel shifter — log2(L) conditional whole-row shifts by
      powers of two, selected by the bits of ``start``.  O(L·logL)
      bytes of pure VPU traffic per flow; TPU gathers serialize (a
      take_along_axis here measured ~0.4s per 500k-flow replay pass,
      3× the whole remaining pipeline).
    - CPU (tests, cpu-pinned verdict engines): plain take_along_axis —
      CPU gathers are fast and the shift chain is slower there.
    """
    f, l = data.shape
    if jax.default_backend() == "cpu":
        idx = start[:, None] + jnp.arange(n, dtype=jnp.int32)[None, :]
        idx = jnp.minimum(idx, l - 1)
        return jnp.take_along_axis(data, idx.astype(jnp.int32), axis=1)
    out = jnp.concatenate([data, jnp.zeros((f, n), data.dtype)], axis=1)
    width = out.shape[1]
    start = jnp.asarray(start, jnp.int32)
    k = 1
    while k < l:
        shifted = jnp.concatenate(
            [out[:, k:], jnp.zeros((f, min(k, width)), data.dtype)], axis=1
        )
        take = (start & k) != 0  # this bit of the shift amount
        out = jnp.where(take[:, None], shifted, out)
        k <<= 1
    return out[:, :n]


def _spans_compare(
    data: jax.Array,
    start: jax.Array,
    end: jax.Array,
    needle: jax.Array,
    needle_len: jax.Array,
    prefix: bool,
) -> jax.Array:
    """Shared core: window each span's first N bytes against the
    needles; ``prefix`` selects starts-with (span may be longer) vs
    exact (lengths must match)."""
    f, l = data.shape
    r, n = needle.shape
    # Degenerate spans (start > end, e.g. a missing token) behave as
    # empty — matching regex span semantics (ops/nfa.py empty spans).
    span_len = jnp.maximum(end - start, 0)  # [F]
    if prefix:
        len_ok = span_len[:, None] >= needle_len[None, :]  # [F, R]
    else:
        len_ok = span_len[:, None] == needle_len[None, :]  # [F, R]
    window = window_at(data, start, n)  # [F, N]
    eq = window[:, None, :] == needle[None, :, :]  # [F, R, N]
    bytes_needed = (
        jnp.arange(n, dtype=jnp.int32)[None, None, :] < needle_len[None, :, None]
    )
    return len_ok & jnp.all(eq | ~bytes_needed, axis=2)


def spans_equal_prefix(
    data: jax.Array,
    start: jax.Array,
    end: jax.Array,
    needle: jax.Array,
    needle_len: jax.Array,
) -> jax.Array:
    """Per (flow, needle): does data[f, start[f]:end[f]] equal needle[r]?

    data: [F, L] uint8; start/end: [F] int32;
    needle: [R, N] uint8 (zero-padded); needle_len: [R] int32.
    Returns [F, R] bool.  Used for exact-token matches (r2d2 cmd, Kafka
    apikey names) without a gather in the inner loop.
    """
    return _spans_compare(data, start, end, needle, needle_len, prefix=False)


def spans_start_with(
    data: jax.Array,
    start: jax.Array,
    end: jax.Array,
    needle: jax.Array,
    needle_len: jax.Array,
) -> jax.Array:
    """Per (flow, needle): does data[f, start[f]:end[f]] START WITH
    needle[r]?  Shapes as in spans_equal_prefix; returns [F, R] bool.
    Used for prefix key matches (memcached keyPrefix)."""
    return _spans_compare(data, start, end, needle, needle_len, prefix=True)
