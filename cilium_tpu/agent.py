"""Agent entrypoint (reference: daemon/main.go runDaemon).

Brings up the daemon and its servers: REST API socket, monitor socket,
access log socket, distribution socket; then serves until interrupted.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

from .accesslog import AccessLogServer
from .api import ApiServer
from .daemon import Daemon
from .distribution.sock import SocketDistributionServer
from .monitor import MonitorServer
from .utils import defaults
from .utils.logging import get_logger, set_log_level
from .utils.option import DaemonConfig

log = get_logger("agent")


class Agent:
    """Owns the daemon plus all listening sockets."""

    def __init__(self, config: DaemonConfig, node_name: str = "local") -> None:
        os.makedirs(config.run_dir, exist_ok=True)
        self.daemon = Daemon(config, node_name=node_name)
        self.api = ApiServer(self.daemon, config.socket_path)
        self.monitor_server = MonitorServer(
            self.daemon.monitor, config.monitor_socket_path
        )
        self.accesslog_server = AccessLogServer(
            os.path.join(config.run_dir, "access_log.sock"),
            on_record=self.daemon.access_logger.log,
        )
        self.dist_sock = SocketDistributionServer(
            self.daemon.dist_server,
            os.path.join(config.run_dir, "npds.sock"),
        )
        log.with_fields(
            api=config.socket_path, monitor=config.monitor_socket_path
        ).info("agent listening")

    def close(self) -> None:
        self.dist_sock.close()
        self.accesslog_server.close()
        self.monitor_server.close()
        self.api.close()
        self.daemon.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="cilium-tpu-agent",
        description="TPU-native cilium node agent",
    )
    p.add_argument("--run-dir", default=defaults.RUNTIME_PATH)
    p.add_argument("--node-name", default="local")
    p.add_argument("--cluster-name", default=defaults.CLUSTER_NAME)
    p.add_argument("--enable-policy", default="default",
                   choices=["default", "always", "never"])
    p.add_argument("--kvstore", default="local",
                   choices=["local", "file", "tcp"])
    p.add_argument("--kvstore-address", default="",
                   help="host:port of the kvstore server (kvstore=tcp); "
                        "comma-separated failover list supported "
                        "(primary,follower)")
    p.add_argument("--dry-mode", action="store_true",
                   help="skip device exports (reference: DryMode)")
    p.add_argument("--restore", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="restore endpoints from the state directory "
                        "(--no-restore for a clean start)")
    p.add_argument("--log-level", default="info")
    args = p.parse_args(argv)

    set_log_level(args.log_level)
    cfg = DaemonConfig(
        run_dir=args.run_dir,
        socket_path=os.path.join(args.run_dir, "cilium-tpu.sock"),
        monitor_socket_path=os.path.join(args.run_dir, "monitor.sock"),
        cluster_name=args.cluster_name,
        enable_policy=args.enable_policy,
        kvstore=args.kvstore,
        kvstore_opts=(
            {"address": args.kvstore_address} if args.kvstore_address else {}
        ),
        dry_mode=args.dry_mode,
        restore_state=args.restore,
    )
    from .policy import set_policy_enabled

    set_policy_enabled(args.enable_policy)
    agent = Agent(cfg, node_name=args.node_name)
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    try:
        stop.wait()
    finally:
        agent.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
