"""Backend interface and watcher events (reference: pkg/kvstore/backend.go
BackendOperations, events.go KeyValueEvent)."""

from __future__ import annotations

import abc
import enum
import queue
from dataclasses import dataclass
from typing import Iterator, Optional


class KvstoreError(RuntimeError):
    pass


class LockError(KvstoreError):
    pass


class EpochFencedError(KvstoreError):
    """A write reached a server whose fencing epoch is below the
    cluster's: a newer primary exists (EPOCH_FENCED).  The rejection
    happens BEFORE any mutation, so retrying against the current
    primary is always safe; callers that cache state derived from the
    stale server must re-resolve against the new primary instead of
    trusting their caches (see kvstore/net.py state machine)."""

    def __init__(self, msg: str, epoch: int = 0) -> None:
        super().__init__(msg)
        self.epoch = epoch  # the fencing (higher) epoch, if known


class NotPrimaryError(KvstoreError):
    """A write reached a still-replicating follower.  Transient by
    design: the follower either promotes (claiming the next epoch) or
    the primary returns — the write was rejected before any mutation,
    so backing off and retrying is always safe."""

    def __init__(self, msg: str, epoch: int = 0) -> None:
        super().__init__(msg)
        self.epoch = epoch


class EventType(enum.Enum):
    """reference: pkg/kvstore/events.go."""

    CREATE = "create"
    MODIFY = "modify"
    DELETE = "delete"
    LIST_DONE = "listDone"
    # Client-local marker (never sent on the wire): the connection was
    # re-established and a fresh snapshot replay follows.  Delivered
    # only to watchers that opted in via ``mark_resync`` — ordinary
    # consumers never see it.
    RESYNC = "resync"


@dataclass
class KeyValueEvent:
    typ: EventType
    key: str = ""
    value: bytes = b""
    # True when the key is lease-owned by a live session at emit time
    # (annotated by the networked server's watch pump; replicas use it
    # to keep leased keys out of their durable snapshots).
    lease: bool = False


class Watcher:
    """Prefix watcher with an event queue (reference: kvstore.Watcher)."""

    def __init__(self, name: str, prefix: str) -> None:
        # Unbounded queue: the snapshot replay in list_and_watch runs under
        # the backend mutex before any consumer exists, so a bounded queue
        # would deadlock the whole backend on large prefixes.
        self.name = name
        self.prefix = prefix
        self.events: "queue.Queue[KeyValueEvent]" = queue.Queue(maxsize=0)
        self._stopped = False
        # Opt-in: receive a RESYNC marker event when the transport
        # reconnects, BEFORE the fresh snapshot replay — consumers that
        # reconcile against replays (the kvstore follower) need the
        # boundary; everyone else stays oblivious.
        self.mark_resync = False

    def stop(self) -> None:
        self._stopped = True

    @property
    def stopped(self) -> bool:
        return self._stopped

    def next_event(self, timeout: float | None = None) -> Optional[KeyValueEvent]:
        try:
            return self.events.get(timeout=timeout)
        except queue.Empty:
            return None

    def __iter__(self) -> Iterator[KeyValueEvent]:
        while not self._stopped:
            ev = self.next_event(timeout=0.2)
            if ev is not None:
                yield ev


CAP_CREATE_IF_EXISTS = 1


class Backend(abc.ABC):
    """reference: backend.go:86 BackendOperations."""

    @abc.abstractmethod
    def status(self) -> str: ...

    @abc.abstractmethod
    def lock_path(self, path: str, timeout: float | None = None): ...

    @abc.abstractmethod
    def get(self, key: str) -> Optional[bytes]: ...

    @abc.abstractmethod
    def get_prefix(self, prefix: str) -> Optional[bytes]: ...

    @abc.abstractmethod
    def set(self, key: str, value: bytes, lease: bool = False) -> None: ...

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    @abc.abstractmethod
    def delete_prefix(self, prefix: str) -> None: ...

    @abc.abstractmethod
    def create_only(self, key: str, value: bytes, lease: bool = False) -> bool: ...

    @abc.abstractmethod
    def create_if_exists(self, cond_key: str, key: str, value: bytes,
                         lease: bool = False) -> bool: ...

    @abc.abstractmethod
    def list_prefix(self, prefix: str) -> dict[str, bytes]: ...

    @abc.abstractmethod
    def list_and_watch(self, name: str, prefix: str) -> Watcher: ...

    @abc.abstractmethod
    def close(self) -> None: ...

    def get_capabilities(self) -> int:
        return CAP_CREATE_IF_EXISTS

    def encode(self, data: bytes) -> str:
        # URL-safe: standard base64 contains '/', which would let one
        # encoded key alias another's '/'-delimited kvstore subtree.
        import base64

        return base64.urlsafe_b64encode(data).decode()

    def decode(self, s: str) -> bytes:
        import base64

        return base64.urlsafe_b64decode(s)
