"""TCP fault-injection proxy for the cluster-state plane.

Sits between any kvstore client (or replicating follower) and a
server, forwarding the length-prefixed frame stream byte-for-byte
while injecting the failure modes the fenced-failover machinery must
survive (reference role: the toxiproxy-style harnesses cilium's etcd
upgrade/partition CI uses; here in-process so tests and bench.py can
script partitions deterministically):

  - partition(direction): blackhole bytes in one or both directions —
    live connections stay open and silent (a true partition: no FIN,
    no RST), and while fully partitioned NEW connections are accepted
    and immediately dropped, so a dialing client sees the same
    dead-network behavior the established ones do.
  - set_delay(seconds): hold every chunk before forwarding (one-way
    latency).
  - set_drop_rate(p): drop a random fraction of forwarded chunks —
    mid-stream loss that corrupts frame alignment, exercising the
    malformed-frame counters and session teardown.
  - set_trickle(bytes_per_sec): forward in 64-byte slices at a
    bounded rate — the slow-network mode that stretches snapshot
    replays across many scheduler quanta.
  - reset_all(): RST every live connection (SO_LINGER 0) — the blip
    that triggers client reconnects without a partition.

All switches are live (no restart); heal() clears partition state.
Counters expose forwarded/dropped volume for bench assertions.
"""

from __future__ import annotations

import logging
import random
import socket
import struct
import threading
import time

from ..utils.sockutil import shutdown_close

log = logging.getLogger(__name__)


class ChaosProxy:
    def __init__(self, target: str, host: str = "127.0.0.1",
                 port: int = 0, seed: int = 0xC1A05) -> None:
        h, _, p = target.rpartition(":")
        self._target = (h, int(p))
        self._rng = random.Random(seed)
        self._mutex = threading.Lock()
        self._partitioned: set[str] = set()  # subset of {"c2s", "s2c"}
        self._delay = 0.0
        self._drop_rate = 0.0
        self._trickle_bps = 0  # 0 = unlimited
        self._conns: list[tuple[socket.socket, socket.socket]] = []
        self._stopped = False
        self.counters = {
            "connections": 0, "refused": 0,
            "bytes_c2s": 0, "bytes_s2c": 0, "chunks_dropped": 0,
        }
        self._listener: socket.socket | None = None
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((host, port))
        listener.listen(64)
        self._host, self._port = listener.getsockname()[:2]
        self.address = f"{self._host}:{self._port}"
        self._start_listener(listener)

    def _start_listener(self, listener: socket.socket) -> None:
        self._listener = listener
        threading.Thread(target=self._accept_loop, args=(listener,),
                         daemon=True, name="chaos-accept").start()

    def _close_listener(self) -> None:
        with self._mutex:
            listener, self._listener = self._listener, None
        if listener is not None:
            # shutdown first: it wakes the accept thread parked in
            # accept(), without which close() defers the fd teardown
            # and the port stays bound — heal()'s rebind would fail.
            shutdown_close(listener)

    def _ensure_listener(self) -> None:
        with self._mutex:
            if self._listener is not None or self._stopped:
                return
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self._host, self._port))
        listener.listen(64)
        self._start_listener(listener)

    # -- fault switches ----------------------------------------------------

    def partition(self, direction: str = "both",
                  reset_existing: bool = False) -> None:
        """Blackhole one or both directions.  ``reset_existing`` RSTs
        live connections first — partition-after-blip, the shape that
        forces clients into their failover walk immediately instead
        of timing out on silent sockets."""
        dirs = {"c2s", "s2c"} if direction == "both" else {direction}
        if not dirs <= {"c2s", "s2c"}:
            raise ValueError(f"bad partition direction {direction!r}")
        with self._mutex:
            self._partitioned |= dirs
            full = self._partitioned == {"c2s", "s2c"}
        if full:
            # A full partition drops SYNs too: close the listener so a
            # dialing client fails fast and walks its failover list —
            # the dead-network shape, not a half-open accept.
            self._close_listener()
        if reset_existing:
            self.reset_all()

    def heal(self) -> None:
        with self._mutex:
            self._partitioned.clear()
        self._ensure_listener()

    @property
    def partitioned(self) -> bool:
        return bool(self._partitioned)

    def set_delay(self, seconds: float) -> None:
        self._delay = max(0.0, seconds)

    def set_drop_rate(self, p: float) -> None:
        self._drop_rate = min(1.0, max(0.0, p))

    def set_trickle(self, bytes_per_sec: int) -> None:
        self._trickle_bps = max(0, int(bytes_per_sec))

    def reset_all(self) -> None:
        """RST every live proxied connection (both legs)."""
        with self._mutex:
            conns = list(self._conns)
            self._conns.clear()
        for a, b in conns:
            self._reset_conn(a, b)

    @staticmethod
    def _reset_conn(*socks: socket.socket) -> None:
        for s in socks:
            try:
                s.setsockopt(
                    socket.SOL_SOCKET, socket.SO_LINGER,
                    struct.pack("ii", 1, 0),
                )
            except OSError:
                pass
            # shutdown BEFORE close: a pump thread blocked in recv
            # on this socket holds the kernel object alive, and a
            # bare close() would defer the teardown (and the
            # RST/FIN to the peers) until that recv returns —
            # which it never would.
            shutdown_close(s)

    # -- plumbing ----------------------------------------------------------

    def _accept_loop(self, listener: socket.socket) -> None:
        # One thread per listener incarnation: partition/heal swap the
        # listener, and each thread dies with its own socket.
        while not self._stopped:
            try:
                client, _ = listener.accept()
            except OSError:
                return
            if "c2s" in self._partitioned and "s2c" in self._partitioned:
                # Fully partitioned: the network beyond this hop does
                # not exist — drop the fresh connection on the floor.
                self.counters["refused"] += 1
                # Same linger-0 + shutdown-then-close teardown as
                # reset_all: RST semantics, and no deferred fd.
                self._reset_conn(client)
                continue
            try:
                server = socket.create_connection(self._target, timeout=5.0)
            except OSError as e:
                log.debug("chaos: target %s unreachable: %s",
                          self._target, e)
                shutdown_close(client)
                continue
            for s in (client, server):
                try:
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                except OSError:
                    pass
            self.counters["connections"] += 1
            with self._mutex:
                self._conns.append((client, server))
                # Re-check under the registration mutex: a partition()
                # that raced this accept (flags set + reset_all drained
                # the list before this conn was registered) must not
                # leave a silently-blackholed survivor behind.
                full = self._partitioned == {"c2s", "s2c"}
            if full:
                with self._mutex:
                    if (client, server) in self._conns:
                        self._conns.remove((client, server))
                self._reset_conn(client, server)
                continue
            threading.Thread(
                target=self._pump, args=(client, server, "c2s"),
                daemon=True, name="chaos-c2s",
            ).start()
            threading.Thread(
                target=self._pump, args=(server, client, "s2c"),
                daemon=True, name="chaos-s2c",
            ).start()

    def _pump(self, src: socket.socket, dst: socket.socket,
              direction: str) -> None:
        try:
            while not self._stopped:
                chunk = src.recv(4096)
                if not chunk:
                    break
                if direction in self._partitioned:
                    # Blackhole: the bytes left the sender (it got its
                    # TCP ACK from us) and never arrive — exactly what
                    # an acknowledged-then-lost write looks like.
                    self.counters["chunks_dropped"] += 1
                    continue
                if self._drop_rate and self._rng.random() < self._drop_rate:
                    self.counters["chunks_dropped"] += 1
                    continue
                if self._delay:
                    time.sleep(self._delay)
                if self._trickle_bps:
                    for i in range(0, len(chunk), 64):
                        dst.sendall(chunk[i:i + 64])
                        time.sleep(64.0 / self._trickle_bps)
                else:
                    dst.sendall(chunk)
                self.counters["bytes_" + direction] += len(chunk)
        except OSError:
            pass
        finally:
            # shutdown BEFORE close, both legs: when this pump exits
            # (its src saw EOF/error) the SIBLING pump is still parked
            # in recv on the other socket — a bare close from this
            # thread defers that fd's teardown and the sibling (plus
            # both kernel objects) leaks until process exit if the
            # remaining peer stays silent.  shutdown wakes it now.
            for s in (src, dst):
                shutdown_close(s)

    def close(self) -> None:
        self._stopped = True
        self._close_listener()
        self.reset_all()
