"""Networked kvstore: TCP server + client Backend.

The distributed-state backbone crossing a real process/machine boundary
— the role etcd plays for the reference (reference: pkg/kvstore/etcd.go:
143 etcd module: leases, CAS transactions, prefix watch; keepalive.go
session liveness).  One KvstoreServer owns the authoritative store (a
LocalBackend); any number of NetBackend clients connect over TCP and
speak a length-prefixed JSON protocol:

  - CRUD + the CAS primitives (create_only / create_if_exists) execute
    atomically inside the server.
  - lease=True keys belong to the client's SESSION (one session per
    connection); session end — clean close or TCP death — deletes them,
    emitting DELETE events to every other client's watchers.  This is
    the etcd lease-expiry model: a dying node's identity references and
    ipcache entries vanish cluster-wide.
  - Locks are server-side with session ownership and auto-release on
    session end (reference: etcd.go LockPath via concurrency.Mutex).
  - list_and_watch replays the snapshot + LIST_DONE, then streams live
    events; the client assigns watch ids so no event can outrun its
    watcher registration.

Wire frame: 4-byte big-endian length + UTF-8 JSON.  Values travel hex.

Fencing epochs — the split-brain arbitration the snapshot-shipping
follower lacked (reference analog: etcd raft terms / consul sessions,
pkg/kvstore/etcd.go:143, consul.go:119).  State machine:

  - The PRIMARY owns a monotonically increasing epoch N, stored in the
    key space under ``EPOCH_KEY`` (so it replicates to followers and
    persists in durable snapshots like any other non-leased key).
  - A FOLLOWER serves reads and watches from the start but REJECTS
    writes with ``not_primary`` while its replication stream lives:
    a write it accepted could be silently pruned at the next
    LIST_DONE resync, so it refuses to accept what it cannot keep.
  - When the follower's replication stream dies and its reconnect
    budget is exhausted, it waits ``failover_grace`` and then PROMOTES:
    it CAS-claims epoch N+1 against the last epoch it replicated
    (durably — the claim lands in its snapshot before any write is
    accepted) and becomes writable.  A promoted follower never
    resubscribes to the old primary, so its accepted writes can never
    be pruned.
  - Every client request carries the highest epoch the client has
    observed; every response carries the server's epoch.  A server
    that sees a request epoch above its own has proof a newer primary
    exists and FENCES itself: all subsequent writes are rejected with
    ``epoch_fenced`` (EPOCH_FENCED) — a partitioned-but-alive old
    primary can never accept writes from any client that has touched
    the new primary.  The promoted follower also dials the old
    primary's address in the background and fences it explicitly the
    moment the partition heals.
  - Clients treat both rejection kinds as rejected-before-apply (safe
    to retry even for CAS creates): ``not_primary`` backs off and
    retries in place (the follower is about to promote or the primary
    is back); ``epoch_fenced`` redials FORWARD along the failover
    list toward the higher epoch, then retries.

Failover ordering contract: promotion strictly follows replication
death (the repl watcher is only stopped after its reconnect budget is
spent — or after the replication HEARTBEAT declares a silent
partition dead), and writability strictly follows the durable epoch
claim.  Exactly two loss windows remain, both documented and asserted
(tests/test_kvstore_partition.py), neither silent:

  1. Replication lag at the cut: replication is asynchronous, so a
     write acked by the primary in the instant before the partition
     may not have reached the follower — it survives on the fenced
     old primary, visible to degraded reads, never merged.
  2. The LWW window: writes acknowledged by the old primary between
     the follower's promotion and the first fencing contact (fencer
     thread on heal, or epoch gossip from any client) — same fate.

Two followers of one primary promoting concurrently would claim the
same epoch (ordered failover lists, one follower per chain, is the
supported topology).

Degraded mode (daemon/daemon.py): when the store is fenced or
unreachable, endpoint regeneration and verdict serving continue on
cached identities (kvstore_degraded metric + monitor notification);
degraded mode guarantees datapath continuity for already-resolved
state, and guarantees nothing for NEW identities or cross-node
propagation until the store returns.
"""

from __future__ import annotations

import json
import logging
import queue
import socket
import struct
import threading
import time
from typing import Optional

from .backend import (
    Backend,
    EpochFencedError,
    EventType,
    KeyValueEvent,
    KvstoreError,
    LockError,
    NotPrimaryError,
    Watcher,
)
from .local import LocalBackend
from ..utils import metrics
from ..utils.backoff import Exponential
from ..utils.sockutil import shutdown_close

log = logging.getLogger(__name__)

_LEN = struct.Struct(">I")
MAX_FRAME = 16 << 20

# The fencing epoch lives in the ordinary key space: it replicates to
# followers through the same watch stream as everything else and lands
# in durable snapshots with no special-casing.
EPOCH_KEY = "cilium/.cluster/epoch"
# The highest epoch this server was fenced BY, persisted the same way:
# a snapshot-backed old primary that restarts comes back still fenced
# instead of silently writable at its stale epoch.  (A memory-only
# server restarts empty — data and fencing alike — which is the
# documented fail-back hazard of running without a snapshot_path.)
FENCED_KEY = "cilium/.cluster/fenced"

# Ops that mutate the store (or grant exclusion tokens derived from
# it): these are what fencing rejects.  Reads and watches stay served
# by fenced/replicating servers — degraded reads keep the datapath up.
WRITE_OPS = frozenset({
    "set", "delete", "delete_prefix", "create_only", "create_if_exists",
    "reclaim", "lock",
})


class KvstoreCounters:
    """Failure/event counters for the swallowed-error paths (reference:
    kvstore errors surface through controller failure counts,
    pkg/kvstore/events.go).  Surfaced through server/client status and
    the daemon status section — a malformed frame or revoke failure
    increments here instead of vanishing.  Every increment is ALSO
    bridged into the global Prometheus registry
    (``cilium_tpu_kvstore_events_total{scope,event}``) so fencing and
    traffic counters appear in ``/metrics``, not only in status RPCs;
    ``scope`` names the owning end (server|client)."""

    def __init__(self, scope: str = "kvstore") -> None:
        self._scope = scope
        self._mutex = threading.Lock()
        self._counts: dict[str, int] = {}

    def inc(self, name: str) -> None:
        with self._mutex:
            self._counts[name] = self._counts.get(name, 0) + 1
        metrics.KvstoreEvents.inc(self._scope, name)

    def snapshot(self) -> dict[str, int]:
        with self._mutex:
            return dict(self._counts)


def _send_frame(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("kvstore peer closed")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> dict:
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    if n > MAX_FRAME:
        raise KvstoreError(f"kvstore frame too large ({n})")
    return json.loads(_recv_exact(sock, n))


# ---------------------------------------------------------------------------
# Server

class _Session:
    """Per-connection state: leased keys, held locks, active watches."""

    def __init__(self, server: "KvstoreServer", sock: socket.socket,
                 peer: str) -> None:
        self.server = server
        self.sock = sock
        self.peer = peer
        self.wlock = threading.Lock()
        self.leased: set[str] = set()
        self.locks: dict[str, object] = {}
        self.watches: dict[int, tuple[Watcher, threading.Thread]] = {}
        self._dead = False

    def send(self, obj: dict) -> None:
        with self.wlock:
            try:
                # Bounded by SO_SNDTIMEO (set at accept): a wedged-but-
                # alive subscriber that stops reading costs ONE bounded
                # wait here, never a forever-parked pump thread.
                # lint: disable=R2 -- wlock exists to serialize frame writes on this socket; the sendall is deadline-bounded and a timeout tears the session down below
                _send_frame(self.sock, obj)
            except OSError as e:
                # A dead peer's reader cleans up on its own; a TIMED
                # OUT send means a wedged-alive peer (or a partial
                # write that desynced the framing) — the reader would
                # never notice either, so tear the session down here:
                # shutdown wakes the serve() recv, whose cleanup stops
                # watches, releases locks, and revokes leases.
                self.server.counters.inc("server_send_failed")
                log.debug("kvstore session %s send failed: %s", self.peer, e)
                shutdown_close(self.sock)

    def serve(self) -> None:
        try:
            while True:
                req = _recv_frame(self.sock)
                op = req.get("op", "")
                if op == "lock":
                    # Lock acquisition blocks; its own thread keeps this
                    # session's other requests flowing.
                    threading.Thread(
                        target=self._handle_safe, args=(req,), daemon=True
                    ).start()
                else:
                    self._handle_safe(req)
        except (ConnectionError, OSError) as e:
            log.debug("kvstore session %s ended: %s", self.peer, e)
        except ValueError as e:
            # Malformed frame: a protocol bug, not a disconnect — count
            # and log it loudly before dropping the session.
            self.server.counters.inc("server_malformed_frame")
            log.warning("kvstore session %s malformed frame: %s",
                        self.peer, e)
        finally:
            self.cleanup()

    def _handle_safe(self, req: dict) -> None:
        rid = req.get("id")
        try:
            epoch = self.server._epoch_gate(req)
            result = self._handle(req)
            # The promotion-CAS is the only epoch mutation a request
            # can cause, and requests never trigger it — the gate-time
            # read is current for the response.
            self.send({"id": rid, "ok": True,
                       "epoch": epoch, **(result or {})})
        except EpochFencedError as e:
            self.send({"id": rid, "ok": False, "error": str(e),
                       "kind": "epoch_fenced",
                       "epoch": self.server.fenced_by or self.server.epoch})
        except NotPrimaryError as e:
            self.send({"id": rid, "ok": False, "error": str(e),
                       "kind": "not_primary", "epoch": self.server.epoch})
        except LockError as e:
            self.send({"id": rid, "ok": False, "error": str(e),
                       "kind": "lock", "epoch": self.server.epoch})
        except Exception as e:  # noqa: BLE001 — surface to the client
            self.send({"id": rid, "ok": False, "error": str(e),
                       "epoch": self.server.epoch})

    def _handle(self, req: dict) -> dict | None:
        b = self.server.backend
        op = req["op"]
        key = req.get("key", "")
        val = bytes.fromhex(req["value"]) if "value" in req else b""
        lease = bool(req.get("lease"))
        if op == "ping":
            return {}
        if op == "fence":
            # Explicit fencing (the promoted follower's heal-time
            # notification; also the CLI's arbitration probe).  The
            # epoch gate above already fences on the carried request
            # epoch; this op additionally accepts an explicit value so
            # a fencer need not fake client state.
            fenced = self.server.fence(int(req.get("fence_epoch", 0) or 0))
            return {"fenced": bool(self.server.fenced_by),
                    "fenced_now": fenced}
        if op == "status":
            return {
                "status": b.status(),
                "counters": self.server.counters.snapshot(),
                "role": self.server.role,
                "fenced": self.server.fenced,
                "fenced_by": self.server.fenced_by,
                "replicating": bool(
                    getattr(self.server, "replicating", False)
                ),
            }
        if op == "get":
            v = b.get(key)
            return {"found": v is not None,
                    "value": v.hex() if v is not None else ""}
        if op == "get_prefix":
            v = b.get_prefix(key)
            return {"found": v is not None,
                    "value": v.hex() if v is not None else ""}
        if op == "set":
            # lease-ness travels into the backend so a durable backend
            # excludes the key from its snapshot ATOMICALLY with the
            # write (persistence happens on the mutation's emit).  The
            # server mutex spans write + ownership record so 'reclaim'
            # cannot interleave between them and double-assign a lease.
            with self.server._mutex:
                b.set(key, val, lease=lease)
                self._claim_locked(key, lease)
            return {}
        if op == "delete":
            with self.server._mutex:
                b.delete(key)
                self.server._lease_owner.pop(key, None)
            self.leased.discard(key)
            return {}
        if op == "delete_prefix":
            b.delete_prefix(key)
            with self.server._mutex:
                for k in [
                    k for k in self.server._lease_owner
                    if k.startswith(key)
                ]:
                    self.server._lease_owner.pop(k)
            self.leased = {k for k in self.leased if not k.startswith(key)}
            return {}
        if op == "create_only":
            with self.server._mutex:
                ok = b.create_only(key, val, lease=lease)
                if ok:
                    self._claim_locked(key, lease)
            return {"created": ok}
        if op == "create_if_exists":
            with self.server._mutex:
                ok = b.create_if_exists(
                    req["cond_key"], key, val, lease=lease
                )
                if ok:
                    self._claim_locked(key, lease)
            return {"created": ok}
        if op == "reclaim":
            # Post-failover lease re-adoption: succeed only if the key
            # still holds OUR bit-identical value AND no live session
            # owns it (the replicated-ghost case).  The owner check and
            # re-claim happen under the server mutex, so another
            # session's create_only/_claim cannot be stolen from.
            # Self-owned keys re-take trivially: the client's replay
            # retries after a not_primary rejection, and a second pass
            # over an already-adopted key must stay a success, not get
            # misread as "claimed elsewhere".
            with self.server._mutex:
                owner = self.server._lease_owner.get(key)
                if owner is not None and owner is not self:
                    return {"taken": False}
                cur = b.get(key)
                if cur != val:
                    return {"taken": False}
                b.set(key, val, lease=True)
                self.server._lease_owner[key] = self
                self.leased.add(key)
            return {"taken": True}
        if op == "list_prefix":
            return {
                "items": {k: v.hex() for k, v in b.list_prefix(key).items()}
            }
        if op == "lock":
            path = req["path"]
            lock = b.lock_path(path, timeout=req.get("timeout"))
            self.locks[path] = lock
            return {}
        if op == "unlock":
            lock = self.locks.pop(req["path"], None)
            if lock is not None:
                lock.unlock()
            return {}
        if op == "watch":
            wid = int(req["wid"])
            w = b.list_and_watch(req.get("name", self.peer), key)
            t = threading.Thread(
                target=self._pump_watch, args=(wid, w), daemon=True,
                name=f"kvstore-watch-{wid}",
            )
            self.watches[wid] = (w, t)
            t.start()
            return {}
        if op == "watch_stop":
            rec = self.watches.pop(int(req["wid"]), None)
            if rec is not None:
                rec[0].stop()
            return {}
        raise KvstoreError(f"unknown kvstore op {op!r}")

    def _claim_locked(self, key: str, lease: bool) -> None:
        """Record lease ownership — CALLER HOLDS server._mutex (the
        claim must be atomic with the backend write or 'reclaim' can
        interleave and double-assign).  A later write by ANY session
        (leased or not) re-associates the key, so an older session's
        death no longer deletes it (etcd semantics: the latest PUT's
        lease — or absence of one — wins).  Lease-ness is mirrored into
        the backend's leased set so a durable backend excludes leased
        keys from its snapshot (they die with their sessions)."""
        if lease:
            self.server._lease_owner[key] = self
            self.leased.add(key)
        else:
            self.server._lease_owner.pop(key, None)

    def _pump_watch(self, wid: int, w: Watcher) -> None:
        while not w.stopped and not self._dead:
            ev = w.next_event(timeout=0.2)
            if ev is None:
                continue
            # ev.lease was stamped ATOMICALLY with the mutation by the
            # backend (a pump-time ownership lookup would race _claim).
            self.send({
                "event": {
                    "wid": wid,
                    "type": ev.typ.value,
                    "key": ev.key,
                    "value": ev.value.hex(),
                    "lease": ev.lease,
                }
            })

    def cleanup(self) -> None:
        """Session death: stop watches, release locks, revoke leases —
        the etcd lease-expiry analog; other clients see DELETE events."""
        if self._dead:
            return
        self._dead = True
        for w, _ in self.watches.values():
            w.stop()
        self.watches.clear()
        for lock in self.locks.values():
            try:
                lock.unlock()
            except Exception as e:  # noqa: BLE001
                self.server.counters.inc("server_unlock_failed")
                log.warning("session %s lock release failed: %s",
                            self.peer, e)
        self.locks.clear()
        for k in sorted(self.leased):
            # Only revoke keys THIS session still owns: a newer session
            # (e.g. the restarted daemon) may have re-registered the key.
            with self.server._mutex:
                owned = self.server._lease_owner.get(k) is self
                if owned:
                    self.server._lease_owner.pop(k)
            if not owned:
                continue
            try:
                self.server.backend.delete(k)
            except Exception as e:  # noqa: BLE001
                self.server.counters.inc("server_lease_revoke_failed")
                log.warning("lease revoke of %s failed: %s", k, e)
        self.leased.clear()
        shutdown_close(self.sock)
        self.server._drop_session(self)


class KvstoreServer:
    """TCP front for a LocalBackend — the cluster's shared store.

    ``snapshot_path`` makes the store durable: every mutation persists
    to disk (lease-owned keys excluded — they die with their sessions,
    exactly like etcd leases) and a restarted server restores from the
    snapshot, so identities and other non-leased cluster state survive
    a store restart (reference: etcd's WAL/snapshot durability that
    pkg/kvstore assumes)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 backend: Backend | None = None,
                 snapshot_path: str | None = None,
                 role: str = "primary",
                 send_timeout: float = 5.0) -> None:
        from .local import FileBackend

        # Slow-consumer containment: session sends are bounded (the
        # interprocedural lint's blocking-through-helper finding — a
        # subscriber that stops reading used to park _pump_watch in
        # sendall forever under the session wlock, with the session's
        # watches/locks/leases pinned alive).  SO_SNDTIMEO over
        # settimeout() so the serve loop's recv stays unbounded: idle
        # sessions are normal, wedged WRITES are not.
        self.send_timeout = send_timeout
        if backend is None:
            backend = (
                FileBackend(snapshot_path) if snapshot_path
                else LocalBackend()
            )
        self.backend = backend
        self.counters = KvstoreCounters("server")
        # Fencing state.  The role is fixed BEFORE the listener starts:
        # a session racing construction must never see a follower as
        # writable (the write it sneaked in would be pruned at the
        # first LIST_DONE — the exact loss fencing exists to prevent).
        self.role = role
        self.fenced_by = 0  # higher epoch this server was fenced by
        raw_fenced = self.backend.get(FENCED_KEY)
        if raw_fenced:
            # Restored from a snapshot taken after this server was
            # fenced: stay fenced — a restart must not reopen the
            # split-brain the fence closed.
            try:
                self.fenced_by = int(raw_fenced.decode())
            except ValueError:
                pass
        if role == "primary":
            # Durable restores keep their snapshot epoch; fresh stores
            # start at 1.  Followers do NOT seed: replication delivers
            # the primary's epoch with the first snapshot replay.
            self.backend.create_only(EPOCH_KEY, b"1")
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self.address = "%s:%d" % self._listener.getsockname()[:2]
        self._sessions: list[_Session] = []
        self._lease_owner: dict[str, _Session] = {}
        self._mutex = threading.Lock()
        self._stopped = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="kvstore-accept"
        )
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self.send_timeout:
                sec = int(self.send_timeout)
                usec = int((self.send_timeout - sec) * 1_000_000)
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                                struct.pack("ll", sec, usec))
            sess = _Session(self, sock, f"{addr[0]}:{addr[1]}")
            with self._mutex:
                self._sessions.append(sess)
            threading.Thread(
                target=sess.serve, daemon=True, name="kvstore-session"
            ).start()

    def _drop_session(self, sess: _Session) -> None:
        with self._mutex:
            if sess in self._sessions:
                self._sessions.remove(sess)

    # -- fencing -----------------------------------------------------------

    @property
    def epoch(self) -> int:
        """This server's fencing epoch (read from the key space so a
        follower's replicated epoch and a promoted epoch need no
        separate bookkeeping)."""
        raw = self.backend.get(EPOCH_KEY)
        if not raw:
            return 0
        try:
            return int(raw.decode())
        except ValueError:
            return 0

    @property
    def fenced(self) -> bool:
        """Fencing is RELATIVE to the current epoch: a replicating
        follower that briefly trailed a client's observed epoch stops
        being fenced once replication (or promotion) catches its epoch
        up; a stale primary can never raise its epoch and stays fenced
        forever."""
        return self.fenced_by > self.epoch

    @property
    def writable(self) -> bool:
        return self.role == "primary" and not self.fenced

    def fence(self, epoch: int) -> bool:
        """Record proof that a primacy at ``epoch`` exists.  Above our
        own epoch, this server is fenced: every subsequent write is
        rejected with EPOCH_FENCED until (if ever) our own epoch
        catches up.  Idempotent; returns True on the transition."""
        if epoch <= self.epoch:
            return False
        with self._mutex:
            if self.fenced_by >= epoch:
                return False
            first = self.fenced_by <= self.epoch
            self.fenced_by = epoch
        # Durability before visibility: a snapshot-backed server must
        # restart still-fenced (the fencer thread stops after one ack,
        # trusting this persists).
        try:
            self.backend.set(FENCED_KEY, str(epoch).encode())
        except Exception as e:  # noqa: BLE001 — fencing still holds
            self.counters.inc("server_fence_persist_failed")  # in memory
            log.warning("could not persist fence marker: %s", e)
        if first:
            self.counters.inc("server_fenced")
            log.warning(
                "kvstore %s (epoch %d) fenced by epoch %d: rejecting "
                "writes", getattr(self, "address", "?"), self.epoch, epoch,
            )
        return first

    def _epoch_gate(self, req: dict) -> int:
        """Per-request fencing check (runs before dispatch); returns
        the server epoch (read ONCE — the property walks the backend)
        for the response.  The client-carried epoch doubles as a
        gossip channel: any client that has touched a newer primary
        fences this server on contact, even while the promoted
        follower cannot reach it."""
        epoch = self.epoch
        observed = int(req.get("epoch", 0) or 0)
        if observed > epoch:
            self.fence(observed)
        if req.get("op", "") not in WRITE_OPS:
            return epoch
        if self.fenced_by > epoch:
            self.counters.inc("server_write_rejected_fenced")
            raise EpochFencedError(
                f"EPOCH_FENCED: server epoch {epoch} fenced by "
                f"epoch {self.fenced_by}", epoch=self.fenced_by,
            )
        if self.role != "primary":
            self.counters.inc("server_write_rejected_not_primary")
            raise NotPrimaryError(
                f"replicating follower (epoch {epoch}) does not "
                f"accept writes", epoch=epoch,
            )
        return epoch

    def close(self) -> None:
        self._stopped = True
        # shutdown() first: it wakes the accept loop so the listening
        # fd actually releases (close() alone leaves the thread parked
        # in accept() holding the socket, and the port stays bound).
        shutdown_close(self._listener)
        with self._mutex:
            sessions = list(self._sessions)
        for s in sessions:
            s.cleanup()


class KvstoreFollower(KvstoreServer):
    """Snapshot-shipping replica with fenced failover: a full
    KvstoreServer whose store is kept in sync from a primary over the
    primary's own watch protocol (list_and_watch("") replays the
    complete snapshot, then streams every mutation).  Clients list the
    follower after the primary in their failover list; when the
    primary dies they redial here and find the replicated state,
    re-claiming their leased keys on fresh sessions (reference role:
    the second interchangeable networked backend behind
    BackendOperations, pkg/kvstore/backend.go:86).

    While replicating, the follower serves reads and watches but
    REJECTS writes (not_primary): anything it accepted could be pruned
    at the next LIST_DONE resync — the silent-loss path fencing
    removes.  When the replication stream dies for good (reconnect
    budget ``repl_timeout`` exhausted) and ``failover_grace`` passes,
    the follower PROMOTES: it durably CAS-claims epoch N+1 in its own
    store, becomes the writable primary, never resubscribes to the old
    primary (so no accepted write can ever be pruned), and keeps
    dialing the old primary's address in the background to fence it
    the moment a partition heals.  See the module docstring for the
    full epoch state machine and the documented LWW window."""

    def __init__(self, primary_address: str, host: str = "127.0.0.1",
                 port: int = 0, backend: Backend | None = None,
                 snapshot_path: str | None = None,
                 repl_timeout: float = 5.0,
                 failover_grace: float = 0.25,
                 auto_promote: bool = True) -> None:
        # Dial the primary BEFORE binding our own listener: a follower
        # pointed at a dead/wrong primary must fail its constructor
        # without leaking a live listening socket + accept thread.
        self.primary_address = primary_address
        self.synced = threading.Event()
        self.promoted = threading.Event()
        self.replicating = True
        self.failover_grace = failover_grace
        self.auto_promote = auto_promote
        self._closing = False
        self._promote_lock = threading.Lock()
        self._repl_client = NetBackend(primary_address, timeout=repl_timeout)
        try:
            self._repl_watch = self._repl_client.list_and_watch(
                "replica", ""
            )
            # Reconnect boundaries must be visible: the prune-at-
            # LIST_DONE reconciliation needs to know where a fresh
            # snapshot replay starts.
            self._repl_watch.mark_resync = True
            super().__init__(host, port, backend=backend,
                             snapshot_path=snapshot_path, role="follower")
        except Exception:
            self._repl_client.close()
            raise
        self._repl_thread = threading.Thread(
            target=self._replicate, daemon=True, name="kvstore-replica"
        )
        self._repl_thread.start()
        # Heartbeat against the primary: a SILENT partition (TCP
        # session up, bytes blackholed) produces no stream error at
        # all — without an end-to-end probe the follower would wait
        # forever and never fail over (reference: etcd keepalives /
        # consul session TTLs detect exactly this).
        self._hb_interval = max(repl_timeout / 2.0, 0.25)
        threading.Thread(
            target=self._heartbeat, daemon=True, name="kvstore-replica-hb"
        ).start()

    def _heartbeat(self) -> None:
        misses = 0
        while not self._closing and self.replicating:
            time.sleep(self._hb_interval)
            if self._closing or not self.replicating:
                return
            if self._repl_client.ping():
                misses = 0
                continue
            misses += 1
            if misses < 2:  # one miss can be a blip mid-reconnect
                continue
            self.counters.inc("replica_heartbeat_dead")
            log.warning(
                "kvstore follower %s: replication heartbeat to %s lost; "
                "declaring the primary dead", self.address,
                self.primary_address,
            )
            # Stopping the watch ends the _replicate loop, whose exit
            # path runs the grace + promotion sequence.
            try:
                self._repl_watch.stop()
            except Exception:  # noqa: BLE001
                pass
            try:
                self._repl_client.close()
            except Exception:  # noqa: BLE001
                pass
            return

    def _replicate(self) -> None:
        # Every snapshot replay (initial sync AND post-reconnect
        # resubscription) ends in LIST_DONE; at that barrier the local
        # store is pruned to the replayed key set, so deletions that
        # happened while the stream was down — or stale keys restored
        # from this follower's own snapshot file — cannot survive as
        # resurrected state.  A key written directly to this follower
        # inside a primary-blip window is pruned too: while the primary
        # lives, it is authoritative (last-write-wins toward primary;
        # no arbitration — see class docstring).
        seen: set[str] = set()
        try:
            for ev in self._repl_watch:
                if ev.typ == EventType.RESYNC:
                    # Stream re-established: the marker was enqueued
                    # BEFORE the fresh replay, so stale pre-blip events
                    # are already behind us — restart the seen set.
                    seen = set()
                    continue
                try:
                    if ev.typ == EventType.LIST_DONE:
                        for k in list(self.backend.list_prefix("")):
                            if k not in seen:
                                self.backend.delete(k)
                        self.synced.set()
                    elif ev.typ == EventType.DELETE:
                        self.backend.delete(ev.key)
                        seen.discard(ev.key)
                    else:  # CREATE / MODIFY
                        # lease-ness travels with the event: leased keys
                        # stay out of a durable follower's snapshot file
                        # (they die with their sessions; the owner
                        # re-claims them after failover via 'reclaim').
                        self.backend.set(ev.key, ev.value, lease=ev.lease)
                        seen.add(ev.key)
                except Exception:  # noqa: BLE001 — one bad apply must
                    self.counters.inc("replica_apply_failed")  # not kill
                    log.exception("replica apply failed: %s", ev.key)
        except Exception:  # noqa: BLE001 — replica must not die noisily
            self.counters.inc("replica_stream_failed")
        finally:
            # Stream ended for good: primary gone (or follower
            # closing).  This store IS the surviving copy — claim the
            # next epoch and take over, or (auto_promote=False) keep
            # serving reads and wait for an operator.
            self.replicating = False
            if self.auto_promote and not self._closing:
                if self.failover_grace:
                    time.sleep(self.failover_grace)
                if not self._closing:
                    self.promote()

    def promote(self) -> bool:
        """Durable epoch claim + role flip, in that order: the epoch
        N+1 claim lands in this store's snapshot BEFORE any write is
        accepted, so a restart of the new primary can never come back
        believing it is still at the old epoch.  CAS against the last
        replicated epoch: a concurrent external epoch mutation fails
        the claim instead of being silently overwritten.

        Callable by an operator (auto_promote=False planned failover)
        as well as the auto path — so it severs a still-live
        replication stream FIRST: a promoted server must never apply
        another snapshot replay, or the LIST_DONE prune would eat the
        writes it acknowledged."""
        with self._promote_lock:
            return self._promote_locked()

    def _promote_locked(self) -> bool:
        if self.promoted.is_set():
            return True
        if self.replicating:
            # Operator-initiated promotion with the stream still up:
            # cut it.  The closed repl client can never resubscribe,
            # so no replay (and no prune) can follow the claim; the
            # _replicate thread's own exit path re-enters promote()
            # and no-ops on the promoted event.
            try:
                self._repl_watch.stop()
            except Exception:  # noqa: BLE001
                pass
            try:
                self._repl_client.close()
            except Exception:  # noqa: BLE001
                pass
            self.replicating = False
        if self.epoch <= 0 and self.fenced_by <= 0:
            # Initial sync never delivered the primary's epoch: we do
            # not know what epoch the primary owns, so any claim we
            # made could COLLIDE with it (claiming 1 against a seed-1
            # primary makes fencing permanently inert — both sides
            # writable at the same epoch, the exact split-brain this
            # machinery prevents).  An unsynced follower has nothing
            # worth serving as primary anyway: stay read-only.
            self.counters.inc("follower_promote_refused_unsynced")
            log.warning(
                "kvstore follower %s refusing promotion: initial sync "
                "never completed (unknown primary epoch)", self.address,
            )
            return False
        cur_raw = self.backend.get(EPOCH_KEY)
        # Claim above everything we have seen: the replicated epoch
        # AND any higher epoch we were fenced by.
        new = max(self.epoch, self.fenced_by) + 1
        if not self.backend.compare_and_swap(
            EPOCH_KEY, cur_raw, str(new).encode()
        ):
            self.counters.inc("follower_promote_cas_failed")
            log.warning("kvstore follower promotion CAS failed")
            return False
        self.role = "primary"
        self.promoted.set()
        self.counters.inc("follower_promoted")
        log.warning(
            "kvstore follower %s promoted to primary at epoch %d "
            "(old primary %s will be fenced)",
            self.address, new, self.primary_address,
        )
        threading.Thread(
            target=self._fence_old_primary, args=(new,), daemon=True,
            name="kvstore-fencer",
        ).start()
        return True

    def _fence_old_primary(self, epoch: int) -> None:
        """Keep dialing the old primary until it acknowledges the
        fence: during a partition the dial fails and backs off; the
        moment the partition heals, the old primary learns a newer
        epoch exists and rejects writes from then on.  (Clients that
        touched the new primary fence it on contact too — this thread
        just closes the no-client-crosses-over gap.)"""
        boff = Exponential(min_duration=0.2, max_duration=2.0,
                           name="kvstore-fence")
        while not self._closing:
            try:
                c = NetBackend(self.primary_address, timeout=2.0)
                try:
                    r = c._request({"op": "fence", "fence_epoch": epoch})
                    if r.get("fenced"):
                        self.counters.inc("old_primary_fenced")
                        log.info("old primary %s fenced at epoch %d",
                                 self.primary_address, epoch)
                        return
                finally:
                    c.close()
            except (KvstoreError, OSError):
                pass
            boff.wait()

    def close(self) -> None:
        self._closing = True
        try:
            self._repl_watch.stop()
        except Exception:  # noqa: BLE001
            pass
        try:
            self._repl_client.close()
        except Exception:  # noqa: BLE001
            pass
        super().close()


# ---------------------------------------------------------------------------
# Client

class _NetLock:
    def __init__(self, backend: "NetBackend", path: str) -> None:
        self._backend = backend
        self._path = path
        self._held = True
        self.lost = False  # session died: the server released this lock

    def unlock(self) -> None:
        if self.lost:
            # Surface the mutual-exclusion violation instead of
            # pretending the critical section was protected
            # (reference: etcd session loss fails the lock holder).
            self._held = False
            raise LockError(f"lock {self._path} lost on session reconnect")
        if self._held:
            self._held = False
            with self._backend._mutex:
                try:
                    self._backend._locks.remove(self)
                except ValueError:
                    pass
            self._backend._request(
                {"op": "unlock", "path": self._path}, retryable=False
            )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.unlock()


class NetBackend(Backend):
    """Client Backend speaking to a KvstoreServer over TCP.

    One socket per backend; a reader thread routes responses to waiting
    callers and watch events to their Watcher queues (so watches stay
    live while requests block).

    ``address`` may be a comma-separated failover list
    ("host1:port1,host2:port2"): the client connects to the first
    reachable server and, on connection loss, walks the list in order
    during reconnect — a primary + KvstoreFollower pair gives the
    cluster store a survivable failure mode (reference: the etcd
    client's endpoint list, pkg/kvstore/etcd.go config)."""

    def __init__(self, address: str, timeout: float = 10.0) -> None:
        self.addresses = [a.strip() for a in address.split(",") if a.strip()]
        if not self.addresses:
            raise KvstoreError("no kvstore address given")
        self.address = self.addresses[0]
        self.timeout = timeout
        self.counters = KvstoreCounters("client")
        # Highest fencing epoch observed on any response: carried on
        # every request (the gossip that fences stale primaries) and
        # surfaced through daemon status / `cilium kvstore status`.
        self.epoch = 0
        self.sock = self._dial_any(first=True)
        self.sock.settimeout(None)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._wlock = threading.Lock()
        self._mutex = threading.Lock()
        self._seq = 0
        self._pending: dict[int, queue.Queue] = {}
        self._watchers: dict[int, Watcher] = {}
        self._closed = False
        # Reconnect state (reference: pkg/kvstore reconnect with
        # pkg/backoff + lease keepalive): session-owned leased keys are
        # replayed on a fresh session, active watches re-subscribed.
        self._leased: dict[str, bytes] = {}
        self._watch_specs: dict[int, tuple[str, str]] = {}
        self._reconnect_lock = threading.Lock()
        self._generation = 0
        # Session-rebuild gate: cleared while a reconnect has swapped
        # the socket but not yet finished replaying leased keys and
        # watches; _request waits on it so no caller can observe a
        # half-rebuilt session as healthy (see _request).
        self._ready = threading.Event()
        self._ready.set()
        self._conn_dead = False  # reader saw EOF; requests must redial
        self._locks: list[_NetLock] = []  # held locks (loss marking)
        self.reconnects = 0
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True, name="kvstore-client-read"
        )
        self._reader.start()

    # -- plumbing ----------------------------------------------------------

    def _dial_any(self, first: bool = False) -> socket.socket:
        """Connect to the first reachable address, starting from the
        CURRENT one: after a failover, a blip must not silently fail
        back to a restarted (possibly empty) primary while other
        clients remain on the follower — sticking to the current
        server keeps the fleet convergent (fail-back is an operator
        action: restart clients with the primary first).  Records the
        connected address in self.address."""
        ordered = [self.address] + [
            a for a in self.addresses if a != self.address
        ]
        last_err: Exception | None = None
        for addr in ordered:
            host, _, port = addr.rpartition(":")
            try:
                sock = socket.create_connection(
                    (host, int(port)), timeout=10.0 if first else 2.0
                )
            except OSError as e:
                last_err = e
                continue
            if addr != self.address:
                self.counters.inc("client_failover")
                log.warning("kvstore failover: %s -> %s", self.address, addr)
            self.address = addr
            return sock
        raise KvstoreError(f"no kvstore server reachable: {last_err}")

    def _read_loop(self) -> None:
        # Capture this thread's session: a stale reader (superseded by a
        # reconnect) must neither recv from the NEW socket nor mark the
        # new session dead.
        with self._mutex:
            gen = self._generation
            sock = self.sock
        try:
            while True:
                msg = _recv_frame(sock)
                if "event" in msg:
                    ev = msg["event"]
                    w = self._watchers.get(int(ev["wid"]))
                    if w is not None and not w.stopped:
                        w.events.put(KeyValueEvent(
                            EventType(ev["type"]), ev["key"],
                            bytes.fromhex(ev["value"]),
                            lease=bool(ev.get("lease")),
                        ))
                    continue
                with self._mutex:
                    q = self._pending.pop(msg.get("id"), None)
                if q is not None:
                    q.put(msg)
        except (ConnectionError, OSError) as e:
            self.counters.inc("client_conn_lost")
            log.debug("kvstore client connection lost: %s", e)
        except ValueError as e:
            self.counters.inc("client_malformed_frame")
            log.warning("kvstore client malformed frame: %s", e)
        finally:
            with self._mutex:
                stale = self._generation != gen
            if not stale:
                self._conn_dead = True
                self._fail_pending()
                # Watch-only clients make no requests, so nothing would
                # ever trigger the reconnect path for them: recover (or
                # signal loss) in the background.
                if not self._closed:
                    threading.Thread(
                        target=self._background_reconnect, args=(gen,),
                        name="kvstore-reconnect", daemon=True,
                    ).start()

    def _background_reconnect(self, gen: int) -> None:
        if not self._reconnect(gen) and not self._closed:
            # Could not rebuild the session within the backoff budget:
            # stop the watchers so consumers SEE the loss instead of
            # waiting forever on a silent stream.
            with self._mutex:
                watchers = list(self._watchers.values())
                self._watchers.clear()
                self._watch_specs.clear()
            for w in watchers:
                w.stop()

    def _fail_pending(self) -> None:
        with self._mutex:
            pending = list(self._pending.values())
            self._pending.clear()
            # Watchers are only torn down on a clean close; an abnormal
            # connection loss keeps them registered so _reconnect can
            # re-subscribe them (they see a fresh snapshot replay).
            watchers = list(self._watchers.values()) if self._closed else []
            if self._closed:
                self._watchers.clear()
        for q in pending:
            q.put({"ok": False, "error": "kvstore connection lost"})
        for w in watchers:
            w.stop()

    def _reconnect(self, observed_gen: int) -> bool:
        """Dial a fresh session and rebuild session state: replay
        leased keys (the keepalive re-registration analog) and
        re-subscribe active watches.  Backoff-bounded; only one caller
        reconnects per generation."""
        with self._reconnect_lock:
            if self._closed:
                return False
            if self._generation != observed_gen:
                return True  # someone else already reconnected
            boff = Exponential(min_duration=0.05, max_duration=1.0,
                               name="kvstore-reconnect")
            deadline = time.monotonic() + self.timeout
            while True:
                try:
                    # Walks the failover list: a dead primary falls
                    # through to the follower.
                    # lint: disable=R2 -- one reconnect per generation holds _reconnect_lock across the dial by design; contenders need this attempt's outcome and each dial leg is settimeout-bounded
                    sock = self._dial_any()
                    break
                except KvstoreError:
                    delay = boff.duration()
                    if time.monotonic() + delay > deadline:
                        return False
                    # lint: disable=R2 -- one reconnect per generation serializes the whole walk by design; contenders need this attempt's outcome and would only dial in parallel
                    time.sleep(delay)
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Close the ready gate for the duration of the rebuild: the
            # fresh socket becomes visible to _request_once NOW, but the
            # session is not healthy until the replay lands (see the
            # _request docstring note) — reopened in the finally below
            # whatever the outcome.
            self._ready.clear()
            # shutdown-then-close: the old generation's reader may be
            # parked in recv on this socket (a writer detected the
            # death first) — wake it so it exits instead of holding
            # the dead fd to process exit.
            shutdown_close(self.sock)
            with self._mutex:
                self.sock = sock
                self._generation += 1
                self._conn_dead = False
                self.reconnects += 1
                # Server-side session death released every lock this
                # client held: mark them lost so holders find out.
                locks = list(self._locks)
                self._locks.clear()
            for lk in locks:
                lk.lost = True
            reader = threading.Thread(
                target=self._read_loop, name="kvstore-net-reader", daemon=True
            )
            self._reader = reader
            reader.start()
            # Replay session-owned state on the fresh session.
            try:
                # lint: disable=R2 -- replay must finish before any contender sees the fresh generation; its sleeps/sends are backoff- and timeout-bounded
                self._replay_session()
            except KvstoreError as e:
                if isinstance(e, (EpochFencedError, NotPrimaryError)):
                    # Rebuilt onto a stale server (fenced) or a
                    # follower that is not promoting (replicating from
                    # a live primary we blipped off): rotate the
                    # address forward so the NEXT attempt dials toward
                    # the writable server instead of re-poisoning here.
                    self._rotate_address()
                # Half-rebuilt sessions are poison: tear the connection
                # down again so the next attempt replays from scratch.
                self._conn_dead = True
                try:
                    sock.close()
                except OSError:
                    pass
                return False
            finally:
                # Reopen the ready gate WHATEVER the outcome: success
                # lets waiters proceed on the healthy session; failure
                # lets them observe the dead one and drive their own
                # reconnect instead of parking forever.
                self._ready.set()
            return True

    def _replay_session(self) -> None:
        """Rebuild session state on a fresh connection: replay leased
        keys (the keepalive re-registration analog), then re-subscribe
        watches.  Each step is IDEMPOTENT (create_only falls through
        to the self-tolerant server-side reclaim; watch registration
        happens once per wid), so a not_primary rejection from a
        follower that has not promoted yet backs off and resumes where
        it left — the normal post-failover path while the follower
        claims its epoch."""
        with self._mutex:
            leased = dict(self._leased)
            specs = dict(self._watch_specs)
        # RESYNC markers land BEFORE the re-subscriptions, so
        # everything behind the marker in an opted-in watcher's
        # queue is pre-blip and everything after it is the
        # fresh snapshot replay — the follower's prune depends
        # on this ordering.
        for wid in specs:
            w = self._watchers.get(wid)
            if w is not None and w.mark_resync and not w.stopped:
                w.events.put(KeyValueEvent(EventType.RESYNC))
        boff = Exponential(min_duration=0.05, max_duration=0.5,
                           name="kvstore-replay")
        deadline = time.monotonic() + self.timeout
        pending_leases = dict(leased)
        pending_watches = dict(specs)
        while pending_leases or pending_watches:
            try:
                while pending_leases:
                    key, value = next(iter(pending_leases.items()))
                    # create_only: the old session's lease revocation
                    # may have let another client legitimately claim
                    # the key — never clobber it, drop our stale claim
                    # instead.
                    r = self._request_once(
                        {"op": "create_only", "key": key,
                         "value": value.hex(), "lease": True}
                    )
                    if not r["created"]:
                        # On a FOLLOWER after failover the key exists
                        # as our own replicated ghost (no owning
                        # session).  The server-side reclaim atomically
                        # re-takes lease ownership iff the value is
                        # bit-identical AND no other live session owns
                        # the key; anything else means another client
                        # genuinely claimed it — drop our stale claim.
                        rr = self._request_once(
                            {"op": "reclaim", "key": key,
                             "value": value.hex()}
                        )
                        if not rr.get("taken"):
                            log.warning(
                                "leased key %s re-claimed elsewhere; "
                                "dropping local claim", key,
                            )
                            with self._mutex:
                                self._leased.pop(key, None)
                    pending_leases.pop(key)
                while pending_watches:
                    wid, (name, prefix) = next(
                        iter(pending_watches.items())
                    )
                    self._request_once(
                        {"op": "watch", "wid": wid, "key": prefix,
                         "name": name}
                    )
                    pending_watches.pop(wid)
            except NotPrimaryError:
                self.counters.inc("client_not_primary_retry")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise
                time.sleep(min(boff.duration(), max(remaining, 0.0)))

    def _request(self, req: dict, timeout: float | None = None,
                 retryable: bool = True) -> dict:
        """One round trip with typed retry classification:

        - TRANSPORT loss: reconnect (walking the failover list) and
          retry, backing off until self.timeout — idempotent ops only.
          Non-idempotent ops (CAS creates, locks) are NEVER blindly
          retried: the first attempt may have been applied with its
          response lost, and a retry would mis-report the outcome —
          callers re-run their own logic instead (reference: etcd
          client retry semantics for non-idempotent mutations).
        - NOT_PRIMARY: the follower rejected BEFORE applying, so every
          op — CAS creates included — retries safely; back off
          (jittered exponential, utils.backoff) until the follower
          promotes or the primary returns, bounded by self.timeout.
        - EPOCH_FENCED: the server is stale; redial FORWARD along the
          failover list toward the newer primary and retry (again
          rejected-before-apply, so always safe).  With nowhere
          forward to go, the typed error surfaces to the caller.
        """
        boff = Exponential(min_duration=0.05, max_duration=0.5,
                           name="kvstore-request")
        deadline = time.monotonic() + self.timeout
        np_retries = 0
        while True:
            # Half-rebuilt sessions are poison for CALLERS too: a
            # reconnect swaps the fresh socket in before replaying
            # leased keys/watches, and a request slipping through on it
            # (a ping served by a still-replicating follower) reports
            # the session healthy while the replay is still owed — a
            # caller that then close()s aborts the replay and strands
            # its leased keys as unowned ghosts on the follower,
            # unrevokable forever.  Wait out the rebuild (bounded by
            # this request's own deadline; the reconnect path sets the
            # gate in a finally, so a failed rebuild releases waiters
            # to observe the dead session and retry themselves).
            if not self._ready.wait(max(deadline - time.monotonic(), 0.0)):
                raise KvstoreError("kvstore session rebuild timed out")
            gen = self._generation
            try:
                return self._request_once(req, timeout)
            except NotPrimaryError:
                self.counters.inc("client_not_primary_retry")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise
                np_retries += 1
                if np_retries % 4 == 0 and len(self.addresses) > 1:
                    # We may have landed on a follower during a mere
                    # primary BLIP: the follower keeps replicating from
                    # the live primary and will never promote, so
                    # waiting here would wedge until the deadline.
                    # Probe around the ring — if the primary is back,
                    # the write lands there; if not, the dial falls
                    # through the failover list right back here.
                    self._redial_forward(gen)
                    continue
                time.sleep(min(boff.duration(), max(remaining, 0.0)))
            except EpochFencedError:
                self.counters.inc("client_fenced")
                if time.monotonic() >= deadline:
                    raise
                if not self._redial_forward(gen):
                    raise
            except KvstoreError as e:
                transport = (
                    "connection lost" in str(e) or "send failed" in str(e)
                )
                if self._closed or not transport:
                    raise
                if not retryable:
                    # Still rebuild the session for later calls.
                    self._reconnect(gen)
                    raise
                if time.monotonic() >= deadline:
                    raise
                if not self._reconnect(gen):
                    # _reconnect spent its own dial budget; one more
                    # pass through the loop only if time remains.
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise
                    time.sleep(min(boff.duration(), max(remaining, 0.0)))

    def _rotate_address(self) -> bool:
        """Advance self.address to the next entry of the failover
        list; False with nowhere to go."""
        with self._mutex:
            if len(self.addresses) <= 1:
                return False
            cur = self.address
            try:
                i = self.addresses.index(cur)
            except ValueError:
                i = -1
            nxt = self.addresses[(i + 1) % len(self.addresses)]
            if nxt == cur:
                return False
            self.address = nxt
        log.warning("kvstore %s not writable; redialing forward to %s",
                    cur, nxt)
        return True

    def _redial_forward(self, observed_gen: int) -> bool:
        """Rotate to the next address in the failover list and rebuild
        the session there — the reaction to EPOCH_FENCED: the newer
        primary is FORWARD in the list, and sticking to the fenced
        server would strand every write."""
        if not self._rotate_address():
            return False
        self.counters.inc("client_fence_redial")
        # Sever the old session; _reconnect (generation-guarded against
        # the reader's own background redial) rebuilds it against the
        # rotated address.
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        return self._reconnect(observed_gen)

    def _request_once(self, req: dict, timeout: float | None = None) -> dict:
        if self._closed:
            raise KvstoreError("kvstore client closed")
        if self._conn_dead:
            # Fail fast into the reconnect path instead of sending into
            # a dead socket and waiting out the timeout.
            raise KvstoreError("kvstore connection lost")
        req["epoch"] = self.epoch
        with self._mutex:
            self._seq += 1
            rid = self._seq
            q: queue.Queue = queue.Queue(maxsize=1)
            self._pending[rid] = q
        req["id"] = rid
        with self._wlock:
            try:
                # lint: disable=R2 -- _wlock exists to serialize frame writes on the shared socket; a dead peer raises immediately and a wedged one is bounded by the reader's liveness teardown
                _send_frame(self.sock, req)
            except OSError as e:
                with self._mutex:
                    self._pending.pop(rid, None)
                raise KvstoreError(f"kvstore send failed: {e}")
        try:
            resp = q.get(timeout=timeout if timeout is not None else self.timeout)
        except queue.Empty:
            with self._mutex:
                self._pending.pop(rid, None)
            raise KvstoreError(f"kvstore request timed out: {req['op']}")
        self._observe_epoch(resp)
        if not resp.get("ok"):
            kind = resp.get("kind")
            if kind == "lock":
                raise LockError(resp.get("error", "lock failed"))
            if kind == "epoch_fenced":
                raise EpochFencedError(
                    resp.get("error", "EPOCH_FENCED"),
                    epoch=int(resp.get("epoch", 0) or 0),
                )
            if kind == "not_primary":
                raise NotPrimaryError(
                    resp.get("error", "not primary"),
                    epoch=int(resp.get("epoch", 0) or 0),
                )
            raise KvstoreError(resp.get("error", "kvstore error"))
        return resp

    def _observe_epoch(self, resp: dict) -> None:
        try:
            e = int(resp.get("epoch", 0) or 0)
        except (TypeError, ValueError):
            return
        if e > self.epoch:
            self.epoch = e

    # -- Backend interface -------------------------------------------------

    def status(self) -> str:
        try:
            r = self._request({"op": "status"})
            role = r.get("role", "?")
            fenced = " FENCED" if r.get("fenced") else ""
            return (
                f"tcp {self.address}: connected ({r['status']}; "
                f"role={role} epoch={self.epoch}{fenced})"
            )
        except KvstoreError as e:
            return f"tcp {self.address}: failure - {e}"

    def server_info(self) -> dict:
        """Structured store status for `cilium kvstore status` and the
        daemon status section: role, fencing epoch, replication state,
        server+client counters."""
        r = self._request({"op": "status"})
        return {
            "address": self.address,
            "addresses": list(self.addresses),
            "role": r.get("role", "?"),
            "epoch": self.epoch,
            "fenced": bool(r.get("fenced")),
            "fenced_by": int(r.get("fenced_by", 0) or 0),
            "replicating": bool(r.get("replicating")),
            "backend": r.get("status", ""),
            "server_counters": r.get("counters", {}),
            "client_counters": self.counters.snapshot(),
            "reconnects": self.reconnects,
        }

    def lock_path(self, path: str, timeout: float | None = 10.0) -> _NetLock:
        t = timeout if timeout is not None else 60.0
        # Transport-retry IS safe for locks, uniquely among the
        # non-idempotent ops: a grant is bound to the SESSION, and a
        # transport loss kills the session — whatever the lost first
        # attempt acquired is released by server-side session cleanup,
        # so the retry (on a fresh session) can block briefly but
        # never double-acquire.  This is what lets the allocator ride
        # through a failover instead of surfacing every blip.
        self._request(
            {"op": "lock", "path": path, "timeout": t}, timeout=t + 5.0,
        )
        lock = _NetLock(self, path)
        with self._mutex:
            self._locks.append(lock)
        return lock

    def get(self, key: str) -> Optional[bytes]:
        r = self._request({"op": "get", "key": key})
        return bytes.fromhex(r["value"]) if r["found"] else None

    def get_prefix(self, prefix: str) -> Optional[bytes]:
        r = self._request({"op": "get_prefix", "key": prefix})
        return bytes.fromhex(r["value"]) if r["found"] else None

    def set(self, key: str, value: bytes, lease: bool = False) -> None:
        self._request(
            {"op": "set", "key": key, "value": value.hex(), "lease": lease}
        )
        with self._mutex:
            if lease:
                self._leased[key] = value
            else:
                self._leased.pop(key, None)

    def delete(self, key: str) -> None:
        self._request({"op": "delete", "key": key})
        with self._mutex:
            self._leased.pop(key, None)

    def delete_prefix(self, prefix: str) -> None:
        self._request({"op": "delete_prefix", "key": prefix})
        with self._mutex:
            for k in [k for k in self._leased if k.startswith(prefix)]:
                del self._leased[k]

    def create_only(self, key: str, value: bytes, lease: bool = False) -> bool:
        r = self._request({
            "op": "create_only", "key": key, "value": value.hex(),
            "lease": lease,
        }, retryable=False)
        if r["created"] and lease:
            with self._mutex:
                self._leased[key] = value
        return bool(r["created"])

    def create_if_exists(self, cond_key: str, key: str, value: bytes,
                         lease: bool = False) -> bool:
        r = self._request({
            "op": "create_if_exists", "cond_key": cond_key, "key": key,
            "value": value.hex(), "lease": lease,
        }, retryable=False)
        if r["created"] and lease:
            with self._mutex:
                self._leased[key] = value
        return bool(r["created"])

    def list_prefix(self, prefix: str) -> dict[str, bytes]:
        r = self._request({"op": "list_prefix", "key": prefix})
        return {k: bytes.fromhex(v) for k, v in r["items"].items()}

    def list_and_watch(self, name: str, prefix: str) -> Watcher:
        with self._mutex:
            self._seq += 1
            wid = self._seq
        w = _NetWatcher(self, wid, name, prefix)
        # Register BEFORE the request: the server's snapshot replay can
        # arrive before the watch response.
        self._watchers[wid] = w
        with self._mutex:
            self._watch_specs[wid] = (name, prefix)
        try:
            self._request(
                {"op": "watch", "wid": wid, "key": prefix, "name": name}
            )
        except KvstoreError:
            self._watchers.pop(wid, None)
            with self._mutex:
                self._watch_specs.pop(wid, None)
            raise
        return w

    def ping(self) -> bool:
        try:
            self._request({"op": "ping"})
            return True
        except KvstoreError:
            return False

    def _stop_watch(self, wid: int) -> None:
        self._watchers.pop(wid, None)
        with self._mutex:
            self._watch_specs.pop(wid, None)
        if not self._closed:
            try:
                self._request({"op": "watch_stop", "wid": wid})
            except KvstoreError:
                pass

    def close(self) -> None:
        """Clean session end: the server revokes this session's leases
        (reference: lease expiry on client shutdown)."""
        if self._closed:
            return
        self._closed = True
        # Release any request parked on the session-rebuild gate: the
        # client is terminal, so waiters must fail fast (_request_once
        # raises on _closed) instead of waiting out their deadline.
        self._ready.set()
        # shutdown() first: close() alone does not send FIN while the
        # reader thread is blocked in recv on the same fd, so the server
        # would never see the session die (and leases would leak).
        shutdown_close(self.sock)
        self._fail_pending()


class _NetWatcher(Watcher):
    def __init__(self, backend: NetBackend, wid: int, name: str,
                 prefix: str) -> None:
        super().__init__(name, prefix)
        self._backend = backend
        self._wid = wid

    def stop(self) -> None:
        if not self.stopped:
            super().stop()
            self._backend._stop_watch(self._wid)
