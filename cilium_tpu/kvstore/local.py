"""In-process and file-persisted kvstore backends.

reference: the etcd/consul modules (pkg/kvstore/{etcd,consul}.go) provide
these semantics against external stores; single-host deployments and tests
use these local equivalents behind the same Backend interface.  Leases are
emulated: lease-attached keys die with the session (close()), matching the
reference's lease-per-client keepalive model (pkg/kvstore/etcd.go leases,
keepalive.go).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from .backend import (
    Backend,
    EventType,
    KeyValueEvent,
    KvstoreError,
    LockError,
    Watcher,
)


class _PathLock:
    def __init__(self, backend: "LocalBackend", path: str) -> None:
        self._backend = backend
        self._path = path
        self._held = True

    def unlock(self) -> None:
        # Idempotent: a second unlock (e.g. explicit + context-manager
        # exit) must not release a lock since acquired by another thread.
        if self._held:
            self._held = False
            self._backend._unlock_path(self._path)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.unlock()


class LocalBackend(Backend):
    """Thread-safe in-memory backend with watch + lease emulation."""

    def __init__(self) -> None:
        self._data: dict[str, bytes] = {}
        self._leased: set[str] = set()
        self._locks: dict[str, threading.Lock] = {}
        self._mutex = threading.RLock()
        self._watchers: list[Watcher] = []
        self._closed = False

    # -- status ------------------------------------------------------------

    def status(self) -> str:
        return "local: connected" if not self._closed else "local: closed"

    # -- locks -------------------------------------------------------------

    def lock_path(self, path: str, timeout: float | None = 10.0) -> _PathLock:
        with self._mutex:
            lock = self._locks.setdefault(path, threading.Lock())
        if not lock.acquire(timeout=timeout if timeout is not None else -1):
            raise LockError(f"timeout locking {path}")
        return _PathLock(self, path)

    def _unlock_path(self, path: str) -> None:
        with self._mutex:
            lock = self._locks.get(path)
        if lock is not None and lock.locked():
            lock.release()

    # -- CRUD --------------------------------------------------------------

    def get(self, key: str) -> Optional[bytes]:
        with self._mutex:
            return self._data.get(key)

    def get_prefix(self, prefix: str) -> Optional[bytes]:
        with self._mutex:
            for k in sorted(self._data):
                if k.startswith(prefix):
                    return self._data[k]
        return None

    def set(self, key: str, value: bytes, lease: bool = False) -> None:
        # Mutation and event emission are atomic under the mutex so watcher
        # event order always matches mutation order.
        with self._mutex:
            existed = key in self._data
            self._data[key] = value
            if lease:
                self._leased.add(key)
            else:
                # A non-leased overwrite downgrades the key BEFORE the
                # emit persists (etcd: the latest PUT's lease — or
                # absence of one — wins).
                self._leased.discard(key)
            self._emit(
                KeyValueEvent(
                    EventType.MODIFY if existed else EventType.CREATE,
                    key, value, lease=lease,
                )
            )

    def delete(self, key: str) -> None:
        with self._mutex:
            existed = self._data.pop(key, None) is not None
            self._leased.discard(key)
            if existed:
                self._emit(KeyValueEvent(EventType.DELETE, key))

    def delete_prefix(self, prefix: str) -> None:
        with self._mutex:
            dead = [k for k in self._data if k.startswith(prefix)]
            for k in dead:
                del self._data[k]
                self._leased.discard(k)
                self._emit(KeyValueEvent(EventType.DELETE, k))

    def create_only(self, key: str, value: bytes, lease: bool = False) -> bool:
        """Atomic create; False if the key already exists
        (reference: backend.go CreateOnly)."""
        with self._mutex:
            if key in self._data:
                return False
            self._data[key] = value
            if lease:
                self._leased.add(key)
            self._emit(
                KeyValueEvent(EventType.CREATE, key, value, lease=lease)
            )
        return True

    def compare_and_swap(self, key: str, expected: bytes | None,
                         value: bytes, lease: bool = False) -> bool:
        """Atomic CAS: write value iff the key currently holds exactly
        ``expected`` (None = key absent).  The epoch-claim primitive of
        the fenced failover (net.py): a promoting follower claims epoch
        N+1 against the last epoch it replicated, so a concurrent
        mutation of the epoch key can never be silently overwritten.
        Emits like set(), so a durable backend persists the claim
        atomically with the mutation."""
        with self._mutex:
            if self._data.get(key) != expected:
                return False
            self.set(key, value, lease=lease)
        return True

    def create_if_exists(self, cond_key: str, key: str, value: bytes,
                         lease: bool = False) -> bool:
        with self._mutex:
            if cond_key not in self._data or key in self._data:
                return False
            self._data[key] = value
            if lease:
                self._leased.add(key)
            self._emit(
                KeyValueEvent(EventType.CREATE, key, value, lease=lease)
            )
        return True

    def list_prefix(self, prefix: str) -> dict[str, bytes]:
        with self._mutex:
            return {
                k: v for k, v in self._data.items() if k.startswith(prefix)
            }

    # -- watch -------------------------------------------------------------

    def list_and_watch(self, name: str, prefix: str) -> Watcher:
        """reference: backend.go:139 — list current keys as CREATE events,
        then a LIST_DONE marker, then live events."""
        w = Watcher(name, prefix)
        with self._mutex:
            # Snapshot replay and registration are atomic with mutations so
            # no live event can precede (and be overwritten by) the snapshot.
            for k, v in sorted(self._data.items()):
                if k.startswith(prefix):
                    w.events.put(KeyValueEvent(
                        EventType.CREATE, k, v,
                        lease=k in self._leased,
                    ))
            w.events.put(KeyValueEvent(EventType.LIST_DONE))
            self._watchers.append(w)
        return w

    def _emit(self, ev: KeyValueEvent) -> None:
        with self._mutex:
            watchers = [
                w for w in self._watchers
                if not w.stopped and ev.key.startswith(w.prefix)
            ]
            self._watchers = [w for w in self._watchers if not w.stopped]
        for w in watchers:
            try:
                w.events.put_nowait(ev)
            except Exception:  # noqa: BLE001 — full queue: drop, like a
                pass  # slow watcher losing events under backpressure

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Session end revokes leases (reference: lease expiry semantics)."""
        with self._mutex:
            leased = list(self._leased)
        for k in leased:
            self.delete(k)
        self._closed = True


class FileBackend(LocalBackend):
    """LocalBackend persisted to a JSON file — state survives restarts
    (the role etcd's disk plays for the reference's agent restarts)."""

    def __init__(self, path: str) -> None:
        super().__init__()
        self._path = path
        self._load()

    def _load(self) -> None:
        if os.path.exists(self._path):
            try:
                with open(self._path) as f:
                    raw = json.load(f)
                with self._mutex:
                    self._data = {
                        k: bytes.fromhex(v) for k, v in raw.items()
                    }
            except (ValueError, OSError) as e:
                raise KvstoreError(f"corrupt kvstore file {self._path}: {e}")

    def _persist(self) -> None:
        tmp = self._path + ".tmp"
        with self._mutex:
            raw = {k: v.hex() for k, v in self._data.items()
                   if k not in self._leased}
        os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(raw, f)
        os.replace(tmp, self._path)

    def _emit(self, ev) -> None:
        super()._emit(ev)
        self._persist()
