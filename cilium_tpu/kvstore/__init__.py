"""Key-value store client layer — the distributed state backbone.

reference: pkg/kvstore — identity allocation, node discovery, ipcache and
service propagation all converge through a kvstore (etcd/consul in the
reference) via BackendOperations (backend.go:86): Get/Set/CAS primitives,
path locks, leases, and prefix watchers feeding event channels.

Backends here: ``LocalBackend`` (in-process, threadsafe, full watch/lease
semantics — the default for single-host and tests), ``FileBackend``
(JSON-file persisted, surviving restarts), and ``NetBackend`` (TCP client
to a ``KvstoreServer`` — the networked store giving multiple daemons one
shared cluster state with session leases, CAS, and live watch; see
net.py).  The consumer layers (allocator, store, ipcache) only use
BackendOperations.
"""

from .backend import (
    Backend,
    CAP_CREATE_IF_EXISTS,
    EpochFencedError,
    EventType,
    KeyValueEvent,
    KvstoreError,
    LockError,
    NotPrimaryError,
    Watcher,
)
from .chaos import ChaosProxy
from .local import FileBackend, LocalBackend
from .net import EPOCH_KEY, KvstoreFollower, KvstoreServer, NetBackend

_default_client: Backend | None = None


def setup_client(backend: Backend) -> Backend:
    """Install the process-global client (reference: kvstore.Client())."""
    global _default_client
    _default_client = backend
    return backend


def client() -> Backend:
    global _default_client
    if _default_client is None:
        _default_client = LocalBackend()
    return _default_client


def close_client() -> None:
    global _default_client
    if _default_client is not None:
        _default_client.close()
        _default_client = None


__all__ = [
    "Backend",
    "CAP_CREATE_IF_EXISTS",
    "ChaosProxy",
    "EPOCH_KEY",
    "EpochFencedError",
    "EventType",
    "FileBackend",
    "KeyValueEvent",
    "KvstoreError",
    "KvstoreFollower",
    "KvstoreServer",
    "LocalBackend",
    "LockError",
    "NetBackend",
    "NotPrimaryError",
    "Watcher",
    "client",
    "close_client",
    "setup_client",
]
