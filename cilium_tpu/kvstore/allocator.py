"""Cluster-wide ID allocator over the kvstore.

reference: pkg/kvstore/allocator/allocator.go:136 — allocates small numeric
IDs for arbitrary keys (label sets) cluster-wide:

  <prefix>/id/<numericID>          -> key string        (master key)
  <prefix>/value/<key>/<nodename>  -> numericID         (per-node use ref)

Allocation first reuses an existing master key for the value (so all nodes
converge on one ID per key), otherwise claims a free ID with an atomic
create.  Node value keys are lease-attached: a dying node's references
disappear, and GC removes master keys with no remaining references.
A watcher keeps a local id->key cache in sync with remote allocations.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass
from typing import Callable, Optional

from .backend import (
    Backend,
    EpochFencedError,
    EventType,
    KvstoreError,
    Watcher,
)


class AllocatorError(KvstoreError):
    pass


class IdPool:
    """Pool of allocatable IDs (reference: pkg/kvstore/allocator/idpool.go)."""

    def __init__(self, start: int, end: int) -> None:
        self.start = start
        self.end = end
        self._free: set[int] = set(range(start, end + 1))
        self._mutex = threading.Lock()

    def lease_random(self) -> Optional[int]:
        with self._mutex:
            if not self._free:
                return None
            val = random.choice(tuple(self._free))
            self._free.discard(val)
            return val

    def remove(self, id_: int) -> None:
        with self._mutex:
            self._free.discard(id_)

    def insert(self, id_: int) -> None:
        with self._mutex:
            if self.start <= id_ <= self.end:
                self._free.add(id_)


@dataclass
class AllocatorEvent:
    typ: EventType
    id: int
    key: str


class Allocator:
    """reference: allocator.go:136 Allocator."""

    def __init__(
        self,
        backend: Backend,
        base_path: str,
        suffix: str,
        min_id: int = 256,
        max_id: int = 65535,
        events: Callable[[AllocatorEvent], None] | None = None,
    ) -> None:
        self.backend = backend
        self.base_path = base_path.rstrip("/")
        self.suffix = suffix  # this node's name
        self.id_pool = IdPool(min_id, max_id)
        self.events = events
        # local cache: key -> (id, refcount) (reference: localkeys.go)
        self._local: dict[str, list[int]] = {}
        # remote cache: id -> key (reference: allocator cache.go)
        self.cache: dict[int, str] = {}
        # value-ref deletes that failed against a fenced/unreachable
        # store: retried by run_gc so a degraded-mode release cannot
        # leak the identity cluster-wide for the agent's lifetime.
        self._pending_unref: set[str] = set()
        # local references taken WITHOUT a remote value-ref
        # (retain_cached in degraded mode): republished by allocate()
        # and run_gc once the store returns, so cluster-wide GC sees
        # this node's use before it can reap the master key.
        self._pending_ref: set[str] = set()
        self._mutex = threading.RLock()
        self._watcher: Watcher | None = None
        self._sync_from_store()

    # -- paths -------------------------------------------------------------

    def _id_path(self, id_: int) -> str:
        return f"{self.base_path}/id/{id_}"

    def _value_prefix(self, key: str) -> str:
        return f"{self.base_path}/value/{self.backend.encode(key.encode())}"

    def _value_path(self, key: str) -> str:
        return f"{self._value_prefix(key)}/{self.suffix}"

    # -- init --------------------------------------------------------------

    def _fire_event(self, ev: AllocatorEvent) -> None:
        """Direct event dispatch, used only while no watcher runs — once
        start_watch is active the watcher delivers every master-key change
        and a direct callback would double-fire."""
        if self.events and self._watcher is None:
            self.events(ev)

    def _sync_from_store(self) -> None:
        for k, v in self.backend.list_prefix(f"{self.base_path}/id/").items():
            try:
                id_ = int(k.rsplit("/", 1)[1])
            except ValueError:
                continue
            self.id_pool.remove(id_)
            self.cache[id_] = v.decode()

    # -- allocation --------------------------------------------------------

    def allocate(self, key: str) -> tuple[int, bool]:
        """Allocate or reuse the cluster-wide ID for key; returns
        (id, is_new) (reference: allocator.go:240 Allocate).

        Epoch-aware: an EPOCH_FENCED rejection means the server our
        caches were derived from is stale (a failover happened
        mid-allocation).  The client has already redialed toward the
        newer primary — re-resolve against IT (drop the remote cache,
        re-list the master keys) and re-run the allocation once, so
        two nodes can never silently converge on divergent IDs from
        different sides of a partition."""
        for attempt in (0, 1):
            try:
                return self._allocate(key)
            except EpochFencedError as e:
                if attempt:
                    raise AllocatorError(
                        f"allocation of {key!r} fenced twice: {e}"
                    ) from e
                self._resync_after_fence()
        raise AssertionError("unreachable")

    def _resync_after_fence(self) -> None:
        """Remote state re-resolution after a fenced write: the id->key
        cache came from the stale primary; rebuild it from the store
        the client failed over to.  Node-local refcounts survive (the
        lease replay re-registers our value refs on the new session);
        GC reconciles any master key the new primary never saw.

        The fresh mapping is built OUTSIDE the mutex (it does kvstore
        I/O) and swapped in atomically: watch threads iterate
        self.cache under the mutex, and a concurrent clear+repopulate
        would blow up their iteration mid-failover."""
        fresh: dict[int, str] = {}
        for k, v in self.backend.list_prefix(f"{self.base_path}/id/").items():
            try:
                id_ = int(k.rsplit("/", 1)[1])
            except ValueError:
                continue
            fresh[id_] = v.decode()
        with self._mutex:
            in_use = {entry[0] for entry in self._local.values()}
            stale = set(self.cache) - set(fresh) - in_use
            # Locally-referenced identities the new primary never saw
            # (replication lag) must keep resolving — already-serving
            # endpoints depend on lookup_by_id — so merge them back
            # where the fresh view has no competing claim.
            for key, entry in self._local.items():
                fresh.setdefault(entry[0], key)
            self.cache.clear()
            self.cache.update(fresh)
        for id_ in fresh:
            self.id_pool.remove(id_)
        for id_ in stale:
            # Gone from the surviving store and not locally referenced:
            # allocatable again.
            self.id_pool.insert(id_)

    def _allocate(self, key: str) -> tuple[int, bool]:
        with self._mutex:
            entry = self._local.get(key)
            if entry is not None:
                entry[1] += 1
                id_, needs_ref = entry[0], key in self._pending_ref
                if not needs_ref:
                    return id_, False
        if entry is not None:
            # The entry came from a degraded-mode retain_cached and
            # has no durable value-ref yet: this allocate is the first
            # store contact since — publish the ref now (best-effort;
            # still degraded keeps it pending for run_gc to retry).
            try:
                self.backend.set(self._value_path(key),
                                 str(id_).encode(), lease=True)
                with self._mutex:
                    self._pending_ref.discard(key)
            except KvstoreError:
                pass
            return id_, False

        lock = self.backend.lock_path(f"{self.base_path}/locks/{key}")
        try:
            # Re-check under the lock: another same-node thread may have
            # allocated while we waited; bump its refcount instead of
            # resetting it to 1 (which would release prematurely).
            with self._mutex:
                entry = self._local.get(key)
                if entry is not None:
                    entry[1] += 1
                    return entry[0], False

            existing = self._lookup_key(key)
            if existing is not None:
                # Reuse the cluster-wide ID; register our reference.
                self.backend.set(self._value_path(key), str(existing).encode(),
                                 lease=True)
                self.id_pool.remove(existing)
                with self._mutex:
                    self._local[key] = [existing, 1]
                    self.cache[existing] = key
                return existing, False

            for _ in range(32):  # bounded retries on races
                id_ = self.id_pool.lease_random()
                if id_ is None:
                    raise AllocatorError("ID space exhausted")
                if self.backend.create_only(self._id_path(id_), key.encode()):
                    self.backend.set(self._value_path(key),
                                     str(id_).encode(), lease=True)
                    with self._mutex:
                        self._local[key] = [id_, 1]
                        self.cache[id_] = key
                    self._fire_event(AllocatorEvent(EventType.CREATE, id_, key))
                    return id_, True
                # Another node claimed this ID concurrently.
            raise AllocatorError(f"unable to allocate ID for key {key!r}")
        finally:
            lock.unlock()

    def _lookup_key(self, key: str) -> Optional[int]:
        """Find an existing master ID for key (reference: GetNoCache path)."""
        for k, v in self.backend.list_prefix(f"{self.base_path}/id/").items():
            if v.decode() == key:
                try:
                    return int(k.rsplit("/", 1)[1])
                except ValueError:
                    continue
        return None

    def get(self, key: str) -> Optional[int]:
        """ID for key from cache, if any (reference: allocator.Get)."""
        with self._mutex:
            entry = self._local.get(key)
            if entry is not None:
                return entry[0]
            for id_, k in self.cache.items():
                if k == key:
                    return id_
        return None

    def retain_cached(self, key: str) -> Optional[int]:
        """Take a LOCAL reference on an identity already known from the
        cache, with zero kvstore I/O — the degraded-mode path: the
        store is fenced/unreachable, but an ID this node (or the
        watch) already resolved keeps serving.  The reference is
        refcounted like allocate()'s, so a later release() balances
        instead of underflowing another endpoint's reference.  Caveat
        (documented degraded guarantee): no remote value-ref is
        written, so cluster-wide GC may not see this node's use until
        the next real allocate() after the store returns."""
        with self._mutex:
            entry = self._local.get(key)
            if entry is not None:
                entry[1] += 1
                return entry[0]
            for id_, k in self.cache.items():
                if k == key:
                    self._local[key] = [id_, 1]
                    # No remote value-ref was written: mark it owed so
                    # allocate()/run_gc republish once the store is
                    # back — until then another node's GC could still
                    # reap the master key (the documented degraded
                    # window).
                    self._pending_ref.add(key)
                    return id_
        return None

    def get_by_id(self, id_: int) -> Optional[str]:
        with self._mutex:
            return self.cache.get(id_)

    def release(self, key: str) -> bool:
        """Drop one local reference; removes our value key at zero
        (reference: allocator.go Release)."""
        with self._mutex:
            entry = self._local.get(key)
            if entry is None:
                return False
            entry[1] -= 1
            if entry[1] > 0:
                return True
        # Zero references: serialize the value-ref delete against
        # allocate() on the same key so we can't destroy a reference a
        # concurrent allocate just re-created.
        try:
            lock = self.backend.lock_path(f"{self.base_path}/locks/{key}")
        except KvstoreError:
            # Could not even reach the store for the lock: settle the
            # local side and defer the remote unref (same contract as
            # a failed delete below).
            with self._mutex:
                entry = self._local.get(key)
                if entry is not None and entry[1] <= 0:
                    del self._local[key]
                    self._pending_ref.discard(key)
                    self._pending_unref.add(key)
            raise
        try:
            with self._mutex:
                entry = self._local.get(key)
                if entry is None or entry[1] > 0:
                    return True  # re-acquired while we waited
                del self._local[key]
                self._pending_ref.discard(key)
            try:
                self.backend.delete(self._value_path(key))
            except KvstoreError:
                # Store fenced/unreachable: the local refcount is
                # already settled — record the remote unref as pending
                # so run_gc retries it, instead of leaking our value
                # key (which would block cluster-wide GC of this
                # identity until the agent restarts).
                with self._mutex:
                    self._pending_unref.add(key)
                raise
        finally:
            lock.unlock()
        return True

    def flush_pending_unrefs(self) -> int:
        """Retry value-ref deletes that failed while the store was
        degraded; returns how many cleared.  Keys re-allocated since
        are live again and simply dropped from the pending set."""
        with self._mutex:
            pending = list(self._pending_unref)
        cleared = 0
        for key in pending:
            with self._mutex:
                if key in self._local:
                    self._pending_unref.discard(key)
                    continue
            try:
                self.backend.delete(self._value_path(key))
            except KvstoreError:
                continue  # still degraded; next pass retries
            with self._mutex:
                self._pending_unref.discard(key)
            cleared += 1
        return cleared

    def flush_pending_refs(self) -> int:
        """Publish value-refs owed by degraded-mode retain_cached
        calls; returns how many landed.  Runs BEFORE the gc scan so
        our in-use identities are visible to every node's gc first."""
        with self._mutex:
            pending = [
                (key, self._local[key][0])
                for key in self._pending_ref
                if key in self._local
            ]
            # Entries released in the meantime owe nothing.
            self._pending_ref &= set(self._local)
        published = 0
        for key, id_ in pending:
            try:
                self.backend.set(self._value_path(key),
                                 str(id_).encode(), lease=True)
            except KvstoreError:
                continue  # still degraded; next pass retries
            with self._mutex:
                self._pending_ref.discard(key)
            published += 1
        return published

    def run_gc(self) -> int:
        """Remove master keys with no value references; returns count
        (reference: allocator.go RunGC)."""
        self.flush_pending_refs()
        self.flush_pending_unrefs()
        return self._run_gc()

    def _run_gc(self) -> int:
        removed = 0
        for k, v in list(
            self.backend.list_prefix(f"{self.base_path}/id/").items()
        ):
            key = v.decode()
            # Serialize against allocate() on the same key: without the
            # lock, GC could delete a master key between another node's
            # reuse-lookup and its value-ref write, causing ID reuse for a
            # different key.
            lock = self.backend.lock_path(f"{self.base_path}/locks/{key}")
            try:
                if self.backend.get(k) is None:
                    continue  # already removed while we waited
                if self.backend.list_prefix(self._value_prefix(key) + "/"):
                    continue  # referenced again
                self.backend.delete(k)
                try:
                    id_ = int(k.rsplit("/", 1)[1])
                except ValueError:
                    continue
                self.id_pool.insert(id_)
                with self._mutex:
                    self.cache.pop(id_, None)
                self._fire_event(AllocatorEvent(EventType.DELETE, id_, key))
                removed += 1
            finally:
                lock.unlock()
        return removed

    # -- watch -------------------------------------------------------------

    def start_watch(self) -> Watcher:
        """Watch master keys, keeping the remote cache in sync and firing
        the events callback (reference: allocator cache.go watcher)."""
        w = self.backend.list_and_watch("allocator", f"{self.base_path}/id/")
        self._watcher = w

        def run() -> None:
            for ev in w:
                if ev.typ == EventType.LIST_DONE:
                    continue
                try:
                    id_ = int(ev.key.rsplit("/", 1)[1])
                except (ValueError, IndexError):
                    continue
                with self._mutex:
                    if ev.typ == EventType.DELETE:
                        key = self.cache.pop(id_, "")
                        self.id_pool.insert(id_)
                    else:
                        key = ev.value.decode()
                        self.cache[id_] = key
                        self.id_pool.remove(id_)
                if self.events:
                    try:
                        self.events(AllocatorEvent(ev.typ, id_, key))
                    except Exception:  # noqa: BLE001 — a bad callback must
                        pass  # not kill the watch loop

        t = threading.Thread(target=run, name="allocator-watch", daemon=True)
        t.start()
        return w

    def stop_watch(self) -> None:
        if self._watcher is not None:
            self._watcher.stop()
