"""Cluster-wide ID allocator over the kvstore.

reference: pkg/kvstore/allocator/allocator.go:136 — allocates small numeric
IDs for arbitrary keys (label sets) cluster-wide:

  <prefix>/id/<numericID>          -> key string        (master key)
  <prefix>/value/<key>/<nodename>  -> numericID         (per-node use ref)

Allocation first reuses an existing master key for the value (so all nodes
converge on one ID per key), otherwise claims a free ID with an atomic
create.  Node value keys are lease-attached: a dying node's references
disappear, and GC removes master keys with no remaining references.
A watcher keeps a local id->key cache in sync with remote allocations.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass
from typing import Callable, Optional

from .backend import Backend, EventType, KvstoreError, Watcher


class AllocatorError(KvstoreError):
    pass


class IdPool:
    """Pool of allocatable IDs (reference: pkg/kvstore/allocator/idpool.go)."""

    def __init__(self, start: int, end: int) -> None:
        self.start = start
        self.end = end
        self._free: set[int] = set(range(start, end + 1))
        self._mutex = threading.Lock()

    def lease_random(self) -> Optional[int]:
        with self._mutex:
            if not self._free:
                return None
            val = random.choice(tuple(self._free))
            self._free.discard(val)
            return val

    def remove(self, id_: int) -> None:
        with self._mutex:
            self._free.discard(id_)

    def insert(self, id_: int) -> None:
        with self._mutex:
            if self.start <= id_ <= self.end:
                self._free.add(id_)


@dataclass
class AllocatorEvent:
    typ: EventType
    id: int
    key: str


class Allocator:
    """reference: allocator.go:136 Allocator."""

    def __init__(
        self,
        backend: Backend,
        base_path: str,
        suffix: str,
        min_id: int = 256,
        max_id: int = 65535,
        events: Callable[[AllocatorEvent], None] | None = None,
    ) -> None:
        self.backend = backend
        self.base_path = base_path.rstrip("/")
        self.suffix = suffix  # this node's name
        self.id_pool = IdPool(min_id, max_id)
        self.events = events
        # local cache: key -> (id, refcount) (reference: localkeys.go)
        self._local: dict[str, list[int]] = {}
        # remote cache: id -> key (reference: allocator cache.go)
        self.cache: dict[int, str] = {}
        self._mutex = threading.RLock()
        self._watcher: Watcher | None = None
        self._sync_from_store()

    # -- paths -------------------------------------------------------------

    def _id_path(self, id_: int) -> str:
        return f"{self.base_path}/id/{id_}"

    def _value_prefix(self, key: str) -> str:
        return f"{self.base_path}/value/{self.backend.encode(key.encode())}"

    def _value_path(self, key: str) -> str:
        return f"{self._value_prefix(key)}/{self.suffix}"

    # -- init --------------------------------------------------------------

    def _fire_event(self, ev: AllocatorEvent) -> None:
        """Direct event dispatch, used only while no watcher runs — once
        start_watch is active the watcher delivers every master-key change
        and a direct callback would double-fire."""
        if self.events and self._watcher is None:
            self.events(ev)

    def _sync_from_store(self) -> None:
        for k, v in self.backend.list_prefix(f"{self.base_path}/id/").items():
            try:
                id_ = int(k.rsplit("/", 1)[1])
            except ValueError:
                continue
            self.id_pool.remove(id_)
            self.cache[id_] = v.decode()

    # -- allocation --------------------------------------------------------

    def allocate(self, key: str) -> tuple[int, bool]:
        """Allocate or reuse the cluster-wide ID for key; returns
        (id, is_new) (reference: allocator.go:240 Allocate)."""
        with self._mutex:
            entry = self._local.get(key)
            if entry is not None:
                entry[1] += 1
                return entry[0], False

        lock = self.backend.lock_path(f"{self.base_path}/locks/{key}")
        try:
            # Re-check under the lock: another same-node thread may have
            # allocated while we waited; bump its refcount instead of
            # resetting it to 1 (which would release prematurely).
            with self._mutex:
                entry = self._local.get(key)
                if entry is not None:
                    entry[1] += 1
                    return entry[0], False

            existing = self._lookup_key(key)
            if existing is not None:
                # Reuse the cluster-wide ID; register our reference.
                self.backend.set(self._value_path(key), str(existing).encode(),
                                 lease=True)
                self.id_pool.remove(existing)
                with self._mutex:
                    self._local[key] = [existing, 1]
                    self.cache[existing] = key
                return existing, False

            for _ in range(32):  # bounded retries on races
                id_ = self.id_pool.lease_random()
                if id_ is None:
                    raise AllocatorError("ID space exhausted")
                if self.backend.create_only(self._id_path(id_), key.encode()):
                    self.backend.set(self._value_path(key),
                                     str(id_).encode(), lease=True)
                    with self._mutex:
                        self._local[key] = [id_, 1]
                        self.cache[id_] = key
                    self._fire_event(AllocatorEvent(EventType.CREATE, id_, key))
                    return id_, True
                # Another node claimed this ID concurrently.
            raise AllocatorError(f"unable to allocate ID for key {key!r}")
        finally:
            lock.unlock()

    def _lookup_key(self, key: str) -> Optional[int]:
        """Find an existing master ID for key (reference: GetNoCache path)."""
        for k, v in self.backend.list_prefix(f"{self.base_path}/id/").items():
            if v.decode() == key:
                try:
                    return int(k.rsplit("/", 1)[1])
                except ValueError:
                    continue
        return None

    def get(self, key: str) -> Optional[int]:
        """ID for key from cache, if any (reference: allocator.Get)."""
        with self._mutex:
            entry = self._local.get(key)
            if entry is not None:
                return entry[0]
            for id_, k in self.cache.items():
                if k == key:
                    return id_
        return None

    def get_by_id(self, id_: int) -> Optional[str]:
        with self._mutex:
            return self.cache.get(id_)

    def release(self, key: str) -> bool:
        """Drop one local reference; removes our value key at zero
        (reference: allocator.go Release)."""
        with self._mutex:
            entry = self._local.get(key)
            if entry is None:
                return False
            entry[1] -= 1
            if entry[1] > 0:
                return True
        # Zero references: serialize the value-ref delete against
        # allocate() on the same key so we can't destroy a reference a
        # concurrent allocate just re-created.
        lock = self.backend.lock_path(f"{self.base_path}/locks/{key}")
        try:
            with self._mutex:
                entry = self._local.get(key)
                if entry is None or entry[1] > 0:
                    return True  # re-acquired while we waited
                del self._local[key]
            self.backend.delete(self._value_path(key))
        finally:
            lock.unlock()
        return True

    def run_gc(self) -> int:
        """Remove master keys with no value references; returns count
        (reference: allocator.go RunGC)."""
        removed = 0
        for k, v in list(
            self.backend.list_prefix(f"{self.base_path}/id/").items()
        ):
            key = v.decode()
            # Serialize against allocate() on the same key: without the
            # lock, GC could delete a master key between another node's
            # reuse-lookup and its value-ref write, causing ID reuse for a
            # different key.
            lock = self.backend.lock_path(f"{self.base_path}/locks/{key}")
            try:
                if self.backend.get(k) is None:
                    continue  # already removed while we waited
                if self.backend.list_prefix(self._value_prefix(key) + "/"):
                    continue  # referenced again
                self.backend.delete(k)
                try:
                    id_ = int(k.rsplit("/", 1)[1])
                except ValueError:
                    continue
                self.id_pool.insert(id_)
                with self._mutex:
                    self.cache.pop(id_, None)
                self._fire_event(AllocatorEvent(EventType.DELETE, id_, key))
                removed += 1
            finally:
                lock.unlock()
        return removed

    # -- watch -------------------------------------------------------------

    def start_watch(self) -> Watcher:
        """Watch master keys, keeping the remote cache in sync and firing
        the events callback (reference: allocator cache.go watcher)."""
        w = self.backend.list_and_watch("allocator", f"{self.base_path}/id/")
        self._watcher = w

        def run() -> None:
            for ev in w:
                if ev.typ == EventType.LIST_DONE:
                    continue
                try:
                    id_ = int(ev.key.rsplit("/", 1)[1])
                except (ValueError, IndexError):
                    continue
                with self._mutex:
                    if ev.typ == EventType.DELETE:
                        key = self.cache.pop(id_, "")
                        self.id_pool.insert(id_)
                    else:
                        key = ev.value.decode()
                        self.cache[id_] = key
                        self.id_pool.remove(id_)
                if self.events:
                    try:
                        self.events(AllocatorEvent(ev.typ, id_, key))
                    except Exception:  # noqa: BLE001 — a bad callback must
                        pass  # not kill the watch loop

        t = threading.Thread(target=run, name="allocator-watch", daemon=True)
        t.start()
        return w

    def stop_watch(self) -> None:
        if self._watcher is not None:
            self._watcher.stop()
