"""Shared key collection over the kvstore.

reference: pkg/kvstore/store/store.go — a generic collection of keys shared
across nodes: each node owns and keeps alive its local keys (lease +
periodic sync), a watcher mirrors all remote keys into a local map, and
observers are notified on updates/deletes.  Node discovery and service
propagation ride on this.
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import dataclass
from typing import Callable, Optional

from .backend import Backend, EventType, KvstoreError

log = logging.getLogger(__name__)


class SharedStore:
    """reference: store.go SharedStore."""

    def __init__(
        self,
        backend: Backend,
        prefix: str,
        node_name: str,
        on_update: Callable[[str, dict], None] | None = None,
        on_delete: Callable[[str], None] | None = None,
    ) -> None:
        self.backend = backend
        self.prefix = prefix.rstrip("/")
        self.node_name = node_name
        self.on_update = on_update
        self.on_delete = on_delete
        self._local: dict[str, dict] = {}
        self._shared: dict[str, dict] = {}
        self._mutex = threading.RLock()
        self._watcher = None
        self._start_watch()

    def _key_path(self, name: str) -> str:
        return f"{self.prefix}/{name}"

    def update_local_key_sync(self, name: str, value: dict) -> None:
        """Publish/refresh one of our keys (reference:
        store.go UpdateLocalKeySync)."""
        with self._mutex:
            self._local[name] = value
        self.backend.set(
            self._key_path(name), json.dumps(value).encode(), lease=True
        )

    def delete_local_key(self, name: str) -> None:
        with self._mutex:
            self._local.pop(name, None)
        self.backend.delete(self._key_path(name))

    def get_shared_keys(self) -> dict[str, dict]:
        with self._mutex:
            return dict(self._shared)

    def get(self, name: str) -> Optional[dict]:
        with self._mutex:
            return self._shared.get(name)

    def sync_local_keys(self) -> None:
        """Re-publish all local keys (periodic keepalive refresh,
        reference: store.go syncLocalKeys)."""
        with self._mutex:
            local = dict(self._local)
        for name, value in local.items():
            self.backend.set(
                self._key_path(name), json.dumps(value).encode(), lease=True
            )

    def _start_watch(self) -> None:
        w = self.backend.list_and_watch(f"store-{self.prefix}", self.prefix + "/")
        self._watcher = w

        def run() -> None:
            for ev in w:
                if ev.typ == EventType.LIST_DONE:
                    continue
                name = ev.key[len(self.prefix) + 1:]
                # Own keys loop back through the prefix watch; the
                # shared view holds REMOTE state only (reference:
                # store.go onUpdate isLocal filter) — a node must not
                # discover itself as a peer.
                with self._mutex:
                    own = name in self._local or name == self.node_name
                if own:
                    continue
                if ev.typ == EventType.DELETE:
                    with self._mutex:
                        self._shared.pop(name, None)
                    if self.on_delete:
                        self.on_delete(name)
                else:
                    try:
                        value = json.loads(ev.value.decode())
                    except ValueError:
                        continue
                    with self._mutex:
                        self._shared[name] = value
                    if self.on_update:
                        self.on_update(name, value)

        threading.Thread(
            target=run, name=f"store-watch-{self.prefix}", daemon=True
        ).start()

    def close(self) -> None:
        if self._watcher is not None:
            self._watcher.stop()
        for name in list(self._local):
            # Best-effort: on a dead/closing kvstore connection the
            # server-side lease revocation removes the key anyway
            # (session death = lease expiry) — teardown must not raise.
            try:
                self.delete_local_key(name)
            except KvstoreError as e:
                log.debug("store close: delete %s skipped: %s", name, e)
