"""Shared key collection over the kvstore.

reference: pkg/kvstore/store/store.go — a generic collection of keys shared
across nodes: each node owns and keeps alive its local keys (lease +
periodic sync), a watcher mirrors all remote keys into a local map, and
observers are notified on updates/deletes.  Node discovery and service
propagation ride on this.
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import dataclass
from typing import Callable, Optional

from .backend import Backend, EventType, KvstoreError

log = logging.getLogger(__name__)


class SharedStore:
    """reference: store.go SharedStore."""

    def __init__(
        self,
        backend: Backend,
        prefix: str,
        node_name: str,
        on_update: Callable[[str, dict], None] | None = None,
        on_delete: Callable[[str], None] | None = None,
    ) -> None:
        self.backend = backend
        self.prefix = prefix.rstrip("/")
        self.node_name = node_name
        self.on_update = on_update
        self.on_delete = on_delete
        self._local: dict[str, dict] = {}
        self._shared: dict[str, dict] = {}
        self._mutex = threading.RLock()
        self._watcher = None
        # True while the last publish/keepalive could not reach the
        # store (fenced or unreachable) — local keys keep serving and
        # are republished by the self-healing resync loop below.
        self.degraded = False
        self._closed = False
        self._resync_active = False
        # Set by any failed publish; the resync loop clears it before
        # a pass and re-checks after — a failure that lands WHILE a
        # pass is in flight (and thus missed it) forces another pass
        # instead of being stranded by the pass's success.
        self._dirty = False
        self._start_watch()

    def _key_path(self, name: str) -> str:
        return f"{self.prefix}/{name}"

    def update_local_key_sync(self, name: str, value: dict) -> None:
        """Publish/refresh one of our keys (reference:
        store.go UpdateLocalKeySync).  The local copy is recorded
        FIRST: if the store is fenced or unreachable the publish is
        deferred — the value is not lost, the periodic
        sync_local_keys keepalive republishes it once the store
        returns (degraded mode: local state keeps serving, cross-node
        propagation pauses)."""
        with self._mutex:
            self._local[name] = value
        try:
            self.backend.set(
                self._key_path(name), json.dumps(value).encode(), lease=True
            )
            self.degraded = False
        except KvstoreError as e:
            with self._mutex:
                self.degraded = True
                self._dirty = True
            log.warning(
                "store %s: publish of %s deferred (kvstore degraded): %s",
                self.prefix, name, e,
            )
            # Nothing else republishes on its own (no consumer runs a
            # periodic keepalive today) — the deferral claim is only
            # true if WE retry until the store takes the keys again.
            self._kick_resync()

    def delete_local_key(self, name: str) -> None:
        with self._mutex:
            self._local.pop(name, None)
        self.backend.delete(self._key_path(name))

    def get_shared_keys(self) -> dict[str, dict]:
        with self._mutex:
            return dict(self._shared)

    def get(self, name: str) -> Optional[dict]:
        with self._mutex:
            return self._shared.get(name)

    def sync_local_keys(self) -> None:
        """Re-publish all local keys (periodic keepalive refresh,
        reference: store.go syncLocalKeys).  Best-effort per key: one
        fenced/unreachable write must not strand the keys behind it —
        the next keepalive tick retries them all; ``degraded`` tracks
        whether the last full pass published everything."""
        with self._mutex:
            local = dict(self._local)
        failed = 0
        for name, value in local.items():
            try:
                self.backend.set(
                    self._key_path(name), json.dumps(value).encode(),
                    lease=True,
                )
            except KvstoreError as e:
                failed += 1
                log.warning("store %s: keepalive of %s failed: %s",
                            self.prefix, name, e)
        with self._mutex:
            self.degraded = failed > 0
            if failed:
                self._dirty = True
        if failed:
            self._kick_resync()

    def _kick_resync(self) -> None:
        """Start (at most one) background republisher that retries
        sync_local_keys with backoff until every local key landed —
        the recovery half of degraded mode."""
        with self._mutex:
            if self._resync_active or self._closed:
                return
            self._resync_active = True
        threading.Thread(
            target=self._resync_loop, daemon=True,
            name=f"store-resync-{self.prefix}",
        ).start()

    def _resync_loop(self) -> None:
        from ..utils.backoff import Exponential

        boff = Exponential(min_duration=1.0, max_duration=15.0,
                           name=f"store-resync-{self.prefix}")
        try:
            while True:
                boff.wait()
                with self._mutex:
                    if self._closed:
                        return
                    self._dirty = False
                    local = dict(self._local)
                ok = True
                for name, value in local.items():
                    try:
                        self.backend.set(
                            self._key_path(name),
                            json.dumps(value).encode(), lease=True,
                        )
                    except KvstoreError:
                        ok = False
                        break
                if ok:
                    with self._mutex:
                        if self._dirty:
                            continue  # a publish failed mid-pass
                        self.degraded = False
                    log.info("store %s: deferred keys republished",
                             self.prefix)
                    return
        finally:
            with self._mutex:
                self._resync_active = False
                # A publish that failed while we were exiting saw
                # _resync_active=True and declined to start a thread:
                # re-kick for it or its key would strand unpublished.
                redo = self._dirty and not self._closed
            if redo:
                self._kick_resync()

    def _start_watch(self) -> None:
        w = self.backend.list_and_watch(f"store-{self.prefix}", self.prefix + "/")
        self._watcher = w

        def run() -> None:
            for ev in w:
                if ev.typ == EventType.LIST_DONE:
                    continue
                name = ev.key[len(self.prefix) + 1:]
                # Own keys loop back through the prefix watch; the
                # shared view holds REMOTE state only (reference:
                # store.go onUpdate isLocal filter) — a node must not
                # discover itself as a peer.
                with self._mutex:
                    own = name in self._local or name == self.node_name
                if own:
                    continue
                if ev.typ == EventType.DELETE:
                    with self._mutex:
                        self._shared.pop(name, None)
                    if self.on_delete:
                        self.on_delete(name)
                else:
                    try:
                        value = json.loads(ev.value.decode())
                    except ValueError:
                        continue
                    with self._mutex:
                        self._shared[name] = value
                    if self.on_update:
                        self.on_update(name, value)

        threading.Thread(
            target=run, name=f"store-watch-{self.prefix}", daemon=True
        ).start()

    def close(self) -> None:
        with self._mutex:
            self._closed = True
        if self._watcher is not None:
            self._watcher.stop()
        for name in list(self._local):
            # Best-effort: on a dead/closing kvstore connection the
            # server-side lease revocation removes the key anyway
            # (session death = lease expiry) — teardown must not raise.
            try:
                self.delete_local_key(name)
            except KvstoreError as e:
                log.debug("store close: delete %s skipped: %s", name, e)
