"""CIDR -> label conversion (reference: pkg/labels/cidr.go).

CIDR prefixes appearing in policy become labels with source ``cidr`` so the
selector machinery can treat IP blocks uniformly with label selectors; IPv6
colons become dashes (selector keys can't contain ':').
"""

from __future__ import annotations

import ipaddress

from . import Label, parse_label

SOURCE_CIDR = "cidr"


def _masked_ip_to_label_string(ip: str, prefix: int) -> str:
    s = ip.replace(":", "-")
    pre = "0" if s.startswith("-") else ""
    post = "0" if s.endswith("-") else ""
    return f"{SOURCE_CIDR}:{pre}{s}{post}/{prefix}"


def ipnet_to_label(net: ipaddress._BaseNetwork) -> Label:
    return parse_label(
        _masked_ip_to_label_string(str(net.network_address), net.prefixlen)
    )


def ip_string_to_label(ip: str) -> Label | None:
    """Parse an IP or CIDR string into its cidr-source label; None if invalid
    (reference: pkg/labels/cidr.go:58-74)."""
    try:
        net = ipaddress.ip_network(ip, strict=False)
    except ValueError:
        try:
            addr = ipaddress.ip_address(ip)
        except ValueError:
            return None
        net = ipaddress.ip_network(f"{addr}/{addr.max_prefixlen}")
    return ipnet_to_label(net)
