"""Label model: source-qualified key/value labels and label sets.

Semantics follow the reference's label model (reference: pkg/labels/labels.go,
pkg/labels/array.go): a label is (source, key, value); string form is
``source:key=value``; ``$x`` and ``reserved:x`` are reserved-source
shorthands; selectors use the "extended key" form ``source.key`` and an
``any``-source label matches any source.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Iterable, Iterator

PATH_DELIMITER = "."

# Special ID names (reference: pkg/labels/labels.go:31-57).
ID_NAME_ALL = "all"
ID_NAME_HOST = "host"
ID_NAME_WORLD = "world"
ID_NAME_CLUSTER = "cluster"
ID_NAME_HEALTH = "health"
ID_NAME_INIT = "init"
ID_NAME_UNMANAGED = "unmanaged"
ID_NAME_UNKNOWN = "unknown"

# Label sources (reference: pkg/labels/filter.go / labels.go).
SOURCE_UNSPEC = "unspec"
SOURCE_ANY = "any"
SOURCE_K8S = "k8s"
SOURCE_CONTAINER = "container"
SOURCE_RESERVED = "reserved"
SOURCE_CILIUM_GENERATED = "cilium-generated"

RESERVED_KEY_PREFIX = SOURCE_RESERVED + ":"


def parse_source(s: str) -> tuple[str, str]:
    """Split ``source:rest`` (also handling the ``$`` reserved shorthand).

    Mirrors the reference's parseSource (pkg/labels/labels.go:595-614).
    """
    if not s:
        return "", ""
    if s[0] == "$":
        return SOURCE_RESERVED, s[1:]
    i = s.find(":")
    if i < 0:
        if s.startswith(RESERVED_KEY_PREFIX):
            return SOURCE_RESERVED, s[len(RESERVED_KEY_PREFIX):]
        return "", s
    return s[:i], s[i + 1:]


@dataclass(frozen=True)
class Label:
    key: str
    value: str = ""
    source: str = SOURCE_UNSPEC

    @staticmethod
    def new(key: str, value: str = "", source: str = "") -> "Label":
        """Create a label, resolving an embedded source prefix in ``key``
        (reference: pkg/labels/labels.go:303-324)."""
        src, key = parse_source(key)
        if not source:
            source = src if src else SOURCE_UNSPEC
        if src == SOURCE_RESERVED and key == "":
            key, value = value, ""
        return Label(key=key, value=value, source=source)

    @property
    def extended_key(self) -> str:
        return self.source + PATH_DELIMITER + self.key

    def is_all_label(self) -> bool:
        return self.source == SOURCE_RESERVED and self.key == ID_NAME_ALL

    def is_any_source(self) -> bool:
        return self.source == SOURCE_ANY

    def is_reserved_source(self) -> bool:
        return self.source == SOURCE_RESERVED

    def is_valid(self) -> bool:
        return self.key != ""

    def equals(self, other: "Label") -> bool:
        """Source-aware equality: an ``any``-source label matches any source
        (reference: pkg/labels/labels.go:326-334)."""
        if not self.is_any_source() and self.source != other.source:
            return False
        return self.key == other.key and self.value == other.value

    def matches(self, target: "Label") -> bool:
        return self.is_all_label() or self.equals(target)

    def __str__(self) -> str:
        if self.value:
            return f"{self.source}:{self.key}={self.value}"
        return f"{self.source}:{self.key}"


def parse_label(s: str) -> Label:
    """Parse ``[source:]key[=value]`` (reference: pkg/labels/labels.go:615)."""
    src, rest = parse_source(s)
    source = src if src else SOURCE_UNSPEC
    i = rest.find("=")
    if i < 0:
        return Label(key=rest, source=source)
    if i == 0 and src == SOURCE_RESERVED:
        return Label(key=rest[1:], source=source)
    return Label(key=rest[:i], value=rest[i + 1:], source=source)


def parse_select_label(s: str) -> Label:
    """Like parse_label but unspecified source defaults to ``any``
    (reference: pkg/labels/labels.go:641)."""
    lbl = parse_label(s)
    if lbl.source == SOURCE_UNSPEC:
        return Label(key=lbl.key, value=lbl.value, source=SOURCE_ANY)
    return lbl


def get_extended_key_from(s: str) -> str:
    """``k8s:foo=bar`` -> ``k8s.foo``; bare keys get the ``any`` source
    (reference: pkg/labels/labels.go:438-455)."""
    src, rest = parse_source(s)
    if not src:
        src = SOURCE_ANY
    i = rest.find("=")
    if i >= 0:
        rest = rest[:i]
    return src + PATH_DELIMITER + rest


def get_cilium_key_from(ext_key: str) -> str:
    """``k8s.foo`` -> ``k8s:foo`` (reference: pkg/labels/labels.go:425)."""
    i = ext_key.find(PATH_DELIMITER)
    if i >= 0:
        return ext_key[:i] + ":" + ext_key[i + 1:]
    return SOURCE_ANY + ":" + ext_key


class LabelArray(tuple):
    """An ordered set of labels (reference: pkg/labels/array.go:18)."""

    def __new__(cls, labels: Iterable[Label] = ()):
        return super().__new__(cls, tuple(labels))

    @staticmethod
    def parse(*strs: str) -> "LabelArray":
        return LabelArray(parse_label(s) for s in strs)

    @staticmethod
    def parse_select(*strs: str) -> "LabelArray":
        return LabelArray(parse_select_label(s) for s in strs)

    def contains(self, needed: "LabelArray") -> bool:
        """True if every needed label matches one of ours
        (reference: pkg/labels/array.go:57-71)."""
        return all(any(n.matches(l) for l in self) for n in needed)

    def lacks(self, needed: "LabelArray") -> "LabelArray":
        return LabelArray(
            n for n in needed if not any(n.matches(l) for l in self)
        )

    def has(self, ext_key: str) -> bool:
        """Key lookup by extended key; ``any.key`` matches any source
        (reference: pkg/labels/array.go:96-131)."""
        any_prefix = SOURCE_ANY + PATH_DELIMITER
        for l in self:
            if l.extended_key == ext_key:
                return True
            if ext_key.startswith(any_prefix) and l.key == ext_key[len(any_prefix):]:
                return True
        return False

    def get(self, ext_key: str) -> str | None:
        any_prefix = SOURCE_ANY + PATH_DELIMITER
        for l in self:
            if l.extended_key == ext_key:
                return l.value
            if ext_key.startswith(any_prefix) and l.key == ext_key[len(any_prefix):]:
                return l.value
        return None

    def sort(self) -> "LabelArray":
        return LabelArray(sorted(self, key=lambda l: (l.source, l.key, l.value)))

    def get_model(self) -> list[str]:
        return [str(l) for l in self]

    def __repr__(self) -> str:
        return f"LabelArray({', '.join(str(l) for l in self)})"


class Labels(dict):
    """Map of key -> Label (reference: pkg/labels/labels.go Labels)."""

    @staticmethod
    def from_model(strs: Iterable[str]) -> "Labels":
        l = Labels()
        for s in strs:
            lbl = parse_label(s)
            if lbl.is_valid():
                l[lbl.key] = lbl
        return l

    @staticmethod
    def from_map(m: dict[str, str], source: str) -> "Labels":
        l = Labels()
        for k, v in m.items():
            lbl = Label.new(k, v, source)
            l[lbl.key] = lbl
        return l

    def upsert(self, lbl: Label) -> None:
        self[lbl.key] = lbl

    def merge(self, other: "Labels") -> None:
        self.update(other)

    def get_from_source(self, source: str) -> "Labels":
        out = Labels()
        for k, v in self.items():
            if v.source == source:
                out[k] = v
        return out

    def to_array(self) -> LabelArray:
        return LabelArray(self[k] for k in sorted(self))

    def sorted_list(self) -> bytes:
        """Canonical serialized form, input to the identity hash
        (reference: pkg/labels/labels.go:541)."""
        return b"".join(
            f"{l.source}:{l.key}={l.value};".encode()
            for l in (self[k] for k in sorted(self))
        )

    def sha256_sum(self) -> str:
        return hashlib.sha256(self.sorted_list()).hexdigest()

    def get_model(self) -> list[str]:
        return [str(self[k]) for k in sorted(self)]

    def equals(self, other: "Labels") -> bool:
        if len(self) != len(other):
            return False
        for k, v in self.items():
            o = other.get(k)
            if o is None or v.source != o.source or v.value != o.value:
                return False
        return True


# Reserved-label singletons.
LABEL_HOST = Label(key=ID_NAME_HOST, source=SOURCE_RESERVED)
LABEL_WORLD = Label(key=ID_NAME_WORLD, source=SOURCE_RESERVED)
LABEL_HEALTH = Label(key=ID_NAME_HEALTH, source=SOURCE_RESERVED)
LABEL_INIT = Label(key=ID_NAME_INIT, source=SOURCE_RESERVED)
LABEL_UNMANAGED = Label(key=ID_NAME_UNMANAGED, source=SOURCE_RESERVED)
LABEL_ALL = Label(key=ID_NAME_ALL, source=SOURCE_RESERVED)
