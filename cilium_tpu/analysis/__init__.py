"""cilium-lint: AST-based concurrency & hot-path invariant analysis.

PRs 1-2 found an entire taxonomy of concurrency bugs in the verdict hot
path by hand (re-read lock release after deposal swap, bare close()
without shutdown(), blocking calls under locks, double-booked reply
counters).  The paper's north star — >=1M L7 verdicts/sec at <1ms added
p99, bit-identical verdicts — demands those invariants hold permanently,
not just at review time, so this package encodes them as machine-checked
rules over the repo's own AST (stdlib ``ast`` only, no third-party
linter):

  R1  lock discipline (acquire/finally pairing, captured-binding
      release, WHOLE-PROGRAM lock-order graph incl. call-mediated
      cross-module inversions)
  R2  blocking calls inside a held-lock region, incl. blocking
      reached THROUGH helper chains (interprocedural taint)
  R3  socket close() with no dominating shutdown()
  R4  purity of functions reached from jax.jit/vmap/scan call sites
      (whole-program reachability through import-resolved calls)
  R5  wire MSG_* / FilterResult handler exhaustiveness + field-level
      JSON payload symmetry (MSG_TRACE/MSG_OBSERVE request & reply)
  R6  thread hygiene (Thread() without daemon= or local join)
  R7  metric hygiene (dead registrations, hot-loop observes)
  R8  recompilation hazards in jit-reached code (concretized scalars,
      weak-typed constants, unhashable static args)
  R9  implicit host transfers (.item()/np coercion in traced code;
      block_until_ready on the dispatch hot path)
  R10 shard_map/pjit in_specs/out_specs vs function arity
  R11 fused-attribution integrity (one shared hit-matrix pass)
  R12 compile-on-dispatch-path (recompiles ride the builder thread)
  R13 epoch-unkeyed caches in hot modules
  R14 exactly-once verdict accounting (admit paths reach an answer
      site or typed hand-off; answer sites are exclusivity-guarded)
  R15 exception containment (no raise out of a per-entry hot loop
      without a typed outcome; interprocedural raise-taint)
  R16 jit shape-closure (dispatch axes drawn from the declared
      power-of-two bucket universe; abstract twin audits the real
      serving surface end to end)
  R17 snapshot round-trip symmetry (snapshot_*/restore_* pairs:
      every written field consumed or versioned-out, no hard-
      required field unwritten, no twin missing)
  R18 declared typestates (every state-field store is a declared
      protocols.py edge, mediated through advance/guard/
      require_edges; every counted edge's site emits its declared
      metric token; the table itself is well-formed)
  R19 column-store lock discipline (declared shared numpy column
      families written only with the owning lock held — lexically
      or at every call site; multi-column snapshots read in ONE
      lock trip, never torn across separate acquisitions)
  R20 wire-protocol lifecycle (each MSG_* matches its declared
      direction, request/reply pairing, fire-and-forget and gate
      rows; native-shim header enum values stay bit-identical)
  R21 parity-coverage registry (every runtime-registered framing
      family carries its full declared landing bar: model, oracle,
      every-offset parity test, bench config, stress-mix slice)
  R22 fail-closed recorder coverage (every FAIL_CLOSED row names a
      declared typestate edge or marker token AND reaches a flight-
      recorder emit site — no invisible fail-closed transitions)
  R23 unledgered compile site (every executable-producing call
      reachable from the dispatch or policy-builder roots routes
      through the device-economics ledger — complete per-cause
      compile census, asserted zero-compile warm churn)
  R0  lint pragma hygiene (malformed / unjustified suppressions)

Layer 1 is the interprocedural engine (``callgraph.py``): a project-
wide call graph with import/attribute resolution, per-function
blocking/lock summaries and a fixed-point taint pass — what upgrades
R1/R2/R4 from per-module to whole-program.  Layer 2 is the device-
contract pair: ``rules_device.py`` (AST half) and ``devicecheck.py``
(abstract tracing of the REAL verdict models via eval_shape/make_jaxpr
under JAX_PLATFORMS=cpu — no device, zero runtime cost).  Layer 3
(v4) is the declared-protocol module (``protocols.py``): typestate
transition tables, column-store families, wire lifecycle rows and
engine landing bars as DATA.  The runtime imports and enforces them
(``Typestate.advance`` raises ``ProtocolViolation`` on an undeclared
edge) while ``rules_typestate``/``rules_columns``/``rules_protocol``/
``rules_parity`` prove the tree against the SAME tables — deleting a
declared edge fails both the checker and the runtime.

Run ``bin/cilium-lint cilium_tpu/`` (see README "Invariants & lint");
``--ratchet`` gates the suppression count one-way downward,
``--device-contracts`` adds the abstract-trace layer (R8-R11 plus the
R16 shape-closure audit), ``--diff <rev>`` scans changed files only
(warm pre-commit mode, fail-closed on a bad rev) and ``--sarif``
emits SARIF 2.1.0 for CI annotation.
Suppress a false positive on its line with a JUSTIFIED pragma::

    risky_call()  # lint: disable=R2 -- why this is safe here

A pragma without a justification is itself a finding (R0) and cannot
be suppressed.
"""

from .core import (  # noqa: F401
    Finding,
    RULE_DOCS,
    SourceFile,
    analyze_paths,
    findings_to_json,
    load_baseline,
    load_baseline_full,
    split_findings,
)
