"""cilium-lint: AST-based concurrency & hot-path invariant analysis.

PRs 1-2 found an entire taxonomy of concurrency bugs in the verdict hot
path by hand (re-read lock release after deposal swap, bare close()
without shutdown(), blocking calls under locks, double-booked reply
counters).  The paper's north star — >=1M L7 verdicts/sec at <1ms added
p99, bit-identical verdicts — demands those invariants hold permanently,
not just at review time, so this package encodes them as machine-checked
rules over the repo's own AST (stdlib ``ast`` only, no third-party
linter):

  R1  lock discipline (acquire/finally pairing, captured-binding
      release, recorded lock-order graph)
  R2  blocking calls inside a held-lock region
  R3  socket close() with no dominating shutdown()
  R4  purity of functions reached from jax.jit/vmap/scan call sites
  R5  wire MSG_* / FilterResult handler exhaustiveness
  R6  thread hygiene (Thread() without daemon= or local join)
  R0  lint pragma hygiene (malformed / unjustified suppressions)

Run ``bin/cilium-lint cilium_tpu/`` (see README "Invariants & lint").
Suppress a false positive on its line with a JUSTIFIED pragma::

    risky_call()  # lint: disable=R2 -- why this is safe here

A pragma without a justification is itself a finding (R0) and cannot
be suppressed.
"""

from .core import (  # noqa: F401
    Finding,
    RULE_DOCS,
    SourceFile,
    analyze_paths,
    findings_to_json,
    load_baseline,
    split_findings,
)
