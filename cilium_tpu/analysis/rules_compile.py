"""R12 — compile-on-dispatch-path.

PR 9's tentpole contract: policy/table recompiles run on the builder
thread and reach the serving tables by a pointer flip — the dispatch
path must never pay an XLA trace, an engine build, or a prewarm.  A
compile that sneaks back onto a dispatch round (or under a handler
lock, where it stalls every reader/writer queued behind it) is exactly
the multi-second stall the async swap was built to remove, and no
functional test notices: verdicts stay bit-identical, only the p99
explodes at the first churned table shape.

Two detection halves, both interprocedural (import-resolved call
graph, the same engine R2/R4 ride):

- **Reachability.**  Compile-class calls (``jax.jit``, ``prewarm``,
  ``build_*_model*``, ``compile_automaton``, ``_make_engine`` /
  ``_build_engine``, ``_measure_dispatch_mode``, ``lower``/
  ``eval_shape``/``.compile``) reachable from the dispatch/service hot
  loops of the hot modules (dispatch.py / service.py / shm.py roots:
  the round entry ``_process*``, the vec/mat/slow runners, the
  completion/send loops, the reader loop, admission).  Findings land
  at call sites inside the hot modules — the first edge off the
  dispatch path — so the sanctioned cold paths (first-bind on a reader
  thread, the builder thread itself) carry their justification where
  the edge is.
- **Held-lock compiles.**  A compile-class call made while ANY lock is
  held, in a hot module: even off the dispatch path, a compile under
  the registry/handler lock stalls every round that snapshots behind
  it (the pre-PR 9 ``policy_update`` bug shape).
"""

from __future__ import annotations

import os
import re

from .callgraph import get_graph
from .core import Finding, call_func_name

_HOT_BASENAMES = {"dispatch.py", "service.py", "shm.py",
                  "dnsengine.py"}

# Functions that ARE the dispatch path in the hot modules: the round
# entry + everything a round runs through, the pipeline loops, and the
# per-session reader loop (a compile there wedges every flow on the
# shim connection — the pre-PR 9 policy_update handler shape).
_DISPATCH_ROOTS = {
    "_process", "_process_entrywise", "_run_mat_group", "_run_vec",
    "_run_fast", "_run_slow_batched", "_run_slow", "_issue_fast",
    "_issue_chunks", "_issue_chunks_blob", "_issue_slow_async",
    "_finish_fast", "_finish_slow_async", "_finish_vec",
    "_completion_loop", "_send_loop", "_admit", "_try_cut_through",
    "submit_data", "submit_matrix", "submit_ring", "read_loop",
    "_shm_doorbell", "_run",
}

_COMPILE_NAMES = {
    "jit", "pjit", "prewarm", "compile_automaton",
    "_make_engine", "_build_engine", "_measure_dispatch_mode",
    "lower", "eval_shape", "compile", "trace",
}
_COMPILE_RE = re.compile(r"^build_\w*model\w*$")


def _is_compile_call(name: str) -> bool:
    return name in _COMPILE_NAMES or bool(_COMPILE_RE.match(name))


def _reachable_from_roots(graph, files):
    """FuncInfos reachable from the dispatch roots of hot modules,
    following the import-resolved call graph plus same-module bare/
    self-call names (mirroring rules_jit's approximation)."""
    roots = [
        fi for fi in graph.funcs.values()
        if os.path.basename(fi.path) in _HOT_BASENAMES
        and fi.qual.split(".")[-1] in _DISPATCH_ROOTS
    ]
    seen: set[str] = set()
    frontier = list(roots)
    reached = []
    while frontier:
        fi = frontier.pop()
        if fi.key in seen:
            continue
        seen.add(fi.key)
        reached.append(fi)
        for _call, _line, _col, _held, keys in fi.calls:
            for key in keys or ():
                callee = graph.funcs.get(key)
                if callee is not None:
                    frontier.append(callee)
    return reached


def check_r12(files):
    graph = get_graph(files)
    emitted: set[tuple] = set()

    def emit(fi, call, line, col, why):
        key = (fi.path, line, col)
        if key in emitted:
            return None
        emitted.add(key)
        name = call_func_name(call)
        return Finding(
            "R12", fi.path, line, col,
            f"compile/trace ({name}) {why}: table recompiles belong "
            f"on the policy builder thread with a pointer-flip swap — "
            f"a compile here stalls dispatch rounds for the full XLA "
            f"trace time and no functional test can see it",
            symbol=fi.qual,
        )

    # Half 1: reachable from the dispatch roots; report sites in hot
    # modules (the first edge off the dispatch path).
    for fi in _reachable_from_roots(graph, files):
        if os.path.basename(fi.path) not in _HOT_BASENAMES:
            continue
        for call, line, col, _held, _keys in fi.calls:
            if _is_compile_call(call_func_name(call)):
                f = emit(fi, call, line, col,
                         "reachable from the dispatch hot path")
                if f is not None:
                    yield f

    # Half 2: compile while holding a lock, anywhere in a hot module.
    for fi in graph.funcs.values():
        if os.path.basename(fi.path) not in _HOT_BASENAMES:
            continue
        for call, line, col, held, _keys in fi.calls:
            if held and _is_compile_call(call_func_name(call)):
                f = emit(fi, call, line, col,
                         f"under held lock(s) {sorted(held)}")
                if f is not None:
                    yield f
