"""Framework for the invariant analyzer: findings, pragmas, file walk.

A *rule* is a function ``check(files) -> Iterable[Finding]`` where
``files`` maps absolute path -> :class:`SourceFile`.  Rules see the
whole scanned set at once so cross-file invariants (R5's wire
exhaustiveness, R1's swappable-attribute pre-pass) need no side
channel.

Suppression model — two layers, both checked in:

- **Pragmas** (per line, justified): ``# lint: disable=R2 -- reason``.
  The justification is mandatory; a pragma without one is an R0
  finding that cannot itself be suppressed.  A pragma on a
  comment-only line applies to the next line (for statements whose
  flagged line has no room).
- **Baseline** (``tests/lint_baseline.json``): a checked-in list of
  ``{rule, file, symbol}`` entries for findings that are accepted
  wholesale.  New violations are never in the baseline, so they fail
  the build.  The shipped baseline is empty — inline pragmas carry
  every accepted suppression with its one-line why.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field

RULE_DOCS = {
    "R0": "lint hygiene: unparseable file or malformed/unjustified pragma",
    "R1": "lock discipline: acquire/finally pairing, captured-binding "
          "release, recorded lock-order graph",
    "R2": "blocking call (socket/queue/join/sleep/device) inside a "
          "held-lock region, or an unbounded spin-wait polling a "
          "shared slot without backoff/deadline",
    "R3": "socket close() with no dominating shutdown() — zombie "
          "listener / wedged-reader bug class",
    "R4": "function reached from jax.jit/vmap/scan mutates self, takes "
          "locks, does I/O, or reads the wall clock",
    "R5": "wire MSG_* constants and FilterResult codes must be "
          "exhaustively handled (or fall into a fail-closed default); "
          "pack_/unpack_ struct formats and JSON fields must be "
          "symmetric across the seam",
    "R6": "threading.Thread(...) without daemon= or a local join — "
          "leaks past the conftest thread guard",
    "R7": "metric hygiene: registered-but-unreferenced metric "
          "(permanently-zero series), or Histogram.observe inside a "
          "dispatch hot loop without per-round/sample guarding",
    "R8": "recompilation hazard in jit-reached code: Python-scalar "
          "concretization (int()/float()/bool() on traced args), "
          "weak-typed scalar constants (jnp.array(0.5) without dtype), "
          "or unhashable static_argnums call sites",
    "R9": "implicit host transfer: .item()/host-numpy coercion/"
          "device_get inside a traced function, or "
          "block_until_ready / readiness spin-polls on the dispatch "
          "hot path (the fenced np.asarray readback is the one "
          "sanctioned sync point)",
    "R10": "sharding-spec consistency: shard_map/pjit in_specs arity "
           "must match the wrapped function's positional signature and "
           "out_specs its return tuple",
    "R11": "fused-attribution integrity: verdicts and verdicts_attr "
           "must consume ONE shared hit-matrix pass — the attr twin "
           "calling the plain twin (or a diverged hits helper) is a "
           "second device pass",
    "R12": "compile-on-dispatch-path: jit/trace/build/prewarm calls "
           "reachable from the dispatch/service hot loops, or made "
           "under a held lock in a hot module — recompiles belong on "
           "the policy builder thread behind a pointer-flip swap",
    "R13": "epoch-unkeyed cache in a hot module: a cache store whose "
           "key carries no epoch/generation term (and no sibling "
           "epoch store in the function), or a cache read with no "
           "epoch check anywhere in the consumer — a policy "
           "pointer-flip leaves such entries serving the old table",
    "R14": "exactly-once verdict accounting: an admit root that can "
           "bare-return without reaching an answer site or typed "
           "hand-off (silent loss), or two answer sites reachable "
           "for the same entry with no dominating exclusivity guard "
           "(answered cell / thread_round_is_shed / drain-lock pop) "
           "— the deposed-round double-reply class",
    "R15": "exception containment: a call chain that can raise out "
           "of a per-entry/per-round hot loop (dispatch/service/"
           "reasm roots) with no enclosing handler that produces a "
           "typed outcome (UNKNOWN_ERROR/SHED/demotion) — one bad "
           "entry aborts the drain and the rest leak unanswered",
    "R16": "jit shape-closure: a dispatch batch axis drawn from raw "
           "len()/.count/.shape instead of the declared power-of-two "
           "bucket universe keys a new executable per size — the "
           "abstract-trace twin (--device-contracts) audits the real "
           "serving surface against the enumerated closure",
    "R17": "snapshot round-trip symmetry: every top-level field a "
           "snapshot_* half writes must be consumed by its same-module "
           "restore_* twin (or named there as versioned-out), no "
           "hard-required restore field may go unwritten, and no "
           "snapshot half may ship without its twin — the "
           "restart-handoff drift class",
    "R18": "declared typestates: every state-field store must be a "
           "declared edge of its protocols.py transition table "
           "(mediated through advance/guard/require_edges), every "
           "counted edge's site must emit its declared metric token, "
           "and the table itself must be well-formed (reachable "
           "states, declared endpoints) — silent state flips and "
           "uncounted transitions are the bug class",
    "R19": "column-store lock discipline: every write to a declared "
           "shared numpy column family (subscript/slice/fill/np.add.at/"
           "rebind) must be reachable only with the owning lock held "
           "(lexically or at every call site), and a multi-column "
           "snapshot must be read in ONE lock trip — torn reads across "
           "separate acquisitions see half-mutated rows",
    "R20": "wire-protocol lifecycle: each MSG_* must match its "
           "declared WIRE_MESSAGES row — direction (who sends/handles "
           "it), request/reply pairing (the handler reaches a send of "
           "the declared reply), fire-and-forget consistency, gate "
           "tokens referenced on both seam ends, and native-shim enum "
           "values bit-identical on shared names",
    "R21": "parity-coverage registry: every runtime-registered framing "
           "family must declare (and actually ship) its landing bar — "
           "columnar model, host oracle, every-byte-offset parity "
           "test, bench config, and stress-mix slice — and every "
           "declared family must be registered",
    "R22": "fail-closed recorder coverage: every FAIL_CLOSED row must "
           "name a declared typestate edge (or carry a marker token) "
           "and reach a flight-recorder emit site — a mediated "
           "transition into the edge's target state, or a "
           "record_mark/broadcast_mark call carrying the token — so "
           "no declared fail-closed transition is invisible to the "
           "incident timeline and its postmortem bundle",
    "R23": "unledgered compile site: every executable-producing call "
           "(jit/prewarm/engine- and mesh-model builds) reachable from "
           "the dispatch or policy-builder roots of the hot modules "
           "must route through the device-economics ledger "
           "(record_compile/broadcast_compile or a cause_scope) so the "
           "per-cause compile census is complete and warm-churn-is-"
           "zero-compiles stays an asserted invariant",
}

# ``# lint: disable=R1,R2 -- why this is safe`` (em-dash also accepted).
_PRAGMA_OK = re.compile(
    r"#\s*lint:\s*disable=([A-Z0-9]+(?:\s*,\s*[A-Z0-9]+)*)\s*"
    r"(?:--|—)\s*(\S.*?)\s*$"
)
_PRAGMA_ANY = re.compile(r"#\s*lint:")


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    symbol: str = ""
    suppressed: bool = False
    justification: str = ""
    baselined: bool = False

    def render(self) -> str:
        where = f" [in {self.symbol}]" if self.symbol else ""
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule}: "
            f"{self.message}{where}"
        )

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "file": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "symbol": self.symbol,
            "suppressed": self.suppressed,
            "justification": self.justification,
            "baselined": self.baselined,
        }


class SourceFile:
    """One parsed file: tree, lines, and its pragma table."""

    def __init__(self, path: str, text: str,
                 content_hash: str | None = None) -> None:
        self.path = path
        self.text = text
        self.content_hash = content_hash or hashlib.sha256(
            text.encode()).hexdigest()
        self.lines = text.splitlines()
        self.tree: ast.Module | None = None
        self.parse_error: str | None = None
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as e:
            self.parse_error = f"{e.msg} (line {e.lineno})"
        # line -> (set of rule ids, justification)
        self.pragmas: dict[int, tuple[set[str], str]] = {}
        # lines carrying a pragma-looking comment that failed the format
        self.bad_pragmas: list[tuple[int, str]] = []
        self._scan_pragmas()

    def _scan_pragmas(self) -> None:
        # Scan real COMMENT tokens, not raw lines: a pragma-shaped
        # substring inside a string/docstring (e.g. this framework's
        # own docs documenting the format) must neither register a
        # suppression nor trip R0.
        try:
            toks = [
                t for t in tokenize.generate_tokens(
                    io.StringIO(self.text).readline)
                if t.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, SyntaxError, IndentationError):
            # Untokenizable ⇒ unparseable: analyze_paths already emits
            # the R0 parse error and never consults this pragma table.
            return
        for tok in toks:
            i, col = tok.start
            comment = tok.string
            if not _PRAGMA_ANY.search(comment):
                continue
            m = _PRAGMA_OK.search(comment)
            if not m:
                self.bad_pragmas.append(
                    (i, "malformed lint pragma: expected "
                        "'# lint: disable=<RULES> -- <justification>' "
                        "(justification mandatory)")
                )
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            just = m.group(2).strip()
            entry = (rules, just)
            self._merge_pragma(i, entry)
            if not self.lines[i - 1][:col].strip():
                # Comment-only line: the pragma governs the next line.
                self._merge_pragma(i + 1, entry)

    def _merge_pragma(self, line: int, entry: tuple[set[str], str]) -> None:
        old = self.pragmas.get(line)
        if old is None:
            self.pragmas[line] = (set(entry[0]), entry[1])
        else:
            old[0].update(entry[0])

    def suppression(self, line: int, rule: str) -> str | None:
        """Justification text if ``rule`` is pragma-suppressed at
        ``line``, else None."""
        got = self.pragmas.get(line)
        if got is not None and rule in got[0]:
            return got[1]
        return None


# --- shared AST helpers ---------------------------------------------------

def unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — defensive; lint must not crash
        return "<?>"


def terminal_name(expr: ast.AST) -> str:
    """Last path component of a Name/Attribute chain ('' otherwise)."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


def call_func_name(call: ast.Call) -> str:
    return terminal_name(call.func)


_LOCK_NAME = re.compile(r"(lock|mutex|mu)$", re.IGNORECASE)
_LOCK_EXTRA = {"_down_once", "_cond", "_done"}
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Mutex", "RWMutex"}


def is_lock_like_name(name: str) -> bool:
    return bool(name) and (bool(_LOCK_NAME.search(name))
                           or name in _LOCK_EXTRA)


def is_lock_ctor(expr: ast.AST) -> bool:
    return (isinstance(expr, ast.Call)
            and call_func_name(expr) in _LOCK_CTORS)


def local_assignments(func: ast.AST) -> dict[str, ast.AST]:
    """name -> last simple-RHS assignment in the function body (used to
    resolve ``lk = self._in_process_lock`` style aliases)."""
    out: dict[str, ast.AST] = {}
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                out[t.id] = node.value
    return out


def lock_terminal(expr: ast.AST, aliases: dict[str, ast.AST]) -> str:
    """Terminal lock name for a with/acquire receiver, following one
    level of local alias (``lk = self._in_process_lock``)."""
    if isinstance(expr, ast.Name) and expr.id in aliases:
        aliased = terminal_name(aliases[expr.id])
        if aliased:
            return aliased
    # ``rw.read()`` reader guard: the lock is the receiver.
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute):
        return lock_terminal(expr.func.value, aliases)
    return terminal_name(expr)


def is_lock_like_expr(expr: ast.AST, aliases: dict[str, ast.AST]) -> bool:
    name = lock_terminal(expr, aliases)
    if is_lock_like_name(name):
        return True
    if isinstance(expr, ast.Name):
        rhs = aliases.get(expr.id)
        if rhs is not None and (is_lock_ctor(rhs)
                                or is_lock_like_name(terminal_name(rhs))):
            return True
    return False


def walk_functions(tree: ast.Module):
    """Yield (funcdef, qualname, enclosing_class_or_None), outermost
    first, for every def/async def in the module."""
    def rec(node, stack, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = ".".join(stack + [child.name])
                yield child, qual, cls
                yield from rec(child, stack + [child.name], cls)
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, stack + [child.name], child)
            else:
                yield from rec(child, stack, cls)

    yield from rec(tree, [], None)


def enclosing_symbol(tree: ast.Module, line: int) -> str:
    """Qualname of the innermost function containing ``line``."""
    best = ""
    best_span = None
    for fn, qual, _cls in walk_functions(tree):
        end = getattr(fn, "end_lineno", fn.lineno)
        if fn.lineno <= line <= end:
            span = end - fn.lineno
            if best_span is None or span <= best_span:
                best, best_span = qual, span
    return best


# --- baseline -------------------------------------------------------------

def load_baseline_full(path: str) -> dict:
    """Normalized baseline: {"accepted": [entries], "max_suppressed":
    int | None}.  Accepts the legacy bare-list form (accepted entries
    only) and the ratchet form ({"accepted": [...], "max_suppressed":
    N} — the count ``--ratchet`` enforces may only decrease)."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, list):
        return {"accepted": data, "max_suppressed": None}
    if isinstance(data, dict):
        accepted = data.get("accepted", [])
        maxs = data.get("max_suppressed")
        if not isinstance(accepted, list) or not (
            maxs is None or isinstance(maxs, int)
        ):
            raise ValueError(
                f"baseline {path}: expected accepted=list, "
                f"max_suppressed=int"
            )
        return {"accepted": accepted, "max_suppressed": maxs}
    raise ValueError(f"baseline {path}: expected a JSON list or object")


def load_baseline(path: str) -> list[dict]:
    """Accepted-entry list (both baseline forms)."""
    return load_baseline_full(path)["accepted"]


def _baseline_matches(entry: dict, f: Finding) -> bool:
    if entry.get("rule") != f.rule:
        return False
    ef = entry.get("file", "")
    norm = f.path.replace(os.sep, "/")
    if ef and not norm.endswith(ef):
        return False
    sym = entry.get("symbol")
    if sym is not None and sym != f.symbol:
        return False
    return True


# --- driver ---------------------------------------------------------------

# Content-hash-keyed parse cache: parsing + tokenizing dominates a lint
# pass, and the tier-1 gate runs analyze_paths dozens of times over the
# same tree in one process (tree gate, corpus cases, CLI-contract
# tests).  Keyed by (path, sha256) so an edited file re-parses while
# everything else is reused; bounded so a long-lived process (or the
# corpus churn of a test run) cannot grow it without limit.
_SF_CACHE: dict[tuple[str, str], SourceFile] = {}
_SF_CACHE_MAX = 4096


def _load_source(path: str, text: str) -> SourceFile:
    digest = hashlib.sha256(text.encode()).hexdigest()
    key = (path, digest)
    sf = _SF_CACHE.get(key)
    if sf is None:
        if len(_SF_CACHE) >= _SF_CACHE_MAX:
            _SF_CACHE.clear()
        sf = SourceFile(path, text, content_hash=digest)
        _SF_CACHE[key] = sf
    return sf


def _collect_py(paths) -> list[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                for n in sorted(names):
                    if n.endswith(".py"):
                        out.append(os.path.join(root, n))
        elif p.endswith(".py"):
            out.append(p)
    return out


def all_rules():
    from . import (
        rules_answers,
        rules_blackbox,
        rules_cache,
        rules_compile,
        rules_contain,
        rules_device,
        rules_handoff,
        rules_jit,
        rules_ledger,
        rules_locks,
        rules_columns,
        rules_metrics,
        rules_parity,
        rules_protocol,
        rules_sockets,
        rules_typestate,
        rules_wire,
    )

    return [
        rules_locks.check_r1,
        rules_locks.check_r2,
        rules_sockets.check_r3,
        rules_jit.check_r4,
        rules_wire.check_r5,
        rules_sockets.check_r6,
        rules_metrics.check_r7,
        rules_device.check_r8,
        rules_device.check_r9,
        rules_device.check_r10,
        rules_device.check_r11,
        rules_compile.check_r12,
        rules_cache.check_r13,
        rules_answers.check_r14,
        rules_contain.check_r15,
        rules_device.check_r16,
        rules_handoff.check_r17,
        rules_typestate.check_r18,
        rules_columns.check_r19,
        rules_protocol.check_r20,
        rules_parity.check_r21,
        rules_blackbox.check_r22,
        rules_ledger.check_r23,
    ]


def _run_rule_cached(rule, files):
    """Run a rule through the content-keyed memo: identical scanned
    content re-yields a rule's findings without re-walking a single
    AST.  Findings are REBUILT fresh on every hit — analyze_paths
    mutates suppression/baseline state per run, and that state must
    never leak between runs with different baselines."""
    from .callgraph import get_graph

    memo = get_graph(files).rule_memo
    key = f"{rule.__module__}.{rule.__qualname__}"
    # Rules that consult files OUTSIDE the scanned set (the native
    # header, tests/, bench.py) expose a ``memo_extra`` callable whose
    # digest of that external state joins the memo key — otherwise an
    # edit out there would re-serve stale findings from the memo.
    extra = getattr(rule, "memo_extra", None)
    if extra is not None:
        key += ":" + extra(files)
    got = memo.get(key)
    if got is None:
        got = list(rule(files))
        memo[key] = got
    return [
        Finding(f.rule, f.path, f.line, f.col, f.message,
                symbol=f.symbol)
        for f in got
    ]


def analyze_paths(
    paths,
    rules=None,
    baseline: list[dict] | None = None,
) -> list[Finding]:
    """Run the rule set; returns ALL findings (suppressed/baselined ones
    flagged, not dropped) sorted by (path, line, rule)."""
    files: dict[str, SourceFile] = {}
    findings: list[Finding] = []
    for path in _collect_py(paths):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                text = fh.read()
        except OSError as e:
            findings.append(Finding("R0", path, 0, 0, f"unreadable: {e}"))
            continue
        sf = _load_source(path, text)
        if sf.parse_error is not None:
            findings.append(
                Finding("R0", path, 0, 0, f"parse error: {sf.parse_error}")
            )
            continue
        files[path] = sf
        for line, msg in sf.bad_pragmas:
            findings.append(Finding("R0", path, line, 0, msg))

    for rule in (rules if rules is not None else all_rules()):
        findings.extend(_run_rule_cached(rule, files))

    for f in findings:
        sf = files.get(f.path)
        if sf is None:
            continue
        if not f.symbol and sf.tree is not None:
            f.symbol = enclosing_symbol(sf.tree, f.line)
        if f.rule == "R0":
            continue  # pragma hygiene findings are unsuppressable
        just = sf.suppression(f.line, f.rule)
        if just is not None:
            f.suppressed = True
            f.justification = just
        if baseline:
            for entry in baseline:
                if _baseline_matches(entry, f):
                    f.baselined = True
                    break

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def split_findings(findings):
    """(active, suppressed) — active findings fail the build."""
    active = [f for f in findings if not f.suppressed and not f.baselined]
    muted = [f for f in findings if f.suppressed or f.baselined]
    return active, muted


def findings_to_json(findings) -> dict:
    active, muted = split_findings(findings)
    counts: dict[str, int] = {}
    for f in active:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "findings": [f.to_dict() for f in active],
        "suppressed": [f.to_dict() for f in muted],
        "counts": counts,
        "total": len(active),
    }
