"""R7 — metric hygiene.

Two halves:

- **Dead metrics.**  Every module-level ``NAME = registry.counter/
  gauge/histogram(...)`` registration in a ``metrics.py`` must be
  referenced by name somewhere OUTSIDE that file.  A registered-but-
  never-incremented metric exports a permanently-zero series: dashboards
  read it as "nothing is wrong" when the truth is "nothing is wired" —
  exactly how ``drop_count_total``/``forward_count_total`` sat dead from
  the seed until PR 4 bridged the datapath metrics map into them.
- **Hot-loop observes.**  In the dispatch hot-path modules (files named
  ``dispatch.py`` or ``service.py``), a ``Histogram.observe`` call
  lexically inside a ``for``/``while`` loop is per-ENTRY cost on the
  path the project exists to make fast.  The latency-decomposition
  contract is one observe per stage per ROUND; a loop observe must be
  sample-guarded (an enclosing ``if`` whose condition mentions
  ``sample``/``slow`` or uses a modulo) or carry a justified pragma.
- **Hot-loop flow-record emission.**  Same modules, same reasoning for
  the flow-record ring (flowlog/ring.py): ``<flowlog>.add(...)`` /
  ``<flowlog>.append(...)`` inside a loop takes the ring lock per
  ENTRY.  The emission contract is per-ROUND columnar batches
  (``add_round``/``add_entries`` — the hot loop builds a plain list,
  the lock is taken once); a per-entry append must be sample-guarded
  or carry a justified pragma.
- **Hot-loop engine feeds (the columnar-reassembly contract).**  In
  the dispatch hot-path modules plus the columnar modules
  (``reasm.py``, ``mixbench.py``), a per-entry engine call —
  ``.feed(...)`` / ``.feed_extract(...)`` / ``.settle_entry(...)`` /
  ``.take_ops(...)`` — inside a loop is exactly the ~25µs/entry slow
  lane the columnar reassembler exists to replace (BENCH_NOTES r5);
  the surviving scalar-rung loops carry justified pragmas.  In the
  columnar modules themselves ANY ``.append(...)`` in a loop is
  flagged too: per-entry list building there means the columnar
  contract regressed to the shape it was built to kill.
"""

from __future__ import annotations

import ast
import os

from .core import Finding, call_func_name, unparse

_REG_CTORS = {"counter", "gauge", "histogram"}
_HOT_BASENAMES = {"dispatch.py", "service.py"}
# Columnar-contract modules: code whose reason to exist is replacing
# per-entry Python with array passes (sidecar/reasm.py and the mixed
# bench's round builder).
_COLUMNAR_BASENAMES = {"reasm.py", "mixbench.py", "dnsengine.py"}
_FEED_ATTRS = {"feed", "feed_extract", "settle_entry", "take_ops"}


def _registrations(sf):
    """Module-level ``NAME = <recv>.counter/gauge/histogram(...)``."""
    for node in sf.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
            and call_func_name(node.value) in _REG_CTORS
        ):
            yield node.targets[0].id, node.lineno


def _referenced_names(sf) -> set[str]:
    out = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Attribute):
            out.add(node.attr)
        elif isinstance(node, ast.Name):
            out.add(node.id)
    return out


def _check_dead_metrics(files):
    reg_files = {
        path: sf for path, sf in files.items()
        if os.path.basename(path) == "metrics.py"
    }
    if not reg_files:
        return
    refs: set[str] = set()
    for path, sf in files.items():
        if path in reg_files:
            continue
        refs |= _referenced_names(sf)
    for path, sf in sorted(reg_files.items()):
        for name, line in _registrations(sf):
            if name not in refs:
                yield Finding(
                    "R7", path, line, 0,
                    f"metric {name} is registered but never referenced "
                    f"outside {os.path.basename(path)} — it exports a "
                    f"permanently-zero series (wire it or delete it)",
                    symbol=name,
                )


def _is_sample_guard(test: ast.AST) -> bool:
    """An If condition that rate-limits: mentions sample/slow or uses a
    modulo (``i % N == 0`` style)."""
    src = unparse(test).lower()
    if "sample" in src or "slow" in src:
        return True
    return any(
        isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mod)
        for n in ast.walk(test)
    )


def _check_hot_loop_observes(files):
    for path, sf in sorted(files.items()):
        if os.path.basename(path) not in (
            _HOT_BASENAMES | _COLUMNAR_BASENAMES
        ):
            continue

        findings = []

        def visit(node, loop_depth, guarded):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "observe"
                and loop_depth > 0
                and not guarded
            ):
                findings.append(
                    Finding(
                        "R7", path, node.lineno, node.col_offset,
                        "Histogram.observe inside a dispatch hot "
                        "loop — per-entry metric cost on the "
                        "verdict path; record per ROUND or guard "
                        "with sampling",
                    )
                )
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("add", "append")
                and "flowlog" in unparse(node.func.value)
                .lower().replace("_", "")
                and loop_depth > 0
                and not guarded
            ):
                findings.append(
                    Finding(
                        "R7", path, node.lineno, node.col_offset,
                        "per-entry flow-record emission inside a "
                        "dispatch hot loop — the ring lock is taken "
                        "per ENTRY; build a plain list and emit one "
                        "add_round/add_entries per ROUND (or guard "
                        "with sampling)",
                    )
                )
            if isinstance(node, ast.If) and _is_sample_guard(node.test):
                # Only the guard's BODY is rate-limited; the else
                # branch runs on every un-sampled iteration.
                for child in node.body:
                    visit(child, loop_depth, True)
                for child in node.orelse:
                    visit(child, loop_depth, guarded)
                for child in (node.test,):
                    visit(child, loop_depth, guarded)
                return
            if isinstance(node, (ast.For, ast.While)):
                # A guard OUTSIDE the loop does not rate-limit the
                # per-entry observes inside it — the guard must sit
                # between the loop and the observe.
                for child in ast.iter_child_nodes(node):
                    visit(child, loop_depth + 1, False)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, loop_depth, guarded)

        visit(sf.tree, 0, False)
        yield from findings


def _check_hot_loop_feeds(files):
    """Per-entry engine feed/settle calls (and, in the columnar
    modules, ANY ``.append``) inside loops — the scalar slow-lane
    shape the columnar reassembler replaces.  Surviving scalar-rung
    loops carry justified pragmas; everything else is a regression."""
    for path, sf in sorted(files.items()):
        base = os.path.basename(path)
        hot = base in _HOT_BASENAMES
        columnar = base in _COLUMNAR_BASENAMES
        if not (hot or columnar):
            continue

        findings = []

        def visit(node, loop_depth, guarded):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and loop_depth > 0
                and not guarded
            ):
                attr = node.func.attr
                if attr in _FEED_ATTRS:
                    findings.append(
                        Finding(
                            "R7", path, node.lineno, node.col_offset,
                            f"per-entry engine .{attr}() inside a "
                            "hot loop — the ~25µs/entry slow-lane "
                            "shape the columnar reassembler "
                            "(sidecar/reasm.py) replaces; batch the "
                            "round columnar, or justify the scalar "
                            "rung with a pragma",
                        )
                    )
                elif columnar and attr == "append":
                    findings.append(
                        Finding(
                            "R7", path, node.lineno, node.col_offset,
                            "per-entry .append() in a columnar "
                            "module loop — reasm/mixbench exist to "
                            "replace per-entry list building with "
                            "array passes; vectorize it or justify "
                            "with a pragma",
                        )
                    )
            if isinstance(node, ast.If) and _is_sample_guard(node.test):
                for child in node.body:
                    visit(child, loop_depth, True)
                for child in node.orelse:
                    visit(child, loop_depth, guarded)
                for child in (node.test,):
                    visit(child, loop_depth, guarded)
                return
            if isinstance(node, (ast.For, ast.While)):
                for child in ast.iter_child_nodes(node):
                    visit(child, loop_depth + 1, False)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, loop_depth, guarded)

        visit(sf.tree, 0, False)
        yield from findings


def check_r7(files):
    yield from _check_dead_metrics(files)
    yield from _check_hot_loop_observes(files)
    yield from _check_hot_loop_feeds(files)
