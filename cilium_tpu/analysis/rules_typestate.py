"""R18 — declared typestates: every state-field store is a declared,
mediated edge and every edge's site emits its declared typed outcome.

The transition tables live in ``analysis/protocols.py`` as
``Typestate(...)`` declarations the RUNTIME imports (one definition:
delete an edge and the runtime raises at the transition while this
pass flags the now-invalid site).  This rule extracts every Typestate
declaration from the scanned set — so a two-file corpus twin carrying
its own table exercises the same machinery the real tree does — and
proves three layers:

- **Table well-formedness**: the initial state is declared, every edge
  endpoint is declared, and every non-initial state keeps at least one
  in-edge (a state whose in-edges were all deleted is unreachable —
  every ``advance`` toward it is statically dead and the runtime would
  raise on the first attempt).
- **Store mediation**: an assignment to a bound state field (``attr``
  kind: ``obj.field = ...``; ``column``: ``self.field[...] = ...`` /
  ``self.field.fill(...)``; ``key``: ``row["field"] = ...``) must take
  its RHS from ``<PROTO>.advance/guard/require_edges(...)`` — the one
  expression shape that validates the edge at runtime.  The only bare
  store allowed is ``__init__`` assigning the declared initial state.
- **Edge + outcome validation at call sites**: every mediation call's
  named states must be declared, every named edge must exist, and when
  the declared outcome of the edge(s) is typed (non-None), at least
  one acceptable outcome token (metric class, counter attribute, or
  literal) must appear in the enclosing function — a silent transition
  on a counted edge is the hand-found bug class PRs 11-17 kept
  shipping.

Binding is conservative: a store binds to a protocol only when the
file also references the protocol object or one of its state
constants, so an unrelated ``self.state = ...`` in a module that never
touches the protocol stays out of scope (precision over recall).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field as _dc_field

from .core import Finding, terminal_name, walk_functions

_MEDIATORS = {"advance", "guard", "require_edges"}


@dataclass
class _Proto:
    obj: str  # assigned object name (e.g. SESSION_PROTOCOL)
    path: str
    line: int
    col: int
    name: str = ""
    owner: str = ""
    field: str = ""
    kind: str = "attr"
    states: tuple = ()
    initial: object = None
    edges: dict = _dc_field(default_factory=dict)  # (frm, to) -> outcome
    values: dict = _dc_field(default_factory=dict)  # state -> stored value
    state_names: set = _dc_field(default_factory=set)  # constant NAMES


def _const_pool(tree: ast.Module) -> dict[str, object]:
    """Module-level ``NAME = <str|int constant>`` assignments."""
    pool: dict[str, object] = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, (str, int))
                and not isinstance(node.value.value, bool)):
            pool[node.targets[0].id] = node.value.value
    return pool


def _resolve(expr: ast.AST, pool: dict) -> object:
    """Constant value of a Name (via the pool) or Constant; else a
    sentinel."""
    if isinstance(expr, ast.Constant):
        return expr.value
    if isinstance(expr, ast.Name):
        return pool.get(expr.id, _UNRESOLVED)
    return _UNRESOLVED


_UNRESOLVED = object()


def _resolve_states(expr: ast.AST, pool: dict) -> list:
    """State names an expression may take: Constant/Name resolve to
    one; an IfExp contributes both branches (the mesh ladder's
    ``FULL if target is full else RESHAPED`` site)."""
    if isinstance(expr, ast.IfExp):
        return (_resolve_states(expr.body, pool)
                + _resolve_states(expr.orelse, pool))
    got = _resolve(expr, pool)
    return [] if got is _UNRESOLVED else [got]


def _outcome_of(expr: ast.AST, pool: dict) -> object:
    """Declared outcome: None, a token string, or a tuple of tokens."""
    if isinstance(expr, ast.Constant):
        return expr.value  # str or None
    if isinstance(expr, (ast.Tuple, ast.List)):
        toks = []
        for e in expr.elts:
            got = _resolve(e, pool)
            if isinstance(got, str):
                toks.append(got)
        return tuple(toks)
    got = _resolve(expr, pool)
    return got if isinstance(got, str) else None


def _kw(call: ast.Call, name: str) -> ast.AST | None:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _pools(files) -> dict[str, dict]:
    """Per-path constant pools, each merged over the whole scanned
    set with the file's OWN module-level constants taking precedence:
    the runtime imports its state constants from protocols.py, so a
    consumer file resolves SESSION_ACTIVE through the defining file's
    pool (and a corpus twin redefining the name locally wins)."""
    own = {path: _const_pool(sf.tree) for path, sf in files.items()}
    merged_all: dict[str, object] = {}
    for path in sorted(own):
        merged_all.update(own[path])
    out: dict[str, dict] = {}
    for path, pool in own.items():
        m = dict(merged_all)
        m.update(pool)
        out[path] = m
    return out


def _extract_protocols(files, pools) -> tuple[list[_Proto], list[Finding]]:
    """Every ``NAME = Typestate(...)`` declaration in the scanned set,
    plus the well-formedness findings for malformed tables."""
    protos: list[_Proto] = []
    bad: list[Finding] = []
    for path, sf in sorted(files.items()):
        pool = pools[path]
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and terminal_name(node.value.func) == "Typestate"):
                continue
            call = node.value
            p = _Proto(obj=node.targets[0].id, path=path,
                       line=node.lineno, col=node.col_offset)
            name_e = _kw(call, "name")
            p.name = (_resolve(name_e, pool)
                      if name_e is not None else p.obj)
            if not isinstance(p.name, str):
                p.name = p.obj
            for attr in ("owner", "field", "kind"):
                e = _kw(call, attr)
                got = _resolve(e, pool) if e is not None else _UNRESOLVED
                if isinstance(got, str):
                    setattr(p, attr, got)
            states_e = _kw(call, "states")
            states: list = []
            if isinstance(states_e, (ast.Tuple, ast.List)):
                for e in states_e.elts:
                    got = _resolve(e, pool)
                    if got is _UNRESOLVED:
                        bad.append(Finding(
                            "R18", path, e.lineno, e.col_offset,
                            f"typestate {p.name!r}: unresolvable state "
                            f"expression (states must be string "
                            f"constants or module-level constant names)",
                        ))
                        continue
                    states.append(got)
                    if isinstance(e, ast.Name):
                        p.state_names.add(e.id)
            p.states = tuple(states)
            init_e = _kw(call, "initial")
            p.initial = (_resolve(init_e, pool)
                         if init_e is not None else _UNRESOLVED)
            if isinstance(init_e, ast.Name):
                p.state_names.add(init_e.id)
            edges_e = _kw(call, "edges")
            if isinstance(edges_e, ast.Dict):
                for k, v in zip(edges_e.keys, edges_e.values):
                    if not (isinstance(k, (ast.Tuple, ast.List))
                            and len(k.elts) == 2):
                        continue
                    frm = _resolve(k.elts[0], pool)
                    to = _resolve(k.elts[1], pool)
                    if frm is _UNRESOLVED or to is _UNRESOLVED:
                        bad.append(Finding(
                            "R18", path, k.lineno, k.col_offset,
                            f"typestate {p.name!r}: unresolvable edge "
                            f"endpoint",
                        ))
                        continue
                    for e in k.elts:
                        if isinstance(e, ast.Name):
                            p.state_names.add(e.id)
                    p.edges[(frm, to)] = _outcome_of(v, pool)
            values_e = _kw(call, "values")
            if isinstance(values_e, ast.Dict):
                for k, v in zip(values_e.keys, values_e.values):
                    ks = _resolve(k, pool)
                    vs = _resolve(v, pool)
                    if ks is not _UNRESOLVED and vs is not _UNRESOLVED:
                        p.values[ks] = vs
            else:
                p.values = {s: s for s in p.states}
            # -- table well-formedness --------------------------------
            sset = set(p.states)
            if p.initial is _UNRESOLVED or p.initial not in sset:
                bad.append(Finding(
                    "R18", path, p.line, p.col,
                    f"typestate {p.name!r}: initial state is not in "
                    f"the declared state set",
                ))
            for (frm, to) in sorted(p.edges, key=repr):
                if frm not in sset or to not in sset:
                    bad.append(Finding(
                        "R18", path, p.line, p.col,
                        f"typestate {p.name!r}: edge ({frm!r} -> "
                        f"{to!r}) names an undeclared state",
                    ))
            reachable = {to for (_f, to) in p.edges}
            for s in p.states:
                if s != p.initial and s not in reachable:
                    bad.append(Finding(
                        "R18", path, p.line, p.col,
                        f"typestate {p.name!r}: state {s!r} has no "
                        f"in-edge — unreachable (every advance toward "
                        f"it is statically dead and would raise at "
                        f"runtime)",
                    ))
            protos.append(p)
    return protos, bad


def _file_identifiers(tree: ast.Module) -> set[str]:
    ids: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            ids.add(node.id)
        elif isinstance(node, ast.Attribute):
            ids.add(node.attr)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                ids.add(a.asname or a.name.split(".")[0])
    return ids


def _fn_tokens(fn: ast.AST) -> set[str]:
    """Outcome-token pool of a function body: attribute names,
    bare names, and string literals (a typed metric class, a counter
    attribute, or a reason label all count as emitting the outcome)."""
    toks: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            toks.add(node.id)
        elif isinstance(node, ast.Attribute):
            toks.add(node.attr)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            toks.add(node.value)
    return toks


def _own_nodes(fn: ast.AST):
    """Walk a function body without descending into nested defs (each
    nested def is visited as its own function by walk_functions)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _mediation_call(expr: ast.AST, objs: set[str]) -> tuple | None:
    """(obj_name, method, call) when expr is
    ``<declared protocol>.advance/guard/require_edges(...)``."""
    if (isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr in _MEDIATORS):
        recv = terminal_name(expr.func.value)
        if recv in objs:
            return recv, expr.func.attr, expr
    return None


def _store_matches(node: ast.AST, proto: _Proto):
    """(rhs, line, col) when ``node`` stores to this protocol's field
    in its declared AST shape; None otherwise."""
    if proto.kind == "attr":
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and t.attr == proto.field):
                    return node.value, node.lineno, node.col_offset
        if (isinstance(node, ast.AugAssign)
                and isinstance(node.target, ast.Attribute)
                and node.target.attr == proto.field):
            return node.value, node.lineno, node.col_offset
    elif proto.kind == "column":
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and t.value.attr == proto.field):
                    return node.value, node.lineno, node.col_offset
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "fill"
                and isinstance(node.func.value, ast.Attribute)
                and node.func.value.attr == proto.field
                and node.args):
            return node.args[0], node.lineno, node.col_offset
    elif proto.kind == "key":
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.slice, ast.Constant)
                        and t.slice.value == proto.field):
                    return node.value, node.lineno, node.col_offset
    return None


def _check_mediation_args(proto: _Proto, method: str, call: ast.Call,
                          pool: dict, fn_tokens: set, path: str):
    """Edge/state validation + outcome-token requirement for one
    mediation call against its protocol."""
    sset = set(proto.states)
    line, col = call.lineno, call.col_offset

    def token_required(edges_used: list):
        """Yield a finding when every possible edge is typed and no
        acceptable token appears in the enclosing function."""
        outcomes = [proto.edges[e] for e in edges_used
                    if e in proto.edges]
        if not outcomes or any(o is None for o in outcomes):
            return  # a declared-silent edge is possible: no demand
        acceptable: set[str] = set()
        for o in outcomes:
            acceptable.update((o,) if isinstance(o, str) else o)
        if not acceptable & fn_tokens:
            yield Finding(
                "R18", path, line, col,
                f"typestate {proto.name!r}: transition site emits "
                f"none of its declared outcome token(s) "
                f"{sorted(acceptable)} — a silent transition on a "
                f"counted edge",
            )

    if method == "advance":
        if len(call.args) < 2:
            return
        for to in _resolve_states(call.args[1], pool):
            if to not in sset:
                yield Finding(
                    "R18", path, line, col,
                    f"typestate {proto.name!r}: advance to undeclared "
                    f"state {to!r}",
                )
                continue
            in_edges = [e for e in proto.edges if e[1] == to]
            if not in_edges:
                yield Finding(
                    "R18", path, line, col,
                    f"typestate {proto.name!r}: advance to state "
                    f"{to!r} which has NO declared in-edge — this "
                    f"site always raises at runtime",
                )
                continue
            yield from token_required(in_edges)
    elif method == "guard":
        if len(call.args) < 2:
            return
        frms = _resolve_states(call.args[0], pool)
        tos = _resolve_states(call.args[1], pool)
        for frm in frms:
            for to in tos:
                if frm not in sset or to not in sset:
                    yield Finding(
                        "R18", path, line, col,
                        f"typestate {proto.name!r}: guard names "
                        f"undeclared state ({frm!r} -> {to!r})",
                    )
                elif (frm, to) not in proto.edges:
                    yield Finding(
                        "R18", path, line, col,
                        f"typestate {proto.name!r}: guard names "
                        f"undeclared edge {frm!r} -> {to!r} — this "
                        f"site always raises at runtime",
                    )
                else:
                    yield from token_required([(frm, to)])
    elif method == "require_edges":
        if len(call.args) < 2:
            return
        frms_e = call.args[0]
        frms: list = []
        if isinstance(frms_e, (ast.Tuple, ast.List)):
            for e in frms_e.elts:
                frms.extend(_resolve_states(e, pool))
        tos = _resolve_states(call.args[1], pool)
        for to in tos:
            for frm in frms:
                if frm not in sset or to not in sset:
                    yield Finding(
                        "R18", path, line, col,
                        f"typestate {proto.name!r}: require_edges "
                        f"names undeclared state ({frm!r} -> {to!r})",
                    )
                elif (frm, to) not in proto.edges:
                    yield Finding(
                        "R18", path, line, col,
                        f"typestate {proto.name!r}: require_edges "
                        f"names undeclared edge {frm!r} -> {to!r} — "
                        f"this site always raises at runtime",
                    )
                else:
                    yield from token_required([(frm, to)])


def check_r18(files):
    pools = _pools(files)
    protos, bad = _extract_protocols(files, pools)
    yield from bad
    if not protos:
        return
    objs = {p.obj for p in protos}
    by_obj = {p.obj: p for p in protos}

    for path, sf in sorted(files.items()):
        pool = pools[path]
        ids = _file_identifiers(sf.tree)
        bound = [
            p for p in protos
            if p.obj in ids or (p.state_names & ids)
        ]
        if not bound:
            continue
        for fn, qual, _cls in walk_functions(sf.tree):
            if isinstance(fn, ast.Lambda):
                continue
            tokens = None  # computed lazily: most functions need none
            for node in _own_nodes(fn):
                # -- mediation-call validation (stores AND bare
                #    validation calls, e.g. the derived mesh ladder) --
                med = _mediation_call(node, objs)
                if med is not None:
                    obj, method, call = med
                    if tokens is None:
                        tokens = _fn_tokens(fn)
                    yield from _check_mediation_args(
                        by_obj[obj], method, call, pool, tokens, path
                    )
                # -- store mediation ---------------------------------
                candidates = []
                hit = None
                for p in bound:
                    got = _store_matches(node, p)
                    if got is not None:
                        candidates.append(p)
                        hit = got
                if not candidates:
                    continue
                rhs, line, col = hit
                ok = False
                for p in candidates:
                    med = _mediation_call(rhs, {p.obj})
                    if med is not None:
                        ok = True
                        break
                    if fn.name == "__init__":
                        init_vals = {
                            p.values.get(p.initial), p.initial,
                        }
                        got = _resolve(rhs, pool)
                        if got is not _UNRESOLVED and got in init_vals:
                            ok = True
                            break
                if not ok:
                    names = ", ".join(
                        sorted(p.name for p in candidates)
                    )
                    yield Finding(
                        "R18", path, line, col,
                        f"bare store to typestate field "
                        f"{candidates[0].field!r} (protocol {names}): "
                        f"transitions must route through "
                        f"<PROTOCOL>.advance/guard/require_edges so "
                        f"the declared edge set is enforced at "
                        f"runtime (only __init__ may assign the "
                        f"initial state directly)",
                        symbol=qual,
                    )
