"""Device-contract verification by ABSTRACT tracing (R8-R11, no device).

The AST half of R8-R11 (``rules_device.py``) pattern-matches hazards;
this half proves the contracts on the REAL verdict models by tracing
them abstractly — ``jax.eval_shape`` / ``jax.make_jaxpr`` over
``ShapeDtypeStruct`` inputs, which runs under ``JAX_PLATFORMS=cpu``,
allocates no buffers, executes no model, and needs no TPU:

- **R8** — the model traces at all on abstract values (any Python
  branch on traced data would raise ConcretizationTypeError), the
  jaxpr is IDENTICAL across two traces (no wall-clock/rng/iteration-
  order dependence — the recompile-storm seed), and no output aval is
  weak-typed (weak types key per-caller-dtype executables downstream).
- **R9** — the traced jaxpr contains no host-callback or transfer
  primitives anywhere in its (recursive) equation tree: a ``.item()``
  or np coercion on a traced value would have failed the trace, and a
  smuggled ``pure_callback``/``device_put`` is a host round-trip the
  dispatch round would pay per batch.
- **R10** — every sharded step in ``parallel/rulesharding.py`` traces
  under 1x1, 1x2, 2x1 and 2x2 (flows, rules) CPU meshes: shard_map
  validates in_specs/out_specs against the function's actual arity and
  rank at trace time, so a drifted spec fails HERE instead of at first
  trace on a real multi-chip mesh.  The gate also pins stacked-leaf
  shard arity (an unbalanced/unpadded shard stack), forbids transfer
  primitives inside the stepped bodies, and requires trace determinism
  per mesh plus a shard-count-independent primitive set.
- **R11** — ``verdicts_attr``'s jaxpr is the verdict jaxpr plus a
  bounded attribution epilogue: output arity 4 with an int32 rule
  row, and an equation count within ``ATTR_EXTRA_EQNS`` of the plain
  twin — a second hit-matrix pass would ~double it.

Import of jax (and the models) happens inside the entry point so the
plain AST lint never pays for it; ``bin/cilium-lint
--device-contracts`` and tests/test_device_contracts.py are the
consumers.
"""

from __future__ import annotations

from .core import Finding

# An attribution epilogue is argmax + where + a handful of selects;
# a SECOND hit-matrix pass is dozens-to-hundreds of equations on these
# models.  The bound is deliberately loose enough for op-by-op jax
# version drift and tight enough that a doubled pass cannot hide.
ATTR_EXTRA_EQNS = 12

# Primitives that mean "host round-trip" when they appear inside a
# traced verdict computation.
_FORBIDDEN_PRIM_SUBSTRINGS = ("callback", "device_put", "infeed",
                              "outfeed")

_BATCH = 8
_WIDTH = 128


def _iter_eqns(jaxpr):
    """Every equation in a (closed) jaxpr, recursing into sub-jaxprs
    (pjit/closed_call/scan/cond carry theirs in params)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _sub_jaxprs(v):
    import jax.core as jcore

    if isinstance(v, jcore.ClosedJaxpr):
        yield v.jaxpr
    elif isinstance(v, jcore.Jaxpr):
        yield v
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _sub_jaxprs(x)


def _model_cases():
    """Tiny-but-representative models per engine family, each touching
    every tier the builders have (literal, prefix, regex, header)."""
    from ..models.base import SeamProbe
    from ..models.dns import build_dns_model_from_rows
    from ..models.http import build_http_model
    from ..models.r2d2 import build_r2d2_model_from_rows
    from ..policy.api import PortRuleHTTP
    from ..proxylib.parsers.dns import DnsRule

    http = build_http_model([
        (frozenset(), PortRuleHTTP(method="GET", path="/api/v1/.*")),
        (frozenset({7}), PortRuleHTTP(method="GET|HEAD",
                                      path="/x/[a-z]+",
                                      host="example[.]com")),
        (frozenset({3}), PortRuleHTTP()),
    ])
    r2d2 = build_r2d2_model_from_rows([
        (frozenset(), "OPEN", "/etc/.*"),
        (frozenset({3}), "", "docs/[a-z]+[.]txt"),
        (frozenset({3, 9}), "RETR", ""),
    ])
    dns = build_dns_model_from_rows([
        (frozenset(), DnsRule(name="www.example.com")),
        (frozenset({3}), DnsRule(pattern="*.svc.cluster.local")),
        (frozenset({3, 9}), DnsRule(regex="internal[.](a|b)")),
        (frozenset({7}), None),
    ])
    return [
        ("http", "cilium_tpu/models/http.py", http),
        ("r2d2", "cilium_tpu/models/r2d2.py", r2d2),
        ("dns", "cilium_tpu/models/dns.py", dns),
        ("seam_probe", "cilium_tpu/models/base.py", SeamProbe()),
    ]


def _abstract_args():
    import jax
    import jax.numpy as jnp

    return (
        jax.ShapeDtypeStruct((_BATCH, _WIDTH), jnp.uint8),
        jax.ShapeDtypeStruct((_BATCH,), jnp.int32),
        jax.ShapeDtypeStruct((_BATCH,), jnp.int32),
    )


def _check_model(name, path, model):
    import jax

    data, lengths, remotes = _abstract_args()
    findings = []

    def fail(rule, msg):
        findings.append(Finding(
            rule, path, 0, 0, f"[device-contract:{name}] {msg}",
            symbol=name,
        ))

    # R8: abstract trace succeeds, twice, identically.
    try:
        jx1 = jax.make_jaxpr(model.__call__)(data, lengths, remotes)
        jx2 = jax.make_jaxpr(model.__call__)(data, lengths, remotes)
    except Exception as e:  # noqa: BLE001 — any trace failure is the finding
        fail("R8", f"verdict model failed to trace abstractly "
                   f"(Python branching on traced data?): {e!r}")
        return findings
    if str(jx1) != str(jx2):
        fail("R8", "two traces of the verdict model produced "
                   "DIFFERENT jaxprs — trace-time nondeterminism "
                   "(wall clock / rng / iteration order) and a "
                   "recompile per dispatch on the hot path")
    for i, aval in enumerate(jx1.out_avals):
        if getattr(aval, "weak_type", False):
            fail("R8", f"verdict output {i} has weak_type=True: a "
                       f"Python-scalar constant leaked into the "
                       f"output dtype lattice — downstream consumers "
                       f"key a separate executable per caller dtype "
                       f"mix")

    # R9: no host-callback / transfer primitives in the whole tree.
    for eqn in _iter_eqns(jx1.jaxpr):
        pname = eqn.primitive.name
        if any(s in pname for s in _FORBIDDEN_PRIM_SUBSTRINGS):
            fail("R9", f"traced verdict computation contains host "
                       f"round-trip primitive {pname!r} — a device->"
                       f"host sync inside the dispatch round")

    # R11: fused attribution — arity-4, int32 rule row, bounded
    # equation delta vs the plain twin.
    if not hasattr(model, "verdicts_attr"):
        return findings
    try:
        jxa = jax.make_jaxpr(model.verdicts_attr)(data, lengths, remotes)
    except Exception as e:  # noqa: BLE001
        fail("R11", f"verdicts_attr failed to trace abstractly: {e!r}")
        return findings
    if len(jxa.out_avals) != 4:
        fail("R11", f"verdicts_attr returns {len(jxa.out_avals)} "
                    f"outputs, contract is 4 (complete, len, allow, "
                    f"rule)")
    else:
        rule_aval = jxa.out_avals[3]
        if str(rule_aval.dtype) != "int32":
            fail("R11", f"attribution rule row dtype is "
                        f"{rule_aval.dtype}, contract is int32 (the "
                        f"wire packs <i4)")
    n_plain = sum(1 for _ in _iter_eqns(jx1.jaxpr))
    n_attr = sum(1 for _ in _iter_eqns(jxa.jaxpr))
    if n_attr > n_plain + ATTR_EXTRA_EQNS:
        fail("R11", f"verdicts_attr traces to {n_attr} equations vs "
                    f"{n_plain} for the plain verdict (+{ATTR_EXTRA_EQNS} "
                    f"allowed): attribution is recomputing the hit "
                    f"matrix — a SECOND device pass the parity tests "
                    f"cannot see")
    for eqn in _iter_eqns(jxa.jaxpr):
        pname = eqn.primitive.name
        if any(s in pname for s in _FORBIDDEN_PRIM_SUBSTRINGS):
            fail("R9", f"attributed verdict computation contains "
                       f"host round-trip primitive {pname!r}")
    return findings


# Mesh aspect ratios the R10 gate traces every sharded step under —
# both axes exercised alone and together so a spec that only works
# when an axis is trivial cannot pass.  The 4-wide rows cover the
# flow widths the reshape ladder lands on (4 -> 2 -> 1) and the
# >2-wide extents ROADMAP 5b's uncapped flow sharding serves; rows
# the local device count cannot fill are skipped as before.
_SHARD_MESHES = ((1, 1), (1, 2), (2, 1), (2, 2), (4, 1), (4, 2))

_SHARD_PATH = "cilium_tpu/parallel/rulesharding.py"


def check_stacked_model(stacked, mesh) -> list[str]:
    """R10 structural half: every leaf of a stacked shard model must
    lead with a shard dim equal to the mesh's RULE_AXIS extent — the
    split-balanced + pad_tables contract.  A builder that skipped the
    cross-shard padding (or stacked for the wrong shard count) shows
    up here before shard_map ever traces.  Returns problem strings."""
    import jax

    from ..parallel.mesh import RULE_AXIS

    n = mesh.shape[RULE_AXIS]
    probs = []
    for i, leaf in enumerate(jax.tree_util.tree_leaves(stacked)):
        shape = tuple(getattr(leaf, "shape", ()))
        if not shape or shape[0] != n:
            probs.append(
                f"stacked leaf {i} shape {shape} does not lead with "
                f"the RULE_AXIS shard dim {n} (unbalanced/unpadded "
                f"shard stack)"
            )
    return probs


def _step_jaxpr_findings(name: str, jx, fail) -> None:
    """Shared per-step jaxpr checks: no host-transfer primitives
    anywhere inside the stepped body, and a second trace must be
    byte-identical (trace-time nondeterminism would recompile per
    shard-count/mesh in production)."""
    for eqn in _iter_eqns(jx.jaxpr):
        pname = eqn.primitive.name
        if any(s in pname for s in _FORBIDDEN_PRIM_SUBSTRINGS):
            fail(f"[device-contract:{name}] stepped body contains "
                 f"host round-trip primitive {pname!r} — a device->"
                 f"host sync inside the mesh round")


def _check_sharded():
    """R10: every sharded step in ``parallel/rulesharding.py`` traces
    under every ``_SHARD_MESHES`` (flows, rules) CPU mesh — shard_map
    validates in_specs/out_specs against the step functions' actual
    arity and rank at trace time, so a drifted spec fails HERE instead
    of at first trace on a real multi-chip mesh.  On top of the trace:
    stacked-leaf shard arity (the unbalanced-pad pin), no transfer
    primitives inside the stepped bodies, repeat-trace jaxpr
    determinism per mesh, and a shard-count-independent primitive set
    (the computation's SHAPE may change with the mesh; its structure
    must not).  Meshes the local device count cannot fill are skipped
    (the 1x1 floor always runs)."""
    import jax
    import numpy as np

    from ..kafka.request import RequestMessage
    from ..models.dns import (
        build_dns_model_from_rows,
        dns_verdicts,
        dns_verdicts_attr,
    )
    from ..models.kafka import build_kafka_model, encode_requests
    from ..models.r2d2 import (
        build_r2d2_model_from_rows,
        r2d2_verdicts,
        r2d2_verdicts_attr,
    )
    from ..proxylib.parsers.dns import DnsRule
    from ..parallel import rulesharding
    from ..parallel.mesh import flow_mesh
    from ..policy.api import PortRuleKafka

    findings = []

    def fail(msg):
        findings.append(Finding("R10", _SHARD_PATH, 0, 0, msg))

    model = build_r2d2_model_from_rows([
        (frozenset(), "OPEN", "/etc/.*"),
        (frozenset({3}), "", "docs/[a-z]+"),
    ])
    dmodel = build_dns_model_from_rows([
        (frozenset(), DnsRule(name="www.example.com")),
        (frozenset({3}), DnsRule(pattern="*.example.com")),
    ])
    kr = PortRuleKafka(topic="orders")
    kr.sanitize()
    kmodel = build_kafka_model([(frozenset(), kr)])
    kbatch = encode_requests(
        [RequestMessage(0, 2, 1, "c", ["orders"], parsed=True)] * _BATCH
    )
    data, lengths, remotes = _abstract_args()
    devices = jax.devices()
    prim_sets: dict[str, dict] = {}
    traced_any = False
    for n_flow, n_rule in _SHARD_MESHES:
        if n_flow * n_rule > len(devices):
            continue
        try:
            mesh = flow_mesh(n_flow=n_flow, n_rule=n_rule,
                             devices=devices[: n_flow * n_rule])
        except Exception as e:  # noqa: BLE001
            fail(f"[device-contract:mesh] cannot build the "
                 f"{n_flow}x{n_rule} CPU mesh: {e!r}")
            continue
        traced_any = True
        stacked = rulesharding._stack_models([model] * n_rule)
        for prob in check_stacked_model(stacked, mesh):
            fail(f"[device-contract:stacked@{n_flow}x{n_rule}] {prob}")
        dstacked = rulesharding._stack_models([dmodel] * n_rule)
        for prob in check_stacked_model(dstacked, mesh):
            fail(f"[device-contract:dns-stacked@{n_flow}x{n_rule}] "
                 f"{prob}")
        offsets = rulesharding.shard_offsets(2, n_rule)
        cases = (
            ("sharded_verdict_step",
             rulesharding.sharded_verdict_step(mesh, r2d2_verdicts),
             (stacked, data, lengths, remotes), 3),
            ("sharded_verdict_step_attr",
             rulesharding.sharded_verdict_step_attr(
                 mesh, r2d2_verdicts_attr),
             (stacked, offsets, data, lengths, remotes), 4),
            ("sharded_dns_step",
             rulesharding.sharded_verdict_step(mesh, dns_verdicts),
             (dstacked, data, lengths, remotes), 3),
            ("sharded_dns_step_attr",
             rulesharding.sharded_verdict_step_attr(
                 mesh, dns_verdicts_attr),
             (dstacked, offsets, data, lengths, remotes), 4),
            ("sharded_kafka_step",
             rulesharding.sharded_kafka_step(mesh),
             (rulesharding._stack_models([kmodel] * n_rule),
              kbatch, np.ones(_BATCH, np.int32)), 1),
        )
        for name, step, args, n_out in cases:
            tag = f"{name}@{n_flow}x{n_rule}"
            try:
                jx1 = jax.make_jaxpr(step)(*args)
                jx2 = jax.make_jaxpr(step)(*args)
            except Exception as e:  # noqa: BLE001
                fail(f"[device-contract:{tag}] failed to trace — "
                     f"in_specs/out_specs drifted from the step "
                     f"function's signature or shard arity: {e!r}")
                continue
            outs = jx1.out_avals
            if len(outs) != n_out:
                fail(f"[device-contract:{tag}] expected {n_out} "
                     f"outputs, got {len(outs)}")
            if name == "sharded_verdict_step_attr" and len(outs) == 4 \
                    and str(outs[3].dtype) != "int32":
                fail(f"[device-contract:{tag}] global first-match "
                     f"rule row dtype is {outs[3].dtype}, contract "
                     f"is int32")
            if str(jx1) != str(jx2):
                fail(f"[device-contract:{tag}] two traces produced "
                     f"DIFFERENT jaxprs — trace-time nondeterminism "
                     f"recompiles per mesh in production")
            _step_jaxpr_findings(tag, jx1, fail)
            prims = frozenset(
                eqn.primitive.name for eqn in _iter_eqns(jx1.jaxpr)
            )
            prev = prim_sets.setdefault(name, {})
            for other, oprims in prev.items():
                if prims != oprims:
                    fail(f"[device-contract:{name}] primitive set "
                         f"differs between meshes {other} and "
                         f"{n_flow}x{n_rule}: "
                         f"{sorted(prims ^ oprims)} — the stepped "
                         f"computation's structure must not depend "
                         f"on the shard count")
            prev[f"{n_flow}x{n_rule}"] = prims
    if not traced_any:
        fail("[device-contract:mesh] no (flows, rules) mesh could be "
             "built from the available devices")
    return findings


def check_reshape_ladder(build=None) -> list[Finding]:
    """R10 reshape half: every DEGRADED rung the width ladder can land
    on (lose a chip, reshape over the survivors — flow extent 4 -> 2
    -> 1 at a preserved-or-halved rule extent) assembles through
    ``mesh_model_from_family_rows`` and traces with the SAME structure
    as full width: stacked-leaf shard arity against the rung's
    RULE_AXIS, a retained single-chip fallback twin (the next
    demotion's landing rung), repeat-trace jaxpr determinism, no
    host-transfer primitives, and a width-INDEPENDENT primitive set —
    a reshape may change shapes, never the stepped computation.
    ``build`` is the assembly seam under audit, injectable so the
    sensitivity unit can pin that a broken reshape model fails here.
    Rungs the local device count cannot fill are skipped; a single
    device has no mesh rungs at all (empty findings)."""
    import jax

    from ..parallel import rulesharding
    from ..parallel.mesh import (
        FLOW_AXIS,
        RULE_AXIS,
        flow_mesh,
        reshape_mesh,
    )
    from ..proxylib.parsers.dns import DnsRule

    if build is None:
        build = rulesharding.mesh_model_from_family_rows

    findings: list[Finding] = []

    def fail(msg):
        findings.append(Finding("R10", _SHARD_PATH, 0, 0, msg))

    family_rows = {
        "r2d2": [
            (frozenset(), "OPEN", "/etc/.*"),
            (frozenset({3}), "", "docs/[a-z]+"),
            (frozenset({7}), "READ", "/pub/.*"),
        ],
        "dns": [
            (frozenset(), DnsRule(name="www.example.com")),
            (frozenset({3}), DnsRule(pattern="*.example.com")),
        ],
    }
    devices = list(jax.devices())
    # Full-width origin: the widest layout the local devices fill
    # (8 CPU devices -> 4x2, 4 -> 2x2, 2 -> 2x1); rule extent 2 when
    # possible so the rule-preserving half of reshape_mesh is on the
    # audited path.
    n_rule = 2 if len(devices) >= 4 else 1
    n_flow = len(devices) // n_rule
    if n_flow < 1 or n_flow * n_rule < 2:
        return findings
    n_flow = min(1 << (n_flow.bit_length() - 1), 4)
    full = flow_mesh(n_flow=n_flow, n_rule=n_rule,
                     devices=devices[: n_flow * n_rule])
    # Walk the ladder: drop the tail chip one at a time and reshape
    # over what remains, collecting each DISTINCT rung width.
    rungs = [("full", full)]
    seen = {(n_flow, n_rule)}
    survivors = devices[: n_flow * n_rule]
    while len(survivors) > 1:
        survivors = survivors[:-1]
        rung = reshape_mesh(survivors, n_rule,
                            max_flow=full.shape[FLOW_AXIS])
        if rung is None:
            break
        key = (rung.shape[FLOW_AXIS], rung.shape[RULE_AXIS])
        if key in seen:
            continue
        seen.add(key)
        rungs.append((f"{key[0]}x{key[1]}", rung))
    args = _abstract_args()
    prim_sets: dict[str, dict] = {}
    for rung_name, mesh in rungs:
        for family, rows in family_rows.items():
            tag = f"reshape:{family}@{rung_name}"
            try:
                model = build(family, rows, mesh)
            except Exception as e:  # noqa: BLE001
                fail(f"[device-contract:{tag}] reshaped assembly "
                     f"raised: {e!r}")
                continue
            if not isinstance(model, rulesharding.ShardedVerdictModel):
                fail(f"[device-contract:{tag}] assembly folded to "
                     f"{type(model).__name__} — these rows must build "
                     f"a mesh-resident model at every rung")
                continue
            for prob in check_stacked_model(model.stacked, mesh):
                fail(f"[device-contract:{tag}] {prob}")
            if model.n_shards != mesh.shape[RULE_AXIS]:
                fail(f"[device-contract:{tag}] shard_offsets arity "
                     f"{model.n_shards} != rung RULE_AXIS extent "
                     f"{mesh.shape[RULE_AXIS]} (stale full-width "
                     f"offsets would mis-attribute global rule rows)")
            if model.fallback is None:
                fail(f"[device-contract:{tag}] reshaped model carries "
                     f"no single-chip fallback twin — the NEXT device "
                     f"loss on this rung would have nothing to demote "
                     f"to")
            try:
                jx1 = jax.make_jaxpr(model.verdicts_attr)(*args)
                jx2 = jax.make_jaxpr(model.verdicts_attr)(*args)
            except Exception as e:  # noqa: BLE001
                fail(f"[device-contract:{tag}] failed to trace the "
                     f"reshaped attributed step: {e!r}")
                continue
            if str(jx1) != str(jx2):
                fail(f"[device-contract:{tag}] two traces produced "
                     f"DIFFERENT jaxprs — a nondeterministic reshape "
                     f"rebuild recompiles per fault in production")
            _step_jaxpr_findings(tag, jx1, fail)
            prims = frozenset(
                eqn.primitive.name for eqn in _iter_eqns(jx1.jaxpr)
            )
            prev = prim_sets.setdefault(family, {})
            for other, oprims in prev.items():
                if prims != oprims:
                    fail(f"[device-contract:reshape:{family}] "
                         f"primitive set differs between rungs "
                         f"{other} and {rung_name}: "
                         f"{sorted(prims ^ oprims)} — a degraded "
                         f"width must change shapes, not the stepped "
                         f"computation")
            prev[rung_name] = prims
    return findings


def check_device_contracts() -> list[Finding]:
    """Run every abstract device-contract check; returns findings
    (empty = all contracts hold).  Safe without a TPU: everything runs
    as abstract evaluation on the CPU backend."""
    import os

    import jax

    try:
        # Force the CPU backend BEFORE any model import touches a
        # device: abstract tracing needs no chip, and on a TPU host
        # (or this container, where libtpu init blocks for minutes)
        # grabbing the real backend for an eval_shape pass is pure
        # waste.  No-op/raises harmlessly when a backend is already
        # initialized (pytest's conftest pins cpu anyway).
        jax.config.update("jax_platforms", "cpu")
        if "--xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""
        ):
            # The R10 gate traces real 2x2 meshes: ask the (not yet
            # initialized) CPU backend for 4 virtual devices.  Read at
            # backend init — harmless if the backend is already up
            # (the multi-device meshes are then skipped, the 1x1
            # floor still runs).
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=4"
            )
    except Exception:  # noqa: BLE001 — backend already up; proceed
        pass
    findings: list[Finding] = []
    for name, path, model in _model_cases():
        findings.extend(_check_model(name, path, model))
    findings.extend(_check_sharded())
    findings.extend(check_reshape_ladder())
    findings.extend(check_shape_closure())
    return findings


# --- R16: shape-closure audit ---------------------------------------------
#
# "No new jit shapes" was prose until now.  This half makes it a gate:
# enumerate the DECLARED executable-shape universe (the service's
# MIN_BUCKET pow2 ladder, pack_buckets' width ladder, the
# MIN_RULE_BUCKET churn buckets, the mesh shard extents, bounded by
# SHAPE_CACHE_MAX), trace the full serving surface abstractly
# (eval_shape — no device, no execution), and assert the traced
# executable set is CLOSED under that universe.  A future engine that
# ships an unbucketed axis — one raw batch size, one unpadded rule
# table — fails HERE, as a tier-1 gate, instead of silently
# re-tracing per shape on the hot path.

_AXIS_CAP = 1 << 22


def _pow2_set(floor: int, cap: int = _AXIS_CAP) -> frozenset:
    out = set()
    v = int(floor)
    while v <= cap:
        out.add(v)
        v *= 2
    return frozenset(out)


def enumerate_shape_universe() -> dict:
    """The statically-declared executable-shape universe, resolved
    from the SAME constants the serving path derives its shapes from
    (a second copy could drift and silently unpair the gate)."""
    from ..models.r2d2 import MIN_RULE_BUCKET
    from ..sidecar.service import VerdictService
    from ..utils import defaults

    return {
        # Dispatch batch (flow) axis: pow2 from the greedy floor; the
        # remote floor (MIN_BUCKET) and every pack_buckets f_pad are
        # members by construction.
        "flows": _pow2_set(VerdictService.MIN_BUCKET_GREEDY),
        # Row width axis: pack_buckets widens base_width << k.
        "widths": _pow2_set(defaults.BATCH_WIDTH),
        # Rule-table churn buckets (models/r2d2.MIN_RULE_BUCKET).
        "rules": _pow2_set(MIN_RULE_BUCKET),
        # Mesh shard extents: pow2, flow extent capped at the
        # smallest dispatch bucket so every bucket divides it.
        "mesh": _pow2_set(1, VerdictService.MIN_BUCKET_GREEDY),
        "cache_max": VerdictService.SHAPE_CACHE_MAX,
    }


_R16_PATH = "cilium_tpu/sidecar/service.py"


def audit_traced_shapes(traced, universe) -> list[Finding]:
    """R16 closure primitive: every traced executable's (flows, width)
    axes must be members of the enumerated universe.  ``traced`` is an
    iterable of (tag, path, n_flows_or_None, width_or_None)."""
    findings = []
    for tag, path, n_flows, width in traced:
        if n_flows is not None and n_flows not in universe["flows"]:
            findings.append(Finding(
                "R16", path, 0, 0,
                f"[shape-closure:{tag}] traced executable batch axis "
                f"{n_flows} is OUTSIDE the declared bucket universe "
                f"(pow2 ladder from MIN_BUCKET_GREEDY): this shape "
                f"re-traces every time it recurs on the hot path",
                symbol=tag,
            ))
        if width is not None and width not in universe["widths"]:
            findings.append(Finding(
                "R16", path, 0, 0,
                f"[shape-closure:{tag}] traced executable row width "
                f"{width} is OUTSIDE the declared width ladder "
                f"(batch_width << k): an unbucketed width axis keys a "
                f"new executable per frame size",
                symbol=tag,
            ))
    return findings


def _bare_shape_key(model):
    """The churn cache's key derivation, locally: treedef + leaf
    shapes/dtypes of the model's bare dispatch pytree (None when the
    model is not shape-keyed)."""
    import jax

    bare_fn = getattr(model, "dispatch_bare", None)
    if bare_fn is None:
        return None
    leaves, treedef = jax.tree_util.tree_flatten(bare_fn())
    return (
        str(treedef),
        tuple((tuple(lf.shape), str(lf.dtype)) for lf in leaves),
    )


def audit_rule_axis(tag: str, path: str, build) -> list[Finding]:
    """Rule-axis churn closure: same-bucket rebuilds must key the SAME
    executable.  ``build(n)`` compiles an n-rule model; 2 and 3 rules
    share the MIN_RULE_BUCKET bucket, so their shape keys must be
    identical — an unbucketed builder keys a new executable per rule
    count, i.e. a full re-trace on every policy churn."""
    k2 = _bare_shape_key(build(2))
    k3 = _bare_shape_key(build(3))
    if k2 is None or k3 is None:
        return [Finding(
            "R16", path, 0, 0,
            f"[shape-closure:{tag}] model exposes no dispatch_bare "
            f"shape key — the shape-keyed churn cache cannot cover it",
            symbol=tag,
        )]
    if k2 != k3:
        return [Finding(
            "R16", path, 0, 0,
            f"[shape-closure:{tag}] rule axis is UNBUCKETED: a 2-rule "
            f"and a 3-rule table key DIFFERENT executables — every "
            f"policy churn re-traces instead of hitting the "
            f"shape-keyed cache; pad the row axis to the "
            f"MIN_RULE_BUCKET power-of-two ladder",
            symbol=tag,
        )]
    return []


def check_shape_closure() -> list[Finding]:
    """R16 abstract-trace half: trace the full serving surface — all
    four engine families (r2d2/http/kafka/dns), single-chip + sharded,
    attr + plain — via eval_shape, plus the real pack_buckets packer
    over adversarial frame lengths, and assert every traced executable
    shape is a member of the enumerated universe, the distinct-
    executable count fits SHAPE_CACHE_MAX, and the shape-keyed rule
    axes are churn-closed."""
    import jax
    import numpy as np

    from ..kafka.request import RequestMessage
    from ..models.dns import (
        build_dns_model_from_rows,
        dns_verdicts,
        dns_verdicts_attr,
    )
    from ..models.http import build_http_model
    from ..models.kafka import (
        build_kafka_model,
        encode_requests,
        kafka_verdicts,
    )
    from ..models.r2d2 import (
        build_r2d2_model_from_rows,
        r2d2_verdicts,
        r2d2_verdicts_attr,
    )
    from ..parallel import rulesharding
    from ..parallel.mesh import FLOW_AXIS, RULE_AXIS, flow_mesh
    from ..policy.api import PortRuleHTTP, PortRuleKafka
    from ..proxylib.parsers.dns import DnsRule
    from ..sidecar.reasm import Reassembler
    from ..sidecar.service import VerdictService
    from ..utils import defaults

    universe = enumerate_shape_universe()
    findings: list[Finding] = []
    traced: list[tuple] = []
    exes: set = set()

    # Rule-axis probes hold the regex VOCABULARY fixed across n: the
    # automaton state/class axes legitimately scale with the compiled
    # pattern set (bucketing them is the open ROADMAP churn-cache
    # extension), so only the row axis may vary here — that is the
    # axis MIN_RULE_BUCKET declares closed.
    def rows_r2d2(n):
        return [(frozenset({i}), "", "/p/.*") for i in range(n)]

    def rows_dns(n):
        return [
            (frozenset({i}), DnsRule(name="w.example.com"))
            for i in range(n)
        ]

    r2 = build_r2d2_model_from_rows(rows_r2d2(2), bucket=True)
    dn = build_dns_model_from_rows(rows_dns(2), bucket=True)
    ht = build_http_model([
        (frozenset(), PortRuleHTTP(method="GET", path="/api/.*")),
        (frozenset({3}), PortRuleHTTP()),
    ])
    kr = PortRuleKafka(topic="orders")
    kr.sanitize()
    km = build_kafka_model([(frozenset(), kr)])
    mods = {
        "r2d2": "cilium_tpu/models/r2d2.py",
        "dns": "cilium_tpu/models/dns.py",
        "http": "cilium_tpu/models/http.py",
        "kafka": "cilium_tpu/models/kafka.py",
    }

    def trace(tag, path, fn, args, flows, width):
        try:
            jax.eval_shape(fn, *args)
        except Exception as e:  # noqa: BLE001 — any trace failure gates
            findings.append(Finding(
                "R16", path, 0, 0,
                f"[shape-closure:{tag}] serving-surface trace "
                f"failed: {e!r}",
                symbol=tag,
            ))
            return
        traced.append((tag, path, flows, width))
        exes.add(tag)

    # Single-chip surface, attr + plain, over the two smallest flow
    # buckets x two widths (membership, not exhaustiveness: the
    # universe is infinite pow2; the serving path can only DERIVE
    # members, which the AST half of R16 pins).
    b0 = VerdictService.MIN_BUCKET_GREEDY
    w0 = defaults.BATCH_WIDTH
    for b in (b0, 2 * b0):
        for w in (w0, 2 * w0):
            args = (
                jax.ShapeDtypeStruct((b, w), np.uint8),
                jax.ShapeDtypeStruct((b,), np.int32),
                jax.ShapeDtypeStruct((b,), np.int32),
            )
            for name, model in (("r2d2", r2), ("dns", dn),
                                ("http", ht)):
                trace(f"{name}.plain@{b}x{w}", mods[name],
                      model.__call__, args, b, w)
                attr = getattr(model, "verdicts_attr", None)
                if attr is not None:
                    trace(f"{name}.attr@{b}x{w}", mods[name],
                          attr, args, b, w)
    kbatch = encode_requests(
        [RequestMessage(0, 2, 1, "c", ["orders"], parsed=True)] * b0
    )
    trace(f"kafka.plain@{b0}", mods["kafka"], kafka_verdicts,
          (km, kbatch, np.ones(b0, np.int32)), b0, None)

    # Sharded surface: every mesh the local device count can fill;
    # shard extents must be universe members, and the stepped
    # executables trace at a bucketed global shape.
    devices = jax.devices()
    for n_flow, n_rule in ((1, 2), (2, 1), (2, 2)):
        if n_flow * n_rule > len(devices):
            continue
        mesh = flow_mesh(n_flow=n_flow, n_rule=n_rule,
                         devices=devices[: n_flow * n_rule])
        for axis, extent in (("flows", mesh.shape[FLOW_AXIS]),
                             ("rules", mesh.shape[RULE_AXIS])):
            if extent not in universe["mesh"]:
                findings.append(Finding(
                    "R16", _SHARD_PATH, 0, 0,
                    f"[shape-closure:mesh@{n_flow}x{n_rule}] {axis} "
                    f"shard extent {extent} is outside the declared "
                    f"mesh universe (pow2, flow extent <= the "
                    f"smallest dispatch bucket)",
                ))
        args = (
            jax.ShapeDtypeStruct((b0, w0), np.uint8),
            jax.ShapeDtypeStruct((b0,), np.int32),
            jax.ShapeDtypeStruct((b0,), np.int32),
        )
        offsets = rulesharding.shard_offsets(2, n_rule)
        for name, model, vfn, afn in (
            ("r2d2", r2, r2d2_verdicts, r2d2_verdicts_attr),
            ("dns", dn, dns_verdicts, dns_verdicts_attr),
        ):
            stacked = rulesharding._stack_models([model] * n_rule)
            trace(f"{name}.sharded@{n_flow}x{n_rule}", _SHARD_PATH,
                  rulesharding.sharded_verdict_step(mesh, vfn),
                  (stacked,) + args, b0, w0)
            trace(f"{name}.sharded_attr@{n_flow}x{n_rule}",
                  _SHARD_PATH,
                  rulesharding.sharded_verdict_step_attr(mesh, afn),
                  (stacked, offsets) + args, b0, w0)
        trace(f"kafka.sharded@{n_flow}x{n_rule}", _SHARD_PATH,
              rulesharding.sharded_kafka_step(mesh),
              (rulesharding._stack_models([km] * n_rule), kbatch,
               np.ones(b0, np.int32)), b0, None)

    # The real packer's output shapes over adversarial frame lengths
    # (minimal, exact-width, width+1, a multi-bucket jump) must land
    # in the same universe the dispatch caches enumerate.
    reasm = Reassembler()
    frame_lens = [2, w0, w0 + 1, 4 * w0 + 5, 17]
    payloads = [b"x" * (fl - 2) + b"\r\n" for fl in frame_lens]
    lens = np.array([len(p) for p in payloads], np.int64)
    ends = np.cumsum(lens)
    rnd = reasm.ingest(
        np.arange(1, len(payloads) + 1, dtype=np.int64),
        ends - lens, lens,
        np.frombuffer(b"".join(payloads), np.uint8),
    )
    for _fi, data, _lengths, _rem in reasm.pack_buckets(
        rnd, w0, b0, np.zeros(len(payloads), np.int32)
    ):
        f_pad, wv = data.shape
        traced.append((f"pack_buckets@{f_pad}x{wv}",
                       "cilium_tpu/sidecar/reasm.py", int(f_pad),
                       int(wv)))

    findings.extend(audit_traced_shapes(traced, universe))
    if len(exes) > universe["cache_max"]:
        findings.append(Finding(
            "R16", _R16_PATH, 0, 0,
            f"[shape-closure] {len(exes)} distinct serving-surface "
            f"executables exceed SHAPE_CACHE_MAX="
            f"{universe['cache_max']} — the executable cache would "
            f"thrash-evict on the hot path",
        ))
    findings.extend(audit_rule_axis(
        "r2d2.rule-axis", mods["r2d2"],
        lambda n: build_r2d2_model_from_rows(rows_r2d2(n),
                                             bucket=True),
    ))
    findings.extend(audit_rule_axis(
        "dns.rule-axis", mods["dns"],
        lambda n: build_dns_model_from_rows(rows_dns(n), bucket=True),
    ))
    return findings
