"""Device-contract verification by ABSTRACT tracing (R8-R11, no device).

The AST half of R8-R11 (``rules_device.py``) pattern-matches hazards;
this half proves the contracts on the REAL verdict models by tracing
them abstractly — ``jax.eval_shape`` / ``jax.make_jaxpr`` over
``ShapeDtypeStruct`` inputs, which runs under ``JAX_PLATFORMS=cpu``,
allocates no buffers, executes no model, and needs no TPU:

- **R8** — the model traces at all on abstract values (any Python
  branch on traced data would raise ConcretizationTypeError), the
  jaxpr is IDENTICAL across two traces (no wall-clock/rng/iteration-
  order dependence — the recompile-storm seed), and no output aval is
  weak-typed (weak types key per-caller-dtype executables downstream).
- **R9** — the traced jaxpr contains no host-callback or transfer
  primitives anywhere in its (recursive) equation tree: a ``.item()``
  or np coercion on a traced value would have failed the trace, and a
  smuggled ``pure_callback``/``device_put`` is a host round-trip the
  dispatch round would pay per batch.
- **R10** — every sharded step in ``parallel/rulesharding.py`` traces
  under a 1x1 (flows, rules) mesh built from the CPU device: shard_map
  validates in_specs/out_specs against the function's actual arity and
  rank at trace time, so a drifted spec fails HERE instead of at first
  trace on a real multi-chip mesh.
- **R11** — ``verdicts_attr``'s jaxpr is the verdict jaxpr plus a
  bounded attribution epilogue: output arity 4 with an int32 rule
  row, and an equation count within ``ATTR_EXTRA_EQNS`` of the plain
  twin — a second hit-matrix pass would ~double it.

Import of jax (and the models) happens inside the entry point so the
plain AST lint never pays for it; ``bin/cilium-lint
--device-contracts`` and tests/test_device_contracts.py are the
consumers.
"""

from __future__ import annotations

from .core import Finding

# An attribution epilogue is argmax + where + a handful of selects;
# a SECOND hit-matrix pass is dozens-to-hundreds of equations on these
# models.  The bound is deliberately loose enough for op-by-op jax
# version drift and tight enough that a doubled pass cannot hide.
ATTR_EXTRA_EQNS = 12

# Primitives that mean "host round-trip" when they appear inside a
# traced verdict computation.
_FORBIDDEN_PRIM_SUBSTRINGS = ("callback", "device_put", "infeed",
                              "outfeed")

_BATCH = 8
_WIDTH = 128


def _iter_eqns(jaxpr):
    """Every equation in a (closed) jaxpr, recursing into sub-jaxprs
    (pjit/closed_call/scan/cond carry theirs in params)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _sub_jaxprs(v):
    import jax.core as jcore

    if isinstance(v, jcore.ClosedJaxpr):
        yield v.jaxpr
    elif isinstance(v, jcore.Jaxpr):
        yield v
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _sub_jaxprs(x)


def _model_cases():
    """Tiny-but-representative models per engine family, each touching
    every tier the builders have (literal, prefix, regex, header)."""
    from ..models.base import SeamProbe
    from ..models.http import build_http_model
    from ..models.r2d2 import build_r2d2_model_from_rows
    from ..policy.api import PortRuleHTTP

    http = build_http_model([
        (frozenset(), PortRuleHTTP(method="GET", path="/api/v1/.*")),
        (frozenset({7}), PortRuleHTTP(method="GET|HEAD",
                                      path="/x/[a-z]+",
                                      host="example[.]com")),
        (frozenset({3}), PortRuleHTTP()),
    ])
    r2d2 = build_r2d2_model_from_rows([
        (frozenset(), "OPEN", "/etc/.*"),
        (frozenset({3}), "", "docs/[a-z]+[.]txt"),
        (frozenset({3, 9}), "RETR", ""),
    ])
    return [
        ("http", "cilium_tpu/models/http.py", http),
        ("r2d2", "cilium_tpu/models/r2d2.py", r2d2),
        ("seam_probe", "cilium_tpu/models/base.py", SeamProbe()),
    ]


def _abstract_args():
    import jax
    import jax.numpy as jnp

    return (
        jax.ShapeDtypeStruct((_BATCH, _WIDTH), jnp.uint8),
        jax.ShapeDtypeStruct((_BATCH,), jnp.int32),
        jax.ShapeDtypeStruct((_BATCH,), jnp.int32),
    )


def _check_model(name, path, model):
    import jax

    data, lengths, remotes = _abstract_args()
    findings = []

    def fail(rule, msg):
        findings.append(Finding(
            rule, path, 0, 0, f"[device-contract:{name}] {msg}",
            symbol=name,
        ))

    # R8: abstract trace succeeds, twice, identically.
    try:
        jx1 = jax.make_jaxpr(model.__call__)(data, lengths, remotes)
        jx2 = jax.make_jaxpr(model.__call__)(data, lengths, remotes)
    except Exception as e:  # noqa: BLE001 — any trace failure is the finding
        fail("R8", f"verdict model failed to trace abstractly "
                   f"(Python branching on traced data?): {e!r}")
        return findings
    if str(jx1) != str(jx2):
        fail("R8", "two traces of the verdict model produced "
                   "DIFFERENT jaxprs — trace-time nondeterminism "
                   "(wall clock / rng / iteration order) and a "
                   "recompile per dispatch on the hot path")
    for i, aval in enumerate(jx1.out_avals):
        if getattr(aval, "weak_type", False):
            fail("R8", f"verdict output {i} has weak_type=True: a "
                       f"Python-scalar constant leaked into the "
                       f"output dtype lattice — downstream consumers "
                       f"key a separate executable per caller dtype "
                       f"mix")

    # R9: no host-callback / transfer primitives in the whole tree.
    for eqn in _iter_eqns(jx1.jaxpr):
        pname = eqn.primitive.name
        if any(s in pname for s in _FORBIDDEN_PRIM_SUBSTRINGS):
            fail("R9", f"traced verdict computation contains host "
                       f"round-trip primitive {pname!r} — a device->"
                       f"host sync inside the dispatch round")

    # R11: fused attribution — arity-4, int32 rule row, bounded
    # equation delta vs the plain twin.
    if not hasattr(model, "verdicts_attr"):
        return findings
    try:
        jxa = jax.make_jaxpr(model.verdicts_attr)(data, lengths, remotes)
    except Exception as e:  # noqa: BLE001
        fail("R11", f"verdicts_attr failed to trace abstractly: {e!r}")
        return findings
    if len(jxa.out_avals) != 4:
        fail("R11", f"verdicts_attr returns {len(jxa.out_avals)} "
                    f"outputs, contract is 4 (complete, len, allow, "
                    f"rule)")
    else:
        rule_aval = jxa.out_avals[3]
        if str(rule_aval.dtype) != "int32":
            fail("R11", f"attribution rule row dtype is "
                        f"{rule_aval.dtype}, contract is int32 (the "
                        f"wire packs <i4)")
    n_plain = sum(1 for _ in _iter_eqns(jx1.jaxpr))
    n_attr = sum(1 for _ in _iter_eqns(jxa.jaxpr))
    if n_attr > n_plain + ATTR_EXTRA_EQNS:
        fail("R11", f"verdicts_attr traces to {n_attr} equations vs "
                    f"{n_plain} for the plain verdict (+{ATTR_EXTRA_EQNS} "
                    f"allowed): attribution is recomputing the hit "
                    f"matrix — a SECOND device pass the parity tests "
                    f"cannot see")
    for eqn in _iter_eqns(jxa.jaxpr):
        pname = eqn.primitive.name
        if any(s in pname for s in _FORBIDDEN_PRIM_SUBSTRINGS):
            fail("R9", f"attributed verdict computation contains "
                       f"host round-trip primitive {pname!r}")
    return findings


def _check_sharded():
    """R10: the sharded steps trace under a 1x1 (flows, rules) CPU
    mesh — shard_map validates specs against real arity/rank at trace
    time, so in_specs/out_specs drift fails here, not on a multi-chip
    mesh in production."""
    import jax

    from ..models.r2d2 import build_r2d2_model_from_rows, r2d2_verdicts
    from ..parallel import rulesharding
    from ..parallel.mesh import flow_mesh

    path = "cilium_tpu/parallel/rulesharding.py"
    findings = []
    try:
        mesh = flow_mesh(n_flow=1, n_rule=1,
                         devices=jax.devices()[:1])
    except Exception as e:  # noqa: BLE001
        findings.append(Finding(
            "R10", path, 0, 0,
            f"[device-contract:mesh] cannot build the 1x1 CPU mesh "
            f"for abstract sharding checks: {e!r}",
        ))
        return findings
    model = build_r2d2_model_from_rows([
        (frozenset(), "OPEN", "/etc/.*"),
        (frozenset({3}), "", "docs/[a-z]+"),
    ])
    stacked = rulesharding._stack_models([model])
    data, lengths, remotes = _abstract_args()
    try:
        step = rulesharding.sharded_verdict_step(mesh, r2d2_verdicts)
        out = jax.eval_shape(step, stacked, data, lengths, remotes)
        if len(out) != 3:
            findings.append(Finding(
                "R10", path, 0, 0,
                f"[device-contract:sharded_verdict_step] expected 3 "
                f"outputs (complete, msg_len, allow), got {len(out)}",
            ))
    except Exception as e:  # noqa: BLE001
        findings.append(Finding(
            "R10", path, 0, 0,
            f"[device-contract:sharded_verdict_step] failed to trace "
            f"under the 1x1 mesh — in_specs/out_specs drifted from "
            f"the step function's signature: {e!r}",
        ))
    return findings


def check_device_contracts() -> list[Finding]:
    """Run every abstract device-contract check; returns findings
    (empty = all contracts hold).  Safe without a TPU: everything runs
    as abstract evaluation on the CPU backend."""
    import jax

    try:
        # Force the CPU backend BEFORE any model import touches a
        # device: abstract tracing needs no chip, and on a TPU host
        # (or this container, where libtpu init blocks for minutes)
        # grabbing the real backend for an eval_shape pass is pure
        # waste.  No-op/raises harmlessly when a backend is already
        # initialized (pytest's conftest pins cpu anyway).
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend already up; proceed
        pass
    findings: list[Finding] = []
    for name, path, model in _model_cases():
        findings.extend(_check_model(name, path, model))
    findings.extend(_check_sharded())
    return findings
