"""Device-contract verification by ABSTRACT tracing (R8-R11, no device).

The AST half of R8-R11 (``rules_device.py``) pattern-matches hazards;
this half proves the contracts on the REAL verdict models by tracing
them abstractly — ``jax.eval_shape`` / ``jax.make_jaxpr`` over
``ShapeDtypeStruct`` inputs, which runs under ``JAX_PLATFORMS=cpu``,
allocates no buffers, executes no model, and needs no TPU:

- **R8** — the model traces at all on abstract values (any Python
  branch on traced data would raise ConcretizationTypeError), the
  jaxpr is IDENTICAL across two traces (no wall-clock/rng/iteration-
  order dependence — the recompile-storm seed), and no output aval is
  weak-typed (weak types key per-caller-dtype executables downstream).
- **R9** — the traced jaxpr contains no host-callback or transfer
  primitives anywhere in its (recursive) equation tree: a ``.item()``
  or np coercion on a traced value would have failed the trace, and a
  smuggled ``pure_callback``/``device_put`` is a host round-trip the
  dispatch round would pay per batch.
- **R10** — every sharded step in ``parallel/rulesharding.py`` traces
  under 1x1, 1x2, 2x1 and 2x2 (flows, rules) CPU meshes: shard_map
  validates in_specs/out_specs against the function's actual arity and
  rank at trace time, so a drifted spec fails HERE instead of at first
  trace on a real multi-chip mesh.  The gate also pins stacked-leaf
  shard arity (an unbalanced/unpadded shard stack), forbids transfer
  primitives inside the stepped bodies, and requires trace determinism
  per mesh plus a shard-count-independent primitive set.
- **R11** — ``verdicts_attr``'s jaxpr is the verdict jaxpr plus a
  bounded attribution epilogue: output arity 4 with an int32 rule
  row, and an equation count within ``ATTR_EXTRA_EQNS`` of the plain
  twin — a second hit-matrix pass would ~double it.

Import of jax (and the models) happens inside the entry point so the
plain AST lint never pays for it; ``bin/cilium-lint
--device-contracts`` and tests/test_device_contracts.py are the
consumers.
"""

from __future__ import annotations

from .core import Finding

# An attribution epilogue is argmax + where + a handful of selects;
# a SECOND hit-matrix pass is dozens-to-hundreds of equations on these
# models.  The bound is deliberately loose enough for op-by-op jax
# version drift and tight enough that a doubled pass cannot hide.
ATTR_EXTRA_EQNS = 12

# Primitives that mean "host round-trip" when they appear inside a
# traced verdict computation.
_FORBIDDEN_PRIM_SUBSTRINGS = ("callback", "device_put", "infeed",
                              "outfeed")

_BATCH = 8
_WIDTH = 128


def _iter_eqns(jaxpr):
    """Every equation in a (closed) jaxpr, recursing into sub-jaxprs
    (pjit/closed_call/scan/cond carry theirs in params)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _sub_jaxprs(v):
    import jax.core as jcore

    if isinstance(v, jcore.ClosedJaxpr):
        yield v.jaxpr
    elif isinstance(v, jcore.Jaxpr):
        yield v
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _sub_jaxprs(x)


def _model_cases():
    """Tiny-but-representative models per engine family, each touching
    every tier the builders have (literal, prefix, regex, header)."""
    from ..models.base import SeamProbe
    from ..models.dns import build_dns_model_from_rows
    from ..models.http import build_http_model
    from ..models.r2d2 import build_r2d2_model_from_rows
    from ..policy.api import PortRuleHTTP
    from ..proxylib.parsers.dns import DnsRule

    http = build_http_model([
        (frozenset(), PortRuleHTTP(method="GET", path="/api/v1/.*")),
        (frozenset({7}), PortRuleHTTP(method="GET|HEAD",
                                      path="/x/[a-z]+",
                                      host="example[.]com")),
        (frozenset({3}), PortRuleHTTP()),
    ])
    r2d2 = build_r2d2_model_from_rows([
        (frozenset(), "OPEN", "/etc/.*"),
        (frozenset({3}), "", "docs/[a-z]+[.]txt"),
        (frozenset({3, 9}), "RETR", ""),
    ])
    dns = build_dns_model_from_rows([
        (frozenset(), DnsRule(name="www.example.com")),
        (frozenset({3}), DnsRule(pattern="*.svc.cluster.local")),
        (frozenset({3, 9}), DnsRule(regex="internal[.](a|b)")),
        (frozenset({7}), None),
    ])
    return [
        ("http", "cilium_tpu/models/http.py", http),
        ("r2d2", "cilium_tpu/models/r2d2.py", r2d2),
        ("dns", "cilium_tpu/models/dns.py", dns),
        ("seam_probe", "cilium_tpu/models/base.py", SeamProbe()),
    ]


def _abstract_args():
    import jax
    import jax.numpy as jnp

    return (
        jax.ShapeDtypeStruct((_BATCH, _WIDTH), jnp.uint8),
        jax.ShapeDtypeStruct((_BATCH,), jnp.int32),
        jax.ShapeDtypeStruct((_BATCH,), jnp.int32),
    )


def _check_model(name, path, model):
    import jax

    data, lengths, remotes = _abstract_args()
    findings = []

    def fail(rule, msg):
        findings.append(Finding(
            rule, path, 0, 0, f"[device-contract:{name}] {msg}",
            symbol=name,
        ))

    # R8: abstract trace succeeds, twice, identically.
    try:
        jx1 = jax.make_jaxpr(model.__call__)(data, lengths, remotes)
        jx2 = jax.make_jaxpr(model.__call__)(data, lengths, remotes)
    except Exception as e:  # noqa: BLE001 — any trace failure is the finding
        fail("R8", f"verdict model failed to trace abstractly "
                   f"(Python branching on traced data?): {e!r}")
        return findings
    if str(jx1) != str(jx2):
        fail("R8", "two traces of the verdict model produced "
                   "DIFFERENT jaxprs — trace-time nondeterminism "
                   "(wall clock / rng / iteration order) and a "
                   "recompile per dispatch on the hot path")
    for i, aval in enumerate(jx1.out_avals):
        if getattr(aval, "weak_type", False):
            fail("R8", f"verdict output {i} has weak_type=True: a "
                       f"Python-scalar constant leaked into the "
                       f"output dtype lattice — downstream consumers "
                       f"key a separate executable per caller dtype "
                       f"mix")

    # R9: no host-callback / transfer primitives in the whole tree.
    for eqn in _iter_eqns(jx1.jaxpr):
        pname = eqn.primitive.name
        if any(s in pname for s in _FORBIDDEN_PRIM_SUBSTRINGS):
            fail("R9", f"traced verdict computation contains host "
                       f"round-trip primitive {pname!r} — a device->"
                       f"host sync inside the dispatch round")

    # R11: fused attribution — arity-4, int32 rule row, bounded
    # equation delta vs the plain twin.
    if not hasattr(model, "verdicts_attr"):
        return findings
    try:
        jxa = jax.make_jaxpr(model.verdicts_attr)(data, lengths, remotes)
    except Exception as e:  # noqa: BLE001
        fail("R11", f"verdicts_attr failed to trace abstractly: {e!r}")
        return findings
    if len(jxa.out_avals) != 4:
        fail("R11", f"verdicts_attr returns {len(jxa.out_avals)} "
                    f"outputs, contract is 4 (complete, len, allow, "
                    f"rule)")
    else:
        rule_aval = jxa.out_avals[3]
        if str(rule_aval.dtype) != "int32":
            fail("R11", f"attribution rule row dtype is "
                        f"{rule_aval.dtype}, contract is int32 (the "
                        f"wire packs <i4)")
    n_plain = sum(1 for _ in _iter_eqns(jx1.jaxpr))
    n_attr = sum(1 for _ in _iter_eqns(jxa.jaxpr))
    if n_attr > n_plain + ATTR_EXTRA_EQNS:
        fail("R11", f"verdicts_attr traces to {n_attr} equations vs "
                    f"{n_plain} for the plain verdict (+{ATTR_EXTRA_EQNS} "
                    f"allowed): attribution is recomputing the hit "
                    f"matrix — a SECOND device pass the parity tests "
                    f"cannot see")
    for eqn in _iter_eqns(jxa.jaxpr):
        pname = eqn.primitive.name
        if any(s in pname for s in _FORBIDDEN_PRIM_SUBSTRINGS):
            fail("R9", f"attributed verdict computation contains "
                       f"host round-trip primitive {pname!r}")
    return findings


# Mesh aspect ratios the R10 gate traces every sharded step under —
# both axes exercised alone and together so a spec that only works
# when an axis is trivial cannot pass.
_SHARD_MESHES = ((1, 1), (1, 2), (2, 1), (2, 2))

_SHARD_PATH = "cilium_tpu/parallel/rulesharding.py"


def check_stacked_model(stacked, mesh) -> list[str]:
    """R10 structural half: every leaf of a stacked shard model must
    lead with a shard dim equal to the mesh's RULE_AXIS extent — the
    split-balanced + pad_tables contract.  A builder that skipped the
    cross-shard padding (or stacked for the wrong shard count) shows
    up here before shard_map ever traces.  Returns problem strings."""
    import jax

    from ..parallel.mesh import RULE_AXIS

    n = mesh.shape[RULE_AXIS]
    probs = []
    for i, leaf in enumerate(jax.tree_util.tree_leaves(stacked)):
        shape = tuple(getattr(leaf, "shape", ()))
        if not shape or shape[0] != n:
            probs.append(
                f"stacked leaf {i} shape {shape} does not lead with "
                f"the RULE_AXIS shard dim {n} (unbalanced/unpadded "
                f"shard stack)"
            )
    return probs


def _step_jaxpr_findings(name: str, jx, fail) -> None:
    """Shared per-step jaxpr checks: no host-transfer primitives
    anywhere inside the stepped body, and a second trace must be
    byte-identical (trace-time nondeterminism would recompile per
    shard-count/mesh in production)."""
    for eqn in _iter_eqns(jx.jaxpr):
        pname = eqn.primitive.name
        if any(s in pname for s in _FORBIDDEN_PRIM_SUBSTRINGS):
            fail(f"[device-contract:{name}] stepped body contains "
                 f"host round-trip primitive {pname!r} — a device->"
                 f"host sync inside the mesh round")


def _check_sharded():
    """R10: every sharded step in ``parallel/rulesharding.py`` traces
    under 1x1, 1x2, 2x1 AND 2x2 (flows, rules) CPU meshes — shard_map
    validates in_specs/out_specs against the step functions' actual
    arity and rank at trace time, so a drifted spec fails HERE instead
    of at first trace on a real multi-chip mesh.  On top of the trace:
    stacked-leaf shard arity (the unbalanced-pad pin), no transfer
    primitives inside the stepped bodies, repeat-trace jaxpr
    determinism per mesh, and a shard-count-independent primitive set
    (the computation's SHAPE may change with the mesh; its structure
    must not).  Meshes the local device count cannot fill are skipped
    (the 1x1 floor always runs)."""
    import jax
    import numpy as np

    from ..kafka.request import RequestMessage
    from ..models.dns import (
        build_dns_model_from_rows,
        dns_verdicts,
        dns_verdicts_attr,
    )
    from ..models.kafka import build_kafka_model, encode_requests
    from ..models.r2d2 import (
        build_r2d2_model_from_rows,
        r2d2_verdicts,
        r2d2_verdicts_attr,
    )
    from ..proxylib.parsers.dns import DnsRule
    from ..parallel import rulesharding
    from ..parallel.mesh import flow_mesh
    from ..policy.api import PortRuleKafka

    findings = []

    def fail(msg):
        findings.append(Finding("R10", _SHARD_PATH, 0, 0, msg))

    model = build_r2d2_model_from_rows([
        (frozenset(), "OPEN", "/etc/.*"),
        (frozenset({3}), "", "docs/[a-z]+"),
    ])
    dmodel = build_dns_model_from_rows([
        (frozenset(), DnsRule(name="www.example.com")),
        (frozenset({3}), DnsRule(pattern="*.example.com")),
    ])
    kr = PortRuleKafka(topic="orders")
    kr.sanitize()
    kmodel = build_kafka_model([(frozenset(), kr)])
    kbatch = encode_requests(
        [RequestMessage(0, 2, 1, "c", ["orders"], parsed=True)] * _BATCH
    )
    data, lengths, remotes = _abstract_args()
    devices = jax.devices()
    prim_sets: dict[str, dict] = {}
    traced_any = False
    for n_flow, n_rule in _SHARD_MESHES:
        if n_flow * n_rule > len(devices):
            continue
        try:
            mesh = flow_mesh(n_flow=n_flow, n_rule=n_rule,
                             devices=devices[: n_flow * n_rule])
        except Exception as e:  # noqa: BLE001
            fail(f"[device-contract:mesh] cannot build the "
                 f"{n_flow}x{n_rule} CPU mesh: {e!r}")
            continue
        traced_any = True
        stacked = rulesharding._stack_models([model] * n_rule)
        for prob in check_stacked_model(stacked, mesh):
            fail(f"[device-contract:stacked@{n_flow}x{n_rule}] {prob}")
        dstacked = rulesharding._stack_models([dmodel] * n_rule)
        for prob in check_stacked_model(dstacked, mesh):
            fail(f"[device-contract:dns-stacked@{n_flow}x{n_rule}] "
                 f"{prob}")
        offsets = rulesharding.shard_offsets(2, n_rule)
        cases = (
            ("sharded_verdict_step",
             rulesharding.sharded_verdict_step(mesh, r2d2_verdicts),
             (stacked, data, lengths, remotes), 3),
            ("sharded_verdict_step_attr",
             rulesharding.sharded_verdict_step_attr(
                 mesh, r2d2_verdicts_attr),
             (stacked, offsets, data, lengths, remotes), 4),
            ("sharded_dns_step",
             rulesharding.sharded_verdict_step(mesh, dns_verdicts),
             (dstacked, data, lengths, remotes), 3),
            ("sharded_dns_step_attr",
             rulesharding.sharded_verdict_step_attr(
                 mesh, dns_verdicts_attr),
             (dstacked, offsets, data, lengths, remotes), 4),
            ("sharded_kafka_step",
             rulesharding.sharded_kafka_step(mesh),
             (rulesharding._stack_models([kmodel] * n_rule),
              kbatch, np.ones(_BATCH, np.int32)), 1),
        )
        for name, step, args, n_out in cases:
            tag = f"{name}@{n_flow}x{n_rule}"
            try:
                jx1 = jax.make_jaxpr(step)(*args)
                jx2 = jax.make_jaxpr(step)(*args)
            except Exception as e:  # noqa: BLE001
                fail(f"[device-contract:{tag}] failed to trace — "
                     f"in_specs/out_specs drifted from the step "
                     f"function's signature or shard arity: {e!r}")
                continue
            outs = jx1.out_avals
            if len(outs) != n_out:
                fail(f"[device-contract:{tag}] expected {n_out} "
                     f"outputs, got {len(outs)}")
            if name == "sharded_verdict_step_attr" and len(outs) == 4 \
                    and str(outs[3].dtype) != "int32":
                fail(f"[device-contract:{tag}] global first-match "
                     f"rule row dtype is {outs[3].dtype}, contract "
                     f"is int32")
            if str(jx1) != str(jx2):
                fail(f"[device-contract:{tag}] two traces produced "
                     f"DIFFERENT jaxprs — trace-time nondeterminism "
                     f"recompiles per mesh in production")
            _step_jaxpr_findings(tag, jx1, fail)
            prims = frozenset(
                eqn.primitive.name for eqn in _iter_eqns(jx1.jaxpr)
            )
            prev = prim_sets.setdefault(name, {})
            for other, oprims in prev.items():
                if prims != oprims:
                    fail(f"[device-contract:{name}] primitive set "
                         f"differs between meshes {other} and "
                         f"{n_flow}x{n_rule}: "
                         f"{sorted(prims ^ oprims)} — the stepped "
                         f"computation's structure must not depend "
                         f"on the shard count")
            prev[f"{n_flow}x{n_rule}"] = prims
    if not traced_any:
        fail("[device-contract:mesh] no (flows, rules) mesh could be "
             "built from the available devices")
    return findings


def check_device_contracts() -> list[Finding]:
    """Run every abstract device-contract check; returns findings
    (empty = all contracts hold).  Safe without a TPU: everything runs
    as abstract evaluation on the CPU backend."""
    import os

    import jax

    try:
        # Force the CPU backend BEFORE any model import touches a
        # device: abstract tracing needs no chip, and on a TPU host
        # (or this container, where libtpu init blocks for minutes)
        # grabbing the real backend for an eval_shape pass is pure
        # waste.  No-op/raises harmlessly when a backend is already
        # initialized (pytest's conftest pins cpu anyway).
        jax.config.update("jax_platforms", "cpu")
        if "--xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""
        ):
            # The R10 gate traces real 2x2 meshes: ask the (not yet
            # initialized) CPU backend for 4 virtual devices.  Read at
            # backend init — harmless if the backend is already up
            # (the multi-device meshes are then skipped, the 1x1
            # floor still runs).
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=4"
            )
    except Exception:  # noqa: BLE001 — backend already up; proceed
        pass
    findings: list[Finding] = []
    for name, path, model in _model_cases():
        findings.extend(_check_model(name, path, model))
    findings.extend(_check_sharded())
    return findings
