"""R14 — exactly-once verdict accounting (the completion seam).

The paper's bit-identical-verdicts contract silently assumes a harder
one: every admitted frame is answered EXACTLY once with a typed
outcome.  Both halves of that invariant have real bug history here —
PR 2's deposed-round double reply (a stuck worker's late send racing
the watchdog's typed SHED sweep) and PR 10's columnar lane exits
(bytes stranded in the arena when a bail path forgot the release), and
PR 12's shim-local grants multiply the answer sites that must be
proven exclusive.  R14 models the seam on the whole-program call
graph:

- **Answer sites** are the sends/records keyed by entry/seq
  (``send_verdicts`` / ``send_frames`` / ``_shed_item`` /
  ``_on_batch_error`` / grant synthesis) and **typed hand-offs** are
  the accountability transfers (dispatcher ``submit*``, the completion
  pipeline ``put``/``_completion_put``, columnar ``assemble``,
  ``_reasm_bail``'s release-to-scalar).  A fixed-point pass lifts both
  through resolved calls (``answers_via``).
- **R14.1 admit accounting.**  A hot-module admit root (``submit_*``,
  ``_process*``, the ring drain) that can take a BARE return with no
  answer site or typed hand-off lexically dominating it is a path
  that drops an admitted entry on the floor — the caller blocks until
  its own timeout, and nothing counts the loss.  (Value-carrying
  returns are the bail PROTOCOL — ``return False`` hands the round
  back to the scalar rung — and are exempt.)
- **R14.2 answer exclusivity.**  Two answer sites reachable in ONE
  execution of a function, sharing an argument identity (the same
  entry/batch), with no dominating exclusivity guard between them —
  the ``answered`` cell, ``thread_round_is_shed``/deposal checks, the
  ``drain_lock`` atomic pop — is the double-reply shape: a packed
  reply stream answering one seq twice desyncs the shim.  Guards may
  live in the CALLEE (``_shed_item`` checks ``batch.answered`` before
  its send; ``send``/``send_frames`` mark under the write lock), so
  the check is interprocedural: only an answer path with no guard
  anywhere along it fires.
"""

from __future__ import annotations

import ast
import os

from .callgraph import get_graph
from .core import Finding, call_func_name

_HOT_BASENAMES = {"dispatch.py", "service.py", "shm.py", "reasm.py",
                  "client.py"}

# Direct answer emission, keyed by entry/seq: sends and typed-reply
# records.  (``send`` itself is covered through send_verdicts/
# send_frames — the bare name would drag control-plane frames in.)
ANSWER_TERMINALS = {
    "send_verdicts", "send_frames", "_shed_item", "_on_batch_error",
    "on_batch_error", "on_stall", "_send_cache_grants",
}

# Typed hand-offs: the entry stays accountable downstream (dispatcher
# queue, completion pipeline, columnar assembly, lane-exit release —
# ``adopt_residue``/``drop`` are the arena-carry accountability
# transfers of the columnar lane exit).
HANDOFF_TERMINALS = {
    "submit", "submit_many", "submit_data", "submit_matrix",
    "submit_ring", "_completion_put", "put", "put_nowait",
    "assemble", "_classify_entry", "_reasm_bail", "close_connection",
    "adopt_residue", "drop",
}

# Exclusivity-guard vocabulary: an expression touching one of these is
# the answered-cell / shed-round / deposal / drain-lock dance.
_GUARD_SUBSTRINGS = ("answered", "suppressed", "deposed", "is_shed",
                     "_shed_rounds", "drain_lock")

# Control-plane job queues: a ``.put`` on these receivers enqueues
# BUILDER work (epoch swaps, rebinds, mesh reshape/reprobe jobs, grant
# pushes), never an admitted entry — it is not an entry hand-off, so
# it neither discharges an admit root's accountability (R14.1) nor
# makes its caller an answer site (R14.2).  Without this, the mesh
# demote path (dispatch -> _mesh_guarded -> _demote_mesh -> reshape
# job enqueue) would turn every model call into a phantom answer site.
_CONTROL_QUEUE_RECEIVERS = ("_build_queue",)


def _is_control_queue_call(call: ast.Call) -> bool:
    fn = call.func
    if not (isinstance(fn, ast.Attribute)
            and fn.attr in ("put", "put_nowait")):
        return False
    recv = fn.value
    return isinstance(recv, ast.Attribute) and (
        recv.attr in _CONTROL_QUEUE_RECEIVERS
    )

_ADMIT_EXACT = {"_shm_doorbell", "_shm_submit_records"}


def _is_admit_root(name: str) -> bool:
    # ``_reasm*``: the columnar lane-exit plumbing — a bail/release
    # that bare-returns without handing the carry anywhere is the
    # PR 10 silent-byte-loss shape.  ``_fanin*``: the multi-session
    # coalescer seam — an admission gate or a coalesced round's
    # per-session slice fan-out that bare-returns (a quarantined
    # session's batch dropped unanswered, or a dead session's slice
    # aborting the remaining sessions' sends) is the same silent-loss
    # class, now scoped to a tenant.  (Value-carrying returns stay the
    # bail protocol: the fan-in admission gate returns its shed reason
    # and the CALLER owes the typed answer.)
    return (name.startswith("submit_") or name.startswith("_process")
            or name.startswith("_reasm") or name.startswith("_fanin")
            or name in _ADMIT_EXACT)


def _has_guard_text(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and any(
                g in sub.attr for g in _GUARD_SUBSTRINGS):
            return True
        if isinstance(sub, ast.Name) and any(
                g in sub.id for g in _GUARD_SUBSTRINGS):
            return True
    return False


def _fn_has_guard_marker(fn: ast.AST) -> bool:
    return _has_guard_text(fn)


def _arg_idents(call: ast.Call) -> set[str]:
    """Name identities flowing into a call's arguments — the 'same
    entry' approximation for R14.2 pairing (two sends that share no
    argument identity answer different entries)."""
    out: set[str] = set()
    for a in list(call.args) + [kw.value for kw in call.keywords]:
        for sub in ast.walk(a):
            if isinstance(sub, ast.Name):
                out.add(sub.id)
            elif isinstance(sub, ast.Attribute) and isinstance(
                    sub.value, ast.Name):
                out.add(sub.value.id)
    out.discard("self")
    return out


# --- whole-program answer summaries ---------------------------------------

class _AnswerState:
    """Per-function answer facts over one graph: ``answers`` (reaches
    an answer site or hand-off), ``chain`` (how), ``exposes`` (has an
    answer path with NO guard anywhere along it — the callee side of
    R14.2), ``guard_marker`` (touches the exclusivity vocabulary)."""

    def __init__(self, graph) -> None:
        self.graph = graph
        self.call_keys: dict[int, list] = {}
        for fi in graph.funcs.values():
            for call, _l, _c, _held, keys in fi.calls:
                self.call_keys[id(call)] = keys or []
        self.answers: dict[str, bool] = {}
        self.chain: dict[str, tuple] = {}
        self.guard_marker: dict[str, bool] = {}
        self.exposes: dict[str, bool] = {}
        self._build()

    def _build(self) -> None:
        graph = self.graph
        for fi in graph.funcs.values():
            self.guard_marker[fi.key] = _fn_has_guard_marker(fi.node)
            direct = None
            for call, line, _c, _held, _keys in fi.calls:
                name = call_func_name(call)
                if (name in ANSWER_TERMINALS
                        or name in HANDOFF_TERMINALS) and not (
                            _is_control_queue_call(call)):
                    direct = (name,)
                    break
            self.answers[fi.key] = direct is not None
            self.chain[fi.key] = direct or ()
            # Direct exposure: an ANSWER_TERMINAL call in a function
            # with no guard vocabulary anywhere.
            self.exposes[fi.key] = bool(
                not self.guard_marker[fi.key]
                and any(
                    call_func_name(call) in ANSWER_TERMINALS
                    for call, *_ in fi.calls
                )
            )
        changed = True
        guard = 0
        while changed and guard < 60:
            changed = False
            guard += 1
            for fi in graph.funcs.values():
                for call, _l, _c, _held, keys in fi.calls:
                    for key in keys or ():
                        callee = graph.funcs.get(key)
                        if callee is None:
                            continue
                        if self.answers.get(key) and not self.answers[
                                fi.key]:
                            chain = self.chain.get(key, ())
                            if len(chain) < 8:
                                self.answers[fi.key] = True
                                self.chain[fi.key] = (
                                    callee.name,
                                ) + chain
                                changed = True
                        if (
                            self.exposes.get(key)
                            and not self.exposes[fi.key]
                            and not self.guard_marker[fi.key]
                        ):
                            self.exposes[fi.key] = True
                            changed = True

    def is_answer_event(self, call: ast.Call) -> bool:
        name = call_func_name(call)
        if name in ANSWER_TERMINALS or name in HANDOFF_TERMINALS:
            return not _is_control_queue_call(call)
        return any(
            self.answers.get(k) for k in self.call_keys.get(id(call), ())
        )

    def needs_guard(self, call: ast.Call) -> bool:
        """True when this answer event has no exclusivity guard
        anywhere along its own path — a second reply through it cannot
        stand itself down."""
        name = call_func_name(call)
        if name in HANDOFF_TERMINALS:
            return False
        keys = self.call_keys.get(id(call), ())
        if keys:
            resolved = [self.graph.funcs.get(k) for k in keys]
            if name in ANSWER_TERMINALS:
                return any(
                    fi is not None and not self.guard_marker.get(fi.key)
                    for fi in resolved
                )
            return any(self.exposes.get(k) for k in keys)
        return name in ANSWER_TERMINALS


def _answer_state(files) -> _AnswerState:
    graph = get_graph(files)
    state = graph.rule_memo.get("r14_state")
    if state is None:
        state = _AnswerState(graph)
        graph.rule_memo["r14_state"] = state
    return state


# --- ordered event walk ---------------------------------------------------

_ANSWER, _GUARD, _ALT = 0, 1, 2


def _stmt_events(node: ast.AST, state: _AnswerState) -> list:
    """Events inside ONE expression/simple statement, in source order:
    (kind, payload).  Nested function bodies are their own scopes."""
    found = []
    for sub in ast.walk(node):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue
        if isinstance(sub, ast.Call) and state.is_answer_event(sub):
            found.append((sub.lineno, sub.col_offset, (_ANSWER, sub)))
    if _has_guard_text(node):
        found.append((node.lineno, -1, (_GUARD, node.lineno)))
    found.sort(key=lambda t: (t[0], t[1]))
    return [ev for _l, _c, ev in found]


def _terminates(stmts) -> bool:
    """A statement list that cannot fall through to the code after its
    If: the function RETURNS before any later answer site runs, so
    answer events inside it can never pair with one below — the fan-in
    admission gates' shed-then-return shape.  A trailing ``raise`` is
    deliberately NOT terminating: it can land in a same-function
    except handler, which is exactly the PR 2 double-reply window the
    Try model pairs body sends with handler sends across."""
    return bool(stmts) and isinstance(stmts[-1], ast.Return)


def _body_events(stmts, state: _AnswerState) -> list:
    out: list = []
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(stmt, ast.If):
            out.extend(_stmt_events(stmt.test, state))
            out.append((_ALT, [
                (_body_events(stmt.body, state),
                 _terminates(stmt.body)),
                (_body_events(stmt.orelse, state),
                 _terminates(stmt.orelse)),
            ]))
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            out.extend(_stmt_events(stmt.iter, state))
            out.extend(_body_events(stmt.body, state))
            out.extend(_body_events(stmt.orelse, state))
        elif isinstance(stmt, ast.While):
            out.extend(_stmt_events(stmt.test, state))
            out.extend(_body_events(stmt.body, state))
            out.extend(_body_events(stmt.orelse, state))
        elif isinstance(stmt, ast.Try):
            out.extend(_body_events(stmt.body, state))
            # Handlers are alternatives of each other but SEQUENTIAL
            # with the body: an exception after the body's send still
            # reaches the handler — exactly the PR 2 double-reply
            # window.
            if stmt.handlers:
                out.append((_ALT, [
                    (_body_events(h.body, state), _terminates(h.body))
                    for h in stmt.handlers
                ] + [([], False)]))
            out.extend(_body_events(stmt.orelse, state))
            out.extend(_body_events(stmt.finalbody, state))
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                out.extend(_stmt_events(item.context_expr, state))
            out.extend(_body_events(stmt.body, state))
        else:
            out.extend(_stmt_events(stmt, state))
    return out


def _walk_pairs(events, opens, state: _AnswerState, findings: list):
    """Sequential double-answer scan: ``opens`` holds answer events not
    yet separated by a guard; a guard clears them; branch alternatives
    fork the state and merge by union."""
    for ev in events:
        if ev[0] == _GUARD:
            opens.clear()
        elif ev[0] == _ALT:
            merged: list = []
            for branch, terminated in ev[1]:
                branch_opens = list(opens)
                _walk_pairs(branch, branch_opens, state, findings)
                if terminated:
                    # The branch returns out of the function: its open
                    # answer events can never meet an answer site below
                    # the If — the admission gates' shed-then-return
                    # bail shape is exclusive by control flow, not by
                    # guard.  (Raise-ending branches are NOT pruned:
                    # they can resume in a same-function handler.)
                    continue
                merged.extend(
                    e for e in branch_opens if e not in merged
                )
            opens[:] = merged
        else:
            call = ev[1]
            if state.needs_guard(call):
                idents = _arg_idents(call)
                for prev in opens:
                    if prev is call:
                        continue
                    if idents & _arg_idents(prev):
                        findings.append((call, prev))
                        break
            if call not in opens:
                opens.append(call)
    return opens


# --- the rule -------------------------------------------------------------

def _own_returns(fn):
    """Return statements of fn's OWN body — nested defs are their own
    scopes and must not contribute returns to the enclosing root."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Return):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def check_r14(files):
    state = _answer_state(files)
    graph = state.graph

    for fi in sorted(graph.funcs.values(), key=lambda f: (f.path,
                                                          f.node.lineno)):
        if os.path.basename(fi.path) not in _HOT_BASENAMES:
            continue

        # R14.1 — admit accounting: bare returns with no dominating
        # answer site / typed hand-off in an admit root.
        if _is_admit_root(fi.node.name):
            event_lines = [
                call.lineno for call, *_ in fi.calls
                if state.is_answer_event(call)
            ]
            for node in _own_returns(fi.node):
                bare = node.value is None or (
                    isinstance(node.value, ast.Constant)
                    and node.value.value is None
                )
                if not bare:
                    continue  # value returns are the bail protocol
                if any(line <= node.lineno for line in event_lines):
                    continue
                yield Finding(
                    "R14", fi.path, node.lineno, node.col_offset,
                    "admit path can return without reaching an answer "
                    "site or a typed hand-off: an entry admitted "
                    "through this root is dropped on the floor — no "
                    "SHED, no error verdict, no dispatcher queue — "
                    "and its caller blocks until its own timeout "
                    "(silent-loss class; answer it typed or hand it "
                    "off before bailing)",
                    symbol=fi.qual,
                )

        # R14.2 — answer exclusivity: two answer sites for the same
        # entry with no dominating guard between them.
        events = _body_events(fi.node.body, state)
        pair_findings: list = []
        _walk_pairs(events, [], state, pair_findings)
        seen: set = set()
        for call, prev in pair_findings:
            key = (call.lineno, call.col_offset)
            if key in seen:
                continue
            seen.add(key)
            yield Finding(
                "R14", fi.path, call.lineno, call.col_offset,
                f"second answer site ({call_func_name(call)}) "
                f"reachable for the same entry as "
                f"{call_func_name(prev)} (line {prev.lineno}) with no "
                f"dominating exclusivity guard — no answered-cell "
                f"check, no thread_round_is_shed/deposal check, no "
                f"drain-lock pop anywhere on the path: a double reply "
                f"for one seq desyncs the shim (the PR 2 "
                f"deposed-round bug class)",
                symbol=fi.qual,
            )
