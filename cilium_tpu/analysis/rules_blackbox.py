"""R22 — fail-closed flight-recorder coverage against ``FAIL_CLOSED``.

R18 proves every typestate store is a declared, mediated edge; this
pass proves the declared FAIL-CLOSED surface is *observable*: every
row of ``analysis/protocols.py::FAIL_CLOSED`` must be a real row (a
declared table + edge for ``kind="edge"``, a token for
``kind="marker"``) AND must reach a recorder emit site somewhere in
the scanned set — a fail-closed transition the flight recorder can
never capture produces no incident timeline and no postmortem bundle,
which is exactly the blind spot the recorder exists to close.

Emit-site resolution per kind:

- **edge** rows ride the ``Typestate.advance/guard/require_edges``
  choke point (the transition observer hooks mediation itself), so an
  edge is covered when some mediated call on its protocol object can
  take it: an ``advance`` whose resolved target state is the edge's
  ``to`` (the from-state is runtime data — any advance into ``to``
  can record the edge), or a ``guard``/``require_edges`` naming the
  exact ``(frm, to)`` pair.
- **marker** rows are recorded explicitly, so the token string must
  appear as the first argument of a ``record_mark`` /
  ``broadcast_mark`` call.

Extraction mirrors R18: the FAIL_CLOSED literal and the Typestate
declarations are read from the scanned set itself, so a corpus twin
carrying its own table exercises the same machinery the real tree
does.  Resolution order is scanned-set first; when the declaring file
belongs to a real package (its grandparent directory carries an
``__init__.py``) and a row stays uncovered, the rest of that package
is parsed from disk before flagging — a partial scan of
``analysis/`` alone must not report the service's emit sites missing
(R21's resolution shape).  Corpus twins live outside any package, so
their coverage is judged on the scanned set alone.
"""

from __future__ import annotations

import ast
import glob as _glob
import hashlib
import os

from .core import Finding, SourceFile, terminal_name, walk_functions
from .rules_typestate import (
    _UNRESOLVED,
    _extract_protocols,
    _mediation_call,
    _pools,
    _resolve,
    _resolve_states,
)

_MARK_CALLS = {"record_mark", "broadcast_mark"}


def _extract_fail_closed(files, pools):
    """(rows, defining path, line) from the first
    ``FAIL_CLOSED = (...)`` tuple in the scanned set.  Row values may
    be constants or module-level constant names (the real table names
    its states symbolically)."""
    for path, sf in sorted(files.items()):
        pool = pools[path]
        for node in sf.tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "FAIL_CLOSED"
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                continue
            rows = []
            for e in node.value.elts:
                if not isinstance(e, ast.Dict):
                    continue
                row: dict = {"_line": e.lineno, "_col": e.col_offset}
                for k, v in zip(e.keys, e.values):
                    key = _resolve(k, pool)
                    if not isinstance(key, str):
                        continue
                    if (key == "edge"
                            and isinstance(v, (ast.Tuple, ast.List))
                            and len(v.elts) == 2):
                        frm = _resolve(v.elts[0], pool)
                        to = _resolve(v.elts[1], pool)
                        if frm is not _UNRESOLVED and to is not _UNRESOLVED:
                            row["edge"] = (frm, to)
                    else:
                        got = _resolve(v, pool)
                        if got is not _UNRESOLVED:
                            row[key] = got
                rows.append(row)
            return rows, path, node.lineno
    return None, None, 0


def _emit_sites(files, pools, protos):
    """(advance_targets, exact_pairs, mark_tokens) over the scanned
    set: which (protocol name, to)-states some advance can enter,
    which (protocol name, frm, to) pairs a guard/require_edges names
    exactly, and which marker tokens reach a record_mark /
    broadcast_mark call."""
    objs = {p.obj for p in protos}
    by_obj = {p.obj: p for p in protos}
    advance_targets: set = set()
    exact_pairs: set = set()
    mark_tokens: set = set()
    for path, sf in sorted(files.items()):
        pool = pools[path]
        for fn, _qual, _cls in walk_functions(sf.tree):
            for node in ast.walk(fn):
                med = _mediation_call(node, objs)
                if med is not None:
                    obj, method, call = med
                    proto = by_obj[obj]
                    if method == "advance" and len(call.args) >= 2:
                        for to in _resolve_states(call.args[1], pool):
                            advance_targets.add((proto.name, to))
                    elif method == "guard" and len(call.args) >= 2:
                        for frm in _resolve_states(call.args[0], pool):
                            for to in _resolve_states(call.args[1], pool):
                                exact_pairs.add((proto.name, frm, to))
                    elif (method == "require_edges"
                          and len(call.args) >= 2):
                        frms_e = call.args[0]
                        frms: list = []
                        if isinstance(frms_e, (ast.Tuple, ast.List)):
                            for e in frms_e.elts:
                                frms.extend(_resolve_states(e, pool))
                        for to in _resolve_states(call.args[1], pool):
                            for frm in frms:
                                exact_pairs.add((proto.name, frm, to))
                    continue
                if (isinstance(node, ast.Call)
                        and terminal_name(node.func) in _MARK_CALLS
                        and node.args):
                    tok = _resolve(node.args[0], pool)
                    if isinstance(tok, str):
                        mark_tokens.add(tok)
    return advance_targets, exact_pairs, mark_tokens


def _pkg_root(decl_path):
    """The declaring file's package root (grandparent directory) — but
    only when it IS a package: corpus twins and tmp-dir fixtures have
    no ``__init__.py`` there, so their coverage stays scanned-set-only."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(decl_path)))
    if os.path.isfile(os.path.join(root, "__init__.py")):
        return root
    return None


def _disk_emit_sites(pkg_root, files, protos):
    """Emit sites harvested from the declaring package's unscanned
    files on disk — the fallback that keeps a partial scan (e.g.
    ``--device-contracts analysis/``) from flagging rows whose emit
    sites live in the sidecar/daemon halves of the same package.
    Pools are built over scanned + disk files together: a disk-side
    consumer resolves its state constants through the scanned
    declaring file, exactly as a full-tree scan would."""
    scanned_abs = {os.path.abspath(p) for p in files}
    extra = {}
    for cand in sorted(_glob.glob(
            os.path.join(pkg_root, "**", "*.py"), recursive=True)):
        if os.path.abspath(cand) in scanned_abs:
            continue
        try:
            with open(cand, "r", encoding="utf-8", errors="replace") as f:
                text = f.read()
        except OSError:
            continue
        sf = SourceFile(cand, text)
        if sf.tree is None:
            continue
        extra[cand] = sf
    if not extra:
        return set(), set(), set()
    both = dict(files)
    both.update(extra)
    return _emit_sites(extra, _pools(both), protos)


def _memo_extra(files) -> str:
    """Stat signature of the declaring package's ``.py`` files on disk —
    the coverage fallback reads them outside the scanned set, so their
    edits must invalidate the rule memo."""
    sig = []
    for path, sf in sorted(files.items()):
        if "FAIL_CLOSED" not in sf.text:
            continue
        root = _pkg_root(path)
        if root is None:
            continue
        for cand in sorted(_glob.glob(
                os.path.join(root, "**", "*.py"), recursive=True)):
            try:
                st = os.stat(cand)
                sig.append(f"{cand}:{st.st_size}:{st.st_mtime_ns}")
            except OSError:
                continue
        break
    return hashlib.sha256("|".join(sig).encode()).hexdigest()[:16]


def check_r22(files):
    pools = _pools(files)
    rows, decl_path, decl_line = _extract_fail_closed(files, pools)
    if rows is None:
        return
    protos, _bad = _extract_protocols(files, pools)  # R18 owns the bad
    by_name = {p.name: p for p in protos}
    advance_targets, exact_pairs, mark_tokens = _emit_sites(
        files, pools, protos
    )
    widened = []

    def _widen():
        # Lazy one-shot union of the package's on-disk emit sites;
        # only triggered when the scanned set alone leaves a row
        # uncovered, and only for real packages (see _pkg_root).
        if widened:
            return
        widened.append(True)
        root = _pkg_root(decl_path)
        if root is None:
            return
        adv, pairs, toks = _disk_emit_sites(root, files, protos)
        advance_targets.update(adv)
        exact_pairs.update(pairs)
        mark_tokens.update(toks)

    for row in rows:
        line, col = row["_line"], row["_col"]
        kind = row.get("kind")
        if kind == "edge":
            table = row.get("table")
            proto = by_name.get(table)
            if proto is None:
                yield Finding(
                    "R22", decl_path, line, col,
                    f"FAIL_CLOSED edge row names undeclared typestate "
                    f"table {table!r}",
                )
                continue
            edge = row.get("edge")
            if edge is None or edge not in proto.edges:
                yield Finding(
                    "R22", decl_path, line, col,
                    f"FAIL_CLOSED row names edge {edge!r} which is not "
                    f"a declared edge of typestate {table!r}",
                )
                continue
            frm, to = edge
            if ((table, to) not in advance_targets
                    and (table, frm, to) not in exact_pairs):
                _widen()
            if ((table, to) not in advance_targets
                    and (table, frm, to) not in exact_pairs):
                yield Finding(
                    "R22", decl_path, line, col,
                    f"fail-closed edge {table!r}: {frm!r} -> {to!r} "
                    f"has no mediated transition site in the scanned "
                    f"set — the flight recorder can never capture "
                    f"this incident (no advance into {to!r}, no "
                    f"guard/require_edges naming the pair)",
                )
        elif kind == "marker":
            token = row.get("token")
            if not isinstance(token, str):
                yield Finding(
                    "R22", decl_path, line, col,
                    "FAIL_CLOSED marker row carries no token string",
                )
                continue
            if token not in mark_tokens:
                _widen()
            if token not in mark_tokens:
                yield Finding(
                    "R22", decl_path, line, col,
                    f"fail-closed marker {token!r} never reaches a "
                    f"record_mark/broadcast_mark call — the flight "
                    f"recorder can never capture this incident",
                )
        else:
            yield Finding(
                "R22", decl_path, line, col,
                f"FAIL_CLOSED row has unknown kind {kind!r} (expected "
                f"'edge' or 'marker')",
            )


check_r22.memo_extra = _memo_extra
