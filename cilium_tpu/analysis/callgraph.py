"""Whole-program interprocedural engine: modules, imports, call graph.

PR 3's rules were deliberately AST-local — R4's docstring said it out
loud ("cross-module reachability is out of scope").  The hazards the
next ROADMAP items introduce are exactly the ones that scoping hides: a
blocking ``sendall`` reached through ``utils.sockutil``, a lock-order
inversion whose two halves live in ``sidecar/`` and ``kvstore/``, JIT
impurity in a ``models/base.py`` helper reached from a ``service.py``
jit call site.  This module gives every rule the project-wide view:

- **Module naming.**  A scanned file's dotted module name is derived
  from the ``__init__.py`` package chain above it
  (``cilium_tpu/sidecar/client.py`` -> ``cilium_tpu.sidecar.client``);
  files outside any package (the lint corpus) are top-level modules
  named by stem, so a two-file corpus pair exercises the same
  resolution the real tree does.
- **Import resolution.**  ``import a.b as c`` / ``from ..utils import
  sockutil`` / ``from .core import Finding`` all resolve against the
  scanned set, including relative levels and the from-import-of-a-
  submodule case.
- **Call resolution.**  Bare names resolve to module-level defs or
  from-imports; ``alias.f()`` resolves through module aliases;
  ``self.m()`` resolves to methods of the enclosing class first, then
  (same-module approximation) any same-named method.  Unresolvable
  receivers stay unresolved — precision over recall, so interprocedural
  findings are trustworthy enough to gate a build on.
- **Function summaries.**  Per function: direct blocking calls, locks
  acquired, call sites with the lock stack held at that point.  A
  fixed-point pass turns those into transitive facts (``blocks_via``:
  the helper chain to a blocking call; ``acquires``: every lock
  identity a call may take), which R1/R2/R4 consume.

Lock identity is qualified — ``Class._lock`` for ``self`` attributes,
``module:name`` for locals/globals — so the whole-program lock-order
graph never conflates two classes' equally-named ``_lock`` attributes:
an inversion finding requires the SAME two identities observed in both
orders.

The graph is memoized per content-hash of the scanned set (see
``get_graph``), which is what keeps the tier-1 gate fast: one build is
shared by every rule and every analyze_paths call in the process.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from .core import (
    is_lock_like_expr,
    local_assignments,
    lock_terminal,
    unparse,
    walk_functions,
)

# Functions that ARE lock implementations or guards (mirrors
# rules_locks: pairing/blocking inside them is the mechanism, not a
# bug) — taint and lock summaries do not propagate OUT of them either,
# or every ``with lock:`` would inherit Lock.acquire's own guts.
WRAPPER_FUNCS = {
    "acquire", "release", "r_acquire", "r_release",
    "__enter__", "__exit__", "locked", "read",
}


def module_name_for(path: str) -> tuple[str, bool]:
    """(dotted module name, in_package) from the ``__init__.py`` chain
    above path.  Files outside any package report in_package=False —
    the caller must key them by DIRECTORY too, or two corpus dirs'
    equally-named ``client.py`` files would clobber each other's
    symbol tables and silently disable the interprocedural rules."""
    path = os.path.abspath(path)
    stem = os.path.splitext(os.path.basename(path))[0]
    parts = [] if stem == "__init__" else [stem]
    d = os.path.dirname(path)
    in_package = os.path.exists(os.path.join(d, "__init__.py"))
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.insert(0, os.path.basename(d))
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return ".".join(parts) if parts else stem, in_package


@dataclass
class FuncInfo:
    node: ast.AST  # FunctionDef / AsyncFunctionDef
    module: str
    path: str
    qual: str
    cls: str  # enclosing class name, "" at module level
    cls_node: "ast.ClassDef | None" = None
    # Uniquifier for duplicate qualnames (property getter/setter
    # pairs, same-name defs in both branches of an if): without it the
    # funcs table is last-wins and the shadowed def silently drops out
    # of jit reachability.
    key_suffix: str = ""
    # direct facts (own body only, nested defs excluded)
    blocking: list = field(default_factory=list)  # (reason, line, col)
    acquired: set = field(default_factory=set)  # lock identities
    # lexical lock nestings: (outer_ident, inner_ident, line, col) —
    # ``with a: with b:`` AND ``with a, b:`` both count
    lex_nestings: list = field(default_factory=list)
    # (call node, line, col, held lock-identity tuple, callee key list)
    calls: list = field(default_factory=list)
    # transitive facts (fixed point)
    blocks_via: "tuple | None" = None  # (chain tuple, reason) or None
    t_acquires: dict = field(default_factory=dict)
    # lock identity -> call chain tuple that reaches its acquire

    @property
    def key(self) -> str:
        return f"{self.module}:{self.qual}{self.key_suffix}"

    @property
    def name(self) -> str:
        return self.node.name


class _Imports:
    """One module's import table: alias -> resolved dotted target."""

    def __init__(self) -> None:
        # alias -> ("module", dotted) or ("symbol", dotted_module, name)
        self.aliases: dict[str, tuple] = {}

    def module_for(self, name: str) -> str | None:
        got = self.aliases.get(name)
        if got is not None and got[0] == "module":
            return got[1]
        return None

    def symbol_for(self, name: str) -> tuple[str, str] | None:
        got = self.aliases.get(name)
        if got is not None and got[0] == "symbol":
            return got[1], got[2]
        return None


class ProjectGraph:
    """Symbol tables + call graph + summaries over one scanned set."""

    def __init__(self, files: dict) -> None:
        self.files = files
        # Per-rule scratch memo: rules stash expensive intermediates
        # (or serialized findings) here; the graph itself is memoized
        # by content hash, so entries inherit correct invalidation.
        self.rule_memo: dict = {}
        self.modules: dict[str, str] = {}  # module key -> path
        self.mod_of_path: dict[str, str] = {}
        # (directory, stem) -> module key, for resolving bare imports
        # between NON-package files: two scanned dirs may each hold a
        # ``client.py``, so their keys carry the directory and a bare
        # ``import wire`` resolves against the importer's own dir.
        self._dir_stems: dict[tuple[str, str], str] = {}
        for path in files:
            mod, in_pkg = module_name_for(path)
            d = os.path.dirname(os.path.abspath(path))
            if not in_pkg:
                key = f"{d}::{mod}"
                self._dir_stems[(d, mod)] = key
                mod = key
            self.modules[mod] = path
            self.mod_of_path[path] = mod
        self.imports: dict[str, _Imports] = {}
        # module -> {func bare/qual name -> [FuncInfo]}
        self.defs: dict[str, dict[str, list[FuncInfo]]] = {}
        # module -> {class name -> {method name -> FuncInfo}}
        self.methods: dict[str, dict[str, dict[str, FuncInfo]]] = {}
        # module -> {class name -> [base class dotted refs]}
        self.bases: dict[str, dict[str, list[str]]] = {}
        self.funcs: dict[str, FuncInfo] = {}  # key -> FuncInfo
        self.by_node: dict[int, FuncInfo] = {}  # id(fn node) -> info
        for path, sf in files.items():
            self._index_module(self.mod_of_path[path], path, sf)
        for fi in self.funcs.values():
            self._summarize(fi)
        self._resolve_calls()
        self._fixpoint()

    # -- indexing ----------------------------------------------------------

    def _resolve_modref(self, name: str, cur_dir: str) -> str:
        """Registered module key for a (possibly bare) module
        reference: dotted package names match directly; bare stems
        resolve against the importer's own directory."""
        if name in self.modules:
            return name
        return self._dir_stems.get((cur_dir, name), name)

    def _index_module(self, mod: str, path: str, sf) -> None:
        imp = _Imports()
        self.imports[mod] = imp
        cur_dir = os.path.dirname(os.path.abspath(path))
        # Relative-import anchor: for pkg/__init__.py the module name
        # IS the package, so level-1 imports anchor at mod itself;
        # everywhere else at the containing package.
        if "::" in mod:
            pkg_parts = []
        elif os.path.basename(path) == "__init__.py":
            pkg_parts = mod.split(".")
        else:
            pkg_parts = mod.split(".")[:-1]
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    target = a.name if a.asname else a.name.split(".")[0]
                    imp.aliases[alias] = (
                        "module", self._resolve_modref(target, cur_dir)
                    )
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    anchor = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    base = ".".join(anchor + ([base] if base else []))
                base = self._resolve_modref(base, cur_dir)
                for a in node.names:
                    if a.name == "*":
                        continue
                    alias = a.asname or a.name
                    sub = f"{base}.{a.name}" if base else a.name
                    if sub in self.modules:
                        imp.aliases[alias] = ("module", sub)
                    else:
                        imp.aliases[alias] = ("symbol", base, a.name)

        table: dict[str, list[FuncInfo]] = {}
        meths: dict[str, dict[str, FuncInfo]] = {}
        bases: dict[str, list[str]] = {}
        for fn, qual, cls in walk_functions(sf.tree):
            fi = FuncInfo(node=fn, module=mod, path=path, qual=qual,
                          cls=cls.name if cls is not None else "",
                          cls_node=cls)
            if fi.key in self.funcs:
                fi.key_suffix = f"@{fn.lineno}"
            table.setdefault(fn.name, []).append(fi)
            if qual != fn.name:
                table.setdefault(qual, []).append(fi)
            if cls is not None:
                meths.setdefault(cls.name, {})[fn.name] = fi
            self.funcs[fi.key] = fi
            self.by_node[id(fn)] = fi
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                bases[node.name] = [unparse(b) for b in node.bases]
        self.defs[mod] = table
        self.methods[mod] = meths
        self.bases[mod] = bases

    # -- call resolution ---------------------------------------------------

    def resolve_call(self, call: ast.Call, fi: FuncInfo) -> list[FuncInfo]:
        """FuncInfos a call site may invoke (empty when unresolvable)."""
        func = call.func
        mod = fi.module
        imp = self.imports[mod]
        if isinstance(func, ast.Name):
            name = func.id
            sym = imp.symbol_for(name)
            if sym is not None:
                tmod, tname = sym
                if tmod in self.defs:
                    return [
                        f for f in self.defs[tmod].get(tname, ())
                        if f.cls == ""
                    ]
                return []
            local = [f for f in self.defs[mod].get(name, ()) if f.cls == ""]
            if local:
                return local
            # class constructor: Foo() runs Foo.__init__
            init = self.methods[mod].get(name, {}).get("__init__")
            if init is not None:
                return [init]
            return []
        if isinstance(func, ast.Attribute):
            attr = func.attr
            recv = func.value
            # self.m() — enclosing class first (incl. resolved bases),
            # then the same-module name approximation.
            if isinstance(recv, ast.Name) and recv.id == "self":
                got = self._resolve_method(mod, fi.cls, attr)
                if got:
                    return got
                out = []
                for meths in self.methods[mod].values():
                    if attr in meths:
                        out.append(meths[attr])
                return out
            # module_alias.f() / pkg.sub.f()
            tmod = self._module_of_expr(recv, imp)
            if tmod is not None and tmod in self.defs:
                return [
                    f for f in self.defs[tmod].get(attr, ())
                    if f.cls == ""
                ]
            # Cls.m() — class referenced by name (same module or import)
            if isinstance(recv, ast.Name):
                got = self.methods[mod].get(recv.id, {}).get(attr)
                if got is not None:
                    return [got]
                sym = imp.symbol_for(recv.id)
                if sym is not None:
                    tmod2, cname = sym
                    got = self.methods.get(tmod2, {}).get(
                        cname, {}
                    ).get(attr)
                    if got is not None:
                        return [got]
        return []

    def _module_of_expr(self, expr: ast.AST, imp: _Imports) -> str | None:
        """Dotted module named by an expression (``alias`` or
        ``alias.sub`` chains), if it is a scanned module."""
        if isinstance(expr, ast.Name):
            return imp.module_for(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self._module_of_expr(expr.value, imp)
            if base is not None:
                cand = f"{base}.{expr.attr}"
                if cand in self.modules:
                    return cand
        return None

    def _resolve_method(self, mod: str, cls: str, attr: str,
                        _seen: frozenset = frozenset()) -> list[FuncInfo]:
        """Method lookup through the (resolved) base-class chain."""
        if not cls or mod not in self.imports or (mod, cls) in _seen:
            return []
        got = self.methods.get(mod, {}).get(cls, {}).get(attr)
        if got is not None:
            return [got]
        out: list[FuncInfo] = []
        seen = _seen | {(mod, cls)}
        imp = self.imports[mod]
        for base_ref in self.bases.get(mod, {}).get(cls, ()):
            base_name = base_ref.split(".")[-1]
            if base_name in self.methods.get(mod, {}):
                out.extend(
                    self._resolve_method(mod, base_name, attr, seen)
                )
                continue
            head = base_ref.split(".")[0]
            sym = imp.symbol_for(head)
            if sym is not None:
                # ``from .base import VerdictModel`` then
                # ``class M(VerdictModel)`` — the base lives in the
                # imported module under its imported name.
                tmod, tname = sym
                out.extend(self._resolve_method(
                    tmod, tname if head == base_ref else base_name,
                    attr, seen))
                continue
            tmod = imp.module_for(head)
            if tmod is not None and "." in base_ref:
                out.extend(
                    self._resolve_method(tmod, base_name, attr, seen)
                )
        return out

    # -- lock identity -----------------------------------------------------

    def lock_identity(self, expr: ast.AST, fi: FuncInfo,
                      aliases: dict) -> str | None:
        """Qualified identity for a lock expression: ``Cls.attr`` for
        self attributes, ``module:name`` for locals/globals, terminal
        name otherwise.  None when the expression isn't lock-like."""
        if not is_lock_like_expr(expr, aliases):
            return None
        term = lock_terminal(expr, aliases)
        if not term:
            return None
        # unwrap rw.read()-style guards to the receiver for ownership
        probe = expr
        if isinstance(probe, ast.Call) and isinstance(
                probe.func, ast.Attribute):
            probe = probe.func.value
        if isinstance(probe, ast.Name) and probe.id in aliases:
            probe = aliases[probe.id]
        if (isinstance(probe, ast.Attribute)
                and isinstance(probe.value, ast.Name)
                and probe.value.id == "self"):
            return f"{fi.cls or fi.module}.{term}"
        if isinstance(probe, ast.Name):
            # A lock imported by name belongs to its DEFINING module:
            # ``from store import _store_lock`` used here is the same
            # object as store's own — the cross-module sharing that
            # makes cross-module deadlocks possible in the first
            # place.
            sym = self.imports[fi.module].symbol_for(probe.id)
            if sym is not None:
                return f"{sym[0]}:{sym[1]}"
            return f"{fi.module}:{term}"
        if isinstance(probe, ast.Attribute):
            # ``store._store_lock`` through a module alias: same
            # defining-module identity as store's own uses.
            tmod = self._module_of_expr(
                probe.value, self.imports[fi.module]
            )
            if tmod is not None:
                return f"{tmod}:{term}"
        return term

    @staticmethod
    def lock_terminal_of(identity: str) -> str:
        """Back out the bare attribute/local name from an identity."""
        return identity.split(".")[-1].split(":")[-1]

    # -- summaries ---------------------------------------------------------

    def _summarize(self, fi: FuncInfo) -> None:
        from .rules_locks import _blocking_reason  # shared taxonomy

        fn = fi.node
        aliases = local_assignments(fn)

        def visit(node, held: tuple) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, ast.With):
                taken = list(held)
                for item in node.items:
                    # Earlier items of the same statement count as
                    # held for later ones (``with a, b:`` nests).
                    visit(item.context_expr, tuple(taken))
                    ident = self.lock_identity(item.context_expr, fi,
                                               aliases)
                    if ident is not None:
                        fi.acquired.add(ident)
                        for h in taken:
                            fi.lex_nestings.append(
                                (h, ident, node.lineno,
                                 node.col_offset)
                            )
                        taken.append(ident)
                for stmt in node.body:
                    visit(stmt, tuple(taken))
                return
            if isinstance(node, ast.Call):
                reason = _blocking_reason(node)
                if reason is not None:
                    fi.blocking.append(
                        (reason, node.lineno, node.col_offset)
                    )
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "acquire"):
                    ident = self.lock_identity(node.func.value, fi,
                                               aliases)
                    if ident is not None:
                        fi.acquired.add(ident)
                fi.calls.append(
                    [node, node.lineno, node.col_offset, held, None]
                )
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, ())

    def _resolve_calls(self) -> None:
        for fi in self.funcs.values():
            for entry in fi.calls:
                targets = self.resolve_call(entry[0], fi)
                entry[4] = [t.key for t in targets if t.key != fi.key]

    def _fixpoint(self) -> None:
        """Propagate blocking taint and transitive lock acquisition up
        the call graph to a fixed point.  Wrapper functions neither
        source nor forward facts (their insides are the mechanism)."""
        for fi in self.funcs.values():
            if fi.name in WRAPPER_FUNCS:
                fi.blocks_via = None
                fi.t_acquires = {}
                continue
            fi.blocks_via = (
                ((), fi.blocking[0][0]) if fi.blocking else None
            )
            fi.t_acquires = {ident: () for ident in fi.acquired}

        changed = True
        guard = 0
        while changed and guard < 100:
            changed = False
            guard += 1
            for fi in self.funcs.values():
                if fi.name in WRAPPER_FUNCS:
                    continue
                for _call, _l, _c, _held, keys in fi.calls:
                    for key in keys or ():
                        callee = self.funcs.get(key)
                        if callee is None or callee.name in WRAPPER_FUNCS:
                            continue
                        if callee.blocks_via is not None and \
                                fi.blocks_via is None:
                            chain, reason = callee.blocks_via
                            if len(chain) < 6:
                                fi.blocks_via = (
                                    (callee.key,) + chain, reason
                                )
                                changed = True
                        for ident, chain in callee.t_acquires.items():
                            if ident not in fi.t_acquires and \
                                    len(chain) < 6:
                                fi.t_acquires[ident] = (
                                    (callee.key,) + chain
                                )
                                changed = True

    # -- rendered helpers --------------------------------------------------

    def chain_text(self, chain: tuple) -> str:
        """Human chain rendering: a -> b -> c (short quals)."""
        return " -> ".join(
            k.rsplit(":", 1)[-1].split("@")[0] if ":" in k else k
            for k in chain
        )

    def info_for(self, fn_node: ast.AST) -> FuncInfo | None:
        return self.by_node.get(id(fn_node))


# --- memoized construction ------------------------------------------------

_GRAPH_CACHE: dict[frozenset, ProjectGraph] = {}
_GRAPH_CACHE_MAX = 8


def get_graph(files: dict) -> ProjectGraph:
    """The ProjectGraph for this scanned set, memoized by content hash
    so every rule (and every analyze_paths call over identical content)
    shares one build — the call-graph half of the lint cache.

    Cache hits additionally require OBJECT identity with the graph's
    own SourceFiles: the graph's node tables are id()-keyed, so a
    graph built from an evicted parse generation would silently miss
    every lookup against freshly re-parsed trees (zero findings, no
    error).  Same content but new objects ⇒ rebuild."""
    key = frozenset(
        (path, sf.content_hash) for path, sf in files.items()
    )
    got = _GRAPH_CACHE.get(key)
    if got is not None and all(
        got.files.get(p) is sf for p, sf in files.items()
    ):
        return got
    if len(_GRAPH_CACHE) >= _GRAPH_CACHE_MAX:
        _GRAPH_CACHE.pop(next(iter(_GRAPH_CACHE)))
    got = ProjectGraph(dict(files))
    _GRAPH_CACHE[key] = got
    return got
