"""Declared typestates and protocol registries — ONE source of truth.

Every safety-critical state machine the runtime grew over PRs 11-17
(session containment, the device-guard latch, the mesh width ladder,
the flow-cache arm lifecycle, the policy-epoch stage/commit path, the
shim grant rows) is declared here as data: states, allowed edges, and
the typed outcome (metric / counter token) each edge must emit.  The
runtime IMPORTS these tables and routes every transition through
:meth:`Typestate.advance` / :meth:`Typestate.guard` /
:meth:`Typestate.require_edges` — an undeclared transition raises
:class:`ProtocolViolation` at runtime, and the R18 lint pass proves by
AST+callgraph that every assignment to a declared state field is a
mediated, declared edge whose site emits its declared outcome.  Delete
an edge here and BOTH halves fail: the checker flags the now-invalid
site and the runtime transition raises fail-closed.

Also declared here, for the same one-definition reason:

- ``COLUMN_STORES`` — the shared numpy column families and the lock
  that owns each (R19 held-lock discipline over every write);
- ``WIRE_MESSAGES`` — the per-``MSG_*`` lifecycle table: direction,
  reply pairing, fire-and-forget flag, version/flag gating (R20);
- ``NATIVE_MIRRORS`` — the native-shim enum constants that must stay
  bit-identical to their Python twins (R20);
- ``ENGINE_FAMILIES`` — the ROADMAP "landing bar" registry: model +
  host oracle + every-offset parity test + bench config + stress-mix
  slice per registered ``reasm.FRAMINGS`` engine family (R21);
- ``FAIL_CLOSED`` — the declared fail-closed surface: every typestate
  edge (plus the two non-typestate markers) that narrows a serving
  tier, and therefore must both reach the flight recorder and trigger
  a postmortem bundle (R22).

Serving-path cost: everything in this module is an import-time
constant.  ``advance``/``guard`` are two dict lookups and run only at
transition sites (session containment, policy swaps, cache arm/disarm,
mesh rungs) — control-plane events, never inside the per-entry verdict
loop.  The R7/R12 passes keep that claim checked (BENCH_NOTES r08).
"""

from __future__ import annotations


class ProtocolViolation(RuntimeError):
    """An undeclared typestate transition was attempted at runtime."""


# -- transition observer (flight recorder hook) ---------------------------
#
# ONE process-wide hook, installed by the sidecar flight recorder
# (``sidecar/blackbox.py``).  Every mediated transition that VALIDATES
# (advance/guard/require_edges) reports ``(table, frm, to, outcome)``
# here AFTER the edge check — an undeclared edge still raises before
# any observation happens.  The hook is ``None`` by default, so the
# unobserved cost is one attribute load + ``is None`` test at each
# (control-plane) transition site, and its invocation is contained: a
# broken observer can never turn a legal transition into a failure.
# This module must stay importable without the sidecar package — the
# recorder pushes its callback in; nothing here imports it.

_TRANSITION_OBSERVER = None


def set_transition_observer(fn) -> None:
    """Install (or clear, with ``None``) the process-wide transition
    observer.  Called by the flight recorder at service start/stop;
    analysis-side code never sets it."""
    global _TRANSITION_OBSERVER
    _TRANSITION_OBSERVER = fn


def _observe(name, frm, to, outcome) -> None:
    obs = _TRANSITION_OBSERVER
    if obs is None:
        return
    try:
        obs(name, frm, to, outcome)
    except Exception:  # noqa: BLE001 -- observer faults must never fail a legal transition
        pass


class Typestate:
    """A declarative transition table.

    ``states`` is the closed state vocabulary; ``initial`` the
    construction-time state; ``edges`` maps ``(frm, to)`` to the typed
    outcome the transition site must emit — ``None`` for a declared-
    silent edge, a token string, or a tuple of acceptable tokens.
    ``values`` maps state names to the stored representation (identity
    for string-state attributes, small ints for numpy columns); a
    PARTIAL values map is legal for value-carrying columns (e.g. the
    grant-epoch column, where "armed" stores the live epoch and only
    the tombstone value is fixed) — such protocols are mediated through
    :meth:`guard` instead of :meth:`advance`.

    ``kind`` tells the R18 checker how stores look in the AST:
    ``"attr"`` (``obj.field = ...``), ``"column"`` (``self.field[...]
    = ...`` numpy subscript), ``"key"`` (``row["field"] = ...``), or
    ``"derived"`` (no stored field — the state is computed from other
    fields and transition sites call :meth:`advance` for validation
    only).
    """

    __slots__ = ("name", "owner", "field", "kind", "states", "initial",
                 "edges", "values", "_by_value")

    def __init__(self, name: str, owner: str, field: str, kind: str,
                 states, initial, edges: dict, values: dict | None = None):
        self.name = name
        self.owner = owner
        self.field = field
        self.kind = kind
        self.states = tuple(states)
        self.initial = initial
        self.edges = dict(edges)
        self.values = (dict(values) if values is not None
                       else {s: s for s in self.states})
        sset = set(self.states)
        if initial not in sset:
            raise ProtocolViolation(
                f"{name}: initial state {initial!r} not in states"
            )
        for frm, to in self.edges:
            if frm not in sset or to not in sset:
                raise ProtocolViolation(
                    f"{name}: edge ({frm!r} -> {to!r}) names an "
                    f"undeclared state"
                )
        for s in self.values:
            if s not in sset:
                raise ProtocolViolation(
                    f"{name}: value mapped for undeclared state {s!r}"
                )
        self._by_value = {v: s for s, v in self.values.items()}

    # -- runtime mediation -------------------------------------------------

    def value(self, state):
        """The stored representation of ``state``."""
        try:
            return self.values[state]
        except KeyError:
            raise ProtocolViolation(
                f"{self.name}: state {state!r} has no declared stored "
                f"value"
            ) from None

    def state_of(self, value):
        """The state name behind a stored value (numpy scalars
        normalized)."""
        try:
            return self._by_value[value]
        except (KeyError, TypeError):
            pass
        item = getattr(value, "item", None)
        if item is not None:
            try:
                return self._by_value[item()]
            except (KeyError, TypeError):
                pass
        raise ProtocolViolation(
            f"{self.name}: stored value {value!r} maps to no declared "
            f"state"
        )

    def advance(self, cur_value, to):
        """Validate the transition from the CURRENT stored value to
        state ``to`` and return ``to``'s stored value — the one
        expression a mediated store site uses::

            self.state = SESSION_PROTOCOL.advance(self.state,
                                                  SESSION_DEAD)
        """
        frm = self.state_of(cur_value)
        if (frm, to) not in self.edges:
            raise ProtocolViolation(
                f"{self.name}: undeclared transition "
                f"{frm!r} -> {to!r}"
            )
        if _TRANSITION_OBSERVER is not None:
            _observe(self.name, frm, to, self.edges[(frm, to)])
        return self.value(to)

    def guard(self, frm, to, value):
        """Validate a declared edge and pass ``value`` through — the
        mediation for value-carrying columns where the stored value is
        dynamic (the grant epoch) and the edge is statically known at
        the site."""
        if (frm, to) not in self.edges:
            raise ProtocolViolation(
                f"{self.name}: undeclared transition "
                f"{frm!r} -> {to!r}"
            )
        if _TRANSITION_OBSERVER is not None:
            _observe(self.name, frm, to, self.edges[(frm, to)])
        return value

    def require_edges(self, frms, to):
        """Validate every ``frm -> to`` edge of a BULK store (slice
        assign / ``.fill``) and return ``to``'s stored value::

            tab[tab != 0] = FLOW_CACHE_PROTOCOL.require_edges(
                (CACHE_ARMED, CACHE_DECLINED), CACHE_UNARMED)
        """
        for frm in frms:
            if (frm, to) not in self.edges:
                raise ProtocolViolation(
                    f"{self.name}: undeclared transition "
                    f"{frm!r} -> {to!r}"
                )
        if _TRANSITION_OBSERVER is not None:
            for frm in frms:
                _observe(self.name, frm, to, self.edges[(frm, to)])
        return self.value(to)


# =========================================================================
# State vocabularies.  The session constants are the SAME objects the
# transport module re-exports — one definition, everywhere.
# =========================================================================

# Fan-in session containment (transport.SessionState.state).
SESSION_ACTIVE = "active"
SESSION_QUARANTINED = "quarantined"
SESSION_DEAD = "dead"

# Device-guard quarantine latch (guard.DeviceGuard._latch).
GUARD_SERVING = "serving"
GUARD_QUARANTINED = "quarantined"

# Per-device health rows (guard.DeviceGuard._devices[key]["state"]).
DEVICE_OK = "ok"
DEVICE_LOST = "lost"

# Mesh width-ladder rung, DERIVED from (_mesh_demoted, _mesh_serving).
MESH_FULL = "full"
MESH_RESHAPED = "reshaped"
MESH_FALLBACK = "fallback"

# Flow-cache arm lifecycle (service._tab_cache column values).
CACHE_UNARMED = "unarmed"
CACHE_ARMED = "armed"
CACHE_DECLINED = "declined"

# Policy-epoch swap job (service._SwapJob.phase).
SWAP_STAGED = "staged"
SWAP_COMMITTED = "committed"
SWAP_REJECTED = "rejected"

# Shim grant rows (client._grant_epoch column; "armed" stores the live
# epoch — value-carrying, mediated via guard()/require_edges()).
GRANT_NONE = "none"
GRANT_ARMED = "armed"

GRANT_TOMBSTONE = -1  # the one fixed stored value ("none")


# =========================================================================
# Typestate tables (R18).
# =========================================================================

SESSION_PROTOCOL = Typestate(
    name="session",
    owner="SessionState",
    field="state",
    kind="attr",
    states=(SESSION_ACTIVE, SESSION_QUARANTINED, SESSION_DEAD),
    initial=SESSION_ACTIVE,
    edges={
        (SESSION_ACTIVE, SESSION_QUARANTINED): "SidecarSessionQuarantines",
        (SESSION_QUARANTINED, SESSION_QUARANTINED):
            "SidecarSessionQuarantines",
        # Lazy heal when the quarantine window passes: declared-silent
        # (the open of the window was the counted event).
        (SESSION_QUARANTINED, SESSION_ACTIVE): None,
        (SESSION_ACTIVE, SESSION_DEAD): "SidecarSessionDeaths",
        (SESSION_QUARANTINED, SESSION_DEAD): "SidecarSessionDeaths",
    },
)

DEVICE_GUARD_PROTOCOL = Typestate(
    name="device_guard",
    owner="DeviceGuard",
    field="_latch",
    kind="attr",
    states=(GUARD_SERVING, GUARD_QUARANTINED),
    initial=GUARD_SERVING,
    edges={
        (GUARD_SERVING, GUARD_QUARANTINED): "quarantine_events",
        (GUARD_QUARANTINED, GUARD_SERVING): "_quarantined_total_s",
    },
)

MESH_DEVICE_PROTOCOL = Typestate(
    name="mesh_device",
    owner="DeviceGuard",
    field="state",
    kind="key",
    states=(DEVICE_OK, DEVICE_LOST),
    initial=DEVICE_OK,
    edges={
        (DEVICE_OK, DEVICE_LOST): "faults",
        (DEVICE_LOST, DEVICE_LOST): "faults",
        (DEVICE_LOST, DEVICE_OK): "heals",
    },
)

MESH_LADDER_PROTOCOL = Typestate(
    name="mesh_ladder",
    owner="VerdictService",
    field="",
    kind="derived",
    states=(MESH_FULL, MESH_RESHAPED, MESH_FALLBACK),
    initial=MESH_FULL,
    edges={
        (MESH_FULL, MESH_FALLBACK): "MeshDemotions",
        (MESH_RESHAPED, MESH_FALLBACK): "MeshDemotions",
        (MESH_FULL, MESH_RESHAPED): "mesh_reshapes",
        (MESH_FALLBACK, MESH_RESHAPED): "mesh_reshapes",
        (MESH_RESHAPED, MESH_RESHAPED): "mesh_reshapes",
        (MESH_FALLBACK, MESH_FULL): "mesh_repromotions",
        (MESH_RESHAPED, MESH_FULL): "mesh_repromotions",
    },
)

FLOW_CACHE_PROTOCOL = Typestate(
    name="flow_cache",
    owner="VerdictService",
    field="_tab_cache",
    kind="column",
    states=(CACHE_UNARMED, CACHE_ARMED, CACHE_DECLINED),
    initial=CACHE_UNARMED,
    values={CACHE_UNARMED: 0, CACHE_ARMED: 1, CACHE_DECLINED: 2},
    edges={
        (CACHE_UNARMED, CACHE_ARMED): None,
        (CACHE_ARMED, CACHE_ARMED): None,
        (CACHE_DECLINED, CACHE_ARMED): None,
        (CACHE_UNARMED, CACHE_DECLINED): None,
        (CACHE_DECLINED, CACHE_DECLINED): None,
        (CACHE_ARMED, CACHE_DECLINED): "VerdictCacheInvalidations",
        (CACHE_ARMED, CACHE_UNARMED): (
            "VerdictCacheEvictions", "VerdictCacheInvalidations",
            "cache_invalidations",
        ),
        (CACHE_DECLINED, CACHE_UNARMED): None,
        (CACHE_UNARMED, CACHE_UNARMED): None,
    },
)

EPOCH_SWAP_PROTOCOL = Typestate(
    name="epoch_swap",
    owner="_SwapJob",
    field="phase",
    kind="attr",
    states=(SWAP_STAGED, SWAP_COMMITTED, SWAP_REJECTED),
    initial=SWAP_STAGED,
    edges={
        (SWAP_STAGED, SWAP_COMMITTED): "_commit_epoch",
        (SWAP_STAGED, SWAP_REJECTED): "_swap_failed",
    },
)

GRANT_PROTOCOL = Typestate(
    name="shim_grant",
    owner="SidecarClient",
    field="_grant_epoch",
    kind="column",
    states=(GRANT_NONE, GRANT_ARMED),
    initial=GRANT_NONE,
    values={GRANT_NONE: GRANT_TOMBSTONE},
    edges={
        (GRANT_NONE, GRANT_ARMED): None,
        (GRANT_ARMED, GRANT_ARMED): None,
        (GRANT_ARMED, GRANT_NONE): None,
        (GRANT_NONE, GRANT_NONE): None,
    },
)


# =========================================================================
# Declared fail-closed surface (R22).  Every row here is an event that
# NARROWS a serving tier — the transitions an operator reconstructing
# an incident must be able to see.  ``kind="edge"`` rows name a
# declared typestate edge (validated against the tables above at
# import time); ``kind="marker"`` rows name the two fail-closed events
# with no typestate table, recorded via ``blackbox.record_mark`` /
# ``blackbox.broadcast_mark``.  The flight recorder arms a postmortem
# bundle on every row, and the R22 lint pass proves each row reaches a
# recorder emit site — a declared fail-closed edge invisible to the
# recorder is a finding.
# =========================================================================

FAIL_CLOSED = (
    {"kind": "edge", "table": "session",
     "edge": (SESSION_ACTIVE, SESSION_QUARANTINED)},
    {"kind": "edge", "table": "session",
     "edge": (SESSION_QUARANTINED, SESSION_QUARANTINED)},
    {"kind": "edge", "table": "session",
     "edge": (SESSION_ACTIVE, SESSION_DEAD)},
    {"kind": "edge", "table": "session",
     "edge": (SESSION_QUARANTINED, SESSION_DEAD)},
    {"kind": "edge", "table": "device_guard",
     "edge": (GUARD_SERVING, GUARD_QUARANTINED)},
    {"kind": "edge", "table": "mesh_device",
     "edge": (DEVICE_OK, DEVICE_LOST)},
    {"kind": "edge", "table": "mesh_device",
     "edge": (DEVICE_LOST, DEVICE_LOST)},
    {"kind": "edge", "table": "mesh_ladder",
     "edge": (MESH_FULL, MESH_FALLBACK)},
    {"kind": "edge", "table": "mesh_ladder",
     "edge": (MESH_RESHAPED, MESH_FALLBACK)},
    # Reshapes are descents only when entered from a WIDER rung; the
    # fallback -> reshaped edge is an ascent (heal) and is excluded.
    {"kind": "edge", "table": "mesh_ladder",
     "edge": (MESH_FULL, MESH_RESHAPED)},
    {"kind": "edge", "table": "mesh_ladder",
     "edge": (MESH_RESHAPED, MESH_RESHAPED)},
    {"kind": "edge", "table": "epoch_swap",
     "edge": (SWAP_STAGED, SWAP_REJECTED)},
    {"kind": "marker", "token": "shm_demotion"},
    {"kind": "marker", "token": "kvstore_degraded"},
)

# Runtime lookup forms: the recorder checks membership per transition.
FAIL_CLOSED_EDGES = frozenset(
    (row["table"],) + tuple(row["edge"])
    for row in FAIL_CLOSED if row["kind"] == "edge"
)
FAIL_CLOSED_MARKERS = frozenset(
    row["token"] for row in FAIL_CLOSED if row["kind"] == "marker"
)

_PROTOCOLS_BY_NAME = {
    p.name: p
    for p in (SESSION_PROTOCOL, DEVICE_GUARD_PROTOCOL,
              MESH_DEVICE_PROTOCOL, MESH_LADDER_PROTOCOL,
              FLOW_CACHE_PROTOCOL, EPOCH_SWAP_PROTOCOL, GRANT_PROTOCOL)
}

for _row in FAIL_CLOSED:
    if _row["kind"] == "edge":
        _p = _PROTOCOLS_BY_NAME.get(_row["table"])
        if _p is None or tuple(_row["edge"]) not in _p.edges:
            raise ProtocolViolation(
                f"FAIL_CLOSED: row {_row!r} names an undeclared table "
                f"or edge"
            )
        del _p
    elif _row["kind"] != "marker":
        raise ProtocolViolation(
            f"FAIL_CLOSED: row {_row!r} has an unknown kind"
        )
del _row


# =========================================================================
# Column-store lock discipline (R19).  Every write to a column whose
# attribute name starts with ``prefix`` on a ``owner`` instance must be
# reachable only with ``lock`` held (lexically or through every
# project call site).  ``unlocked_ok`` waives the check with a
# recorded justification (the arena is single-writer by construction).
# =========================================================================

COLUMN_STORES = (
    {"name": "conn_table", "owner": "VerdictService",
     "prefix": "_tab_", "lock": "_lock", "unlocked_ok": None},
    {"name": "shim_grants", "owner": "SidecarClient",
     "prefix": "_grant_", "lock": "_glock", "unlocked_ok": None},
    {"name": "reasm_arena", "owner": "ByteArena",
     "prefix": "s_", "lock": None,
     "unlocked_ok": "single-writer: the arena is owned by the reasm "
                    "pass on the dispatch thread; no concurrent "
                    "mutator exists by construction"},
)


# =========================================================================
# Wire-protocol lifecycle table (R20).  One row per MSG_* constant:
# direction ("c2s" client->service, "s2c" service->client, "peer"
# service<->service over the handoff dial), the declared reply message
# (None for fire-and-forget), whether the reply is DEFERRED (answered
# by a later dispatcher round, not the handler chain), and the
# flag/version gate tokens both seam ends must reference.
# =========================================================================

WIRE_MESSAGES = {
    "MSG_OPEN_MODULE": {
        "dir": "c2s", "reply": "MSG_MODULE_ID", "fnf": False,
        "deferred": False, "gates": ()},
    "MSG_MODULE_ID": {
        "dir": "s2c", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
    "MSG_NEW_CONNECTION": {
        "dir": "c2s", "reply": "MSG_CONN_RESULT", "fnf": False,
        "deferred": False, "gates": ("CONN_FLAG_RETAINED",)},
    "MSG_CONN_RESULT": {
        "dir": "s2c", "reply": None, "fnf": True, "deferred": False,
        "gates": ("CONN_RESULT_FLAG_RESIDUE_ADOPTED",)},
    "MSG_DATA_BATCH": {
        "dir": "c2s", "reply": "MSG_VERDICT_BATCH", "fnf": False,
        "deferred": True, "gates": ()},
    "MSG_DATA_BATCH_DL": {
        "dir": "c2s", "reply": "MSG_VERDICT_BATCH", "fnf": False,
        "deferred": True, "gates": ()},
    "MSG_DATA_MATRIX": {
        "dir": "c2s", "reply": "MSG_VERDICT_BATCH", "fnf": False,
        "deferred": True, "gates": ()},
    "MSG_VERDICT_BATCH": {
        "dir": "s2c", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
    "MSG_VERDICT_MULTI": {
        "dir": "s2c", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
    "MSG_CLOSE": {
        "dir": "c2s", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
    "MSG_POLICY_UPDATE": {
        "dir": "c2s", "reply": "MSG_ACK", "fnf": False,
        "deferred": False, "gates": ()},
    "MSG_ACK": {
        "dir": "s2c", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
    "MSG_STATUS": {
        "dir": "c2s", "reply": "MSG_STATUS_REPLY", "fnf": False,
        "deferred": False, "gates": ()},
    "MSG_STATUS_REPLY": {
        "dir": "s2c", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
    "MSG_TRACE": {
        "dir": "c2s", "reply": "MSG_TRACE_REPLY", "fnf": False,
        "deferred": False, "gates": ()},
    "MSG_TRACE_REPLY": {
        "dir": "s2c", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
    "MSG_OBSERVE": {
        "dir": "c2s", "reply": "MSG_OBSERVE_REPLY", "fnf": False,
        "deferred": False, "gates": ()},
    "MSG_OBSERVE_REPLY": {
        "dir": "s2c", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
    "MSG_SHM_ATTACH": {
        "dir": "c2s", "reply": "MSG_SHM_ATTACH_REPLY", "fnf": False,
        "deferred": False, "gates": ()},
    "MSG_SHM_ATTACH_REPLY": {
        "dir": "s2c", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
    "MSG_SHM_DOORBELL": {
        "dir": "c2s", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
    "MSG_SHM_CREDIT": {
        "dir": "s2c", "reply": None, "fnf": True, "deferred": False,
        "gates": ("CREDIT_FLAG_QUARANTINED",)},
    "MSG_SHM_DETACH": {
        "dir": "c2s", "reply": "MSG_ACK", "fnf": False,
        "deferred": False, "gates": ("DETACH_FLAG_NO_ACK",)},
    "MSG_CACHE_ENABLE": {
        "dir": "c2s", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
    "MSG_CACHE_GRANT": {
        "dir": "s2c", "reply": None, "fnf": True, "deferred": False,
        "gates": ("CACHE_FLAG_ALLOW",)},
    "MSG_CACHE_REVOKE": {
        "dir": "s2c", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
    "MSG_SESSION_HELLO": {
        "dir": "c2s", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
    "MSG_HANDOFF": {
        "dir": "peer", "reply": "MSG_HANDOFF_REPLY", "fnf": False,
        "deferred": False, "gates": ("HANDOFF_VERSION",)},
    "MSG_HANDOFF_REPLY": {
        "dir": "peer", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
    "MSG_TIMELINE": {
        "dir": "c2s", "reply": "MSG_TIMELINE_REPLY", "fnf": False,
        "deferred": False, "gates": ()},
    "MSG_TIMELINE_REPLY": {
        "dir": "s2c", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
    "MSG_LEDGER": {
        "dir": "c2s", "reply": "MSG_LEDGER_REPLY", "fnf": False,
        "deferred": False, "gates": ()},
    "MSG_LEDGER_REPLY": {
        "dir": "s2c", "reply": None, "fnf": True,
        "deferred": False, "gates": ()},
}

# Native-shim coexistence: the C header's enum constants mirror the
# Python IntEnums member-for-member on every SHARED name (the Python
# side may extend beyond the ABI range — FilterResult >= 8 stays
# fail-closed on old consumers by construction, so a header that lags
# on the extensions is fine; a VALUE mismatch on a shared name is not).
NATIVE_MIRRORS = (
    {"header": "native/cilium_tpu_shim.h",
     "prefix": "CT_FILTEROP_", "enum": "OpType"},
    {"header": "native/cilium_tpu_shim.h",
     "prefix": "CT_FILTER_", "enum": "FilterResult"},
)


# =========================================================================
# Parity-coverage registry (R21).  One row per registered
# ``reasm.FRAMINGS`` engine family: the ROADMAP landing bar says each
# family ships a device model, a host oracle, an every-offset parity
# test, a bench config, and a stress-mix slice — this registry makes
# that bar machine-checked, so a future TLS-SNI/HTTP2 engine cannot
# land half-covered.  ``parity_test`` rows use ``file::name`` so two
# families sharing a test NAME still each pin their own FILE.
# =========================================================================

ENGINE_FAMILIES = (
    {"kind": "crlf",
     "model": "models/r2d2.py",
     "oracle": "proxylib/parsers/r2d2.py",
     "parity_test": "test_reasm.py::test_columnar_parity_every_byte_offset",
     "bench_config": "r2d2",
     "stress_slice": "MixBench"},
    {"kind": "dns",
     "model": "models/dns.py",
     "oracle": "proxylib/parsers/dns.py",
     "parity_test": "test_dns.py::test_columnar_parity_every_byte_offset",
     "bench_config": "dns",
     "stress_slice": "_stress_dns_pattern"},
)
