"""R19 — column-store lock discipline over the shared numpy columns.

The declared column families live in ``analysis/protocols.py``
(``COLUMN_STORES``): each maps an attribute-name prefix on an owner
class (the ``_tab_*`` conn table on VerdictService, the ``_grant_*``
rows on SidecarClient) to the ONE lock that owns every write.  Two
halves, both interprocedural over the callgraph engine:

- **Unlocked write**: every write shape that mutates a column —
  subscript store (``self._tab_x[i] = v``), bulk slice assign
  (``self._tab_x[:] = v``), augmented subscript store, ``.fill()``,
  ``np.add.at(self._tab_x, ...)``, and whole-array REBINDS outside
  ``__init__`` (a reallocation racing a lock-free store loses the
  store into the discarded array) — must be reachable only with the
  owning lock held: lexically at the write, or at EVERY project call
  site into the enclosing function (transitively, bounded depth).  A
  function containing an unprotected write with zero scanned callers
  is an unprotected entry point and flags too.
- **Torn snapshot**: a function that reads two or more distinct
  columns of one family under two or more SEPARATE owning-lock
  acquisitions, with no single acquisition covering all of them, can
  observe a row mutated between its lock trips — a multi-column
  snapshot must be taken in one trip.  Deliberately lock-free reads
  (no lock at all) are the data path's publish-order contract and are
  not this rule's business.

``unlocked_ok`` on a family waives the write check with a recorded
justification (the reasm arena is single-writer by construction).
"""

from __future__ import annotations

import ast

from .core import Finding, local_assignments, terminal_name

_WRITE_KINDS = {
    "subscript": "subscript store",
    "aug": "augmented subscript store",
    "fill": ".fill() bulk store",
    "ufunc": "np.add.at scatter store",
    "rebind": "whole-array rebind",
}


def _extract_families(files) -> list[tuple[dict, str, int]]:
    """Every (family dict, path, line) from ``COLUMN_STORES = (...)``
    declarations in the scanned set (all-literal tuples of dicts)."""
    out = []
    for path, sf in sorted(files.items()):
        for node in sf.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "COLUMN_STORES"):
                try:
                    rows = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    continue
                for row in rows:
                    if isinstance(row, dict) and row.get("prefix"):
                        out.append((row, path, node.lineno))
    return out


def _self_column(expr: ast.AST, prefix: str) -> str | None:
    """Attribute name when ``expr`` is ``self.<prefix>...``."""
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr.startswith(prefix)):
        return expr.attr
    return None


def _held_has(held, owner: str, lock: str) -> bool:
    want = f"{owner}.{lock}"
    for ident in held:
        if ident == want or ident.split(".")[-1].split(":")[-1] == lock:
            return True
    return False


def _collect_sites(graph, fi, prefix: str, owner: str, lock: str):
    """(writes, read_regions) for one function.

    writes: [(kind, attr, line, col, held_tuple)]
    read_regions: {region_id: set(attrs)} — region_id is a fresh int
    per owning-lock ``with`` block, None outside any owning lock.
    Regions that WRITE a family column are mutation transactions, not
    snapshot assembly — their reads re-validate bounds under the lock
    they already hold — so they are dropped from the read map.
    """
    fn = fi.node
    aliases = local_assignments(fn)
    writes: list = []
    regions: dict = {}
    write_regions: set = set()
    region_seq = [0]

    def note_read(attr: str, region) -> None:
        if region is not None:
            regions.setdefault(region, set()).add(attr)

    def note_write(kind, attr, node, held, region) -> None:
        writes.append((kind, attr, node.lineno, node.col_offset, held))
        if region is not None:
            write_regions.add(region)

    def visit(node, held: tuple, region) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.With):
            taken = list(held)
            inner_region = region
            for item in node.items:
                visit(item.context_expr, tuple(taken), inner_region)
                ident = graph.lock_identity(item.context_expr, fi,
                                            aliases)
                if ident is not None:
                    taken.append(ident)
                    if _held_has((ident,), owner, lock):
                        region_seq[0] += 1
                        inner_region = region_seq[0]
            for stmt in node.body:
                visit(stmt, tuple(taken), inner_region)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    attr = _self_column(t.value, prefix)
                    if attr is not None:
                        note_write("subscript", attr, node, held, region)
                attr = _self_column(t, prefix)
                if attr is not None and fn.name != "__init__":
                    note_write("rebind", attr, node, held, region)
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Subscript):
                attr = _self_column(node.target.value, prefix)
                if attr is not None:
                    note_write("aug", attr, node, held, region)
        elif isinstance(node, ast.Call):
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "fill"):
                attr = _self_column(node.func.value, prefix)
                if attr is not None:
                    note_write("fill", attr, node, held, region)
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "at"
                    and terminal_name(node.func.value) == "add"
                    and node.args):
                attr = _self_column(node.args[0], prefix)
                if attr is not None:
                    note_write("ufunc", attr, node, held, region)
        elif (isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)):
            attr = _self_column(node, prefix)
            if attr is not None:
                note_read(attr, region)
        for child in ast.iter_child_nodes(node):
            visit(child, held, region)

    for stmt in fn.body:
        visit(stmt, (), None)
    regions = {r: attrs for r, attrs in regions.items()
               if r not in write_regions}
    return writes, regions


def _build_reverse(graph) -> dict:
    """callee key -> [(caller key, held tuple at the call site)]."""
    rev: dict = {}
    for fi in graph.funcs.values():
        for _call, _l, _c, held, keys in fi.calls:
            for key in keys or ():
                rev.setdefault(key, []).append((fi.key, held))
    return rev


def _protected(rev, key: str, owner: str, lock: str,
               depth: int = 0, stack=None) -> bool:
    """True when every scanned call path into ``key`` holds the owning
    lock somewhere above the call.  Zero callers ⇒ unprotected entry."""
    if depth > 4:
        return False
    if stack is None:
        stack = set()
    callers = rev.get(key)
    if not callers:
        return False
    for caller_key, held in callers:
        if _held_has(held, owner, lock):
            continue
        if caller_key in stack:
            continue  # cycle: this path adds no new unlocked entry
        stack.add(caller_key)
        ok = _protected(rev, caller_key, owner, lock, depth + 1, stack)
        stack.discard(caller_key)
        if not ok:
            return False
    return True


def check_r19(files):
    from .callgraph import get_graph

    families = _extract_families(files)
    if not families:
        return
    graph = get_graph(files)
    rev = _build_reverse(graph)

    for fam, _decl_path, _decl_line in families:
        owner = fam.get("owner", "")
        prefix = fam["prefix"]
        lock = fam.get("lock")
        if fam.get("unlocked_ok"):
            continue  # waived with a recorded justification
        if not lock:
            continue
        for fi in sorted(graph.funcs.values(), key=lambda f: f.key):
            if fi.cls != owner:
                continue
            writes, regions = _collect_sites(graph, fi, prefix,
                                             owner, lock)
            for kind, attr, line, col, held in writes:
                if _held_has(held, owner, lock):
                    continue
                if fi.name == "__init__":
                    continue  # construction precedes sharing
                if _protected(rev, fi.key, owner, lock):
                    continue
                yield Finding(
                    "R19", fi.path, line, col,
                    f"{_WRITE_KINDS[kind]} to shared column {attr!r} "
                    f"(family {fam.get('name', prefix)!r}) reachable "
                    f"without owning lock {owner}.{lock} held — "
                    f"lock-free writers race reallocation and "
                    f"multi-column row publication",
                    symbol=fi.qual,
                )
            # -- torn multi-column snapshot across lock trips --------
            if len(regions) >= 2:
                union: set = set()
                for attrs in regions.values():
                    union |= attrs
                if len(union) >= 2 and not any(
                    attrs == union for attrs in regions.values()
                ):
                    yield Finding(
                        "R19", fi.path, fi.node.lineno,
                        fi.node.col_offset,
                        f"torn snapshot: columns {sorted(union)} "
                        f"(family {fam.get('name', prefix)!r}) are "
                        f"read across {len(regions)} separate "
                        f"{owner}.{lock} acquisitions with no single "
                        f"trip covering all of them — a row can "
                        f"mutate between the trips",
                        symbol=fi.qual,
                    )
