"""R1 (lock discipline) and R2 (blocking-under-lock).

R1 encodes the lock invariants PRs 1-2 paid for in review time:

- **R1.1 unpaired acquire** — a blocking ``X.acquire()`` statement must
  have a ``try/finally`` releasing the *same binding* ``X`` in the same
  function.  Try-locks (``blocking=False`` / ``timeout=``) are exempt,
  as are lock-wrapper classes (a class defining ``release`` IS the
  pairing, spanning methods by design).
- **R1.2 re-read-attribute capture** — ``self.X.acquire()`` /
  ``self.X.release()`` where attribute ``X`` is *swapped at runtime*
  (assigned outside ``__init__`` anywhere in the tree).  The exact
  ``_in_process_lock`` deposal bug: the stall watchdog swaps the
  attribute, so release-by-re-read releases a DIFFERENT lock object,
  raising out of the hot path while leaking the held lock.  The fix
  captures the object in a local before acquire (``with self.X:`` is
  safe — the expression is evaluated once).
- **R1.3 lock-order inversion** — lexically nested ``with`` statements
  must not invert the recorded lock-order graph.  Seeded from the
  sidecar session machinery: ``_wlock`` may be held when taking
  ``_down_once`` (client.py _resume), NEVER the reverse — _down_once
  holders run in disconnect callbacks that must not wait behind a
  sendall wedged under ``_wlock``.  Same-lock nesting of a
  non-reentrant lock is self-deadlock and also flagged.

R2 flags blocking calls — socket ops, ``queue.get``, ``Thread.join``,
``sleep``, device readbacks — lexically inside a held-lock ``with``
region.  ``.wait()`` is exempt everywhere: Condition.wait RELEASES the
lock, and flagging it would outlaw the dispatcher's core idiom.
R2.2 flags unbounded spin-waits: a ``while`` polling a shared slot
(subscript condition, or while-True with a subscript-compare break)
with no backoff, blocking call, or deadline — the shared-memory ring
transport's bug shape (its sanctioned shapes are doorbell-driven
consumption or backoff+deadline).

Both rules are WHOLE-PROGRAM since the interprocedural engine
(``analysis/callgraph.py``) landed:

- **R1.4 call-mediated lock-order graph** — every observed nesting,
  lexical or through a call chain, contributes an edge
  ``(held, taken)`` to a project-wide graph over QUALIFIED lock
  identities (``Cls._lock`` / ``module:name``).  Flagged: an edge
  inverting the recorded LOCK_ORDER, a pair of opposite edges observed
  anywhere in the project (a cross-module deadlock cycle — each end
  may look locally sane), and a call chain that re-acquires a
  non-reentrant lock already held at the call site.
- **R2 taint** — a call made under a held lock whose callee
  TRANSITIVELY blocks (through helpers like ``utils.sockutil``) is the
  same stall as a lexical ``sendall`` under the lock; the finding
  names the helper chain.
"""

from __future__ import annotations

import ast

from .callgraph import get_graph
from .core import (
    Finding,
    call_func_name,
    is_lock_like_expr,
    is_lock_like_name,
    local_assignments,
    lock_terminal,
    unparse,
    walk_functions,
)

# Recorded lock-order graph: (outer, inner) pairs that are LEGAL; taking
# `outer` while already holding `inner` is an inversion.  Seeded from
# sidecar/client.py (_resume nests _down_once inside _wlock; _down_once
# holders never take _wlock).
LOCK_ORDER: set[tuple[str, str]] = {
    ("_wlock", "_down_once"),
}

# Functions that ARE lock implementations or guards: the
# acquire/release pairing intentionally spans call boundaries there.
_WRAPPER_FUNCS = {
    "acquire", "release", "r_acquire", "r_release",
    "__enter__", "__exit__", "locked", "read",
}


def _class_defines_release(cls: ast.ClassDef | None) -> bool:
    if cls is None:
        return False
    return any(
        isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n.name in ("release", "r_release")
        for n in cls.body
    )


def _own_nodes(root: ast.AST):
    """``ast.walk`` limited to the function's OWN body: nested
    defs/lambdas are separate functions (walk_functions yields them on
    their own), so a finally-release tucked inside a closure must not
    satisfy the enclosing function's acquire pairing — the closure may
    never run on the exception path."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_try_lock(call: ast.Call) -> bool:
    if any(kw.arg in ("blocking", "timeout") for kw in call.keywords):
        return True
    return bool(call.args)  # acquire(<blocking/timeout expr>)


def _swappable_lock_attrs(files) -> set[str]:
    """Lock-like attribute names assigned ANYWHERE outside __init__ —
    the attributes a concurrent swap can re-point between an acquire
    and a re-read release."""
    out: set[str] = set()
    for sf in files.values():
        for fn, _qual, _cls in walk_functions(sf.tree):
            if fn.name == "__init__":
                continue
            for node in ast.walk(fn):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and is_lock_like_name(t.attr)):
                        out.add(t.attr)
    return out


def _reentrant_names(files) -> set[str]:
    """Attribute/local names bound to threading.RLock() anywhere —
    exempt from the same-lock-nesting check."""
    out: set[str] = set()
    for sf in files.values():
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ) and call_func_name(node.value) == "RLock":
                for t in node.targets:
                    name = (t.attr if isinstance(t, ast.Attribute)
                            else t.id if isinstance(t, ast.Name) else "")
                    if name:
                        out.add(name)
    return out


def check_r1(files):
    swappable = _swappable_lock_attrs(files)
    reentrant = _reentrant_names(files)
    for sf in files.values():
        for fn, qual, cls in walk_functions(sf.tree):
            if fn.name in _WRAPPER_FUNCS or _class_defines_release(cls):
                continue
            aliases = local_assignments(fn)
            yield from _r1_acquire_pairing(sf, fn, qual, aliases,
                                           swappable)
            yield from _r1_with_order(sf, fn, qual, aliases, reentrant)
    yield from _r1_lock_graph(files, reentrant)


def _r1_acquire_pairing(sf, fn, qual, aliases, swappable):
    finally_released: set[str] = set()
    for node in _own_nodes(fn):
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                for sub in _own_nodes(stmt):
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "release"):
                        finally_released.add(unparse(sub.func.value))

    for node in _own_nodes(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        recv = node.func.value
        if node.func.attr == "acquire":
            if not is_lock_like_expr(recv, aliases):
                continue
            if (isinstance(recv, ast.Attribute)
                    and recv.attr in swappable):
                yield Finding(
                    "R1", sf.path, node.lineno, node.col_offset,
                    f"acquire on swappable lock attribute "
                    f"{recv.attr!r} (assigned outside __init__): "
                    f"capture the lock object in a local first, or a "
                    f"concurrent swap makes the paired release free a "
                    f"DIFFERENT lock (the _in_process_lock deposal "
                    f"bug)",
                    symbol=qual,
                )
            if _is_try_lock(node):
                continue
            if unparse(recv) not in finally_released:
                yield Finding(
                    "R1", sf.path, node.lineno, node.col_offset,
                    f"blocking {unparse(recv)}.acquire() without a "
                    f"try/finally release of the same binding in this "
                    f"function — an exception between acquire and "
                    f"release leaks the lock",
                    symbol=qual,
                )
        elif node.func.attr == "release":
            if (isinstance(recv, ast.Attribute)
                    and recv.attr in swappable):
                yield Finding(
                    "R1", sf.path, node.lineno, node.col_offset,
                    f"release re-reads swappable lock attribute "
                    f"{recv.attr!r}: if the attribute was swapped "
                    f"while held (stall-watchdog deposal), this "
                    f"releases a different lock and raises with the "
                    f"real lock still held — release the binding "
                    f"captured at acquire instead",
                    symbol=qual,
                )


def _r1_with_order(sf, fn, qual, aliases, reentrant):
    findings: list[Finding] = []

    def handle_with(node: ast.With, held: list[str]) -> None:
        taken = []
        for item in node.items:
            expr = item.context_expr
            if not is_lock_like_expr(expr, aliases):
                continue
            name = lock_terminal(expr, aliases)
            # ``with a, b:`` nests b inside a — earlier items of the
            # same statement count as held for the later ones.
            effective = held + taken
            if name in effective and name not in reentrant:
                findings.append(Finding(
                    "R1", sf.path, node.lineno, node.col_offset,
                    f"nested re-acquire of non-reentrant lock "
                    f"{name!r} — self-deadlock",
                    symbol=qual,
                ))
            for h in effective:
                if (name, h) in LOCK_ORDER:
                    findings.append(Finding(
                        "R1", sf.path, node.lineno, node.col_offset,
                        f"lock-order inversion: taking {name!r} while "
                        f"holding {h!r} inverts the recorded order "
                        f"{name!r} outside {h!r} — deadlocks against "
                        f"the legal nesting",
                        symbol=qual,
                    ))
            taken.append(name)
        for stmt in node.body:
            walk(stmt, held + taken)

    def walk(node: ast.AST, held: list[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # analyzed under their own (empty) stack
        if isinstance(node, ast.With):
            handle_with(node, held)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for stmt in fn.body:
        walk(stmt, [])
    yield from findings


# --- R1.4 whole-program lock-order graph ----------------------------------

def _r1_lock_graph(files, reentrant):
    """Project-wide lock-order edges over qualified identities.

    An edge ``(A, B)`` means "B was taken (directly or through a call
    chain) while A was held".  Three findings:

    - a CALL-MEDIATED edge inverting the recorded LOCK_ORDER (the
      lexical case is R1.3's);
    - opposite edges ``(A, B)`` and ``(B, A)`` observed anywhere in the
      scanned set — the classic distributed deadlock, each half locally
      sane, often in different modules;
    - a call chain that re-acquires a non-reentrant lock already held
      at the call site (self-deadlock through a helper).
    """
    graph = get_graph(files)
    # (outer_ident, inner_ident) -> [(path,line,col,qual,chain|None)]
    edges: dict[tuple[str, str], list] = {}

    def add_edge(outer, inner, site):
        edges.setdefault((outer, inner), []).append(site)

    for fi in graph.funcs.values():
        if fi.name in _WRAPPER_FUNCS:
            continue
        # Lexical nestings come straight from the graph's function
        # summaries (ONE With-walker, shared with the taint pass) and
        # feed the global graph so a cross-FILE opposite nesting
        # pairs up.
        for outer, inner, line, col in fi.lex_nestings:
            add_edge(outer, inner, (fi.path, line, col, fi.qual, None))

        # Call-mediated acquisitions under a held lock.
        for _call, line, col, held, keys in fi.calls:
            if not held:
                continue
            for key in keys or ():
                callee = graph.funcs.get(key)
                if callee is None:
                    continue
                for ident, chain in callee.t_acquires.items():
                    via = (key,) + chain
                    for h in held:
                        add_edge(h, ident,
                                 (fi.path, line, col, fi.qual, via))

    emitted: set = set()

    def emit(path, line, col, qual, msg):
        k = (path, line, col, msg[:60])
        if k in emitted:
            return None
        emitted.add(k)
        return Finding("R1", path, line, col, msg, symbol=qual)

    term = graph.lock_terminal_of
    for (outer, inner), sites in sorted(edges.items()):
        # recorded-order inversion through a call chain
        if (term(inner), term(outer)) in LOCK_ORDER and outer != inner:
            for path, line, col, qual, via in sites:
                if via is None:
                    continue  # lexical: R1.3 already owns it
                f = emit(
                    path, line, col, qual,
                    f"lock-order inversion via call chain "
                    f"{graph.chain_text(via)}: the chain acquires "
                    f"{term(inner)!r} while {term(outer)!r} is held "
                    f"here, inverting the recorded order "
                    f"{term(inner)!r} outside {term(outer)!r}",
                )
                if f:
                    yield f
            continue
        # self-deadlock through a helper
        if outer == inner and term(inner) not in reentrant:
            for path, line, col, qual, via in sites:
                if via is None:
                    continue  # lexical same-lock nesting is R1.3's
                f = emit(
                    path, line, col, qual,
                    f"call chain {graph.chain_text(via)} re-acquires "
                    f"non-reentrant lock {term(inner)!r} already held "
                    f"at this call site — self-deadlock through the "
                    f"helper",
                )
                if f:
                    yield f
            continue
        # opposite edges observed anywhere in the project
        rev = edges.get((inner, outer))
        if rev and outer != inner and outer < inner:
            for direction, dsites in (((outer, inner), sites),
                                      ((inner, outer), rev)):
                for path, line, col, qual, via in dsites:
                    how = (
                        f"via call chain {graph.chain_text(via)} "
                        if via else ""
                    )
                    f = emit(
                        path, line, col, qual,
                        f"lock-order cycle: {term(direction[1])!r} is "
                        f"taken {how}while {term(direction[0])!r} is "
                        f"held here, and the OPPOSITE nesting "
                        f"({term(direction[0])!r} inside "
                        f"{term(direction[1])!r}) is also reachable in "
                        f"this tree — two threads on the two paths "
                        f"deadlock",
                    )
                    if f:
                        yield f


# --- R2 -------------------------------------------------------------------

_SOCKET_BLOCKING = {
    "recv", "recv_into", "recvfrom", "accept", "connect", "connect_ex",
    "sendall", "create_connection",
    # The repo's frame-write primitive (wire.send_msg) is a sendall.
    "send_msg",
}
_DEVICE_BLOCKING = {"block_until_ready", "device_put", "device_get"}


def _blocking_reason(call: ast.Call) -> str | None:
    name = call_func_name(call)
    if name in _SOCKET_BLOCKING:
        return f"socket {name}()"
    if name in _DEVICE_BLOCKING:
        return f"device {name}()"
    if name == "sleep":
        return "sleep()"
    if isinstance(call.func, ast.Attribute):
        if name == "join":
            if isinstance(call.func.value, ast.Constant):
                return None  # "sep".join(...)
            if not call.args:
                return "join()"  # thread/queue join (kwargs-only)
            return None
        if name == "get":
            if not call.args and not call.keywords:
                return "queue get()"
            if any(kw.arg in ("timeout", "block")
                   for kw in call.keywords):
                return "queue get()"
            if (call.args
                    and isinstance(call.args[0], ast.Constant)
                    and call.args[0].value is True):
                return "queue get(True)"
    return None


# --- R2.2 spin-wait -------------------------------------------------------
#
# The shared-memory ring transport (sidecar/shm.py) made this bug shape
# reachable: an unbounded ``while`` that polls a shared slot — a
# subscript read in the loop condition, or a ``while True`` whose only
# exit compares a subscripted read — without yielding (sleep / wait /
# a blocking recv) and without bounding the wait (deadline / timeout /
# retry budget).  Under the GIL a spinning consumer actively STARVES
# the producer it waits on; the sanctioned shapes are doorbell-driven
# consumption (no wait at all) or a backoff loop with a deadline.

_SPIN_YIELDING = {
    "sleep", "wait", "recv", "recv_into", "recv_msg", "accept", "get",
    "select", "poll", "acquire", "join", "backoff",
}
_SPIN_BOUND_HINTS = (
    "deadline", "timeout", "budget", "remaining", "retries", "attempts",
    "waited", "tries",
)


def _spin_names(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr


def _spin_bounded(node) -> bool:
    return any(
        any(h in name.lower() for h in _SPIN_BOUND_HINTS)
        for name in _spin_names(node)
    )


def _spin_yields(body_nodes) -> bool:
    for node in body_nodes:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and (
                call_func_name(sub) in _SPIN_YIELDING
            ):
                return True
    return False


def _subscript_bases(node) -> set[str]:
    """Terminal base names of Subscript LOADS in ``node``."""
    out: set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Subscript) and isinstance(sub.ctx, ast.Load):
            base = sub.value
            while isinstance(base, ast.Subscript):
                base = base.value
            name = (base.attr if isinstance(base, ast.Attribute)
                    else base.id if isinstance(base, ast.Name) else "")
            if name:
                out.add(name)
    return out


def _body_mutates(body_nodes, bases: set[str]) -> bool:
    """True when the loop body writes/mutates any polled base — the
    loop is making its own progress (growing a list, compacting a
    buffer), not waiting on another thread."""
    for node in body_nodes:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Assign, ast.AugAssign, ast.Delete)):
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target]
                           if isinstance(sub, ast.AugAssign)
                           else sub.targets)
                for t in targets:
                    while isinstance(t, ast.Subscript):
                        t = t.value
                    name = (t.attr if isinstance(t, ast.Attribute)
                            else t.id if isinstance(t, ast.Name) else "")
                    if name in bases:
                        return True
            elif (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)):
                recv = sub.func.value
                name = (recv.attr if isinstance(recv, ast.Attribute)
                        else recv.id if isinstance(recv, ast.Name)
                        else "")
                if name in bases:
                    return True  # method call on the polled object
    return False


def _while_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def _r2_spin_wait(files):
    for sf in files.values():
        for fn, qual, _cls in walk_functions(sf.tree):
            for node in ast.walk(fn):
                if not isinstance(node, ast.While):
                    continue
                bases = _subscript_bases(node.test)
                if not bases and _while_true(node.test):
                    # while True whose ONLY exits are subscript-compare
                    # breaks: the poll moved into the body.
                    for sub in node.body:
                        for inner in ast.walk(sub):
                            if (isinstance(inner, ast.If)
                                    and any(isinstance(s, ast.Break)
                                            for s in inner.body)):
                                bases |= _subscript_bases(inner.test)
                if not bases:
                    continue
                scope = [node.test, *node.body]
                if _spin_yields(scope):
                    continue
                if any(_spin_bounded(s) for s in scope):
                    continue
                if _body_mutates(node.body, bases):
                    continue
                yield Finding(
                    "R2", sf.path, node.lineno, node.col_offset,
                    f"unbounded spin-wait polling shared slot(s) "
                    f"{sorted(bases)} with no backoff, blocking call, "
                    f"or deadline — under the GIL a spinning consumer "
                    f"starves the very producer it waits on; use "
                    f"doorbell-driven consumption or a "
                    f"backoff+deadline loop",
                    symbol=qual,
                )


def check_r2(files):
    yield from _r2_spin_wait(files)
    for sf in files.values():
        for fn, qual, cls in walk_functions(sf.tree):
            if fn.name in _WRAPPER_FUNCS or _class_defines_release(cls):
                continue
            aliases = local_assignments(fn)
            findings: list[Finding] = []

            def walk(node, lock_name, findings=findings,
                     aliases=aliases, sf=sf, qual=qual):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    return
                if isinstance(node, ast.With):
                    inner = lock_name
                    for item in node.items:
                        if is_lock_like_expr(item.context_expr, aliases):
                            inner = lock_terminal(item.context_expr,
                                                  aliases)
                    for stmt in node.body:
                        walk(stmt, inner)
                    return
                if lock_name is not None and isinstance(node, ast.Call):
                    reason = _blocking_reason(node)
                    if reason is not None:
                        findings.append(Finding(
                            "R2", sf.path, node.lineno, node.col_offset,
                            f"blocking {reason} while holding "
                            f"{lock_name!r} — stalls every thread "
                            f"contending on the lock for the full "
                            f"wait",
                            symbol=qual,
                        ))
                for child in ast.iter_child_nodes(node):
                    walk(child, lock_name)

            for stmt in fn.body:
                walk(stmt, None)
            yield from findings
    yield from _r2_taint(files)


def _r2_taint(files):
    """Blocking-call taint through helpers: a call under a held lock
    whose callee TRANSITIVELY blocks is the same stall as a lexical
    sendall under the lock — the helper boundary must not launder it.
    Directly-blocking calls are the lexical rule's; this pass only
    fires when the blocking site is at least one call away."""
    graph = get_graph(files)
    for fi in graph.funcs.values():
        # Same exemptions as the lexical rule: lock wrappers and
        # lock-implementation classes pair/block by design.
        if fi.name in _WRAPPER_FUNCS:
            continue
        if _class_defines_release(fi.cls_node):
            continue
        for call, line, col, held, keys in fi.calls:
            if not held:
                continue
            if _blocking_reason(call) is not None:
                continue  # lexical R2 already flags it here
            for key in keys or ():
                callee = graph.funcs.get(key)
                if callee is None or callee.blocks_via is None:
                    continue
                chain, reason = callee.blocks_via
                via = (key,) + chain
                yield Finding(
                    "R2", fi.path, line, col,
                    f"call while holding "
                    f"{graph.lock_terminal_of(held[-1])!r} blocks via "
                    f"helper chain {graph.chain_text(via)} "
                    f"({reason}) — every thread contending on the "
                    f"lock stalls for the full wait",
                    symbol=fi.qual,
                )
                break  # one finding per call site is plenty
