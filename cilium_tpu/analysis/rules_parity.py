"""R21 — machine-checked parity coverage for the framing registry.

ROADMAP's landing bar says every framing family ships five artifacts:
a columnar model, a host-side oracle parser, an every-byte-offset
parity test, a bench config, and a stress-mix slice.  This pass turns
that prose into a checked registry: ``analysis/protocols.py::
ENGINE_FAMILIES`` declares the five artifact coordinates per family,
and the checker proves (a) the declared families and the runtime
``reasm.FRAMINGS`` registration agree in BOTH directions — an
unregistered family is dead coverage, an undeclared framing is an
engine with no landing bar — and (b) every declared artifact actually
exists and names the family where it claims to.

Resolution order is scanned-set first (so a corpus twin directory is
self-contained), then disk relative to the roots derived from the
``FRAMINGS``-defining file: ``pkg_root`` is two levels above it
(``pkg/sidecar/reasm.py`` → ``pkg/``) and ``repo_root`` one above
that.  Disk fallback is what lets the tree gate — which scans only the
package — verify artifacts living in ``tests/`` and ``bench.py``; the
rule's ``memo_extra`` keys the memo on those files' stat signatures so
editing them invalidates cached findings.
"""

from __future__ import annotations

import ast
import glob as _glob
import hashlib
import os

from .core import Finding


def _extract_families(files):
    """(rows list, defining path, line) for ``ENGINE_FAMILIES``."""
    for path, sf in sorted(files.items()):
        for node in sf.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "ENGINE_FAMILIES"):
                try:
                    rows = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    continue
                rows = [r for r in rows
                        if isinstance(r, dict) and r.get("kind")]
                return rows, path, node.lineno
    return [], None, 0


def _const_pool(sf) -> dict[str, str]:
    pool: dict[str, str] = {}
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            pool[node.targets[0].id] = node.value.value
    return pool


def _extract_framings(files):
    """(registered kind -> line, defining path) from the runtime
    ``FRAMINGS = {...}`` registry (plain or annotated assign); dict
    keys may be names resolved through the file's constant pool."""
    for path, sf in sorted(files.items()):
        pool = None
        for node in sf.tree.body:
            value = None
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "FRAMINGS"):
                value = node.value
            elif (isinstance(node, ast.AnnAssign)
                    and isinstance(node.target, ast.Name)
                    and node.target.id == "FRAMINGS"):
                value = node.value
            if not isinstance(value, ast.Dict):
                continue
            if pool is None:
                pool = _const_pool(sf)
            kinds: dict[str, int] = {}
            for k in value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    kinds[k.value] = k.lineno
                elif isinstance(k, ast.Name) and k.id in pool:
                    kinds[pool[k.id]] = k.lineno
            return kinds, path
    return None, None


def _scanned_suffix(files, rel: str):
    want = rel.replace("/", os.sep)
    for path in sorted(files):
        if path.endswith(os.sep + want) or path == want:
            return path
    return None


def _scanned_basename_text(files, base: str):
    for path, sf in sorted(files.items()):
        if os.path.basename(path) == base:
            return sf.text
    return None


def _disk_text(path: str) -> str | None:
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            return f.read()
    except OSError:
        return None


def _roots(files):
    """(pkg_root, repo_root) derived from the FRAMINGS-defining file —
    or from any scanned file as a degraded fallback."""
    kinds_path = None
    for path, sf in sorted(files.items()):
        if "FRAMINGS" in sf.text:
            k, p = _extract_framings({path: sf})
            if k is not None:
                kinds_path = p
                break
    if kinds_path is None:
        kinds_path = next(iter(sorted(files)), ".")
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(kinds_path)))
    return pkg_root, os.path.dirname(pkg_root)


def _memo_extra(files) -> str:
    """Stat signature of the disk-resolved artifact files (bench.py and
    tests/test_*.py under the derived repo root) — they sit outside the
    scanned set, so their edits must invalidate the rule memo."""
    _pkg, repo_root = _roots(files)
    sig = []
    for cand in sorted(
        [os.path.join(repo_root, "bench.py")]
        + _glob.glob(os.path.join(repo_root, "tests", "test_*.py"))
    ):
        try:
            st = os.stat(cand)
            sig.append(f"{cand}:{st.st_size}:{st.st_mtime_ns}")
        except OSError:
            continue
    return hashlib.sha256("|".join(sig).encode()).hexdigest()[:16]


def check_r21(files):
    rows, decl_path, decl_line = _extract_families(files)
    if not rows:
        return
    kinds_by_name = {r["kind"]: r for r in rows}
    registered, framings_path = _extract_framings(files)
    pkg_root, repo_root = _roots(files)

    # -- bidirectional registry <-> runtime coverage -----------------
    if registered is not None:
        for kind, line in sorted(registered.items()):
            if kind not in kinds_by_name:
                yield Finding(
                    "R21", decl_path, decl_line, 0,
                    f"framing {kind!r} is registered in the runtime "
                    f"FRAMINGS but has no ENGINE_FAMILIES row — an "
                    f"engine with no parity landing bar",
                )
        for kind in sorted(kinds_by_name):
            if kind not in registered:
                yield Finding(
                    "R21", decl_path, decl_line, 0,
                    f"family {kind!r} declares a landing bar but is "
                    f"not registered in {os.path.basename(framings_path)}"
                    f"'s FRAMINGS — dead coverage",
                )

    # -- per-family artifact existence + family-name attestation -----
    for row in rows:
        kind = row["kind"]
        for slot in ("model", "oracle"):
            rel = row.get(slot, "")
            if not rel:
                yield Finding(
                    "R21", decl_path, decl_line, 0,
                    f"family {kind!r}: no {slot} declared",
                )
                continue
            path = _scanned_suffix(files, rel)
            if path is None and not os.path.isfile(
                os.path.join(pkg_root, rel.replace("/", os.sep))
            ):
                yield Finding(
                    "R21", decl_path, decl_line, 0,
                    f"family {kind!r}: declared {slot} {rel!r} exists "
                    f"neither in the scanned set nor under "
                    f"{os.path.basename(pkg_root)}/",
                )

        spec = row.get("parity_test", "")
        base, _sep, token = spec.partition("::")
        if not base or not token:
            yield Finding(
                "R21", decl_path, decl_line, 0,
                f"family {kind!r}: parity_test must be "
                f"'file::test_name', got {spec!r}",
            )
        else:
            text = _scanned_basename_text(files, base)
            if text is None:
                text = _disk_text(os.path.join(repo_root, "tests", base))
            if text is None:
                yield Finding(
                    "R21", decl_path, decl_line, 0,
                    f"family {kind!r}: parity test file {base!r} not "
                    f"found (scanned set or tests/)",
                )
            elif token not in text:
                yield Finding(
                    "R21", decl_path, decl_line, 0,
                    f"family {kind!r}: {base} does not define the "
                    f"declared every-offset parity test {token!r}",
                )

        bench_cfg = row.get("bench_config", "")
        bench_text = _scanned_basename_text(files, "bench.py")
        if bench_text is None:
            bench_text = _disk_text(os.path.join(repo_root, "bench.py"))
        if not bench_cfg:
            yield Finding(
                "R21", decl_path, decl_line, 0,
                f"family {kind!r}: no bench_config declared",
            )
        elif bench_text is None:
            yield Finding(
                "R21", decl_path, decl_line, 0,
                f"family {kind!r}: bench.py not found to verify "
                f"bench_config {bench_cfg!r}",
            )
        elif (f'"{bench_cfg}"' not in bench_text
                and f"'{bench_cfg}'" not in bench_text):
            yield Finding(
                "R21", decl_path, decl_line, 0,
                f"family {kind!r}: bench.py never names bench config "
                f"{bench_cfg!r} — the family is unbenchmarked",
            )

        slice_tok = row.get("stress_slice", "")
        if not slice_tok:
            yield Finding(
                "R21", decl_path, decl_line, 0,
                f"family {kind!r}: no stress_slice declared",
            )
            continue
        found = False
        for path, sf in sorted(files.items()):
            b = os.path.basename(path)
            if ((b.startswith("test_") or b == "bench.py")
                    and slice_tok in sf.text):
                found = True
                break
        if not found:
            for cand in ([os.path.join(repo_root, "bench.py")]
                         + sorted(_glob.glob(os.path.join(
                             repo_root, "tests", "test_*.py")))):
                text = _disk_text(cand)
                if text is not None and slice_tok in text:
                    found = True
                    break
        if not found:
            yield Finding(
                "R21", decl_path, decl_line, 0,
                f"family {kind!r}: stress-mix slice {slice_tok!r} "
                f"appears in no stress/bench harness — the family "
                f"never rides the mixed-load soak",
            )


check_r21.memo_extra = _memo_extra
