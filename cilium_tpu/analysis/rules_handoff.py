"""R17 — snapshot round-trip symmetry (the restart-handoff contract).

A ``snapshot_X`` / ``restore_X`` pair is a serialization seam exactly
like a wire ``pack_X``/``unpack_X`` pair (R5's struct half), but the
payload is a dict and the desync mode is quieter: a field the snapshot
writes that the restore never reads is state that silently dies at the
restart boundary (the successor serves without it and nothing parses
wrong), and a field the restore REQUIRES (hard subscript) that the
snapshot never writes makes every restore take the malformed-refusal
path — the handoff degrades to a cold boot forever and no test that
only exercises one process half will notice.

Two halves, anchored on same-module ``snapshot_*``/``restore_*`` def
pairs:

- **written-but-never-consumed**: every constant top-level key the
  snapshot half writes (returned dict literal, keys assigned onto the
  returned name) must be consumed by the restore half — a subscript
  read, a ``.get("key")``, or (the versioned-out escape) the key named
  as a plain string constant in the restore body (a dropped-fields
  tuple / version-gate branch), which records the retirement where the
  next reader looks.
- **required-but-never-written**: a HARD read (``snap["key"]``) in the
  restore half for a key the snapshot half never writes.  Tolerant
  ``.get`` reads are exempt — that is the sanctioned versioned-in form
  for fields newer snapshots may carry.

A ``snapshot_X`` with no ``restore_X`` twin in its module is a
write-only state transfer: flagged too (the Envoy hot-restart lesson —
serialization halves drift the moment they stop being reviewed as a
pair).
"""

from __future__ import annotations

import ast

from .core import Finding, walk_functions

_SNAP = "snapshot_"
_REST = "restore_"


def _top_level_written_keys(fn: ast.AST) -> dict[str, int]:
    """Constant top-level keys of the dict(s) ``fn`` returns:
    {key: lineno}.  Follows one level of name indirection (``out =
    {...}; out["k"] = ...; return out``); nested row dicts inside
    comprehensions are deliberately NOT schema — their keys are
    consumed row-by-row at replay time, not by the restore half."""
    keys: dict[str, int] = {}
    ret_names: set[str] = set()

    def dict_keys(d: ast.Dict) -> None:
        for k in d.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.setdefault(k.value, k.lineno)

    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Dict):
                dict_keys(node.value)
            elif isinstance(node.value, ast.Name):
                ret_names.add(node.value.id)
    if not ret_names:
        return keys
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if (isinstance(t, ast.Name) and t.id in ret_names
                    and isinstance(node.value, ast.Dict)):
                dict_keys(node.value)
            elif (isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id in ret_names
                    and isinstance(t.slice, ast.Constant)
                    and isinstance(t.slice.value, str)):
                keys.setdefault(t.slice.value, t.lineno)
    return keys


def _snap_param(fn) -> str | None:
    """The restore half's snapshot parameter name (first non-self
    positional arg)."""
    args = [a.arg for a in fn.args.args if a.arg != "self"]
    return args[0] if args else None


def _consumed_keys(fn: ast.AST, param: str | None):
    """(hard_reads {key: lineno}, tolerant_reads set, string_pool set)
    in the restore half.  Hard reads are subscripts ON THE SNAPSHOT
    PARAM specifically; tolerant/get reads and the bare-string pool
    (the versioned-out escape) are collected from the whole body —
    restore halves routinely rebind rows to locals."""
    hard: dict[str, int] = {}
    tolerant: set[str] = set()
    pool: set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)):
            key = node.slice.value
            if (isinstance(node.value, ast.Name)
                    and param is not None and node.value.id == param):
                hard.setdefault(key, node.lineno)
            tolerant.add(key)
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            tolerant.add(node.args[0].value)
        elif (isinstance(node, ast.Constant)
                and isinstance(node.value, str)):
            pool.add(node.value)
    return hard, tolerant, pool


def check_r17(files):
    for path, sf in sorted(files.items()):
        fns = {}
        for fn, qual, _cls in walk_functions(sf.tree):
            if isinstance(fn, ast.Lambda):
                continue
            if fn.name.startswith((_SNAP, _REST)):
                fns.setdefault(fn.name, (fn, qual))
        for name, (snap_fn, snap_qual) in sorted(fns.items()):
            if not name.startswith(_SNAP):
                continue
            suffix = name[len(_SNAP):]
            got = fns.get(_REST + suffix)
            if got is None:
                yield Finding(
                    "R17", path, snap_fn.lineno, snap_fn.col_offset,
                    f"{name} has no restore_{suffix} twin in this "
                    f"module: a write-only state transfer — the "
                    f"serialization halves must live (and be reviewed) "
                    f"as a pair",
                    symbol=snap_qual,
                )
                continue
            rest_fn, rest_qual = got
            written = _top_level_written_keys(snap_fn)
            hard, tolerant, pool = _consumed_keys(
                rest_fn, _snap_param(rest_fn)
            )
            consumed = tolerant | set(hard) | pool
            for key, line in sorted(written.items()):
                if key in consumed:
                    continue
                yield Finding(
                    "R17", path, line, 0,
                    f"snapshot field {key!r} written by {name} is "
                    f"never consumed by restore_{suffix} (no read, no "
                    f"versioned-out mention): state that silently dies "
                    f"at the restart boundary",
                    symbol=snap_qual,
                )
            for key, line in sorted(hard.items()):
                if key in written:
                    continue
                yield Finding(
                    "R17", path, line, 0,
                    f"restore_{suffix} REQUIRES snapshot field {key!r} "
                    f"(hard subscript) but {name} never writes it: "
                    f"every restore takes the malformed-refusal path "
                    f"and the handoff silently degrades to a cold "
                    f"boot (use .get for versioned-in fields)",
                    symbol=rest_qual,
                )
