"""R15 — exception containment on the hot loops (raise-taint).

PR 2's finding (14): one entry's ``settle_entry`` crash aborted the
whole batch drain — every other entry in the round leaked unanswered.
The repo's containment contract since then: a raise must never escape
a per-entry/per-round hot loop except through a handler that produces
a TYPED outcome (UNKNOWN_ERROR / SHED verdicts, a demotion, a typed
fallback to the scalar rung).  The good shape is the per-entry ``try``
inside the batch drain; the bug shape is a bare call chain to a
``raise`` — one malformed entry then costs the whole round (or wedges
a pipeline loop that has no round-level backstop at all).

Interprocedural raise-taint on the shared call-graph engine:

- **Sources** are explicit ``raise`` statements (``NotImplementedError``
  stubs excluded — abstract contracts, not crash paths) that are not
  contained by a handler in their own function.
- **Propagation** follows resolved calls made outside any
  try-with-handlers; unresolved attribute calls fall back to a bounded
  same-module/import-closure name match (``reasm.ingest`` →
  ``Reassembler.ingest``) so the reassembler/framing hooks — the
  raise-capable per-framing callbacks — are not invisible.
- **Findings** land at call sites inside for/while loops of the hot
  dispatch/service/reasm roots (``_process*``, the dispatcher worker,
  the completion/send loops, the ring drain, the reader loop) where
  the chain can raise out of the loop and no enclosing handler in the
  root produces a typed outcome.
"""

from __future__ import annotations

import ast
import os

from .callgraph import get_graph
from .core import Finding, call_func_name, unparse

_HOT_BASENAMES = {"dispatch.py", "service.py", "shm.py", "reasm.py",
                  "client.py"}

_ROOT_EXACT = {"_run", "_watch", "_completion_loop", "_send_loop",
               "read_loop", "_shm_doorbell"}


def _is_root(name: str) -> bool:
    return name.startswith("_process") or name in _ROOT_EXACT


# Handler vocabulary that counts as a TYPED outcome: the crash turns
# into an answered entry (shed/error verdict), a demotion, or a typed
# fallback — never a silent drop.
_TYPED_TERMS = {
    "_shed_item", "_on_batch_error", "on_batch_error", "on_stall",
    "send_verdicts", "send_frames", "_typed_entries",
    "_record_contained_failure", "_demote_mesh", "record_stall",
    "_reasm_bail", "_reasm_fallback", "_kill", "_teardown",
    "_shm_quarantine", "quarantine",
}
_TYPED_TEXT = ("UNKNOWN_ERROR", "SHED", "demote", "fallback", "bail")

# Attribute names too generic to fall back on by name: container and
# socket verbs that would alias half the stdlib.
_COMMON_METHODS = {
    "get", "put", "pop", "append", "add", "items", "keys", "values",
    "read", "write", "close", "send", "recv", "join", "start",
    "release", "acquire", "copy", "update", "clear", "discard",
    "remove", "submit", "result", "set", "extend", "insert", "index",
    "count", "sort", "split", "strip", "encode", "decode", "wait",
    "notify", "notify_all", "flush", "tobytes", "astype", "sum",
    "max", "min", "any", "all", "item", "take",
}


def _handler_is_typed(handler: ast.ExceptHandler) -> bool:
    for sub in ast.walk(handler):
        if isinstance(sub, ast.Call) and call_func_name(
                sub) in _TYPED_TERMS:
            return True
        if isinstance(sub, ast.Attribute) and any(
                t in sub.attr for t in _TYPED_TEXT):
            return True
        if isinstance(sub, ast.Name) and any(
                t in sub.id for t in _TYPED_TEXT):
            return True
    return False


def _raise_reason(node: ast.Raise) -> str:
    if node.exc is None:
        return "re-raise"
    exc = node.exc
    if isinstance(exc, ast.Call):
        return unparse(exc.func)
    return unparse(exc)


def _is_stub_raise(node: ast.Raise) -> bool:
    # NotImplementedError: abstract contract, not a crash path.
    # ProtocolViolation: the typestate tables' fail-closed assertion —
    # R18 statically proves every in-tree mediated transition is a
    # declared edge, so these raises are machine-checked-unreachable
    # invariant backstops; counting them would demand a pragma on
    # every mediated state flip inside the hot loops.
    exc = node.exc
    name = ""
    if isinstance(exc, ast.Call):
        name = unparse(exc.func)
    elif exc is not None:
        name = unparse(exc)
    return "NotImplementedError" in name or "ProtocolViolation" in name


# --- per-function facts ---------------------------------------------------

def _direct_facts(fn):
    """(uncontained_calls, uncontained_raises) of fn's own body: nodes
    not under a try-with-handlers within fn.  A raise/call inside an
    except handler escapes unless an OUTER try contains it."""
    calls: list = []
    raises: list = []

    def visit(node, contained: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.Try):
            inner = contained or bool(node.handlers)
            for stmt in node.body + node.orelse:
                visit(stmt, inner)
            for h in node.handlers:
                for stmt in h.body:
                    visit(stmt, contained)
            for stmt in node.finalbody:
                visit(stmt, contained)
            return
        if isinstance(node, ast.Raise):
            if not contained and not _is_stub_raise(node):
                raises.append(node)
        if isinstance(node, ast.Call) and not contained:
            calls.append(node)
        for child in ast.iter_child_nodes(node):
            visit(child, contained)

    for stmt in fn.body:
        visit(stmt, False)
    return calls, raises


def _fallback_keys(graph, fi, call: ast.Call) -> list[str]:
    """Bounded name-match resolution for attribute calls the import
    resolver cannot see (``self._reasm.ingest``): defs named like the
    attribute in the caller's module or its direct imports, capped so
    a generic name never aliases the world."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        return []
    name = func.attr
    if name in _COMMON_METHODS or name.startswith("__"):
        return []
    mods = {fi.module}
    imp = graph.imports.get(fi.module)
    if imp is not None:
        for tgt in imp.aliases.values():
            mods.add(tgt[1])
    keys: list[str] = []
    for m in sorted(mods):
        for f in graph.defs.get(m, {}).get(name, ()):
            if f.key not in keys and f.key != fi.key:
                keys.append(f.key)
    return keys if 0 < len(keys) <= 4 else []


class _RaiseState:
    """raises[key] = (chain-of-names, reason, source line) when the
    function can raise out of itself through uncontained sites."""

    def __init__(self, graph) -> None:
        self.graph = graph
        self.facts: dict[str, tuple] = {}
        self.targets: dict[int, list] = {}
        resolved = {}
        for fi in graph.funcs.values():
            for call, _l, _c, _held, keys in fi.calls:
                resolved[id(call)] = keys or []
        for fi in graph.funcs.values():
            calls, raises = _direct_facts(fi.node)
            for call in calls:
                keys = resolved.get(id(call)) or _fallback_keys(
                    graph, fi, call
                )
                if keys:
                    self.targets[id(call)] = keys
            self.facts[fi.key] = (calls, raises)
        self.raises: dict[str, tuple | None] = {}
        for fi in graph.funcs.values():
            _calls, raises = self.facts[fi.key]
            self.raises[fi.key] = (
                ((), _raise_reason(raises[0]), raises[0].lineno)
                if raises else None
            )
        changed = True
        guard = 0
        while changed and guard < 60:
            changed = False
            guard += 1
            for fi in graph.funcs.values():
                if self.raises[fi.key] is not None:
                    continue
                calls, _raises = self.facts[fi.key]
                for call in calls:
                    for key in self.targets.get(id(call), ()):
                        got = self.raises.get(key)
                        if got is None:
                            continue
                        chain, reason, line = got
                        if len(chain) < 8:
                            callee = graph.funcs.get(key)
                            self.raises[fi.key] = (
                                (callee.name,) + chain, reason,
                                call.lineno,
                            )
                            changed = True
                            break
                    if self.raises[fi.key] is not None:
                        break

    def call_raise(self, call: ast.Call):
        """(chain, reason) when this call site can raise, else None."""
        for key in self.targets.get(id(call), ()):
            got = self.raises.get(key)
            if got is not None:
                callee = self.graph.funcs.get(key)
                chain, reason, _line = got
                return (callee.name,) + chain, reason
        return None


def _raise_state(files) -> _RaiseState:
    graph = get_graph(files)
    state = graph.rule_memo.get("r15_state")
    if state is None:
        state = _RaiseState(graph)
        graph.rule_memo["r15_state"] = state
    return state


# --- the rule -------------------------------------------------------------

def _loop_findings(fi, loop, state: _RaiseState, emitted: set):
    """Findings inside one hot loop: uncontained raising call chains
    and direct raises (a try WITH handlers inside the loop is the
    per-entry containment good shape and blesses its body)."""

    def visit(node, contained: bool):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.Try):
            inner = contained or bool(node.handlers)
            for stmt in node.body + node.orelse:
                yield from visit(stmt, inner)
            for h in node.handlers:
                for stmt in h.body:
                    yield from visit(stmt, contained)
            for stmt in node.finalbody:
                yield from visit(stmt, contained)
            return
        if isinstance(node, ast.Raise) and not contained \
                and not _is_stub_raise(node):
            key = (fi.path, node.lineno, node.col_offset)
            if key not in emitted:
                emitted.add(key)
                yield Finding(
                    "R15", fi.path, node.lineno, node.col_offset,
                    f"raise {_raise_reason(node)} escapes the "
                    f"per-entry hot loop in {fi.qual} with no typed "
                    f"outcome: one malformed entry aborts the whole "
                    f"drain and every other entry leaks unanswered "
                    f"(the PR 2 settle_entry crash class) — contain "
                    f"it per entry and answer typed "
                    f"(UNKNOWN_ERROR/SHED/demotion)",
                    symbol=fi.qual,
                )
        if isinstance(node, ast.Call) and not contained:
            got = state.call_raise(node)
            if got is not None:
                chain, reason = got
                key = (fi.path, node.lineno, node.col_offset)
                if key not in emitted:
                    emitted.add(key)
                    text = " -> ".join(chain)
                    yield Finding(
                        "R15", fi.path, node.lineno, node.col_offset,
                        f"call chain {text} can raise {reason} out of "
                        f"the per-entry hot loop in {fi.qual} with no "
                        f"enclosing handler that produces a typed "
                        f"outcome: one bad entry aborts the whole "
                        f"drain and the rest leak unanswered — wrap "
                        f"the per-entry work in a try that answers "
                        f"typed (UNKNOWN_ERROR/SHED/typed fallback)",
                        symbol=fi.qual,
                    )
        for child in ast.iter_child_nodes(node):
            yield from visit(child, contained)

    for stmt in loop.body:
        yield from visit(stmt, False)


def _walk_root(fi, state: _RaiseState, emitted: set):
    """Loops of one root function, honoring enclosing typed-outcome
    tries: a loop whose crash reaches a handler (in this root) that
    answers typed is the sanctioned round-containment shape."""

    def visit(node, typed_guarded: bool):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.Try):
            inner = typed_guarded or any(
                _handler_is_typed(h) for h in node.handlers
            )
            for stmt in node.body + node.orelse:
                yield from visit(stmt, inner)
            for h in node.handlers:
                for stmt in h.body:
                    yield from visit(stmt, typed_guarded)
            for stmt in node.finalbody:
                yield from visit(stmt, typed_guarded)
            return
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            if not typed_guarded:
                yield from _loop_findings(fi, node, state, emitted)
            # Nested loops under a contained outer loop are still
            # visited for their own (deeper) context.
            for stmt in node.body + node.orelse:
                yield from visit(stmt, typed_guarded)
            return
        for child in ast.iter_child_nodes(node):
            yield from visit(child, typed_guarded)

    for stmt in fi.node.body:
        yield from visit(stmt, False)


def check_r15(files):
    state = _raise_state(files)
    graph = state.graph
    emitted: set = set()
    for fi in sorted(graph.funcs.values(),
                     key=lambda f: (f.path, f.node.lineno)):
        if os.path.basename(fi.path) not in _HOT_BASENAMES:
            continue
        if not _is_root(fi.node.name):
            continue
        yield from _walk_root(fi, state, emitted)
