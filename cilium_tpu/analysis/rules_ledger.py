"""R23 — unledgered compile site.

PR 20's tentpole contract: every executable-producing site — jit
traces, engine builds, mesh model builds, prewarm launches — routes
through the device-economics ledger (sidecar/ledger.py), so
``device_compiles_total{cause}`` is a complete census and "warm churn
performs ZERO compiles" is an asserted invariant rather than a hope.
A compile site that bypasses the ledger silently un-censuses itself:
the soak's zero-compile assertion goes vacuous for that site, and the
ROADMAP item 5 before/after metric (executable-cache hit economics)
under-counts.

Detection (interprocedural, same import-resolved call graph R2/R4/R12
ride): compile-class calls (``jax.jit``/``pjit``, ``prewarm``,
``compile_automaton``, ``_make_engine``/``_build_engine``,
``_measure_dispatch_mode``, ``eval_shape``, ``build_*model*`` /
``mesh_*model*`` builders) in the hot modules, reachable from the R12
dispatch roots PLUS the policy-builder roots (swap/rebind/mesh-ladder
— the off-path compile sites R12 deliberately sanctions are exactly
the ones the ledger must still see).  A site is LEDGERED when its
enclosing function shows ledger evidence: a ``record_compile`` /
``broadcast_compile`` call, a ``cause_scope(...)`` entry, or the
choke point's own residency bookkeeping (``executable_resident``).
Everything else is a finding — or carries a justified pragma naming
why that site is exempt (e.g. the cold first-bind whose default
"cold" cause IS the ledger contract).
"""

from __future__ import annotations

import os
import re

from .callgraph import get_graph
from .core import Finding, call_func_name
from .rules_compile import _DISPATCH_ROOTS, _HOT_BASENAMES

# The policy-builder half of the root set: R12 keeps compiles OFF these
# paths' dispatch rounds; R23 makes the sanctioned off-path compiles
# visible to the ledger.
_BUILDER_ROOTS = {
    "_policy_builder_loop", "_run_swap", "_run_rebind",
    "_run_mesh_ladder", "_run_mesh_rebuild", "_promote_mesh_classic",
    "_bind_engine", "create_engine_for_redirect",
}

# engines.py: the daemon-side engine factory (broadcast_compile path).
_LEDGER_HOT_BASENAMES = _HOT_BASENAMES | {"engines.py"}

# Executable-producing names only — narrower than R12's set (no bare
# ``compile``/``trace``/``lower``, which R12 bounds by dispatch-path
# reachability; R23's wider root set would false-positive on
# ``re.compile`` / ``str.lower`` in builder helpers).
_COMPILE_NAMES = {
    "jit", "pjit", "prewarm", "compile_automaton",
    "_make_engine", "_build_engine", "_measure_dispatch_mode",
    "eval_shape",
}
_COMPILE_RE = re.compile(r"^(build|mesh)_\w*model\w*$")

# Function-level ledger evidence: the record call itself, the cause
# scope that classifies everything beneath it, or the choke point's
# residency bookkeeping.
_LEDGER_EVIDENCE = {
    "record_compile", "broadcast_compile", "cause_scope",
    "executable_resident",
}


def _is_compile_call(name: str) -> bool:
    return name in _COMPILE_NAMES or bool(_COMPILE_RE.match(name))


def _reachable(graph):
    """FuncInfos reachable from the dispatch + builder roots of hot
    modules (same traversal as rules_compile._reachable_from_roots,
    over the widened root set)."""
    roots = [
        fi for fi in graph.funcs.values()
        if os.path.basename(fi.path) in _LEDGER_HOT_BASENAMES
        and fi.qual.split(".")[-1] in (_DISPATCH_ROOTS | _BUILDER_ROOTS)
    ]
    seen: set[str] = set()
    frontier = list(roots)
    reached = []
    while frontier:
        fi = frontier.pop()
        if fi.key in seen:
            continue
        seen.add(fi.key)
        reached.append(fi)
        for _call, _line, _col, _held, keys in fi.calls:
            for key in keys or ():
                callee = graph.funcs.get(key)
                if callee is not None:
                    frontier.append(callee)
    return reached


def check_r23(files):
    graph = get_graph(files)
    emitted: set[tuple] = set()
    for fi in _reachable(graph):
        if os.path.basename(fi.path) not in _LEDGER_HOT_BASENAMES:
            continue
        names = {call_func_name(c) for c, *_ in fi.calls}
        if names & _LEDGER_EVIDENCE:
            continue  # the function ledgers its compiles
        for call, line, col, _held, _keys in fi.calls:
            name = call_func_name(call)
            if not _is_compile_call(name):
                continue
            key = (fi.path, line, col)
            if key in emitted:
                continue
            emitted.add(key)
            yield Finding(
                "R23", fi.path, line, col,
                f"unledgered compile site ({name}): every "
                f"executable-producing call routes through the device "
                f"ledger (record_compile/broadcast_compile, or a "
                f"cause_scope classifying the build) so the per-cause "
                f"compile census stays complete and the zero-compile "
                f"warm-churn invariant stays asserted",
                symbol=fi.qual,
            )
