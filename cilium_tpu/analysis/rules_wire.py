"""R5 — wire / verdict exhaustiveness.

Two halves:

- **MSG coverage.**  Every ``MSG_*`` constant defined in a ``wire.py``
  must be referenced by its sibling ``service.py`` AND ``client.py``
  (the two ends of the seam).  A constant one side never mentions is a
  message the other side can emit into a peer that has no branch for
  it — at best dropped on the floor, at worst desynchronizing the
  framing.  PR 2's MSG_DATA_BATCH_DL landed correctly only because
  review checked both ends by hand; this rule makes that permanent.
- **FilterResult coverage.**  A module that dispatches on specific
  non-OK FilterResult codes (equality compares) must either cover
  every member or carry the fail-closed OK-gate default
  (``res != FilterResult.OK`` / ``== FilterResult.OK``): any code it
  has no branch for then lands in the non-OK arm, which is deny.  The
  extension codes (SHED=8, SERVICE_UNAVAILABLE=9) were designed to be
  safe on old consumers exactly because of this gate — the rule keeps
  new consumers honest.
"""

from __future__ import annotations

import ast
import os
import re

from .core import Finding, unparse

_FR_TOKEN = re.compile(r"FilterResult\.([A-Z_]+)")


def _msg_constants(sf):
    out = []
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.startswith("MSG_")):
            out.append((node.targets[0].id, node.lineno))
    return out


def _referenced_msgs(sf) -> set[str]:
    out = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Attribute) and node.attr.startswith("MSG_"):
            out.add(node.attr)
        elif isinstance(node, ast.Name) and node.id.startswith("MSG_"):
            out.add(node.id)
    return out


def _filter_result_members(files) -> list[str]:
    for sf in files.values():
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and node.name == "FilterResult":
                return [
                    n.targets[0].id
                    for n in node.body
                    if isinstance(n, ast.Assign)
                    and isinstance(n.targets[0], ast.Name)
                ]
    try:  # linting a subset: fall back to the canonical enum
        from ..proxylib.types import FilterResult

        return [m.name for m in FilterResult]
    except Exception:  # noqa: BLE001 — standalone corpus run
        return []


def check_r5(files):
    # --- MSG coverage, per directory holding a wire.py ---
    by_dir: dict[str, dict[str, object]] = {}
    for path, sf in files.items():
        base = os.path.basename(path)
        if base in ("wire.py", "service.py", "client.py"):
            by_dir.setdefault(os.path.dirname(path), {})[base] = sf

    for dirname, group in sorted(by_dir.items()):
        wire = group.get("wire.py")
        if wire is None:
            continue
        consts = _msg_constants(wire)
        if not consts:
            continue
        siblings = [
            (name, group[name])
            for name in ("service.py", "client.py")
            if name in group
        ]
        for name, sib in siblings:
            refs = _referenced_msgs(sib)
            for const, line in consts:
                if const not in refs:
                    yield Finding(
                        "R5", wire.path, line, 0,
                        f"wire constant {const} has no handler "
                        f"reference in sibling {name}: one seam end "
                        f"can emit a message the other has no branch "
                        f"for",
                        symbol=const,
                    )

    # --- FilterResult dispatch coverage, per module ---
    members = _filter_result_members(files)
    if not members:
        return
    member_set = set(members)
    for path, sf in files.items():
        compared: set[str] = set()
        first: tuple[int, int] | None = None
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Compare):
                continue
            if not all(
                isinstance(op, (ast.Eq, ast.NotEq, ast.In, ast.NotIn))
                for op in node.ops
            ):
                continue
            toks = set(_FR_TOKEN.findall(unparse(node)))
            got = toks & member_set
            if got:
                compared |= got
                if first is None:
                    first = (node.lineno, node.col_offset)
        non_ok = compared - {"OK"}
        if not non_ok:
            continue  # produces codes or only uses the OK gate: fine
        if "OK" in compared or compared >= member_set:
            continue
        missing = sorted(member_set - compared)
        yield Finding(
            "R5", path, first[0], first[1],
            f"dispatch over FilterResult codes covers "
            f"{sorted(compared)} but not {missing} and has no "
            f"fail-closed OK-gate default (compare against "
            f"FilterResult.OK so every unknown code lands in the "
            f"deny arm)",
        )
