"""R5 — wire / verdict exhaustiveness.

Four halves:

- **MSG coverage.**  Every ``MSG_*`` constant defined in a ``wire.py``
  must be referenced by its sibling ``service.py`` AND ``client.py``
  (the two ends of the seam).  A constant one side never mentions is a
  message the other side can emit into a peer that has no branch for
  it — at best dropped on the floor, at worst desynchronizing the
  framing.  PR 2's MSG_DATA_BATCH_DL landed correctly only because
  review checked both ends by hand; this rule makes that permanent.
- **FilterResult coverage.**  A module that dispatches on specific
  non-OK FilterResult codes (equality compares) must either cover
  every member or carry the fail-closed OK-gate default
  (``res != FilterResult.OK`` / ``== FilterResult.OK``): any code it
  has no branch for then lands in the non-OK arm, which is deny.  The
  extension codes (SHED=8, SERVICE_UNAVAILABLE=9) were designed to be
  safe on old consumers exactly because of this gate — the rule keeps
  new consumers honest.
- **JSON field symmetry** (the PR 4/5 payloads).  MSG_TRACE /
  MSG_OBSERVE and their replies carry ``json.dumps`` payloads, so
  message-NAME coverage alone proves nothing about fields: a request
  key the client writes that the service never reads is a filter
  silently ignored; a reply key the service emits that no consumer
  anywhere reads is a dead field (and the next rename breaks the CLI
  with no lint to catch it).  For every json-carried send site, each
  written key must be read either by the PEER's handler chain
  (import-resolved, two hops deep) or — for reply payloads the client
  returns opaquely — by SOME consumer in the scanned tree.
- **Struct field symmetry** (the MSG_SHM_* payloads).  For every
  ``pack_X``/``unpack_X`` pair in a ``wire.py``, the struct format
  literals used inside the pair must agree: a doorbell packed
  ``<IQQ`` but unpacked ``<IQ`` silently truncates a cursor and the
  ring protocol desynchronizes with no parse error — message-name
  coverage alone cannot see it.
"""

from __future__ import annotations

import ast
import os
import re

from .callgraph import get_graph
from .core import Finding, unparse, walk_functions

_FR_TOKEN = re.compile(r"FilterResult\.([A-Z_]+)")


def _msg_constants(sf):
    out = []
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.startswith("MSG_")):
            out.append((node.targets[0].id, node.lineno))
    return out


def _referenced_msgs(sf) -> set[str]:
    out = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Attribute) and node.attr.startswith("MSG_"):
            out.add(node.attr)
        elif isinstance(node, ast.Name) and node.id.startswith("MSG_"):
            out.add(node.id)
    return out


def _filter_result_members(files) -> list[str]:
    for sf in files.values():
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and node.name == "FilterResult":
                return [
                    n.targets[0].id
                    for n in node.body
                    if isinstance(n, ast.Assign)
                    and isinstance(n.targets[0], ast.Name)
                ]
    try:  # linting a subset: fall back to the canonical enum
        from ..proxylib.types import FilterResult

        return [m.name for m in FilterResult]
    except Exception:  # noqa: BLE001 — standalone corpus run
        return []


# --- JSON field symmetry --------------------------------------------------

def _is_msg_token(node) -> str | None:
    if isinstance(node, ast.Attribute) and node.attr.startswith("MSG_"):
        return node.attr
    if isinstance(node, ast.Name) and node.id.startswith("MSG_"):
        return node.id
    return None


def _dumps_inner(node) -> ast.AST | None:
    """The EXPR of ``json.dumps(EXPR)`` / ``json.dumps(EXPR).encode()``."""
    if (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "encode"):
        return _dumps_inner(node.func.value)
    if (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "dumps"
            and node.args):
        return node.args[0]
    return None


def _own_nodes_with_lambdas(fn):
    """A function's own body, lambdas included, nested defs excluded —
    a payload built in a method and shipped via a ``lambda:`` send
    thunk belongs to the METHOD."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _json_send_sites(sf):
    """(msg_name, inner_expr, enclosing_fn_node, line, col): every
    tuple/call that pairs a MSG_* token with a json.dumps payload."""
    for fn, _qual, _cls in walk_functions(sf.tree):
        if isinstance(fn, ast.Lambda):
            continue
        for node in _own_nodes_with_lambdas(fn):
            parts = []
            if isinstance(node, ast.Tuple):
                parts = node.elts
            elif isinstance(node, ast.Call):
                parts = list(node.args)
            if len(parts) < 2:
                continue
            msg = None
            inner = None
            for p in parts:
                m = _is_msg_token(p)
                if m is not None:
                    msg = m
                d = _dumps_inner(p)
                if d is not None:
                    inner = d
            if msg is not None and inner is not None:
                yield msg, inner, fn, node.lineno, node.col_offset


def _written_keys(inner, fn, sf, graph) -> set[str]:
    """Constant keys the payload expression carries: dict literals and
    subscript-assigns for a Name; returned-dict keys (resolved through
    the call graph) for a producing Call."""
    keys: set[str] = set()

    def dict_keys(d: ast.Dict):
        for k in d.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.add(k.value)

    if isinstance(inner, ast.Dict):
        dict_keys(inner)
        return keys
    if isinstance(inner, ast.Name):
        target = inner.id
        for node in _own_nodes_with_lambdas(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (isinstance(t, ast.Name) and t.id == target
                            and isinstance(node.value, ast.Dict)):
                        dict_keys(node.value)
                    if (isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == target
                            and isinstance(t.slice, ast.Constant)
                            and isinstance(t.slice.value, str)):
                        keys.add(t.slice.value)
        return keys
    if isinstance(inner, ast.Call):
        fi = graph.info_for(fn)
        if fi is None:
            return keys
        for target in graph.resolve_call(inner, fi):
            tnode = target.node
            ret_names: set[str] = set()
            for node in ast.walk(tnode):
                if isinstance(node, ast.Return) and node.value is not None:
                    if isinstance(node.value, ast.Dict):
                        dict_keys(node.value)
                    elif isinstance(node.value, ast.Name):
                        ret_names.add(node.value.id)
            for node in ast.walk(tnode):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if (isinstance(t, ast.Name)
                                and t.id in ret_names
                                and isinstance(node.value, ast.Dict)):
                            dict_keys(node.value)
                        if (isinstance(t, ast.Subscript)
                                and isinstance(t.value, ast.Name)
                                and t.value.id in ret_names
                                and isinstance(t.slice, ast.Constant)
                                and isinstance(t.slice.value, str)):
                            keys.add(t.slice.value)
    return keys


def _read_keys_in(fn) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)):
            out.add(node.slice.value)
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out.add(node.args[0].value)
    return out


def _peer_reader_keys(peer_sf, msg, graph, depth=2) -> set[str]:
    """Keys read by the peer functions that reference ``msg``, plus
    their import-resolved callees ``depth`` hops out (the handler
    delegates to observe_dump/trace_dump)."""
    keys: set[str] = set()
    seeds = []
    for fn, _qual, _cls in walk_functions(peer_sf.tree):
        for node in ast.walk(fn):
            if _is_msg_token(node) == msg:
                seeds.append(fn)
                break
    seen: set[int] = set()
    frontier = [(fn, 0) for fn in seeds]
    while frontier:
        fn, d = frontier.pop()
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        keys |= _read_keys_in(fn)
        if d >= depth:
            continue
        fi = graph.info_for(fn)
        if fi is None:
            continue
        for _call, _l, _c, _held, ks in fi.calls:
            for key in ks or ():
                callee = graph.funcs.get(key)
                if callee is not None:
                    frontier.append((callee.node, d + 1))
    return keys


def _check_json_fields(files, by_dir):
    graph = get_graph(files)
    # Fallback read-key pool (reply payloads are returned opaquely by
    # the client and consumed by the CLI/tests/monitor layers).  The
    # pool for one seam is its OWN directory plus every non-seam file:
    # another seam's equally-named keys must not mask this seam's
    # dropped field.
    global_reads: dict[str, set[str]] = {
        path: _read_keys_in(sf.tree) for path, sf in files.items()
    }
    seam_dirs = {
        d for d, g in by_dir.items()
        if "service.py" in g and "client.py" in g
    }
    for dirname, group in sorted(by_dir.items()):
        pair = {"service.py": "client.py", "client.py": "service.py"}
        for base, peer_base in pair.items():
            sf = group.get(base)
            peer = group.get(peer_base)
            if sf is None or peer is None:
                continue
            for msg, inner, fn, line, col in _json_send_sites(sf):
                written = _written_keys(inner, fn, sf, graph)
                if not written:
                    continue
                peer_keys = _peer_reader_keys(peer, msg, graph)
                missing = sorted(written - peer_keys)
                for key in missing:
                    read_somewhere = any(
                        key in ks
                        for path, ks in global_reads.items()
                        if path != sf.path and (
                            os.path.dirname(path) == dirname
                            or os.path.dirname(path) not in seam_dirs
                        )
                    )
                    if read_somewhere:
                        continue
                    yield Finding(
                        "R5", sf.path, line, col,
                        f"json field {key!r} of {msg} is written here "
                        f"but never read by {peer_base}'s handler "
                        f"chain nor any consumer in the tree — a "
                        f"dropped field passes the message-name "
                        f"coverage check silently",
                        symbol=msg,
                    )


# --- struct field symmetry ------------------------------------------------

_STRUCT_CALLS = {"pack", "pack_into", "unpack", "unpack_from", "Struct",
                 "calcsize"}
_FMT = re.compile(r"^[@=<>!]?[0-9xcbB?hHiIlLqQnNefdspP]+$")


def _struct_formats(fn) -> list[str]:
    """Struct format literals used by struct pack/unpack calls in
    ``fn``'s own body (sorted multiset)."""
    out: list[str] = []
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, (ast.Attribute, ast.Name))):
            continue
        name = (node.func.attr if isinstance(node.func, ast.Attribute)
                else node.func.id)
        if name not in _STRUCT_CALLS or not node.args:
            continue
        arg = node.args[0]
        if (isinstance(arg, ast.Constant) and isinstance(arg.value, str)
                and _FMT.match(arg.value)):
            out.append(arg.value)
    return sorted(out)


def _check_struct_symmetry(files):
    """pack_X/unpack_X pairs in a wire.py must use matching struct
    format literals (both halves considered as multisets — helpers
    shared at module level, like a module-level Struct, contribute to
    neither and stay exempt)."""
    for path, sf in sorted(files.items()):
        if os.path.basename(path) != "wire.py":
            continue
        fns = {
            fn.name: fn
            for fn in sf.tree.body
            if isinstance(fn, ast.FunctionDef)
        }
        for name, fn in sorted(fns.items()):
            if not name.startswith("pack_"):
                continue
            base = name[len("pack_"):]
            if base.endswith("_parts"):
                # Scatter-gather builders share the layout with their
                # joined twin; their unpack is the base name's.
                base = base[: -len("_parts")]
            peer = fns.get("unpack_" + base)
            if peer is None:
                continue
            got = _struct_formats(fn)
            want = _struct_formats(peer)
            if got and want and got != want:
                yield Finding(
                    "R5", path, fn.lineno, fn.col_offset,
                    f"struct-format asymmetry: {name} packs "
                    f"{got} but {peer.name} reads {want} — the "
                    f"truncated/reordered field desynchronizes the "
                    f"frame with no parse error",
                    symbol=name,
                )


def check_r5(files):
    yield from _check_struct_symmetry(files)

    # --- MSG coverage, per directory holding a wire.py ---
    by_dir: dict[str, dict[str, object]] = {}
    for path, sf in files.items():
        base = os.path.basename(path)
        if base in ("wire.py", "service.py", "client.py"):
            by_dir.setdefault(os.path.dirname(path), {})[base] = sf

    yield from _check_json_fields(files, by_dir)

    for dirname, group in sorted(by_dir.items()):
        wire = group.get("wire.py")
        if wire is None:
            continue
        consts = _msg_constants(wire)
        if not consts:
            continue
        siblings = [
            (name, group[name])
            for name in ("service.py", "client.py")
            if name in group
        ]
        for name, sib in siblings:
            refs = _referenced_msgs(sib)
            for const, line in consts:
                if const not in refs:
                    yield Finding(
                        "R5", wire.path, line, 0,
                        f"wire constant {const} has no handler "
                        f"reference in sibling {name}: one seam end "
                        f"can emit a message the other has no branch "
                        f"for",
                        symbol=const,
                    )

    # --- FilterResult dispatch coverage, per module ---
    members = _filter_result_members(files)
    if not members:
        return
    member_set = set(members)
    for path, sf in files.items():
        compared: set[str] = set()
        first: tuple[int, int] | None = None
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Compare):
                continue
            if not all(
                isinstance(op, (ast.Eq, ast.NotEq, ast.In, ast.NotIn))
                for op in node.ops
            ):
                continue
            toks = set(_FR_TOKEN.findall(unparse(node)))
            got = toks & member_set
            if got:
                compared |= got
                if first is None:
                    first = (node.lineno, node.col_offset)
        non_ok = compared - {"OK"}
        if not non_ok:
            continue  # produces codes or only uses the OK gate: fine
        if "OK" in compared or compared >= member_set:
            continue
        missing = sorted(member_set - compared)
        yield Finding(
            "R5", path, first[0], first[1],
            f"dispatch over FilterResult codes covers "
            f"{sorted(compared)} but not {missing} and has no "
            f"fail-closed OK-gate default (compare against "
            f"FilterResult.OK so every unknown code lands in the "
            f"deny arm)",
        )
