"""``cilium-lint`` CLI.

Text mode prints one line per active finding and exits 1 when any
survive suppression; ``--json`` emits the full machine-readable report
(active + suppressed + per-rule counts) for CI consumption.  The
baseline (``--baseline``, default ``tests/lint_baseline.json`` when it
exists next to the scanned tree) accepts findings wholesale so new
violations fail the build while grandfathered ones don't.

``--ratchet`` turns the baseline's ``max_suppressed`` into a one-way
gate: the tree's suppressed-finding count (pragmas + baselined) may
only DECREASE.  Growth fails the build — a new pragma must displace an
old one or argue its way into a recorded, reviewed ratchet bump via
``--ratchet-update``; a missing ``max_suppressed`` fails CLOSED, so
the gate cannot be disarmed by deleting the number.

``--device-contracts`` additionally runs the abstract-trace layer
(``analysis/devicecheck.py``): the real verdict models are traced
under ``JAX_PLATFORMS=cpu`` (eval_shape/make_jaxpr — no device, no
execution) and the R8-R11 contracts verified on the jaxprs themselves.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .core import (
    RULE_DOCS,
    _collect_py,
    analyze_paths,
    findings_to_json,
    load_baseline_full,
    split_findings,
)


def _default_baseline(paths) -> str | None:
    """tests/lint_baseline.json next to the scanned package, if any."""
    for p in paths:
        d = os.path.abspath(p)
        if not os.path.isdir(d):
            d = os.path.dirname(d)
        for root in (d, os.path.dirname(d)):
            cand = os.path.join(root, "tests", "lint_baseline.json")
            if os.path.exists(cand):
                return cand
    return None


def _ratchet(args, baseline_path, baseline_full, muted) -> int | None:
    """Enforce max_suppressed; returns an exit code to stop with, or
    None to continue into normal reporting."""
    count = len(muted)
    if baseline_path is None or baseline_full is None:
        print("cilium-lint: --ratchet needs a baseline file "
              "(tests/lint_baseline.json) to ratchet against",
              file=sys.stderr)
        return 2
    # Advisory/status lines go to stderr: --ratchet composes with
    # --json, whose stdout must stay pure machine-readable report.
    def write_count(verb, old):
        baseline_full["max_suppressed"] = count
        with open(baseline_path, "w", encoding="utf-8") as f:
            json.dump(baseline_full, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"cilium-lint: ratchet {verb} "
              f"{old if old is not None else '(unset)'} -> {count}",
              file=sys.stderr)

    recorded = baseline_full.get("max_suppressed")
    if recorded is None:
        if args.ratchet_update:  # bootstrap the ratchet
            write_count("recorded", None)
            return None
        # Fail CLOSED: an unrecorded ratchet is indistinguishable from
        # a deleted one.
        print(f"cilium-lint: baseline {baseline_path} has no "
              f"max_suppressed — record the current count "
              f"({count}) with --ratchet --ratchet-update",
              file=sys.stderr)
        return 2
    if count > recorded:
        if args.ratchet_update:
            # The reviewed-bump path: the flag on the command line IS
            # the explicit sign-off, and the diff to the baseline file
            # is what review sees.
            write_count("RAISED", recorded)
            return None
        print(f"cilium-lint: RATCHET VIOLATION — {count} suppressed "
              f"finding(s), baseline allows {recorded}.  The "
              f"suppressed count may only decrease; remove a pragma "
              f"or record a reviewed bump with --ratchet "
              f"--ratchet-update.", file=sys.stderr)
        return 1
    if count < recorded:
        if args.ratchet_update:
            write_count("lowered", recorded)
        else:
            print(f"cilium-lint: suppressed count {count} is below "
                  f"the recorded {recorded} — lock in the progress "
                  f"with --ratchet --ratchet-update", file=sys.stderr)
    return None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="cilium-lint",
        description="whole-program concurrency & device-contract "
                    "invariant analyzer (rules R0-R13; see README "
                    "'Invariants & lint')",
    )
    p.add_argument("paths", nargs="*", default=["cilium_tpu"],
                   help="files or directories to scan "
                        "(default: cilium_tpu)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit a JSON report (active + suppressed + "
                        "per-rule counts)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="JSON list of {rule,file,symbol} accepted "
                        "findings (default: tests/lint_baseline.json "
                        "next to the scanned tree, when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print pragma-/baseline-suppressed "
                        "findings (text mode)")
    p.add_argument("--ratchet", action="store_true",
                   help="enforce the baseline's max_suppressed: the "
                        "suppressed-finding count may only decrease "
                        "(fails closed when unrecorded)")
    p.add_argument("--ratchet-update", action="store_true",
                   help="with --ratchet: record the current (lower) "
                        "suppressed count into the baseline file")
    p.add_argument("--device-contracts", action="store_true",
                   help="also verify R8-R11 on the real verdict "
                        "models by abstract tracing (JAX_PLATFORMS="
                        "cpu; no device, no model execution)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule set and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule, doc in sorted(RULE_DOCS.items()):
            print(f"{rule}  {doc}")
        return 0

    # The gate must fail CLOSED on a misconfigured invocation: a
    # typo'd path (or a CI job run from the wrong cwd) scanning zero
    # files would otherwise print '0 finding(s)' and go green forever.
    missing = [pth for pth in args.paths if not os.path.exists(pth)]
    if missing:
        for pth in missing:
            print(f"cilium-lint: no such path: {pth}", file=sys.stderr)
        return 2
    if not _collect_py(args.paths):
        print("cilium-lint: no Python files found under "
              + " ".join(args.paths), file=sys.stderr)
        return 2

    baseline = None
    baseline_path = None
    baseline_full = None
    if not args.no_baseline:
        baseline_path = args.baseline or _default_baseline(args.paths)
        if baseline_path is not None:
            try:
                baseline_full = load_baseline_full(baseline_path)
                baseline = baseline_full["accepted"]
            except (OSError, ValueError) as e:
                print(f"cilium-lint: bad baseline {baseline_path}: {e}",
                      file=sys.stderr)
                return 2

    findings = analyze_paths(args.paths, baseline=baseline)
    if args.device_contracts:
        from . import devicecheck
        from .core import _baseline_matches

        extra = devicecheck.check_device_contracts()
        # Device-contract findings have no source line, so a pragma
        # can never reach them — the baseline's accepted list is their
        # ONE escape hatch (a jax upgrade shifting an equation count
        # must be acceptable without editing the tool).
        if baseline:
            for f in extra:
                if any(_baseline_matches(e, f) for e in baseline):
                    f.baselined = True
        findings.extend(extra)
    active, muted = split_findings(findings)

    if args.ratchet:
        rc = _ratchet(args, baseline_path, baseline_full, muted)
        if rc is not None:
            return rc

    if args.as_json:
        print(json.dumps(findings_to_json(findings), indent=2))
        return 1 if active else 0

    for f in active:
        print(f.render())
    if args.show_suppressed:
        for f in muted:
            tag = "baseline" if f.baselined else "pragma"
            why = f" ({f.justification})" if f.justification else ""
            print(f"suppressed[{tag}]: {f.render()}{why}")
    n_files = len({f.path for f in findings}) if findings else 0
    print(
        f"cilium-lint: {len(active)} finding(s), "
        f"{len(muted)} suppressed"
        + (f" across {n_files} file(s)" if findings else "")
    )
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
