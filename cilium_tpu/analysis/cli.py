"""``cilium-lint`` CLI.

Text mode prints one line per active finding and exits 1 when any
survive suppression; ``--json`` emits the full machine-readable report
(active + suppressed + per-rule counts) for CI consumption.  The
baseline (``--baseline``, default ``tests/lint_baseline.json`` when it
exists next to the scanned tree) accepts findings wholesale so new
violations fail the build while grandfathered ones don't.

``--ratchet`` turns the baseline's ``max_suppressed`` into a one-way
gate: the tree's suppressed-finding count (pragmas + baselined) may
only DECREASE.  Growth fails the build — a new pragma must displace an
old one or argue its way into a recorded, reviewed ratchet bump via
``--ratchet-update``; a missing ``max_suppressed`` fails CLOSED, so
the gate cannot be disarmed by deleting the number.

``--device-contracts`` additionally runs the abstract-trace layer
(``analysis/devicecheck.py``): the real verdict models are traced
under ``JAX_PLATFORMS=cpu`` (eval_shape/make_jaxpr — no device, no
execution) and the R8-R11 contracts plus the R16 shape-closure audit
verified on the jaxprs themselves.

``--diff <rev>`` reports only findings in files changed since ``rev``
(plus untracked files) — the warm-cache pre-commit mode.  The
ANALYSIS still covers the full scan target: the interprocedural rules
are whole-program (R5's seam symmetry, R7's cross-file metric
references, R14's answer fixpoint), so scanning only the changed
files would both invent findings (half a seam looks broken) and miss
real ones; the content-hash parse/graph cache is what makes the full
pass cheap on a warm tree.  The rev is validated through git and a
failure is rc 2 (fail closed, like a typo'd path); zero CHANGED
Python files is a legitimate no-op (rc 0), unlike a zero-file scan
target, which stays rc 2.

``--sarif`` emits a SARIF 2.1.0 report on stdout for CI annotation
(one result per ACTIVE finding; pragma/baseline suppressions are
recorded as inSource/external suppressions so code-scanning UIs show
them resolved).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from .core import (
    RULE_DOCS,
    _collect_py,
    analyze_paths,
    findings_to_json,
    load_baseline_full,
    split_findings,
)


def _default_baseline(paths) -> str | None:
    """tests/lint_baseline.json next to the scanned package, if any."""
    for p in paths:
        d = os.path.abspath(p)
        if not os.path.isdir(d):
            d = os.path.dirname(d)
        for root in (d, os.path.dirname(d)):
            cand = os.path.join(root, "tests", "lint_baseline.json")
            if os.path.exists(cand):
                return cand
    return None


def _ratchet(args, baseline_path, baseline_full, muted) -> int | None:
    """Enforce max_suppressed; returns an exit code to stop with, or
    None to continue into normal reporting."""
    count = len(muted)
    if baseline_path is None or baseline_full is None:
        print("cilium-lint: --ratchet needs a baseline file "
              "(tests/lint_baseline.json) to ratchet against",
              file=sys.stderr)
        return 2
    # Advisory/status lines go to stderr: --ratchet composes with
    # --json, whose stdout must stay pure machine-readable report.
    def write_count(verb, old):
        baseline_full["max_suppressed"] = count
        with open(baseline_path, "w", encoding="utf-8") as f:
            json.dump(baseline_full, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"cilium-lint: ratchet {verb} "
              f"{old if old is not None else '(unset)'} -> {count}",
              file=sys.stderr)

    recorded = baseline_full.get("max_suppressed")
    if recorded is None:
        if args.ratchet_update:  # bootstrap the ratchet
            write_count("recorded", None)
            return None
        # Fail CLOSED: an unrecorded ratchet is indistinguishable from
        # a deleted one.
        print(f"cilium-lint: baseline {baseline_path} has no "
              f"max_suppressed — record the current count "
              f"({count}) with --ratchet --ratchet-update",
              file=sys.stderr)
        return 2
    if count > recorded:
        if args.ratchet_update:
            # The reviewed-bump path: the flag on the command line IS
            # the explicit sign-off, and the diff to the baseline file
            # is what review sees.
            write_count("RAISED", recorded)
            return None
        print(f"cilium-lint: RATCHET VIOLATION — {count} suppressed "
              f"finding(s), baseline allows {recorded}.  The "
              f"suppressed count may only decrease; remove a pragma "
              f"or record a reviewed bump with --ratchet "
              f"--ratchet-update.", file=sys.stderr)
        return 1
    if count < recorded:
        if args.ratchet_update:
            write_count("lowered", recorded)
        else:
            print(f"cilium-lint: suppressed count {count} is below "
                  f"the recorded {recorded} — lock in the progress "
                  f"with --ratchet --ratchet-update", file=sys.stderr)
    return None


def _changed_files(rev: str) -> set[str] | None:
    """Absolute paths changed since ``rev`` plus untracked files, or
    None when git cannot answer (bad rev / not a repo) — the caller
    fails CLOSED on None: a silent empty diff would green-light
    anything."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=30, check=True,
        ).stdout.strip()
        diff = subprocess.run(
            ["git", "diff", "--name-only", rev],
            capture_output=True, text=True, timeout=60, check=True,
            cwd=top,
        ).stdout
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            capture_output=True, text=True, timeout=60, check=True,
            cwd=top,
        ).stdout
    except (OSError, subprocess.SubprocessError):
        return None
    # realpath on BOTH sides of the membership test: git reports
    # physical paths, and a symlinked scan path (macOS /tmp) abspath'd
    # naively would intersect to nothing — a silent empty diff in an
    # explicitly fail-closed gate.
    return {
        os.path.realpath(os.path.join(top, line.strip()))
        for line in (diff + untracked).splitlines()
        if line.strip()
    }


def _sarif_report(findings) -> dict:
    """SARIF 2.1.0 for CI annotation: active findings as results,
    suppressed ones carried with their suppression kind so the
    code-scanning UI shows them resolved instead of re-opening them."""
    from .core import RULE_DOCS

    results = []
    for f in findings:
        res = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace(os.sep, "/"),
                    },
                    "region": {
                        "startLine": max(f.line, 1),
                        "startColumn": max(f.col + 1, 1),
                    },
                },
            }],
        }
        if f.suppressed or f.baselined:
            res["suppressions"] = [{
                "kind": "inSource" if f.suppressed else "external",
                "justification": f.justification,
            }]
        results.append(res)
    return {
        "$schema": ("https://json.schemastore.org/sarif-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                # No informationUri: SARIF 2.1.0 requires an absolute
                # URI there and this repo has no canonical public URL
                # — strict consumers reject a relative reference.
                "name": "cilium-lint",
                "rules": [
                    {"id": rule,
                     "shortDescription": {"text": doc}}
                    for rule, doc in sorted(RULE_DOCS.items())
                ],
            }},
            "results": results,
        }],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="cilium-lint",
        description="whole-program concurrency & device-contract "
                    "invariant analyzer (rules R0-R23; see README "
                    "'Invariants & lint')",
    )
    p.add_argument("paths", nargs="*", default=["cilium_tpu"],
                   help="files or directories to scan "
                        "(default: cilium_tpu)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit a JSON report (active + suppressed + "
                        "per-rule counts)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="JSON list of {rule,file,symbol} accepted "
                        "findings (default: tests/lint_baseline.json "
                        "next to the scanned tree, when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print pragma-/baseline-suppressed "
                        "findings (text mode)")
    p.add_argument("--ratchet", action="store_true",
                   help="enforce the baseline's max_suppressed: the "
                        "suppressed-finding count may only decrease "
                        "(fails closed when unrecorded)")
    p.add_argument("--ratchet-update", action="store_true",
                   help="with --ratchet: record the current (lower) "
                        "suppressed count into the baseline file")
    p.add_argument("--device-contracts", action="store_true",
                   help="also verify R8-R11 and the R16 shape-closure "
                        "audit on the real verdict models by abstract "
                        "tracing (JAX_PLATFORMS=cpu; no device, no "
                        "model execution)")
    p.add_argument("--diff", default=None, metavar="REV",
                   help="report only findings in files changed since "
                        "REV (plus untracked files); the whole-"
                        "program analysis still covers the full scan "
                        "target (warm-cache pre-commit mode) — a bad "
                        "rev fails closed (rc 2), zero changed "
                        "Python files is a no-op (rc 0)")
    p.add_argument("--sarif", action="store_true",
                   help="emit a SARIF 2.1.0 report on stdout for CI "
                        "annotation (mutually exclusive with --json)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule set and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule, doc in sorted(RULE_DOCS.items()):
            print(f"{rule}  {doc}")
        return 0

    if args.as_json and args.sarif:
        print("cilium-lint: --json and --sarif are mutually "
              "exclusive", file=sys.stderr)
        return 2

    # The gate must fail CLOSED on a misconfigured invocation: a
    # typo'd path (or a CI job run from the wrong cwd) scanning zero
    # files would otherwise print '0 finding(s)' and go green forever.
    missing = [pth for pth in args.paths if not os.path.exists(pth)]
    if missing:
        for pth in missing:
            print(f"cilium-lint: no such path: {pth}", file=sys.stderr)
        return 2
    if not _collect_py(args.paths):
        print("cilium-lint: no Python files found under "
              + " ".join(args.paths), file=sys.stderr)
        return 2
    diff_filter: set[str] | None = None
    if args.diff is not None:
        changed = _changed_files(args.diff)
        if changed is None:
            # A bad rev (or no git) must not masquerade as a clean
            # scan — same fail-closed stance as a typo'd path.
            print(f"cilium-lint: --diff {args.diff}: git could not "
                  f"resolve the diff; fix the rev or drop --diff",
                  file=sys.stderr)
            return 2
        diff_filter = {
            os.path.realpath(f) for f in _collect_py(args.paths)
            if os.path.realpath(f) in changed
        }
        if not diff_filter:
            # The rev resolved and nothing under the scan paths
            # changed: a legitimate no-op (the pre-commit fast path),
            # NOT the misconfigured-scan case above.
            print(f"cilium-lint: no Python files under "
                  f"{' '.join(args.paths)} changed since "
                  f"{args.diff}; nothing to scan", file=sys.stderr)
            return 0

    baseline = None
    baseline_path = None
    baseline_full = None
    if not args.no_baseline:
        baseline_path = args.baseline or _default_baseline(args.paths)
        if baseline_path is not None:
            try:
                baseline_full = load_baseline_full(baseline_path)
                baseline = baseline_full["accepted"]
            except (OSError, ValueError) as e:
                print(f"cilium-lint: bad baseline {baseline_path}: {e}",
                      file=sys.stderr)
                return 2

    # The analysis ALWAYS sees the full scan target — the
    # interprocedural rules need both halves of every seam; --diff
    # only narrows the REPORT (the warm content-hash cache is what
    # makes the full pass cheap pre-commit).
    findings = analyze_paths(args.paths, baseline=baseline)
    if args.device_contracts:
        from . import devicecheck
        from .core import _baseline_matches

        extra = devicecheck.check_device_contracts()
        # Device-contract findings have no source line, so a pragma
        # can never reach them — the baseline's accepted list is their
        # ONE escape hatch (a jax upgrade shifting an equation count
        # must be acceptable without editing the tool).
        if baseline:
            for f in extra:
                if any(_baseline_matches(e, f) for e in baseline):
                    f.baselined = True
        findings.extend(extra)

    if args.ratchet:
        # The ratchet counts the FULL (pre-filter) view: it gates the
        # tree-wide suppression total, and letting a --diff run record
        # a changed-files-only count would corrupt the baseline for
        # every full run after it.
        _, full_muted = split_findings(findings)
        rc = _ratchet(args, baseline_path, baseline_full, full_muted)
        if rc is not None:
            return rc

    # The report filter runs LAST — after the device-contract extend —
    # so diff mode never reports (or fails on) a finding in a file the
    # rev did not touch.
    if diff_filter is not None:
        findings = [
            f for f in findings
            if os.path.realpath(f.path) in diff_filter
        ]
    active, muted = split_findings(findings)

    if args.as_json:
        print(json.dumps(findings_to_json(findings), indent=2))
        return 1 if active else 0

    if args.sarif:
        print(json.dumps(_sarif_report(findings), indent=2))
        return 1 if active else 0

    for f in active:
        print(f.render())
    if args.show_suppressed:
        for f in muted:
            tag = "baseline" if f.baselined else "pragma"
            why = f" ({f.justification})" if f.justification else ""
            print(f"suppressed[{tag}]: {f.render()}{why}")
    n_files = len({f.path for f in findings}) if findings else 0
    print(
        f"cilium-lint: {len(active)} finding(s), "
        f"{len(muted)} suppressed"
        + (f" across {n_files} file(s)" if findings else "")
    )
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
