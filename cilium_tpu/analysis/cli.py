"""``cilium-lint`` CLI.

Text mode prints one line per active finding and exits 1 when any
survive suppression; ``--json`` emits the full machine-readable report
(active + suppressed + per-rule counts) for CI consumption.  The
baseline (``--baseline``, default ``tests/lint_baseline.json`` when it
exists next to the scanned tree) accepts findings wholesale so new
violations fail the build while grandfathered ones don't.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .core import (
    RULE_DOCS,
    _collect_py,
    analyze_paths,
    findings_to_json,
    load_baseline,
    split_findings,
)


def _default_baseline(paths) -> str | None:
    """tests/lint_baseline.json next to the scanned package, if any."""
    for p in paths:
        d = os.path.abspath(p)
        if not os.path.isdir(d):
            d = os.path.dirname(d)
        for root in (d, os.path.dirname(d)):
            cand = os.path.join(root, "tests", "lint_baseline.json")
            if os.path.exists(cand):
                return cand
    return None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="cilium-lint",
        description="AST-based concurrency & hot-path invariant "
                    "analyzer (rules R1-R6; see README 'Invariants & "
                    "lint')",
    )
    p.add_argument("paths", nargs="*", default=["cilium_tpu"],
                   help="files or directories to scan "
                        "(default: cilium_tpu)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit a JSON report (active + suppressed + "
                        "per-rule counts)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="JSON list of {rule,file,symbol} accepted "
                        "findings (default: tests/lint_baseline.json "
                        "next to the scanned tree, when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print pragma-/baseline-suppressed "
                        "findings (text mode)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule set and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule, doc in sorted(RULE_DOCS.items()):
            print(f"{rule}  {doc}")
        return 0

    # The gate must fail CLOSED on a misconfigured invocation: a
    # typo'd path (or a CI job run from the wrong cwd) scanning zero
    # files would otherwise print '0 finding(s)' and go green forever.
    missing = [pth for pth in args.paths if not os.path.exists(pth)]
    if missing:
        for pth in missing:
            print(f"cilium-lint: no such path: {pth}", file=sys.stderr)
        return 2
    if not _collect_py(args.paths):
        print("cilium-lint: no Python files found under "
              + " ".join(args.paths), file=sys.stderr)
        return 2

    baseline = None
    if not args.no_baseline:
        path = args.baseline or _default_baseline(args.paths)
        if path is not None:
            try:
                baseline = load_baseline(path)
            except (OSError, ValueError) as e:
                print(f"cilium-lint: bad baseline {path}: {e}",
                      file=sys.stderr)
                return 2

    findings = analyze_paths(args.paths, baseline=baseline)
    active, muted = split_findings(findings)

    if args.as_json:
        print(json.dumps(findings_to_json(findings), indent=2))
        return 1 if active else 0

    for f in active:
        print(f.render())
    if args.show_suppressed:
        for f in muted:
            tag = "baseline" if f.baselined else "pragma"
            why = f" ({f.justification})" if f.justification else ""
            print(f"suppressed[{tag}]: {f.render()}{why}")
    n_files = len({f.path for f in findings}) if findings else 0
    print(
        f"cilium-lint: {len(active)} finding(s), "
        f"{len(muted)} suppressed"
        + (f" across {n_files} file(s)" if findings else "")
    )
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
