"""R20 — wire-protocol lifecycle graph against the declared MSG table.

R5 proves the seam handles every ``MSG_*`` somewhere; this pass proves
each message's LIFECYCLE matches its declared row in
``analysis/protocols.py::WIRE_MESSAGES``: direction (who may send it),
reply pairing (a request handler must reach a send of its declared
reply), fire-and-forget consistency, and the version/flag gate tokens
both seam ends must reference.  The native-shim coexistence constants
(``NATIVE_MIRRORS``) are cross-checked value-for-value on every SHARED
name — the Python enums may extend past the reference ABI (fail-closed
on old consumers), the header may lag on the extensions, but a VALUE
drift on a shared name is silent verdict corruption at the C seam.

Seams are grouped by directory exactly like R5: a scanned dir holding
``wire.py`` + ``service.py`` + ``client.py`` is one seam, so a corpus
twin dir exercises the same resolution the real sidecar does.

Send-site strictness matters: a MSG token is a *send* only as a direct
positional argument of a send-named call (``send``, ``send_msg``,
``_send``...) — the client's control round-trips pass expected-REPLY
tokens as wait arguments, which must not count as the client sending a
service-direction frame.  A *handle* site is a MSG token inside an
equality/membership Compare (the dispatch chains' shape).
"""

from __future__ import annotations

import ast
import hashlib
import os
import re

from .core import Finding, terminal_name, walk_functions

_SEND_NAMES = {
    "send", "send_frames", "send_msg", "_send", "_send_round",
    "_transport_send",
}
_SEAM_BASES = ("wire.py", "service.py", "client.py")

_HDR_DEFINE = re.compile(r"#\s*define\s+(CT_[A-Z0-9_]+)\s+(\d+)")
_HDR_ENUM = re.compile(r"\b(CT_[A-Z0-9_]+)\s*=\s*(\d+)")


def _extract_table(files):
    """(table dict, defining path, line) from the first
    ``WIRE_MESSAGES = {...}`` literal in the scanned set."""
    for path, sf in sorted(files.items()):
        for node in sf.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "WIRE_MESSAGES"):
                try:
                    table = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    continue
                if isinstance(table, dict):
                    return table, path, node.lineno
    return None, None, 0


def _extract_mirrors(files):
    for path, sf in sorted(files.items()):
        for node in sf.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "NATIVE_MIRRORS"):
                try:
                    rows = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    continue
                return list(rows), path, node.lineno
    return [], None, 0


def _wire_msgs(sf) -> dict[str, int]:
    """Module-level ``MSG_X = <int>`` constants of a wire module."""
    out: dict[str, int] = {}
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.startswith("MSG_")
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)):
            out[node.targets[0].id] = node.value.value
    return out


def _msg_token(node: ast.AST) -> str | None:
    if isinstance(node, ast.Name) and node.id.startswith("MSG_"):
        return node.id
    if isinstance(node, ast.Attribute) and node.attr.startswith("MSG_"):
        return node.attr
    return None


def _send_sites_in(node: ast.AST) -> dict[str, list[tuple[int, int]]]:
    """msg -> [(line, col)] for send-named calls carrying a MSG token
    as a direct positional argument."""
    out: dict[str, list] = {}
    for n in ast.walk(node):
        if (isinstance(n, ast.Call)
                and terminal_name(n.func) in _SEND_NAMES):
            for arg in n.args:
                msg = _msg_token(arg)
                if msg is not None:
                    out.setdefault(msg, []).append(
                        (n.lineno, n.col_offset)
                    )
    return out


def _handle_sites(sf) -> dict[str, list]:
    """msg -> [enclosing function node] for MSG tokens compared with
    ``==`` / ``in`` (the handler-dispatch shapes)."""
    out: dict[str, list] = {}
    for fn, _qual, _cls in walk_functions(sf.tree):
        if isinstance(fn, ast.Lambda):
            continue
        for n in ast.walk(fn):
            if not isinstance(n, ast.Compare):
                continue
            if not any(isinstance(op, (ast.Eq, ast.In)) for op in n.ops):
                continue
            for side in [n.left, *n.comparators]:
                msg = _msg_token(side)
                if msg is not None:
                    out.setdefault(msg, []).append(fn)
                elif isinstance(side, (ast.Tuple, ast.List, ast.Set)):
                    for e in side.elts:
                        m2 = _msg_token(e)
                        if m2 is not None:
                            out.setdefault(m2, []).append(fn)
    return out


def _identifiers(sf) -> set[str]:
    ids: set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Name):
            ids.add(node.id)
        elif isinstance(node, ast.Attribute):
            ids.add(node.attr)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            ids.add(node.value)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                ids.add(a.asname or a.name.split(".")[0])
    return ids


def _references(sf) -> set[str]:
    refs: set[str] = set()
    for node in ast.walk(sf.tree):
        msg = _msg_token(node)
        if msg is not None:
            refs.add(msg)
    return refs


def _handler_reaches_send(graph, fn_node, reply: str, depth=2) -> bool:
    """Does the handler (or a scanned callee within ``depth`` hops)
    contain a send-site of ``reply``?"""
    seen: set[str] = set()
    frontier = [(fn_node, 0)]
    while frontier:
        node, d = frontier.pop()
        if reply in _send_sites_in(node):
            return True
        if d >= depth:
            continue
        fi = graph.info_for(node)
        if fi is None:
            continue
        for _call, _l, _c, _held, keys in fi.calls:
            for key in keys or ():
                if key in seen:
                    continue
                seen.add(key)
                callee = graph.funcs.get(key)
                if callee is not None:
                    frontier.append((callee.node, d + 1))
    return False


def _header_candidates(files) -> list[str]:
    """Possible native-header locations derived from the scanned set:
    next to each scanned dir and at each dir's great-grandparent (the
    repo root when the tables file sits at pkg/analysis/protocols.py)."""
    roots: set[str] = set()
    for path in files:
        d = os.path.dirname(os.path.abspath(path))
        roots.add(d)
        roots.add(os.path.dirname(os.path.dirname(d)))
    return sorted(roots)


def _memo_extra(files) -> str:
    """Disk-state digest for the rule memo: the native header is read
    from OUTSIDE the scanned set, so its (path, size, mtime) must key
    the cache or an edited header would re-serve stale findings."""
    sig = []
    for root in _header_candidates(files):
        hdr = os.path.join(root, "native", "cilium_tpu_shim.h")
        try:
            st = os.stat(hdr)
            sig.append(f"{hdr}:{st.st_size}:{st.st_mtime_ns}")
        except OSError:
            continue
    return hashlib.sha256("|".join(sig).encode()).hexdigest()[:16]


def _find_header(files, header_rel: str) -> str | None:
    for root in _header_candidates(files):
        cand = os.path.join(root, header_rel.replace("/", os.sep))
        if os.path.isfile(cand):
            return cand
    return None


def _python_enum_members(files, enum: str) -> dict[str, int] | None:
    for _path, sf in sorted(files.items()):
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and node.name == enum:
                out: dict[str, int] = {}
                for stmt in node.body:
                    if (isinstance(stmt, ast.Assign)
                            and len(stmt.targets) == 1
                            and isinstance(stmt.targets[0], ast.Name)
                            and isinstance(stmt.value, ast.Constant)
                            and isinstance(stmt.value.value, int)):
                        out[stmt.targets[0].id] = stmt.value.value
                return out
    return None


def _check_native_mirrors(files, mirrors, decl_path, decl_line):
    if not mirrors:
        return
    header_texts: dict[str, str | None] = {}
    # Longest-prefix wins so CT_FILTEROP_* never misfiles under the
    # CT_FILTER_* row.
    ordered = sorted(mirrors, key=lambda m: -len(m.get("prefix", "")))
    for row in ordered:
        rel = row.get("header", "")
        if rel not in header_texts:
            found = _find_header(files, rel)
            if found is None:
                header_texts[rel] = None
            else:
                try:
                    with open(found, "r", encoding="utf-8",
                              errors="replace") as f:
                        header_texts[rel] = f.read()
                except OSError:
                    header_texts[rel] = None
        text = header_texts[rel]
        if text is None:
            continue  # no native build here: nothing to coexist with
        prefix = row.get("prefix", "")
        members = _python_enum_members(files, row.get("enum", ""))
        if members is None:
            continue
        consts: dict[str, int] = {}
        for rx in (_HDR_DEFINE, _HDR_ENUM):
            for m in rx.finditer(text):
                consts.setdefault(m.group(1), int(m.group(2)))
        longer = [
            m.get("prefix", "") for m in ordered
            if len(m.get("prefix", "")) > len(prefix)
        ]
        for cname, cval in sorted(consts.items()):
            if not cname.startswith(prefix):
                continue
            if any(cname.startswith(lp) for lp in longer):
                continue  # belongs to a more specific mirror row
            member = cname[len(prefix):]
            if member not in members:
                yield Finding(
                    "R20", decl_path, decl_line, 0,
                    f"native header constant {cname} has no "
                    f"{row['enum']} twin — the C seam carries a "
                    f"value Python cannot classify",
                )
            elif members[member] != cval:
                yield Finding(
                    "R20", decl_path, decl_line, 0,
                    f"native/Python enum drift: {cname}={cval} but "
                    f"{row['enum']}.{member}={members[member]} — "
                    f"shared ABI names must stay bit-identical",
                )


def check_r20(files):
    from .callgraph import get_graph

    table, decl_path, decl_line = _extract_table(files)
    if table is None:
        return

    # -- table self-consistency -------------------------------------
    for msg, row in sorted(table.items()):
        if row.get("fnf") and row.get("reply"):
            yield Finding(
                "R20", decl_path, decl_line, 0,
                f"{msg}: declared fire-and-forget but names reply "
                f"{row['reply']} — pick one",
            )
        if not row.get("fnf") and not row.get("reply"):
            yield Finding(
                "R20", decl_path, decl_line, 0,
                f"{msg}: neither fire-and-forget nor paired with a "
                f"reply — an unanswerable request",
            )
        reply = row.get("reply")
        if reply is not None and reply not in table:
            yield Finding(
                "R20", decl_path, decl_line, 0,
                f"{msg}: declared reply {reply} is not a declared "
                f"message",
            )

    # -- native mirror cross-check ----------------------------------
    mirrors, mdecl_path, mdecl_line = _extract_mirrors(files)
    yield from _check_native_mirrors(
        files, mirrors, mdecl_path or decl_path, mdecl_line or decl_line
    )

    # -- seam grouping (R5's shape) ---------------------------------
    by_dir: dict[str, dict] = {}
    for path in files:
        base = os.path.basename(path)
        if base in _SEAM_BASES:
            by_dir.setdefault(os.path.dirname(path), {})[base] = path
    graph = None
    for d, seam in sorted(by_dir.items()):
        if set(seam) != set(_SEAM_BASES):
            continue
        if graph is None:
            graph = get_graph(files)
        wire_sf = files[seam["wire.py"]]
        svc_sf = files[seam["service.py"]]
        cli_sf = files[seam["client.py"]]
        wire_path = seam["wire.py"]
        msgs = _wire_msgs(wire_sf)

        for msg in sorted(msgs):
            if msg not in table:
                yield Finding(
                    "R20", wire_path, wire_sf.tree.body[0].lineno, 0,
                    f"{msg} is defined on the wire but has no "
                    f"WIRE_MESSAGES lifecycle row — direction/reply/"
                    f"gating unchecked",
                )
        for msg in sorted(table):
            if msg not in msgs:
                yield Finding(
                    "R20", decl_path, decl_line, 0,
                    f"{msg} has a lifecycle row but no wire constant "
                    f"in {os.path.basename(d)}/wire.py",
                )

        svc_sends = _send_sites_in(svc_sf.tree)
        cli_sends = _send_sites_in(cli_sf.tree)
        svc_handles = _handle_sites(svc_sf)
        svc_ids = _identifiers(svc_sf)
        cli_ids = _identifiers(cli_sf)
        cli_refs = _references(cli_sf)

        for msg, row in sorted(table.items()):
            if msg not in msgs:
                continue
            direction = row.get("dir")
            if direction == "c2s":
                if msg not in svc_handles:
                    yield Finding(
                        "R20", seam["service.py"], 1, 0,
                        f"{msg} is declared client->service but the "
                        f"service dispatch chain never handles it "
                        f"(no ==/in compare)",
                    )
                if msg not in cli_refs:
                    yield Finding(
                        "R20", seam["client.py"], 1, 0,
                        f"{msg} is declared client->service but the "
                        f"client never references it",
                    )
                if msg in svc_sends:
                    line, col = svc_sends[msg][0]
                    yield Finding(
                        "R20", seam["service.py"], line, col,
                        f"{msg} is declared client->service but the "
                        f"service SENDS it — wrong direction",
                    )
            elif direction == "s2c":
                if msg not in svc_sends:
                    yield Finding(
                        "R20", seam["service.py"], 1, 0,
                        f"{msg} is declared service->client but the "
                        f"service never sends it",
                    )
                if msg not in cli_refs:
                    yield Finding(
                        "R20", seam["client.py"], 1, 0,
                        f"{msg} is declared service->client but the "
                        f"client never references it",
                    )
                if msg in cli_sends:
                    line, col = cli_sends[msg][0]
                    yield Finding(
                        "R20", seam["client.py"], line, col,
                        f"{msg} is declared service->client but the "
                        f"client SENDS it — wrong direction",
                    )
            # -- reply pairing (request handler reaches the send) ----
            reply = row.get("reply")
            if (reply is not None and not row.get("deferred")
                    and direction in ("c2s", "peer")
                    and msg in svc_handles):
                if not any(
                    _handler_reaches_send(graph, fn, reply)
                    for fn in svc_handles[msg]
                ):
                    fn0 = svc_handles[msg][0]
                    yield Finding(
                        "R20", seam["service.py"], fn0.lineno,
                        fn0.col_offset,
                        f"{msg} handler never reaches a send of its "
                        f"declared reply {reply} (within 2 call "
                        f"hops) — the requester hangs until its "
                        f"timeout",
                    )
            # -- gate tokens on both seam ends (a peer message's two
            # ends are BOTH the service module, so the client half is
            # out of scope for its gates) ----------------------------
            for gate in row.get("gates", ()):
                if gate not in svc_ids:
                    yield Finding(
                        "R20", seam["service.py"], 1, 0,
                        f"{msg}: gate token {gate} is never "
                        f"referenced by the service half",
                    )
                if direction != "peer" and gate not in cli_ids:
                    yield Finding(
                        "R20", seam["client.py"], 1, 0,
                        f"{msg}: gate token {gate} is never "
                        f"referenced by the client half",
                    )


check_r20.memo_extra = _memo_extra
