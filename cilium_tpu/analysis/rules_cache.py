"""R13 — epoch-unkeyed cache in hot modules.

PR 12's verdict cache made the repo's central caching contract explicit:
any cache consulted on the serving path must be keyed (or guarded) by
the policy epoch / generation it was derived under, or a pointer-flip
swap leaves it serving stale decisions with no functional test able to
see it (verdicts stay plausible — they are just the OLD table's).  The
conn-table cache columns pair every row with a ``*_epoch`` twin and the
hit mask compares it against the snapshot epoch; the shim grant table
stores epochs and compares against the latest revoke.  This rule pins
the pattern:

- **Unkeyed write.**  A subscript store into a cache-named container
  (``*cache*`` / ``*memo*``) in a hot module whose key derivation
  carries no epoch/generation term, in a function that maintains no
  sibling epoch store (``<base>_epoch[...]`` / any ``*epoch*`` /
  ``*generation*`` identifier) — nothing ties the entry to the table
  generation it was computed from.
- **Unchecked read.**  A subscript load / ``.get()`` on such a
  container in a function that never touches an epoch/generation
  identifier — the consumer cannot be validating the entry's
  generation.

Caches that are deliberately generation-free carry a justified pragma
naming WHY (the shape-keyed executable cache survives swaps by design:
its keys are table shapes, not table contents, and the id-keyed halves
are popped at the flip).
"""

from __future__ import annotations

import ast
import os

from .core import Finding

_HOT_BASENAMES = {
    "service.py", "dispatch.py", "client.py", "reasm.py", "shm.py",
    "transport.py", "wire.py", "dnsengine.py",
}

_CACHE_TOKENS = ("cache", "memo")
_EPOCH_TOKENS = ("epoch", "generation")


def _base_name(node) -> str | None:
    """Rightmost identifier of a subscript/call base: ``self._x[k]`` ->
    ``_x``, ``cache[k]`` -> ``cache``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_cache_name(name: str | None) -> bool:
    if not name:
        return False
    low = name.lower()
    return any(t in low for t in _CACHE_TOKENS)


def _has_epoch_token(name: str | None) -> bool:
    if not name:
        return False
    low = name.lower()
    return any(t in low for t in _EPOCH_TOKENS)


def _idents(node) -> set[str]:
    """All identifier strings under ``node`` (names + attribute
    components) — deliberately NOT source text, so a docstring merely
    mentioning 'epoch' cannot satisfy the rule."""
    out: set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _func_epoch_idents(fn: ast.AST) -> bool:
    return any(_has_epoch_token(i) for i in _idents(fn))


def _walk_own(fn):
    """Yield ``fn``'s own nodes, pruning nested function BODIES —
    ``ast.walk`` would keep descending past a nested def (a bare
    ``continue`` on the def node skips only the node itself), double-
    reporting every cache site inside a closure under both the closure
    and its parent."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # nested defs get their own visit
        stack.extend(ast.iter_child_nodes(node))


def check_r13(files):
    for path, sf in files.items():
        if os.path.basename(path) not in _HOT_BASENAMES:
            continue
        tree = sf.tree
        if tree is None:
            continue
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            epoch_aware = _func_epoch_idents(fn)
            if epoch_aware:
                # The function maintains/compares a generation term
                # somewhere — the sibling-epoch-store pattern (or an
                # explicit guard).  Per-site key analysis would only
                # produce noise on top of that signal.
                continue
            for node in _walk_own(fn):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if not isinstance(tgt, ast.Subscript):
                            continue
                        base = _base_name(tgt.value)
                        if not _is_cache_name(base) \
                                or _has_epoch_token(base):
                            continue
                        if any(_has_epoch_token(i)
                               for i in _idents(tgt.slice)):
                            continue
                        yield Finding(
                            "R13", sf.path, node.lineno,
                            node.col_offset,
                            f"cache store {base}[...] keyed without an "
                            f"epoch/generation term (and no sibling "
                            f"epoch store in {fn.name}): a policy "
                            f"pointer-flip leaves this entry serving "
                            f"the OLD table's decision",
                            symbol=fn.name,
                        )
                elif isinstance(node, ast.Subscript) and isinstance(
                    node.ctx, ast.Load
                ):
                    base = _base_name(node.value)
                    if _is_cache_name(base) and not _has_epoch_token(
                        base
                    ):
                        yield Finding(
                            "R13", sf.path, node.lineno,
                            node.col_offset,
                            f"cache read {base}[...] with no epoch/"
                            f"generation check anywhere in {fn.name}: "
                            f"the consumer cannot be validating the "
                            f"entry's table generation",
                            symbol=fn.name,
                        )
                elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ) and node.func.attr == "get":
                    base = _base_name(node.func.value)
                    if _is_cache_name(base) and not _has_epoch_token(
                        base
                    ):
                        yield Finding(
                            "R13", sf.path, node.lineno,
                            node.col_offset,
                            f"cache read {base}.get(...) with no "
                            f"epoch/generation check anywhere in "
                            f"{fn.name}: the consumer cannot be "
                            f"validating the entry's table generation",
                            symbol=fn.name,
                        )
