"""R8-R11 — device-contract rules (AST half).

The benches only catch hot-path contract violations at runtime, on a
chip, after a recompile storm or a silent host round-trip has already
eaten the p99.  These rules pin the contracts statically; their twin
half (``analysis/devicecheck.py``) verifies the SAME contracts by
abstract-tracing the real verdict models under ``JAX_PLATFORMS=cpu``
— no device, no model execution, zero runtime cost.

- **R8 recompilation hazards.**  Inside jit-reached code (whole-program
  reachability shared with R4): ``int()/float()/bool()`` on a traced
  parameter concretizes at trace time — the value is baked in and
  every new value retraces; ``jnp.array(0.5)``-style scalar constants
  without ``dtype=`` are weak-typed, and weak types flow through
  comparisons into outputs where they key a NEW executable per caller
  dtype mix.  At jit call boundaries: a ``static_argnums`` argument
  fed a list/dict/set literal is unhashable — every call either
  raises or recompiles.
- **R9 implicit host transfers.**  ``.item()``, host-numpy coercion
  (``np.*``), ``device_get`` and ``block_until_ready`` inside a traced
  function are a trace error or a silent device->host sync.  In the
  dispatch hot-path modules the ONLY sanctioned sync point is the
  fenced ``np.asarray`` readback (BENCH_NOTES r4: block_until_ready
  can return pre-execution on tunneled transports AND serializes the
  round) — ``.item()`` / ``block_until_ready`` there is per-entry
  latency hidden from the stage histograms.
- **R10 sharding-spec consistency.**  A ``shard_map``/``pjit`` call
  site's ``in_specs`` arity must match the wrapped function's
  positional signature, and a tuple ``out_specs`` must match the
  function's return-tuple length — today this explodes at first trace
  ON A MESH, i.e. in the multi-chip path the single-chip CI never
  exercises (ROADMAP open item 1 pays for this rule).
- **R11 fused-attribution integrity.**  The PR 5 contract: ``verdicts``
  and ``verdicts_attr`` must consume ONE shared hit-matrix pass.  An
  attr twin that calls the plain twin (or re-runs the hits helper)
  is a second device pass — double hot-path cost that no parity test
  notices because the RESULTS are identical.
- **R16 shape-closure (AST half).**  Every jit dispatch must draw its
  batch axis from the declared bucket universe (``MIN_BUCKET`` pow2
  round-up, ``pack_buckets`` widths, ``MIN_RULE_BUCKET`` tables): an
  allocation whose leading dim comes straight from ``len()`` /
  ``.count`` / ``.shape[0]`` keys a NEW executable per distinct batch
  size — a silent re-trace on the hot path that "no new jit shapes"
  prose cannot prevent.  The abstract-trace twin
  (``devicecheck.check_shape_closure``) proves the same closure on the
  real serving surface.
"""

from __future__ import annotations

import ast
import os

from .callgraph import get_graph
from .core import Finding, call_func_name, terminal_name, unparse
from .rules_jit import jit_reached

_HOT_BASENAMES = {"dispatch.py", "service.py", "shm.py"}
_NP_NAMES = {"np", "numpy"}
_JNP_NAMES = {"jnp", "numpy", "np"}  # jnp aliases checked w/ receiver
_CONCRETIZERS = {"int", "float", "bool"}
_SCALAR_CTORS = {"array", "asarray"}


def _fn_params(fn) -> set[str]:
    a = fn.args
    names = [p.arg for p in list(a.posonlyargs) + list(a.args)
             + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    return {n for n in names if n != "self"}


def _has_dtype(call: ast.Call, n_positional_for_dtype: int) -> bool:
    if any(kw.arg == "dtype" for kw in call.keywords):
        return True
    return len(call.args) >= n_positional_for_dtype


# --- R8 -------------------------------------------------------------------

def _r8_traced_body(sf, fn, qual):
    params = _fn_params(fn) if not isinstance(fn, ast.Lambda) else {
        p.arg for p in fn.args.args
    }
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = call_func_name(node)
        if (isinstance(node.func, ast.Name)
                and name in _CONCRETIZERS
                and node.args
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in params):
            yield Finding(
                "R8", sf.path, node.lineno, node.col_offset,
                f"{name}() on traced argument "
                f"{node.args[0].id!r} concretizes at trace time: the "
                f"Python scalar is baked into the executable and every "
                f"distinct value triggers a silent recompile (or a "
                f"ConcretizationTypeError on a real tracer)",
                symbol=qual,
            )
        elif (isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in _JNP_NAMES):
            if (name in _SCALAR_CTORS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, (int, float))
                    and not isinstance(node.args[0].value, bool)
                    and not _has_dtype(node, 2)):
                yield Finding(
                    "R8", sf.path, node.lineno, node.col_offset,
                    f"weak-typed scalar constant "
                    f"{unparse(node)}: without dtype= the constant's "
                    f"weak type flows into the outputs, where it keys "
                    f"a separate compiled executable per caller dtype "
                    f"mix — pin the dtype",
                    symbol=qual,
                )
            elif (name == "full"
                    and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, (int, float))
                    and not isinstance(node.args[1].value, bool)
                    and not _has_dtype(node, 3)):
                yield Finding(
                    "R8", sf.path, node.lineno, node.col_offset,
                    f"weak-typed fill constant {unparse(node)}: "
                    f"without dtype= the fill value's weak type flows "
                    f"into the outputs and keys per-caller recompiles "
                    f"— pin the dtype",
                    symbol=qual,
                )


def _jit_static_positions(sf):
    """{function name: (static positions, static names)} for ONE file,
    from jax.jit(..., static_argnums=...) wrap sites and
    @partial(jax.jit, static_argnums=...) decorators.  Per-file
    scoping keeps the bare-name call-site match precise: an unrelated
    same-named function in another module must not inherit this
    file's static-arg contract."""
    out: dict[str, tuple[set, set]] = {}

    def record(fname: str, call: ast.Call) -> None:
        nums: set[int] = set()
        names: set[str] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                vals = (kw.value.elts
                        if isinstance(kw.value, (ast.Tuple, ast.List))
                        else [kw.value])
                for v in vals:
                    if isinstance(v, ast.Constant) and isinstance(
                            v.value, int):
                        nums.add(v.value)
            elif kw.arg == "static_argnames":
                vals = (kw.value.elts
                        if isinstance(kw.value, (ast.Tuple, ast.List))
                        else [kw.value])
                for v in vals:
                    if isinstance(v, ast.Constant) and isinstance(
                            v.value, str):
                        names.add(v.value)
        if nums or names:
            prev = out.get(fname, (set(), set()))
            out[fname] = (prev[0] | nums, prev[1] | names)

    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if (isinstance(dec, ast.Call)
                        and call_func_name(dec) == "partial"
                        and dec.args
                        and "jit" in unparse(dec.args[0])):
                    record(node.name, dec)
        elif isinstance(node, ast.Call) and call_func_name(
                node) == "jit":
            if node.args and isinstance(
                    node.args[0], (ast.Name, ast.Attribute)):
                record(terminal_name(node.args[0]), node)
    return out


_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp)


def _r8_static_args(files):
    for sf in files.values():
        statics = _jit_static_positions(sf)
        if not statics:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = call_func_name(node)
            got = statics.get(fname)
            if got is None:
                continue
            nums, names = got
            for i, a in enumerate(node.args):
                if i in nums and isinstance(a, _UNHASHABLE):
                    yield Finding(
                        "R8", sf.path, a.lineno, a.col_offset,
                        f"unhashable literal passed for static arg "
                        f"{i} of jitted {fname}(): static args key "
                        f"the compile cache by hash — this call "
                        f"raises (or recompiles) every time; pass a "
                        f"tuple or a hashable config object",
                    )
            for kw in node.keywords:
                if kw.arg in names and isinstance(kw.value, _UNHASHABLE):
                    yield Finding(
                        "R8", sf.path, kw.value.lineno,
                        kw.value.col_offset,
                        f"unhashable literal passed for static arg "
                        f"{kw.arg!r} of jitted {fname}(): static args "
                        f"key the compile cache by hash — this call "
                        f"raises (or recompiles) every time; pass a "
                        f"tuple or a hashable config object",
                    )


def check_r8(files):
    reached, all_lambdas = jit_reached(files)
    emitted: set = set()
    for fi in reached:
        sf = files.get(fi.path)
        if sf is None:
            continue
        for f in _r8_traced_body(sf, fi.node, fi.qual):
            key = (f.path, f.line, f.col)
            if key not in emitted:
                emitted.add(key)
                yield f
    for sf, lam in all_lambdas:
        for f in _r8_traced_body(sf, lam, "<lambda>"):
            key = (f.path, f.line, f.col)
            if key not in emitted:
                emitted.add(key)
                yield f
    for f in _r8_static_args(files):
        key = (f.path, f.line, f.col)
        if key not in emitted:
            emitted.add(key)
            yield f


# --- R9 -------------------------------------------------------------------

_TRANSFER_METHODS = {"item", "block_until_ready", "device_get"}

# numpy dtype-scalar constructors: on a LITERAL they build a typed
# constant that traces device-side for free (the dual host/device
# hash-constant idiom in datapath/pipeline.py) — only a non-constant
# argument makes them a concretization/transfer.
_NP_DTYPE_CTORS = {
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "float32", "float64", "bool_",
}


def _r9_traced_body(sf, fn, qual):
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = call_func_name(node)
        if (isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in _NP_NAMES):
            def _const(a):  # literals incl. signed: np.int32(-163...)
                return isinstance(a, ast.Constant) or (
                    isinstance(a, ast.UnaryOp)
                    and isinstance(a.op, (ast.USub, ast.UAdd))
                    and isinstance(a.operand, ast.Constant)
                )

            if name in _NP_DTYPE_CTORS and all(
                _const(a) for a in node.args
            ):
                continue
            yield Finding(
                "R9", sf.path, node.lineno, node.col_offset,
                f"host-numpy call {unparse(node.func)}() inside a "
                f"traced function: on a tracer this is a "
                f"ConcretizationTypeError; on constants it silently "
                f"pins a host round-trip into every dispatch",
                symbol=qual,
            )
        elif (name in _TRANSFER_METHODS
                and isinstance(node.func, ast.Attribute)):
            yield Finding(
                "R9", sf.path, node.lineno, node.col_offset,
                f"{name}() inside a traced function forces a "
                f"device->host transfer at trace time — the value is "
                f"stale for every later batch and the sync point is "
                f"invisible to the stage histograms",
                symbol=qual,
            )


_POLL_METHODS = {"is_ready", "is_deleted"}


def _r9_spin_poll(path, sf):
    """A ``while`` spinning on device-array readiness (is_ready /
    is_deleted in the loop condition) in a hot-path module: the
    device-future poll twin of R2.2's shared-slot spin — it burns a
    core per outstanding round and hides the sync from the stage
    histograms.  The fenced np.asarray readback (or the completion
    pipeline's batched device_get) is the sanctioned wait."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.While):
            continue
        for sub in ast.walk(node.test):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _POLL_METHODS):
                yield Finding(
                    "R9", path, node.lineno, node.col_offset,
                    f"spin-polling {sub.func.attr}() on the dispatch "
                    f"hot path: the readiness loop burns a core per "
                    f"outstanding round and the sync is invisible to "
                    f"the stage histograms — use the fenced "
                    f"np.asarray readback (or the completion "
                    f"pipeline's batched device_get)",
                )
                break


def _r9_hot_path(files):
    """In dispatch hot-path modules, the fenced np.asarray readback is
    the ONE sanctioned sync point; .item() / block_until_ready are
    per-entry host syncs the latency decomposition cannot see."""
    for path, sf in sorted(files.items()):
        if os.path.basename(path) not in _HOT_BASENAMES:
            continue
        yield from _r9_spin_poll(path, sf)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_func_name(node)
            if name == "block_until_ready" and isinstance(
                    node.func, ast.Attribute):
                yield Finding(
                    "R9", path, node.lineno, node.col_offset,
                    "block_until_ready on the dispatch hot path: "
                    "BENCH_NOTES r4 — it can return pre-execution on "
                    "tunneled transports and serializes the round; "
                    "the fenced np.asarray readback is the sanctioned "
                    "sync point",
                )
            elif (name == "item"
                    and isinstance(node.func, ast.Attribute)
                    and not node.args and not node.keywords):
                yield Finding(
                    "R9", path, node.lineno, node.col_offset,
                    ".item() on the dispatch hot path is a per-entry "
                    "device->host sync outside the fenced readback — "
                    "read the whole array once via np.asarray and "
                    "index on host",
                )


def check_r9(files):
    reached, all_lambdas = jit_reached(files)
    emitted: set = set()
    for fi in reached:
        sf = files.get(fi.path)
        if sf is None:
            continue
        for f in _r9_traced_body(sf, fi.node, fi.qual):
            key = (f.path, f.line, f.col)
            if key not in emitted:
                emitted.add(key)
                yield f
    for sf, lam in all_lambdas:
        for f in _r9_traced_body(sf, lam, "<lambda>"):
            key = (f.path, f.line, f.col)
            if key not in emitted:
                emitted.add(key)
                yield f
    for f in _r9_hot_path(files):
        key = (f.path, f.line, f.col)
        if key not in emitted:
            emitted.add(key)
            yield f


# --- R10 ------------------------------------------------------------------

def _spec_len(expr: ast.AST) -> int | None:
    """Arity of an in_specs/out_specs expression: tuple/list literal
    length; None for single specs (broadcast / pytree prefix) or
    anything non-literal."""
    if isinstance(expr, (ast.Tuple, ast.List)):
        return len(expr.elts)
    return None


def _positional_arity(fn) -> tuple[int, bool]:
    """(positional param count, has_varargs)."""
    a = fn.args
    return len(a.posonlyargs) + len(a.args), a.vararg is not None


def _return_tuple_lens(fn) -> set[int] | None:
    """Lengths of tuple-literal returns in fn's OWN body (nested defs
    are their own functions — their returns must not leak in); None
    when any own return is not a tuple literal (arity unknowable
    statically)."""
    lens: set[int] = set()
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Tuple):
                lens.add(len(node.value.elts))
            else:
                return None
        stack.extend(ast.iter_child_nodes(node))
    return lens or None


def _shard_sites(sf):
    """Yield (call node, target fn name or None, target fn node or
    None, kind) for shard_map/pjit call sites and partial-decorators."""
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if (isinstance(dec, ast.Call)
                        and call_func_name(dec) == "partial"
                        and dec.args
                        and terminal_name(dec.args[0]) in (
                            "shard_map", "pjit")):
                    yield dec, node.name, node, terminal_name(
                        dec.args[0])
        elif isinstance(node, ast.Call) and call_func_name(node) in (
                "shard_map", "pjit"):
            target = node.args[0] if node.args else None
            yield node, (
                terminal_name(target) if target is not None else None
            ), None, call_func_name(node)


def check_r10(files):
    graph = get_graph(files)
    for path, sf in sorted(files.items()):
        mod = graph.mod_of_path[path]
        for call, tname, tnode, kind in _shard_sites(sf):
            in_specs = out_specs = None
            for kw in call.keywords:
                if kw.arg in ("in_specs", "in_shardings"):
                    in_specs = kw.value
                elif kw.arg in ("out_specs", "out_shardings"):
                    out_specs = kw.value
            if in_specs is None and out_specs is None:
                continue
            # resolve the wrapped function
            fn = tnode
            if fn is None and tname:
                for cand in graph.defs.get(mod, {}).get(tname, ()):
                    if cand.cls == "":
                        fn = cand.node
                        break
            if fn is None:
                continue
            n_in = _spec_len(in_specs) if in_specs is not None else None
            if n_in is not None:
                arity, varargs = _positional_arity(fn)
                if not varargs and n_in != arity:
                    yield Finding(
                        "R10", path, call.lineno, call.col_offset,
                        f"{kind} in_specs has {n_in} spec(s) but "
                        f"{fn.name}() takes {arity} positional "
                        f"argument(s) — the mismatch only explodes at "
                        f"first trace on a real mesh (the multi-chip "
                        f"path single-chip CI never runs)",
                        symbol=fn.name,
                    )
            n_out = _spec_len(out_specs) if out_specs is not None \
                else None
            if n_out is not None:
                lens = _return_tuple_lens(fn)
                if lens is not None and lens != {n_out}:
                    got = sorted(lens)
                    yield Finding(
                        "R10", path, call.lineno, call.col_offset,
                        f"{kind} out_specs has {n_out} spec(s) but "
                        f"{fn.name}() returns tuple(s) of length "
                        f"{got} — sharded outputs would be mis-"
                        f"assembled (or the trace explodes) on a "
                        f"real mesh",
                        symbol=fn.name,
                    )


# --- R11 ------------------------------------------------------------------

def _callee_names(fn) -> list[str]:
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            out.append(call_func_name(node))
    return out


def _hits_callees(names: list[str]) -> set[str]:
    return {n for n in names if "hits" in n}


def check_r11(files):
    for path, sf in sorted(files.items()):
        # (plain fn, attr fn) twin pairs: module-level X / X_attr.
        mod_fns: dict[str, ast.AST] = {}
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod_fns[node.name] = node
        for name, fn in sorted(mod_fns.items()):
            if not name.endswith("_attr"):
                continue
            plain = mod_fns.get(name[: -len("_attr")])
            if plain is None:
                continue
            attr_calls = _callee_names(fn)
            plain_name = name[: -len("_attr")]
            plain_hits = _hits_callees(_callee_names(plain))
            attr_hits = _hits_callees(attr_calls)
            if plain_name in attr_calls:
                yield Finding(
                    "R11", path, fn.lineno, fn.col_offset,
                    f"{name}() calls {plain_name}(): a SECOND device "
                    f"pass for attribution — the contract is one "
                    f"shared hit-matrix pass consumed by both the "
                    f"verdict reduction and the argmax (PR 5's fused "
                    f"design); the parity tests cannot see the "
                    f"doubled cost because the results are identical",
                    symbol=name,
                )
            elif plain_hits and attr_hits and not (
                    plain_hits & attr_hits):
                yield Finding(
                    "R11", path, fn.lineno, fn.col_offset,
                    f"{name}() consumes hit pass {sorted(attr_hits)} "
                    f"but {plain_name}() consumes "
                    f"{sorted(plain_hits)} — the twins must share ONE "
                    f"hit-matrix helper or verdict and attribution "
                    f"can drift apart (and each pays its own device "
                    f"pass)",
                    symbol=name,
                )
            elif attr_hits:
                shared = attr_hits & plain_hits
                for h in sorted(shared):
                    if attr_calls.count(h) > 1:
                        yield Finding(
                            "R11", path, fn.lineno, fn.col_offset,
                            f"{name}() invokes the shared hit pass "
                            f"{h}() {attr_calls.count(h)} times — a "
                            f"second device pass for attribution; "
                            f"compute the hit matrix once and feed "
                            f"both reductions",
                            symbol=name,
                        )

# --- R16 ------------------------------------------------------------------

# Dispatch boundaries whose array arguments must carry bucketed batch
# axes (the service's jit seams).
_DISPATCH_NAMES = {"_model_call", "_model_call_attr", "_gathered_call"}
_JIT_WRAPPERS = {"jit", "pjit", "_jit_for"}

_ALLOC_NAMES = {"zeros", "empty", "ones", "full"}

_BUCKET_TEXT = ("bucket", "BUCKET", "pow2", "next_pow")


def _doubled_in_while(fn, name: str) -> bool:
    """True when ``name`` is the target of the pow2 round-up idiom:
    ``while name < n: name *= 2`` (or ``<<=``) anywhere in fn."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.While):
            continue
        for sub in ast.walk(node):
            if (isinstance(sub, ast.AugAssign)
                    and isinstance(sub.target, ast.Name)
                    and sub.target.id == name
                    and isinstance(sub.op, (ast.Mult, ast.LShift))):
                return True
    return False


def _dim_class(expr, assigns, fn, depth: int = 0):
    """'bucket' | 'raw' | None for a batch-dim expression: bucket-
    derived dims come from the MIN_BUCKET family / pow2 round-ups /
    shifts; raw dims come straight from len()/.count/.shape[0]/sum().
    Anything unprovable stays None (precision over recall)."""
    if depth > 6:
        return None
    if isinstance(expr, ast.Constant):
        return "bucket" if isinstance(expr.value, int) else None
    if isinstance(expr, ast.Name):
        if _doubled_in_while(fn, expr.id):
            return "bucket"
        rhs = assigns.get(expr.id)
        if rhs is not None and rhs is not expr:
            return _dim_class(rhs, assigns, fn, depth + 1)
        return None
    if isinstance(expr, ast.Attribute):
        if any(t in expr.attr for t in _BUCKET_TEXT):
            return "bucket"
        if expr.attr == "count":
            return "raw"
        return None
    if isinstance(expr, ast.Subscript):
        v = expr.value
        if isinstance(v, ast.Attribute) and v.attr == "shape":
            return "raw"
        return None
    if isinstance(expr, ast.Call):
        name = call_func_name(expr)
        if any(t in name for t in _BUCKET_TEXT):
            return "bucket"
        if name in ("len", "sum"):
            return "raw"
        if name in ("int", "max", "min"):
            for a in expr.args:
                got = _dim_class(a, assigns, fn, depth + 1)
                if got is not None:
                    return got
        return None
    if isinstance(expr, ast.BinOp):
        if isinstance(expr.op, ast.LShift):
            return "bucket"
        left = _dim_class(expr.left, assigns, fn, depth + 1)
        right = _dim_class(expr.right, assigns, fn, depth + 1)
        if "raw" in (left, right):
            return "raw"
        if "bucket" in (left, right):
            return "bucket"
        return None
    return None


def _r16_fn(sf, fn, qual):
    from .core import local_assignments

    assigns = local_assignments(fn)
    # Names bound to jit-wrapped callables: fn = jax.jit(f)
    jit_names = {
        name for name, rhs in assigns.items()
        if isinstance(rhs, ast.Call)
        and call_func_name(rhs) in _JIT_WRAPPERS
    }
    # Allocations by local name: data = np.zeros((X, W), ...)
    allocs: dict[str, ast.Call] = {}
    for name, rhs in assigns.items():
        if (isinstance(rhs, ast.Call)
                and call_func_name(rhs) in _ALLOC_NAMES
                and rhs.args
                and isinstance(rhs.args[0], ast.Tuple)
                and rhs.args[0].elts):
            allocs[name] = rhs

    def dispatch_call(node: ast.Call) -> bool:
        name = call_func_name(node)
        if name in _DISPATCH_NAMES:
            return True
        if isinstance(node.func, ast.Name) and node.func.id in jit_names:
            return True
        # jit(f)(...) inline
        if isinstance(node.func, ast.Call) and call_func_name(
                node.func) in _JIT_WRAPPERS:
            return True
        return False

    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not fn:
                continue
        if not isinstance(node, ast.Call) or not dispatch_call(node):
            continue
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            alloc = None
            if isinstance(a, ast.Name) and a.id in allocs:
                alloc = allocs[a.id]
            elif (isinstance(a, ast.Call)
                    and call_func_name(a) in _ALLOC_NAMES
                    and a.args and isinstance(a.args[0], ast.Tuple)
                    and a.args[0].elts):
                alloc = a
            if alloc is None:
                continue
            dim0 = alloc.args[0].elts[0]
            if _dim_class(dim0, assigns, fn) == "raw":
                yield Finding(
                    "R16", sf.path, alloc.lineno, alloc.col_offset,
                    f"unbucketed batch axis ({unparse(dim0)}) feeds "
                    f"the jit dispatch {call_func_name(node)}(): "
                    f"every distinct batch size keys a NEW compiled "
                    f"executable — a silent re-trace per size on the "
                    f"hot path, outside the declared shape-closure "
                    f"universe; round the axis up to the power-of-two "
                    f"bucket (MIN_BUCKET floor, pack_buckets widths)",
                    symbol=qual,
                )


def check_r16(files):
    from .core import walk_functions

    emitted: set = set()
    for path, sf in sorted(files.items()):
        for fn, qual, _cls in walk_functions(sf.tree):
            for f in _r16_fn(sf, fn, qual):
                key = (f.path, f.line, f.col)
                if key not in emitted:
                    emitted.add(key)
                    yield f
