"""R3 (socket hygiene) and R6 (thread hygiene).

R3 — the PR 2 zombie-service bug class, mechanized.  A bare
``sock.close()`` while ANOTHER thread is blocked in ``accept()`` /
``recv()`` on the same fd does not tear the kernel object down: the
close is deferred until that call returns — which it never does,
because only the teardown would have woken it.  Listeners keep
accepting into a dead service; readers wedge to process exit.  The fix
is always ``shutdown(SHUT_RDWR)`` *then* ``close()`` (see
``utils/sockutil.shutdown_close``).  The rule flags ``X.close()`` on a
socket-typed binding with no dominating ``X.shutdown(...)`` — a
shutdown (or a teardown-helper call taking X) lexically earlier in the
same function.

Socket typing is inferred, not guessed from bare names: a binding is
socket-typed when it is assigned from ``socket.socket(...)`` /
``socket.create_connection(...)`` / an ``accept()`` unpack, or is a
parameter annotated ``socket.socket`` — and attribute names assigned
from any of those anywhere in the tree are socket-typed attributes.

R6 — ``threading.Thread(...)`` without ``daemon=`` and without a local
``join()`` outlives its spawner silently; the conftest leak guard then
fails the whole module instead of the offending site.  Pass
``daemon=True`` (and a ``name=``) or join the thread where it is
spawned.
"""

from __future__ import annotations

import ast

from .core import Finding, call_func_name, unparse, walk_functions

_SOCK_CTORS = {"socket", "create_connection", "socketpair", "fromfd"}
# Helper callables that perform shutdown-then-close on their argument.
_TEARDOWN_HELPERS = ("teardown", "shutdown_close", "reset_conn")


def _is_socket_ctor(expr: ast.AST) -> bool:
    return (isinstance(expr, ast.Call)
            and call_func_name(expr) in _SOCK_CTORS)


def _socket_annotated(arg: ast.arg) -> bool:
    ann = arg.annotation
    return ann is not None and "socket" in unparse(ann)


def _socket_attr_names(files) -> set[str]:
    """Attribute names bound to sockets anywhere in the tree: direct
    constructor assigns, accept() unpacks, or assignment from a
    socket-annotated parameter."""
    out: set[str] = set()
    for sf in files.values():
        for fn, _qual, _cls in walk_functions(sf.tree):
            ann_params = {
                a.arg for a in list(fn.args.args)
                + list(fn.args.kwonlyargs) if _socket_annotated(a)
            }
            for node in ast.walk(fn):
                if isinstance(node, ast.AnnAssign):
                    # ``self._socks: dict[str, socket.socket]`` —
                    # socket-typed containers count: their elements
                    # are sockets when iterated.
                    if (isinstance(node.target, ast.Attribute)
                            and node.annotation is not None
                            and "socket" in unparse(node.annotation)):
                        out.add(node.target.attr)
                    continue
                if not isinstance(node, ast.Assign):
                    continue
                value = node.value
                sockety = _is_socket_ctor(value) or (
                    isinstance(value, ast.Name) and value.id in ann_params
                )
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and sockety:
                        out.add(t.attr)
                    if (isinstance(t, ast.Tuple)
                            and isinstance(value, ast.Call)
                            and call_func_name(value) == "accept"
                            and t.elts
                            and isinstance(t.elts[0], ast.Attribute)):
                        out.add(t.elts[0].attr)
    return out


def _local_socket_names(fn, sock_attrs: set[str]) -> set[str]:
    """Locals in ``fn`` that are socket-typed."""
    out = {
        a.arg for a in list(fn.args.args) + list(fn.args.kwonlyargs)
        if _socket_annotated(a)
    }

    def sockety_expr(expr) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in out
        if isinstance(expr, ast.Attribute):
            return expr.attr in sock_attrs
        return False

    # Iterate to a fixed point: for-loop targets and aliases can chain
    # (``for a, b in conns: ... for s in (a, b): s.close()``).
    changed = True
    while changed:
        changed = False

        def add(name: str) -> None:
            nonlocal changed
            if name not in out:
                out.add(name)
                changed = True

        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                value = node.value
                for t in node.targets:
                    if isinstance(t, ast.Name) and (
                        _is_socket_ctor(value) or sockety_expr(value)
                    ):
                        add(t.id)
                    if (isinstance(t, ast.Tuple)
                            and isinstance(value, ast.Call)
                            and call_func_name(value) == "accept"
                            and t.elts
                            and isinstance(t.elts[0], ast.Name)):
                        add(t.elts[0].id)
            elif isinstance(node, ast.For):
                it = node.iter
                elem_sockety = False
                if isinstance(it, (ast.Tuple, ast.List)):
                    elem_sockety = any(sockety_expr(e) for e in it.elts)
                elif isinstance(it, ast.Call) and call_func_name(
                    it
                ) == "values" and isinstance(it.func, ast.Attribute):
                    elem_sockety = sockety_expr(it.func.value)
                elif sockety_expr(it):
                    # Iterating a socket-typed container attribute
                    # (``for s in self._socks`` / a conns list).
                    elem_sockety = True
                if not elem_sockety:
                    continue
                if isinstance(node.target, ast.Name):
                    add(node.target.id)
                elif isinstance(node.target, ast.Tuple):
                    for e in node.target.elts:
                        if isinstance(e, ast.Name):
                            add(e.id)
    return out


def check_r3(files):
    sock_attrs = _socket_attr_names(files)
    for sf in files.values():
        for fn, qual, _cls in walk_functions(sf.tree):
            sock_locals = _local_socket_names(fn, sock_attrs)

            def is_socket_expr(expr) -> bool:
                if isinstance(expr, ast.Name):
                    return expr.id in sock_locals
                if isinstance(expr, ast.Attribute):
                    return expr.attr in sock_attrs
                return False

            # Lexically-earlier shutdowns / teardown-helper calls, by
            # receiver source.
            shutdown_lines: dict[str, int] = {}
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "shutdown"):
                    src = unparse(node.func.value)
                    prev = shutdown_lines.get(src)
                    if prev is None or node.lineno < prev:
                        shutdown_lines[src] = node.lineno
                fname = call_func_name(node)
                if any(h in fname for h in _TEARDOWN_HELPERS):
                    for a in node.args:
                        src = unparse(a)
                        prev = shutdown_lines.get(src)
                        if prev is None or node.lineno < prev:
                            shutdown_lines[src] = node.lineno

            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "close"
                        and not node.args):
                    continue
                recv = node.func.value
                if not is_socket_expr(recv):
                    continue
                src = unparse(recv)
                dom = shutdown_lines.get(src)
                if dom is not None and dom <= node.lineno:
                    continue
                yield Finding(
                    "R3", sf.path, node.lineno, node.col_offset,
                    f"bare {src}.close() with no dominating "
                    f"shutdown(): a thread blocked in accept()/recv() "
                    f"on this socket defers the teardown forever "
                    f"(zombie listener / wedged reader) — use "
                    f"utils.sockutil.shutdown_close",
                    symbol=qual,
                )


# --- R6 -------------------------------------------------------------------

def check_r6(files):
    for sf in files.values():
        for fn, qual, _cls in walk_functions(sf.tree):
            # Locals with a later ``.daemon = True`` or ``.join(...)``.
            daemonized: set[str] = set()
            joined: set[str] = set()
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Attribute)
                        and node.targets[0].attr == "daemon"
                        and isinstance(node.targets[0].value, ast.Name)):
                    daemonized.add(node.targets[0].value.id)
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "join"
                        and isinstance(node.func.value, ast.Name)):
                    joined.add(node.func.value.id)

            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and call_func_name(node) == "Thread"):
                    continue
                if any(kw.arg == "daemon" for kw in node.keywords):
                    continue
                # ``t = Thread(...)`` then ``t.daemon = True`` or a
                # local join both keep the leak guard quiet.
                assigned = _assigned_name(fn, node)
                if assigned and assigned in (daemonized | joined):
                    continue
                yield Finding(
                    "R6", sf.path, node.lineno, node.col_offset,
                    "Thread(...) without daemon= and without a local "
                    "join: survivors hang interpreter exit and trip "
                    "the conftest thread-leak guard module-wide — "
                    "pass daemon=True (and name=) or join locally",
                    symbol=qual,
                )


def _assigned_name(fn, call: ast.Call) -> str | None:
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign)
                and node.value is call
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            return node.targets[0].id
    return None
