"""R4 — JIT purity.

Functions reached from ``jax.jit`` / ``jax.vmap`` / ``jax.lax.scan``
call sites execute as traced computations: they run ONCE at trace time
and never again, so any side effect — mutating ``self``, taking a
lock, doing I/O, reading the wall clock — silently bakes the
trace-time value into the compiled executable.  PR 2's fault-injection
caveat is the operational proof: Python-level wrappers only fire on
eager calls; the jitted vec path never re-enters Python.  A lock taken
inside a jitted function is worse than useless (it guards one trace,
then lies), and wall-clock reads make verdicts non-bit-identical
across replicas — breaking the paper's determinism north star.

Reachability is WHOLE-PROGRAM: decorated functions (``@jax.jit``,
``@partial(jax.jit, ...)``), functions passed to jit/vmap/pmap or
``lax.scan``/``while_loop``/``fori_loop``/``cond``/``switch`` call
sites, plus everything they call — by simple name or ``self.method``
within the module (the PR 3 approximation), and through the
interprocedural engine's import-resolved call graph across modules
(``service.py`` jit sites reach ``models/base.py`` helpers; a clock
read hidden in a helper two modules away is still a determinism
break).  Findings land in the impure function's own file.
"""

from __future__ import annotations

import ast
import re

from .callgraph import get_graph
from .core import (
    Finding,
    call_func_name,
    is_lock_like_expr,
    local_assignments,
    unparse,
    walk_functions,
)

_JIT_WRAPPERS = {"jit", "vmap", "pmap"}
_LAX_COMBINATORS = {
    "scan", "while_loop", "fori_loop", "cond", "switch",
    "associative_scan",
}
_IO_CALLS = {
    "open", "print", "recv", "recv_into", "recvfrom", "accept",
    "connect", "sendall", "send_msg", "unlink", "makedirs", "remove",
}
_CLOCK_ATTRS = {
    "time", "monotonic", "perf_counter", "time_ns",
    "perf_counter_ns", "monotonic_ns", "now",
}
_CLOCK_MODULES = {"time", "datetime", "datetime.datetime"}


def _decorated_jit(fn) -> bool:
    return any("jit" in unparse(d) or "vmap" in unparse(d)
               for d in fn.decorator_list)


def _module_functions(tree):
    """name -> [function nodes]; methods are also indexed by bare name
    so ``self.step`` resolves (approximately) across the module."""
    table: dict[str, list] = {}
    for fn, _qual, _cls in walk_functions(tree):
        table.setdefault(fn.name, []).append(fn)
    return table


def _jit_roots(tree, table):
    roots = []
    lambdas = []
    for fn, _qual, _cls in walk_functions(tree):
        if _decorated_jit(fn):
            roots.append(fn)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_func_name(node)
        fargs = []
        if name in _JIT_WRAPPERS and node.args:
            fargs = [node.args[0]]
        elif name in _LAX_COMBINATORS and "lax" in unparse(node.func):
            fargs = list(node.args)
        for a in fargs:
            if isinstance(a, ast.Lambda):
                lambdas.append(a)
            else:
                tname = (a.attr if isinstance(a, ast.Attribute)
                         else a.id if isinstance(a, ast.Name) else "")
                roots.extend(table.get(tname, ()))
    return roots, lambdas


def _called_names(fn):
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                out.add(f.id)
            elif (isinstance(f, ast.Attribute)
                  and isinstance(f.value, ast.Name)
                  and f.value.id == "self"):
                out.add(f.attr)
    return out


def _impurities(sf, fn, qual):
    aliases = local_assignments(fn) if not isinstance(fn, ast.Lambda) \
        else {}
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    yield Finding(
                        "R4", sf.path, node.lineno, node.col_offset,
                        f"jit-reached function mutates self."
                        f"{t.attr}: traced once, the mutation happens "
                        f"at trace time only and the compiled "
                        f"executable silently reuses the stale value",
                        symbol=qual,
                    )
        elif isinstance(node, ast.With):
            for item in node.items:
                if is_lock_like_expr(item.context_expr, aliases):
                    yield Finding(
                        "R4", sf.path, node.lineno, node.col_offset,
                        "jit-reached function takes a lock: it guards "
                        "one trace and then lies — the compiled "
                        "executable never re-enters Python",
                        symbol=qual,
                    )
        elif isinstance(node, ast.Call):
            name = call_func_name(node)
            if (name == "acquire"
                    and isinstance(node.func, ast.Attribute)
                    and is_lock_like_expr(node.func.value, aliases)):
                yield Finding(
                    "R4", sf.path, node.lineno, node.col_offset,
                    "jit-reached function takes a lock: it guards one "
                    "trace and then lies — the compiled executable "
                    "never re-enters Python",
                    symbol=qual,
                )
            elif name in _IO_CALLS:
                yield Finding(
                    "R4", sf.path, node.lineno, node.col_offset,
                    f"jit-reached function performs I/O ({name}): "
                    f"runs at trace time only, never per verdict",
                    symbol=qual,
                )
            elif (name in _CLOCK_ATTRS
                  and isinstance(node.func, ast.Attribute)
                  and unparse(node.func.value) in _CLOCK_MODULES):
                yield Finding(
                    "R4", sf.path, node.lineno, node.col_offset,
                    f"jit-reached function reads the wall clock "
                    f"({unparse(node.func)}): the trace-time value is "
                    f"baked into the executable, and verdicts stop "
                    f"being bit-identical across replicas",
                    symbol=qual,
                )


def jit_reached(files):
    """Whole-program jit reachability, memoized on the graph: the
    FuncInfos reachable from any jit/vmap/scan site plus the lambdas
    passed to them — shared by R4 (purity) and the device-contract
    rules R8/R9 (recompile hazards / host transfers), which police the
    same traced scope for different sins."""
    graph = get_graph(files)
    memo = graph.rule_memo.get("jit_reached")
    if memo is not None:
        return memo

    # Jit roots + lambdas per module (lexical detection is unchanged).
    seen: set[int] = set()
    frontier: list = []
    all_lambdas: list[tuple] = []
    tables: dict[str, dict] = {}
    for path, sf in files.items():
        table = _module_functions(sf.tree)
        tables[path] = table
        roots, lambdas = _jit_roots(sf.tree, table)
        frontier.extend(roots)
        all_lambdas.extend((sf, lam) for lam in lambdas)

    # Whole-program reachability: same-module bare-name/self tables
    # (the PR 3 approximation) PLUS import-resolved cross-module
    # targets from the interprocedural engine.
    reached: list = []
    while frontier:
        fn = frontier.pop()
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        fi = graph.by_node.get(id(fn))
        if fi is None:
            continue
        reached.append(fi)
        table = tables.get(fi.path, {})
        for cname in _called_names(fn):
            frontier.extend(table.get(cname, ()))
        for _call, _line, _col, _held, keys in fi.calls:
            for key in keys or ():
                callee = graph.funcs.get(key)
                if callee is not None and callee.path != fi.path:
                    frontier.append(callee.node)

    memo = (reached, all_lambdas)
    graph.rule_memo["jit_reached"] = memo
    return memo


def check_r4(files):
    reached, all_lambdas = jit_reached(files)
    emitted: set[tuple[str, int, int, str]] = set()
    for fi in reached:
        sf = files.get(fi.path)
        if sf is None:
            continue
        for f in _impurities(sf, fi.node, fi.qual):
            key = (f.path, f.line, f.col, f.message[:40])
            if key not in emitted:
                emitted.add(key)
                yield f
    for sf, lam in all_lambdas:
        for f in _impurities(sf, lam, "<lambda>"):
            key = (f.path, f.line, f.col, f.message[:40])
            if key not in emitted:
                emitted.add(key)
                yield f
