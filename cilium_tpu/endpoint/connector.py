"""Endpoint connector: veth-pair provisioning records.

reference: pkg/endpoint/connector/veth.go SetupVeth — creates the
host-side veth ``lxc<sha>`` and the container peer, derives MACs,
applies the MTU, and hands the peer to the orchestrator to move into
the container netns and rename to eth0.  This build has no kernel to
plumb, so provisioning produces DETERMINISTIC RECORDS of what the
kernel-side connector would have created — the CNI/docker plugins
store them per container and the tests (and bugtool) can audit the
exact interface state a real node would carry.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field


@dataclass
class VethRecord:
    """What SetupVeth would have created for one endpoint."""

    container_id: str
    host_ifname: str  # lxc<sha> on the host side
    tmp_ifname: str  # temporary peer name before the netns move
    container_ifname: str  # name inside the netns (eth0)
    netns: str  # the sandbox netns path
    mtu: int
    host_mac: str
    container_mac: str
    moved_to_netns: bool = False
    routes: list[str] = field(default_factory=list)


def _mac(seed: bytes) -> str:
    """Locally-administered unicast MAC from a hash (reference:
    connector derives the MAC from the endpoint)."""
    h = hashlib.sha256(seed).digest()
    octets = [h[0] & 0b11111110 | 0b00000010, *h[1:6]]
    return ":".join(f"{o:02x}" for o in octets)


def setup_veth(container_id: str, netns: str, mtu: int = 1500) -> VethRecord:
    """reference: connector/veth.go SetupVeth — name derivation is the
    reference's: ``lxc`` + first 10 hex chars of sha256(containerID)."""
    sha = hashlib.sha256(container_id.encode()).hexdigest()
    rec = VethRecord(
        container_id=container_id,
        host_ifname=f"lxc{sha[:10]}",
        tmp_ifname=f"tmp{sha[:5]}",
        container_ifname="eth0",
        netns=netns,
        mtu=mtu,
        host_mac=_mac(b"host:" + container_id.encode()),
        container_mac=_mac(b"peer:" + container_id.encode()),
    )
    return rec


def move_to_netns(rec: VethRecord) -> None:
    """The orchestrator step: peer moves into the sandbox netns and is
    renamed to eth0 (reference: cilium-cni.go netns.Do + ip link set)."""
    rec.moved_to_netns = True
