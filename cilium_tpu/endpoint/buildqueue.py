"""Bounded build queue with per-endpoint serialization.

reference: daemon/daemon.go:212-272 (StartEndpointBuilders: bounded channel
+ N builder workers) and pkg/buildqueue (per-UUID build serialization:
concurrent enqueues of the same endpoint fold, and one endpoint never
builds on two workers at once).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable

from ..utils import defaults
from ..utils.logging import get_logger

log = get_logger("buildqueue")


class BuildQueue:
    def __init__(
        self,
        build_func: Callable[[object], None],
        workers: int = defaults.MIN_ENDPOINT_BUILDERS,
        maxsize: int = 1024,
    ) -> None:
        self.build_func = build_func
        self._queue: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._pending: set = set()  # keys queued but not started
        self._building: set = set()  # keys currently building
        self._requeue_items: dict = {}  # key -> item enqueued while building
        self._mutex = threading.Lock()
        self._stop = threading.Event()
        self._idle = threading.Condition(self._mutex)
        self._threads = [
            threading.Thread(target=self._worker, name=f"builder-{i}",
                             daemon=True)
            for i in range(max(1, workers))
        ]
        for t in self._threads:
            t.start()

    def enqueue(self, item, key=None) -> bool:
        """Queue a build; folds duplicates of the same key
        (reference: buildqueue Enqueue serialization)."""
        key = key if key is not None else item
        with self._mutex:
            if key in self._pending:
                return False  # already queued: folded
            if key in self._building:
                # Rebuild after the current one finishes.
                self._requeue_items[key] = item
                return False
            self._pending.add(key)
        self._queue.put((key, item))
        return True

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                key, item = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            with self._mutex:
                self._pending.discard(key)
                self._building.add(key)
            try:
                self.build_func(item)
            except Exception as e:  # noqa: BLE001 — a failing build must
                log.with_fields(key=str(key), error=str(e)).error(
                    "build failed"
                )  # not kill the worker
            finally:
                with self._mutex:
                    self._building.discard(key)
                    requeued = self._requeue_items.pop(key, None)
                    if requeued is not None:
                        # Mark pending before releasing the mutex so
                        # wait_idle can't observe a false idle between
                        # the pop and the re-enqueue.
                        self._pending.add(key)
                    self._idle.notify_all()
                if requeued is not None:
                    self._queue.put((key, requeued))
                self._queue.task_done()

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Block until nothing is pending or building (test helper)."""
        import time

        deadline = time.monotonic() + timeout
        with self._idle:
            while (self._pending or self._building or self._requeue_items
                   or not self._queue.empty()):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(timeout=min(remaining, 0.1))
        return True

    def stop(self) -> None:
        self._stop.set()
