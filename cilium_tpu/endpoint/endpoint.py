"""The Endpoint object and its regeneration pipeline.

reference: pkg/endpoint/{endpoint,policy,bpf,restore}.go.  See package
docstring for the mapping onto the array-native datapath.
"""

from __future__ import annotations

import enum
import json
import os
import threading
from dataclasses import dataclass, field
from typing import Optional, Protocol

from ..identity import (
    Identity,
    RESERVED_HOST,
    RESERVED_WORLD,
)
from ..labels import Labels
from ..maps.policymap import (
    DIR_EGRESS,
    DIR_INGRESS,
    DevicePolicyMap,
    PolicyKey,
    PolicyMap,
)
from ..policy import (
    ALWAYS_ENFORCE,
    Decision,
    L4Filter,
    L4Policy,
    NEVER_ENFORCE,
    Repository,
    SearchContext,
    get_policy_enabled,
    proxy_id as make_proxy_id,
)
from ..policy.l3 import CIDRPolicy
from ..utils.logging import get_logger
from ..utils.metrics import (
    EndpointRegenerationCount,
    EndpointRegenerationTime,
)
from ..utils import option
from ..utils.option import OptionMap
from ..utils.spanstat import SpanStats

log = get_logger("endpoint")

# Keys always consulted for localhost/world legacy allows
# (reference: pkg/endpoint/policy.go localHostKey/worldKey).
LOCALHOST_KEY = PolicyKey(RESERVED_HOST, 0, 0, DIR_INGRESS)
WORLD_KEY = PolicyKey(RESERVED_WORLD, 0, 0, DIR_INGRESS)


class EndpointState(str, enum.Enum):
    """reference: pkg/endpoint/endpoint.go state strings."""

    CREATING = "creating"
    WAITING_FOR_IDENTITY = "waiting-for-identity"
    READY = "ready"
    WAITING_TO_REGENERATE = "waiting-to-regenerate"
    REGENERATING = "regenerating"
    RESTORING = "restoring"
    DISCONNECTING = "disconnecting"
    DISCONNECTED = "disconnected"
    NOT_READY = "not-ready"


# Allowed transitions (reference: endpoint.go SetStateLocked switch).
_TRANSITIONS: dict[EndpointState, set[EndpointState]] = {
    EndpointState.CREATING: {
        EndpointState.WAITING_FOR_IDENTITY,
        EndpointState.DISCONNECTING,
    },
    EndpointState.WAITING_FOR_IDENTITY: {
        EndpointState.READY,
        EndpointState.WAITING_TO_REGENERATE,
        EndpointState.DISCONNECTING,
    },
    EndpointState.READY: {
        EndpointState.WAITING_TO_REGENERATE,
        EndpointState.WAITING_FOR_IDENTITY,
        EndpointState.DISCONNECTING,
        EndpointState.NOT_READY,
    },
    EndpointState.WAITING_TO_REGENERATE: {
        EndpointState.REGENERATING,
        EndpointState.WAITING_FOR_IDENTITY,
        EndpointState.DISCONNECTING,
    },
    EndpointState.REGENERATING: {
        EndpointState.READY,
        EndpointState.NOT_READY,
        EndpointState.WAITING_TO_REGENERATE,
        EndpointState.WAITING_FOR_IDENTITY,
        EndpointState.DISCONNECTING,
    },
    EndpointState.RESTORING: {
        EndpointState.WAITING_TO_REGENERATE,
        EndpointState.WAITING_FOR_IDENTITY,
        EndpointState.DISCONNECTING,
    },
    EndpointState.NOT_READY: {
        EndpointState.WAITING_TO_REGENERATE,
        EndpointState.WAITING_FOR_IDENTITY,
        EndpointState.DISCONNECTING,
    },
    EndpointState.DISCONNECTING: {EndpointState.DISCONNECTED},
    EndpointState.DISCONNECTED: set(),
}


@dataclass
class PolicyMapStateEntry:
    """reference: pkg/endpoint/policy.go PolicyMapStateEntry."""

    proxy_port: int = 0


class EndpointOwner(Protocol):
    """What an endpoint needs from its daemon
    (reference: pkg/endpoint Owner interface)."""

    def get_policy_repository(self) -> Repository: ...

    def get_identity_cache(self) -> dict[int, "Labels"]: ...

    def get_proxy_manager(self): ...

    def update_network_policy(self, ep: "Endpoint") -> bool:
        """Push the endpoint's resolved policy to the proxy layer and
        block until it is acknowledged; False fails the regeneration
        (reference: pkg/endpoint/policy.go:402 updateNetworkPolicy →
        envoy server push, ACK-gated via completion.WaitGroup at
        pkg/endpoint/bpf.go:555)."""
        ...


class Endpoint:
    """reference: pkg/endpoint/endpoint.go Endpoint."""

    def __init__(
        self,
        endpoint_id: int,
        ipv4: str = "",
        ipv6: str = "",
        container_name: str = "",
        labels: Optional[Labels] = None,
    ) -> None:
        self.id = endpoint_id
        self.ipv4 = ipv4
        self.ipv6 = ipv6
        self.container_name = container_name
        self.labels = labels or Labels()
        self.security_identity: Optional[Identity] = None
        self.state = EndpointState.CREATING
        self.mutex = threading.RLock()

        # Policy state
        self.policy_map = PolicyMap(endpoint_id)
        self.device_policy_map: Optional[DevicePolicyMap] = None
        self.desired_l4_policy: Optional[L4Policy] = None
        self.l3_policy: Optional[CIDRPolicy] = None
        self.desired_map_state: dict[PolicyKey, PolicyMapStateEntry] = {}
        self.realized_map_state: dict[PolicyKey, PolicyMapStateEntry] = {}
        self.realized_redirects: dict[str, int] = {}  # proxyID -> port
        self.policy_revision = 0
        self.next_policy_revision = 0
        self.force_policy_compute = False
        self.ingress_policy_enabled = False
        self.egress_policy_enabled = False
        self._stale_redirects: list[str] = []
        self._prev_identity_cache: Optional[dict[int, object]] = None

        # Per-endpoint option overlay (reference: pkg/option/endpoint.go).
        self.opts = OptionMap(parent=option.config.opts)
        self.stats = SpanStats()

    # -- state machine -----------------------------------------------------

    def set_state(self, new: EndpointState, reason: str = "") -> bool:
        """Validated transition; False if not allowed
        (reference: endpoint.go SetStateLocked)."""
        with self.mutex:
            if new == self.state:
                return False
            if new not in _TRANSITIONS.get(self.state, set()):
                log.with_fields(
                    endpointID=self.id, frm=self.state.value, to=new.value
                ).debug("invalid state transition")
                return False
            self.state = new
        if reason:
            log.with_fields(endpointID=self.id, state=new.value,
                            reason=reason).debug("state transition")
        return True

    def is_disconnecting(self) -> bool:
        return self.state in (
            EndpointState.DISCONNECTING, EndpointState.DISCONNECTED
        )

    # -- identity ----------------------------------------------------------

    def set_identity(self, identity: Identity) -> None:
        with self.mutex:
            self.security_identity = identity

    def security_label_array(self):
        return self.security_identity.labels.to_array()

    # -- policy computation (reference: policy.go:482 regeneratePolicy) ----

    def compute_policy_enforcement(self, repo: Repository) -> tuple[bool, bool]:
        """Whether ingress/egress policy applies (reference:
        pkg/endpoint/policy.go ComputePolicyEnforcement): default mode
        enforces a direction iff some rule selects the endpoint there."""
        mode = get_policy_enabled()
        if mode == NEVER_ENFORCE:
            return False, False
        if mode == ALWAYS_ENFORCE:
            return True, True
        return repo.get_rules_matching(self.security_label_array())

    def _convert_l4_filter_to_keys(
        self, f: L4Filter, direction: int, identity_cache: dict
    ) -> list[PolicyKey]:
        """reference: policy.go:111 convertL4FilterToPolicyMapKeys."""
        keys = []
        for sel in f.endpoints:
            for numeric_id, lbls in identity_cache.items():
                if sel.is_wildcard() or sel.matches(lbls.to_array()):
                    keys.append(
                        PolicyKey(numeric_id, f.port, f.u8_proto, direction)
                    )
        return keys

    def _lookup_redirect_port(self, f: L4Filter) -> int:
        """reference: policy.go:134 lookupRedirectPort."""
        if not f.is_redirect():
            return 0
        return self.realized_redirects.get(self.proxy_id(f), 0)

    def proxy_id(self, f: L4Filter) -> str:
        return make_proxy_id(self.id, f.ingress, f.protocol, f.port)

    def _compute_desired_l4_entries(self, desired, identity_cache) -> None:
        """reference: policy.go:144 computeDesiredL4PolicyMapEntries."""
        if self.desired_l4_policy is None:
            return
        for l4map, direction in (
            (self.desired_l4_policy.ingress, DIR_INGRESS),
            (self.desired_l4_policy.egress, DIR_EGRESS),
        ):
            for f in l4map.values():
                proxy_port = 0
                if f.is_redirect():
                    proxy_port = self._lookup_redirect_port(f)
                    if proxy_port == 0:
                        # New redirect without an allocated port yet —
                        # added once the port exists (policy.go:160-166).
                        continue
                for key in self._convert_l4_filter_to_keys(
                    f, direction, identity_cache
                ):
                    desired[key] = PolicyMapStateEntry(proxy_port=proxy_port)

    def _determine_allow_localhost(self, desired) -> None:
        """reference: policy.go:262 determineAllowLocalhost."""
        if option.config.always_allow_localhost() or (
            self.desired_l4_policy is not None
            and self.desired_l4_policy.has_redirect()
        ):
            desired[LOCALHOST_KEY] = PolicyMapStateEntry()

    def _determine_allow_world(self, desired) -> None:
        """reference: policy.go:281 determineAllowFromWorld (legacy)."""
        if option.config.host_allows_world and LOCALHOST_KEY in desired:
            desired[WORLD_KEY] = PolicyMapStateEntry()

    def _compute_desired_l3_entries(self, repo, desired, identity_cache) -> None:
        """Per-identity L3 verdict walk (reference: policy.go:297)."""
        my_labels = self.security_label_array()
        for numeric_id, lbls in identity_cache.items():
            remote = lbls.to_array()
            if self.ingress_policy_enabled:
                ctx = SearchContext(from_labels=remote, to_labels=my_labels)
                allowed = (
                    repo.allows_ingress(ctx) == Decision.ALLOWED
                    if repo.num_rules()
                    else False
                )
            else:
                allowed = True
            if allowed:
                desired[PolicyKey(numeric_id, 0, 0, DIR_INGRESS)] = (
                    PolicyMapStateEntry()
                )
            if self.egress_policy_enabled:
                ctx = SearchContext(from_labels=my_labels, to_labels=remote)
                allowed = (
                    repo.allows_egress(ctx) == Decision.ALLOWED
                    if repo.num_rules()
                    else False
                )
            else:
                allowed = True
            if allowed:
                desired[PolicyKey(numeric_id, 0, 0, DIR_EGRESS)] = (
                    PolicyMapStateEntry()
                )

    def regenerate_policy(self, owner: EndpointOwner) -> bool:
        """Recompute desired policy; returns whether anything may have
        changed (reference: policy.go:482 regeneratePolicy)."""
        if self.security_identity is None:
            log.with_field("endpointID", self.id).warning(
                "endpoint lacks identity, skipping policy calculation"
            )
            return False

        identity_cache = owner.get_identity_cache()
        repo = owner.get_policy_repository()
        revision = repo.get_revision()

        # Skip if already computed for this revision with the same cache
        # (reference: policy.go:513-525).
        if (
            not self.force_policy_compute
            and self.next_policy_revision >= revision
            and self._prev_identity_cache == identity_cache
        ):
            return False
        self._prev_identity_cache = identity_cache

        self.ingress_policy_enabled, self.egress_policy_enabled = (
            self.compute_policy_enforcement(repo)
        )

        ingress_ctx = SearchContext(to_labels=self.security_label_array())
        egress_ctx = SearchContext(from_labels=self.security_label_array())

        new_l4 = L4Policy(revision=revision)
        if self.ingress_policy_enabled:
            new_l4.ingress = repo.resolve_l4_ingress_policy(ingress_ctx)
        if self.egress_policy_enabled:
            new_l4.egress = repo.resolve_l4_egress_policy(egress_ctx)
        self.desired_l4_policy = new_l4

        l3 = repo.resolve_cidr_policy(
            SearchContext(to_labels=self.security_label_array())
        )
        l3.validate()
        self.l3_policy = l3

        desired: dict[PolicyKey, PolicyMapStateEntry] = {}
        self._compute_desired_l4_entries(desired, identity_cache)
        self._determine_allow_localhost(desired)
        self._determine_allow_world(desired)
        self._compute_desired_l3_entries(repo, desired, identity_cache)
        self.desired_map_state = desired

        self.force_policy_compute = False
        self.next_policy_revision = revision
        return True

    # -- datapath sync (reference: bpf.go regenerateBPF/syncPolicyMap) -----

    def _add_new_redirects(self, owner: EndpointOwner, identity_cache) -> None:
        """Create redirects for redirect filters and patch their proxy
        ports into the desired state (reference: bpf.go:356
        addNewRedirects + addNewRedirectsFromMap)."""
        proxy = owner.get_proxy_manager()
        if proxy is None or self.desired_l4_policy is None:
            return
        active: set[str] = set()
        for l4map, direction in (
            (self.desired_l4_policy.ingress, DIR_INGRESS),
            (self.desired_l4_policy.egress, DIR_EGRESS),
        ):
            for f in l4map.values():
                if not f.is_redirect():
                    continue
                pid = self.proxy_id(f)
                redirect = proxy.create_or_update_redirect(f, pid, self.id)
                self.realized_redirects[pid] = redirect.proxy_port
                active.add(pid)
                for key in self._convert_l4_filter_to_keys(
                    f, direction, identity_cache
                ):
                    self.desired_map_state[key] = PolicyMapStateEntry(
                        proxy_port=redirect.proxy_port
                    )
        # Stale-redirect removal is DEFERRED to after the proxy-ACK
        # gate (reference: removeOldRedirects runs in the finalize
        # stage, bpf.go:446): tearing a redirect down before the ACK
        # would leave a reverted map pointing at a dead proxy port.
        self._stale_redirects = [
            pid for pid in self.realized_redirects if pid not in active
        ]

    def _remove_old_redirects(self, owner: EndpointOwner) -> None:
        """Finalize stage: drop redirects the new (ACKed) policy no
        longer references (reference: bpf.go removeOldRedirects)."""
        proxy = owner.get_proxy_manager()
        for pid in getattr(self, "_stale_redirects", ()):  # set by add
            if proxy is not None:
                proxy.remove_redirect(pid)
            self.realized_redirects.pop(pid, None)
        self._stale_redirects = []

    def sync_policy_map(self) -> tuple[int, int]:
        """Diff desired vs realized into the policy map; returns
        (added, deleted) (reference: bpf.go syncPolicyMap +
        pkg/maps/policymap Allow/DeleteKey)."""
        added = deleted = 0
        for key, entry in self.desired_map_state.items():
            realized = self.realized_map_state.get(key)
            if realized is None or realized.proxy_port != entry.proxy_port:
                self.policy_map.allow(
                    key.identity, key.dest_port, key.proto, key.direction,
                    proxy_port=entry.proxy_port,
                )
                added += 1
        for key in list(self.realized_map_state):
            if key not in self.desired_map_state:
                self.policy_map.delete(
                    key.identity, key.dest_port, key.proto, key.direction
                )
                deleted += 1
        self.realized_map_state = {
            k: PolicyMapStateEntry(v.proxy_port)
            for k, v in self.desired_map_state.items()
        }
        return added, deleted

    def regenerate(self, owner: EndpointOwner, reason: str = "") -> bool:
        """Full regeneration (reference: policy.go:812 Regenerate +
        :642 regenerate): policy recompute -> redirects -> map sync ->
        device export."""
        if self.security_identity is None:
            # No identity yet: policy cannot be computed; stay in the
            # identity wait (reference: regeneratePolicy identity gate).
            return False
        # READY/NOT_READY endpoints pass through WAITING_TO_REGENERATE
        # first (reference: the build queue sets waiting-to-regenerate on
        # enqueue, regenerating on pickup).
        if self.state not in (
            EndpointState.WAITING_TO_REGENERATE, EndpointState.REGENERATING
        ):
            self.set_state(EndpointState.WAITING_TO_REGENERATE, reason)
        if not self.set_state(EndpointState.REGENERATING, reason):
            # Disconnecting/disconnected endpoints must not regenerate:
            # doing so would recreate redirects torn down by the daemon.
            return False
        # Fresh spans per regeneration so the histogram observes this
        # run's duration, not the endpoint's lifetime accumulation.
        self.stats = SpanStats()
        stats = self.stats
        ok = False
        # Revert checkpoint (reference: pkg/revert stack built through
        # regenerateBPF, bpf.go:561-584): enough state to roll the
        # datapath back if the proxy layer never ACKs the new policy.
        prev_desired = dict(self.desired_map_state)
        prev_revision = self.policy_revision
        try:
            stats.span("policy").start()
            self.regenerate_policy(owner)
            stats.span("policy").end()

            identity_cache = owner.get_identity_cache()
            stats.span("proxy").start()
            self._add_new_redirects(owner, identity_cache)
            stats.span("proxy").end()

            stats.span("mapSync").start()
            self.sync_policy_map()
            stats.span("mapSync").end()

            # Proxy ACK gate: regeneration blocks until the verdict
            # service acknowledges the pushed policy; no ACK -> the
            # endpoint must NOT report ready with a datapath enforcing a
            # policy the L7 layer never received (reference:
            # pkg/endpoint/bpf.go:555 completion wait on the xDS ACK,
            # pkg/envoy/xds/ack.go:138).
            stats.span("proxyAck").start()
            acked = owner.update_network_policy(self)
            stats.span("proxyAck").end()
            if not acked:
                # Revert the map to its pre-regeneration state
                # (reference: revert stack unwind, bpf.go:561-584).
                # Old redirects were NOT torn down yet (deferred to the
                # finalize stage below), so the restored entries still
                # point at live proxy ports.
                self.desired_map_state = prev_desired
                self.sync_policy_map()
                if not option.config.dry_mode:
                    self.device_policy_map = self.policy_map.to_device()
                self.policy_revision = prev_revision
                # The retry must recompute policy from scratch — the
                # skip check in regenerate_policy would otherwise see
                # next_policy_revision already current and promote the
                # reverted OLD map as the NEW revision.
                self.force_policy_compute = True
                return False

            # Finalize: now that the proxy ACKed, tear down redirects
            # the new policy no longer references.
            self._remove_old_redirects(owner)

            # "Compile": pack the policy map into device arrays (the BPF
            # compile+attach analog, skipped in DryMode like the
            # reference's bpf.go:510).
            if not option.config.dry_mode:
                stats.span("deviceExport").start()
                self.device_policy_map = self.policy_map.to_device()
                stats.span("deviceExport").end()

            self.policy_revision = self.next_policy_revision
            ok = True
        finally:
            outcome = "success" if ok else "fail"
            EndpointRegenerationCount.inc(outcome)
            EndpointRegenerationTime.observe(
                sum(stats.report().values())
            )
            self.set_state(
                EndpointState.READY if ok else EndpointState.NOT_READY,
                "regeneration " + outcome,
            )
        return ok

    # -- serialization / restore (reference: restore.go) -------------------

    def to_serialized(self) -> dict:
        return {
            "id": self.id,
            "ipv4": self.ipv4,
            "ipv6": self.ipv6,
            "container_name": self.container_name,
            "labels": self.labels.get_model(),
            "identity": (
                self.security_identity.id if self.security_identity else 0
            ),
            "identity_labels": (
                self.security_identity.labels.get_model()
                if self.security_identity
                else []
            ),
            "policy_revision": self.policy_revision,
            "state": self.state.value,
            "options": self.opts.snapshot(),
        }

    def write_state(self, state_dir: str) -> str:
        """Persist to <state_dir>/<id>/ep_config.json (the header-file
        analog, reference: pkg/endpoint/bpf.go:88 writeHeaderfile)."""
        ep_dir = os.path.join(state_dir, str(self.id))
        os.makedirs(ep_dir, exist_ok=True)
        path = os.path.join(ep_dir, "ep_config.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_serialized(), f, indent=2)
        os.replace(tmp, path)
        return path

    @staticmethod
    def from_serialized(data: dict) -> "Endpoint":
        ep = Endpoint(
            endpoint_id=data["id"],
            ipv4=data.get("ipv4", ""),
            ipv6=data.get("ipv6", ""),
            container_name=data.get("container_name", ""),
            labels=Labels.from_model(data.get("labels", [])),
        )
        if data.get("identity"):
            ep.security_identity = Identity(
                id=data["identity"],
                labels=Labels.from_model(data.get("identity_labels", [])),
            )
        ep.policy_revision = data.get("policy_revision", 0)
        ep.state = EndpointState.RESTORING
        return ep

    @staticmethod
    def restore_from_dir(state_dir: str) -> list["Endpoint"]:
        """reference: restore.go + daemon restoreOldEndpoints."""
        out: list[Endpoint] = []
        if not os.path.isdir(state_dir):
            return out
        for name in sorted(os.listdir(state_dir)):
            path = os.path.join(state_dir, name, "ep_config.json")
            if not os.path.isfile(path):
                continue
            try:
                with open(path) as f:
                    out.append(Endpoint.from_serialized(json.load(f)))
            except (ValueError, KeyError) as e:
                log.with_fields(path=path, error=str(e)).warning(
                    "skipping corrupt endpoint state"
                )
        return out
