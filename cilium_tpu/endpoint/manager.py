"""Endpoint manager: ID/name/IP indexes over all local endpoints.

reference: pkg/endpointmanager — insert/remove with index maintenance,
lookups by ID, container name, IP; bulk policy-update triggering across
endpoints (endpointmanager.go TriggerPolicyUpdates).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from .endpoint import Endpoint


class EndpointManager:
    def __init__(self) -> None:
        self._by_id: dict[int, Endpoint] = {}
        self._by_container: dict[str, Endpoint] = {}
        self._by_ipv4: dict[str, Endpoint] = {}
        self.mutex = threading.RLock()

    def insert(self, ep: Endpoint) -> None:
        with self.mutex:
            self._by_id[ep.id] = ep
            if ep.container_name:
                self._by_container[ep.container_name] = ep
            if ep.ipv4:
                self._by_ipv4[ep.ipv4] = ep

    def remove(self, ep: Endpoint) -> bool:
        with self.mutex:
            found = self._by_id.pop(ep.id, None) is not None
            if ep.container_name:
                self._by_container.pop(ep.container_name, None)
            if ep.ipv4:
                self._by_ipv4.pop(ep.ipv4, None)
        return found

    def lookup(self, endpoint_id: int) -> Optional[Endpoint]:
        return self._by_id.get(endpoint_id)

    def lookup_container(self, name: str) -> Optional[Endpoint]:
        return self._by_container.get(name)

    def lookup_ipv4(self, ip: str) -> Optional[Endpoint]:
        return self._by_ipv4.get(ip)

    def get_endpoints(self) -> list[Endpoint]:
        with self.mutex:
            return sorted(self._by_id.values(), key=lambda e: e.id)

    def __len__(self) -> int:
        return len(self._by_id)

    def trigger_policy_updates(
        self, enqueue: Callable[[Endpoint], None]
    ) -> int:
        """Queue every endpoint for regeneration (reference:
        endpointmanager TriggerPolicyUpdates feeding the build queue)."""
        eps = self.get_endpoints()
        for ep in eps:
            enqueue(ep)
        return len(eps)
