"""Endpoint lifecycle: state machine, policy regeneration, restore.

reference: pkg/endpoint — the Endpoint object owns its identity, policy
state and datapath map; Regenerate (policy.go:812) recomputes policy from
the repository (regeneratePolicy policy.go:482), converts it into a
desired policy-map state keyed by {identity, port, proto, direction}
(policy.go:144-254), then syncs the per-endpoint policy map by diffing
desired vs realized (bpf.go syncPolicyMap) and installs L7 redirects.
Where the reference compiles and loads a BPF program per endpoint, this
build exports the policy map to device arrays for the batched verdict ops
— "compile" is a device-table pack, not a clang exec.
"""

from .endpoint import (
    Endpoint,
    EndpointOwner,
    EndpointState,
    PolicyMapStateEntry,
)
from .manager import EndpointManager
from .buildqueue import BuildQueue

__all__ = [
    "BuildQueue",
    "Endpoint",
    "EndpointManager",
    "EndpointOwner",
    "EndpointState",
    "PolicyMapStateEntry",
]
