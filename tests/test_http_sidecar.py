"""HTTP through the parser seam and the sidecar verdict service
(reference: envoy/cilium_l7policy.cc — here served by proxylib-style
parsing + the HTTP batch model instead of an Envoy HTTP filter)."""

import json
from dataclasses import asdict

import pytest

from cilium_tpu.proxylib import (
    NetworkPolicy,
    PortNetworkPolicy,
    PortNetworkPolicyRule,
)
from cilium_tpu.proxylib import instance as inst
from cilium_tpu.proxylib.parsers.http import HTTP_403, head_and_body_len
from cilium_tpu.proxylib.types import DROP, MORE, PASS, FilterResult
from cilium_tpu.sidecar.client import SidecarClient
from cilium_tpu.sidecar.service import VerdictService
from cilium_tpu.utils.option import DaemonConfig

from proxylib_harness import new_connection


def http_policy(name="http-pol"):
    return NetworkPolicy(
        name=name,
        policy=2,
        ingress_per_port_policies=[
            PortNetworkPolicy(
                port=80,
                rules=[
                    PortNetworkPolicyRule(
                        remote_policies=[1, 3],
                        http_rules=[
                            {"method": "GET", "path": "/public/.*"},
                            {"method": "POST", "path": "/api/v[0-9]+/submit"},
                        ],
                    )
                ],
            )
        ],
    )


def req(method="GET", path="/", headers=(), body=b""):
    head = f"{method} {path} HTTP/1.1\r\n".encode()
    for h in headers:
        head += h.encode() + b"\r\n"
    if body:
        head += f"Content-Length: {len(body)}\r\n".encode()
    return head + b"\r\n" + body


def test_framing():
    r = req("GET", "/x", body=b"hello")
    assert head_and_body_len(r) == (len(r) - 5, 5)
    assert head_and_body_len(r[:-1]) is None  # body short
    assert head_and_body_len(b"GET / HTTP/1.1\r\n") is None  # head open


# --- streaming parser (the oracle) ----------------------------------------

@pytest.fixture
def conn():
    inst.reset_module_registry()
    mod = inst.open_module([], True)
    ins = inst.find_instance(mod)
    ins.policy_update([http_policy()])
    res, c = new_connection(
        mod, "http", True, 1, 2, "1.1.1.1:1", "2.2.2.2:80", "http-pol"
    )
    assert res == FilterResult.OK
    yield c
    inst.close_module(mod)
    inst.reset_module_registry()


def drive(c, reply, buf):
    ops = []
    c.on_data(reply, False, [buf], ops)
    return ops, c.reply_buf.take()


def test_parser_allow_deny_and_403(conn):
    r_ok = req("GET", "/public/a.html")
    ops, inj = drive(conn, False, r_ok)
    assert ops == [(PASS, len(r_ok)), (MORE, 1)]
    assert inj == b""

    r_bad = req("GET", "/private/x")
    ops, inj = drive(conn, False, r_bad)
    assert ops == [(DROP, len(r_bad)), (MORE, 1)]
    assert inj == HTTP_403

    # method must match too
    r_post = req("POST", "/public/a.html")
    ops, _ = drive(conn, False, r_post)
    assert ops[0][0] == DROP


def test_parser_body_rides_verdict_and_replies_pass(conn):
    r = req("POST", "/api/v2/submit", body=b"payload-bytes")
    ops, inj = drive(conn, False, r)
    assert ops == [(PASS, len(r)), (MORE, 1)]
    # partial frame: MORE until the body arrives
    r2 = req("POST", "/api/v2/submit", body=b"xyz")
    ops, _ = drive(conn, False, r2[:-2])
    assert ops == [(MORE, 1)]
    ops, _ = drive(conn, False, r2)
    assert ops == [(PASS, len(r2)), (MORE, 1)]
    # reply direction passes untouched
    resp = b"HTTP/1.1 200 OK\r\ncontent-length: 2\r\n\r\nok"
    ops, _ = drive(conn, True, resp)
    assert ops == [(PASS, len(resp)), (MORE, 1)]


# --- sidecar end-to-end ----------------------------------------------------

@pytest.fixture
def service(tmp_path):
    inst.reset_module_registry()
    svc = VerdictService(
        str(tmp_path / "http.sock"), DaemonConfig(batch_timeout_ms=2.0)
    ).start()
    yield svc
    svc.stop()
    inst.reset_module_registry()


def test_http_through_sidecar(service):
    client = SidecarClient(service.socket_path)
    try:
        mod = client.open_module([])
        assert mod != 0
        assert client.policy_update(mod, [http_policy()]) == int(
            FilterResult.OK
        )
        res, shim = client.new_connection(
            mod, "http", 7001, True, 1, 2, "1.1.1.1:1", "2.2.2.2:80",
            "http-pol",
        )
        assert res == int(FilterResult.OK)

        r_ok = req("GET", "/public/index.html")
        _, out = shim.on_io(False, r_ok)
        assert out == r_ok

        # denied: dropped + 403 on the reply side
        r_bad = req("GET", "/secret")
        _, out = shim.on_io(False, r_bad)
        assert out == b""
        _, out = shim.on_io(True, b"")
        assert out == HTTP_403

        # frame split across calls, with body
        r3 = req("POST", "/api/v9/submit", body=b"0123456789")
        _, out_a = shim.on_io(False, r3[:20])
        _, out_b = shim.on_io(False, r3[20:])
        assert out_a + out_b == r3

        # disallowed remote: same request denied for identity 9
        res, shim9 = client.new_connection(
            mod, "http", 7002, True, 9, 2, "9.9.9.9:1", "2.2.2.2:80",
            "http-pol",
        )
        assert res == int(FilterResult.OK)
        _, out = shim9.on_io(False, r_ok)
        assert out == b""

        # the device path actually judged frames
        engines = [
            e for e in service._engines.values()
            if getattr(e, "proto", "") == "http"
        ]
        assert engines and engines[0].device_judged >= 1
    finally:
        client.close()


def test_negative_content_length_does_not_loop(conn):
    """A negative Content-Length must not walk framing backwards
    (unauthenticated DoS vector in the peek loop)."""
    evil = b"GET /public/a HTTP/1.1\r\ncontent-length: -44\r\n\r\n"
    assert head_and_body_len(evil) == (len(evil), 0)
    ops, _ = drive(conn, False, evil)
    assert ops[0][0] == PASS and ops[0][1] == len(evil)


def test_malformed_request_line_keeps_verdict_queue_aligned(service):
    """A frame whose request line cannot parse is denied WITHOUT a
    device verdict; a pipelined valid frame after it must still get ITS
    verdict, not the malformed frame's (policy-bypass regression)."""
    client = SidecarClient(service.socket_path)
    try:
        mod = client.open_module([])
        assert client.policy_update(mod, [http_policy()]) == int(
            FilterResult.OK
        )
        res, shim = client.new_connection(
            mod, "http", 7100, True, 1, 2, "1.1.1.1:1", "2.2.2.2:80",
            "http-pol",
        )
        assert res == int(FilterResult.OK)
        bad = b"GET /\r\n\r\n"  # two tokens: parse_head rejects
        good = req("GET", "/public/ok")
        denied = req("GET", "/private/no")
        _, out = shim.on_io(False, bad + good + denied)
        # malformed frame dropped, good frame passed, denied dropped
        assert out == good
    finally:
        client.close()


def test_http_wave_batching_parity(tmp_path):
    """Aggregated rounds with MULTIPLE pipelined requests per conn run
    through the wave-batched slow path (nth entry of every conn judged
    in one device batch per wave) — verdict sequences must match the
    per-request oracle exactly."""
    import threading

    import numpy as np

    from cilium_tpu.proxylib import instance as inst
    from cilium_tpu.sidecar.client import SidecarClient
    from cilium_tpu.sidecar.service import VerdictService
    from cilium_tpu.utils.option import DaemonConfig

    inst.reset_module_registry()
    svc = VerdictService(
        str(tmp_path / "wv.sock"), DaemonConfig(batch_timeout_ms=0.0)
    ).start()
    cl = SidecarClient(svc.socket_path, timeout=300.0)
    try:
        mod = cl.open_module([])
        assert cl.policy_update(mod, [http_policy()]) == int(FilterResult.OK)
        N = 4
        for cid in range(1, N + 1):
            res, _ = cl.new_connection(
                mod, "http", cid, True, 1, 2, "1.1.1.1:1", "2.2.2.2:80",
                "http-pol",
            )
            assert res == int(FilterResult.OK)
        reqs = [
            b"GET /public/a HTTP/1.1\r\nHost: h\r\n\r\n",   # allow
            b"GET /private/b HTTP/1.1\r\nHost: h\r\n\r\n",  # deny
            b"GET /public/c HTTP/1.1\r\nHost: h\r\n\r\n",   # allow
        ]
        got: dict[int, object] = {}
        evt = threading.Event()

        def cb(vb):
            got[vb.seq] = vb
            evt.set()

        cl.verdict_callback = cb
        # ONE DataBatch carrying all three requests PER CONN (repeated
        # conn ids) — a single round whose slow set has three entries
        # per conn, deterministically exercising waves 0..2 and their
        # per-conn op attribution.
        ids = np.concatenate(
            [np.arange(1, N + 1, dtype=np.uint64)] * len(reqs)
        )
        lens = np.concatenate(
            [np.full(N, len(r), np.uint32) for r in reqs]
        )
        blob = b"".join(r * N for r in reqs)
        cl.send_batch(77, ids, np.zeros(len(ids), np.uint8), lens, blob)
        assert evt.wait(240), sorted(got)

        vb = got[77]
        assert vb.count == N * len(reqs)
        for j in range(vb.count):
            cid, res, ops, _io, ir = vb.entry(j)
            k = j // N  # request index (entries in send order)
            assert res == int(FilterResult.OK)
            kinds = [int(o) for o, _ in ops]
            allow = k != 1
            if allow:
                assert int(PASS) in kinds and int(DROP) not in kinds, (
                    k, cid, ops,
                )
                assert ir == b""
            else:
                assert int(DROP) in kinds, (k, cid, ops)
                assert b"403" in ir  # injected denial response
    finally:
        cl.close()
        svc.stop()
        inst.reset_module_registry()
