"""Policy engine tests: labels, selectors, rule validation, repository
resolution and merge semantics.

Golden cases modeled on the reference's test strategy (reference:
pkg/policy/l4Filter_test.go case table, pkg/policy/repository_test.go,
pkg/policy/api/rule_validation_test.go).
"""

import pytest

from cilium_tpu.labels import (
    Label,
    LabelArray,
    Labels,
    get_extended_key_from,
    parse_label,
    parse_select_label,
)
from cilium_tpu.labels.cidr import ip_string_to_label
from cilium_tpu.policy import (
    CIDRRule,
    Decision,
    DPort,
    EgressRule,
    EndpointSelector,
    IngressRule,
    L7Rules,
    PARSER_TYPE_HTTP,
    PARSER_TYPE_KAFKA,
    PolicyMergeError,
    PolicyValidationError,
    PortProtocol,
    PortRule,
    PortRuleHTTP,
    PortRuleKafka,
    PortRuleL7,
    Repository,
    Rule,
    SearchContext,
    SelectorRequirement,
    WILDCARD_SELECTOR,
    parse_proxy_id,
    proxy_id,
    rules_from_json,
    rules_to_json,
)
from cilium_tpu.policy.api import (
    KAFKA_CONSUME_KEYS,
    KAFKA_PRODUCE_KEYS,
    compute_resultant_cidr_set,
)


def sel(*lbls: str) -> EndpointSelector:
    return EndpointSelector.from_labels(*(parse_select_label(l) for l in lbls))


def ctx_to(*lbls: str) -> SearchContext:
    return SearchContext(to_labels=LabelArray.parse_select(*lbls))


# ---------------------------------------------------------------------------
# labels


class TestLabels:
    def test_parse_label_forms(self):
        l = parse_label("k8s:role=frontend")
        assert (l.source, l.key, l.value) == ("k8s", "role", "frontend")
        l = parse_label("$host")
        assert (l.source, l.key) == ("reserved", "host")
        l = parse_label("reserved:world")
        assert (l.source, l.key) == ("reserved", "world")
        l = parse_label("foo=bar")
        assert (l.source, l.key, l.value) == ("unspec", "foo", "bar")
        assert parse_select_label("foo=bar").source == "any"

    def test_extended_key(self):
        assert get_extended_key_from("k8s:foo=bar") == "k8s.foo"
        assert get_extended_key_from("foo=bar") == "any.foo"
        assert Label.new("k8s:x", "1").extended_key == "k8s.x"

    def test_any_source_matches_all_sources(self):
        any_l = parse_select_label("role=frontend")
        k8s_l = parse_label("k8s:role=frontend")
        assert any_l.equals(k8s_l)
        assert not k8s_l.equals(parse_label("container:role=frontend"))

    def test_label_array_contains(self):
        arr = LabelArray.parse("k8s:a=1", "k8s:b=2")
        assert arr.contains(LabelArray.parse_select("a=1"))
        assert not arr.contains(LabelArray.parse_select("a=2"))
        assert arr.contains(LabelArray())

    def test_labels_sha(self):
        l1 = Labels.from_model(["k8s:a=1", "k8s:b=2"])
        l2 = Labels.from_model(["k8s:b=2", "k8s:a=1"])
        assert l1.sha256_sum() == l2.sha256_sum()

    def test_cidr_label(self):
        l = ip_string_to_label("10.0.0.0/8")
        assert l.source == "cidr"
        assert l.key == "10.0.0.0/8"
        l = ip_string_to_label("192.0.2.3")
        assert l.key == "192.0.2.3/32"
        assert ip_string_to_label("f00d::1").key == "f00d--1/128"
        assert ip_string_to_label("not-an-ip") is None


# ---------------------------------------------------------------------------
# selectors


class TestSelectors:
    def test_wildcard_matches_everything(self):
        assert WILDCARD_SELECTOR.matches(LabelArray.parse_select("anything"))
        assert WILDCARD_SELECTOR.matches(LabelArray())
        assert WILDCARD_SELECTOR.is_wildcard()

    def test_match_labels(self):
        s = sel("role=frontend")
        assert s.matches(LabelArray.parse("k8s:role=frontend"))
        assert not s.matches(LabelArray.parse("k8s:role=backend"))
        assert not s.matches(LabelArray())

    def test_reserved_all_label_short_circuits(self):
        s = sel("reserved:all")
        assert s.matches(LabelArray.parse("k8s:whatever=x"))
        assert s.matches(LabelArray())

    def test_match_expressions(self):
        s = EndpointSelector.from_dict(
            None,
            [SelectorRequirement("env", "In", ("prod", "staging"))],
        )
        assert s.matches(LabelArray.parse_select("env=prod"))
        assert not s.matches(LabelArray.parse_select("env=dev"))
        s = EndpointSelector.from_dict(None, [SelectorRequirement("env", "Exists")])
        assert s.matches(LabelArray.parse_select("env=x"))
        assert not s.matches(LabelArray.parse_select("other=x"))
        s = EndpointSelector.from_dict(
            None, [SelectorRequirement("env", "DoesNotExist")]
        )
        assert s.matches(LabelArray.parse_select("other=x"))
        # NotIn matches when key is absent (k8s semantics).
        s = EndpointSelector.from_dict(
            None, [SelectorRequirement("env", "NotIn", ("prod",))]
        )
        assert s.matches(LabelArray.parse_select("other=x"))
        assert not s.matches(LabelArray.parse_select("env=prod"))

    def test_requirement_validation(self):
        with pytest.raises(PolicyValidationError):
            SelectorRequirement("k", "In").validate()
        with pytest.raises(PolicyValidationError):
            SelectorRequirement("k", "Exists", ("v",)).validate()
        with pytest.raises(PolicyValidationError):
            SelectorRequirement("k", "Bogus").validate()


# ---------------------------------------------------------------------------
# rule validation (reference: rule_validation_test.go)


class TestSanitize:
    def test_nil_selector_rejected(self):
        with pytest.raises(PolicyValidationError):
            Rule().sanitize()

    def test_l3_member_exclusivity(self):
        r = Rule(
            endpoint_selector=WILDCARD_SELECTOR,
            ingress=[
                IngressRule(
                    from_endpoints=[sel("a")],
                    from_cidr=["10.0.0.0/8"],
                )
            ],
        )
        with pytest.raises(PolicyValidationError, match="[Cc]ombining"):
            r.sanitize()

    def test_cidr_with_to_ports_rejected_ingress(self):
        r = Rule(
            endpoint_selector=WILDCARD_SELECTOR,
            ingress=[
                IngressRule(
                    from_cidr=["10.0.0.0/8"],
                    to_ports=[PortRule(ports=[PortProtocol("80", "TCP")])],
                )
            ],
        )
        with pytest.raises(PolicyValidationError, match="ToPorts"):
            r.sanitize()

    def test_cidr_with_to_ports_allowed_egress(self):
        r = Rule(
            endpoint_selector=WILDCARD_SELECTOR,
            egress=[
                EgressRule(
                    to_cidr=["10.0.0.0/8"],
                    to_ports=[PortRule(ports=[PortProtocol("80", "TCP")])],
                )
            ],
        )
        r.sanitize()  # L3-dependent L4 is supported on all egress members

    def test_l7_requires_tcp(self):
        r = Rule(
            endpoint_selector=WILDCARD_SELECTOR,
            ingress=[
                IngressRule(
                    to_ports=[
                        PortRule(
                            ports=[PortProtocol("53", "UDP")],
                            rules=L7Rules(http=[PortRuleHTTP(path="/")]),
                        )
                    ]
                )
            ],
        )
        with pytest.raises(PolicyValidationError, match="TCP"):
            r.sanitize()

    def test_mixed_l7_types_rejected(self):
        pr = PortRule(
            ports=[PortProtocol("80", "TCP")],
            rules=L7Rules(
                http=[PortRuleHTTP(path="/")], kafka=[PortRuleKafka(topic="t")]
            ),
        )
        with pytest.raises(PolicyValidationError, match="multiple L7"):
            pr.sanitize()

    def test_port_validation(self):
        with pytest.raises(PolicyValidationError):
            PortProtocol("0", "TCP").sanitize()
        with pytest.raises(PolicyValidationError):
            PortProtocol("notaport", "TCP").sanitize()
        with pytest.raises(PolicyValidationError):
            PortProtocol("80", "SCTP").sanitize()
        assert PortProtocol("80", "tcp").sanitize().protocol == "TCP"
        assert PortProtocol("80", "").sanitize().protocol == "ANY"

    def test_l7_without_l7proto_rejected(self):
        pr = PortRule(
            ports=[PortProtocol("80", "TCP")],
            rules=L7Rules(l7=[PortRuleL7({"cmd": "READ"})]),
        )
        with pytest.raises(PolicyValidationError, match="l7proto"):
            pr.sanitize()

    def test_cidr_exception_containment(self):
        CIDRRule("10.0.0.0/8", ("10.96.0.0/12",)).sanitize()
        with pytest.raises(PolicyValidationError, match="does not contain"):
            CIDRRule("10.0.0.0/8", ("192.168.0.0/16",)).sanitize()

    def test_kafka_role_apikey_exclusive(self):
        k = PortRuleKafka(role="produce", api_key="fetch")
        with pytest.raises(PolicyValidationError):
            k.sanitize()

    def test_kafka_role_expansion(self):
        k = PortRuleKafka(role="produce")
        k.sanitize()
        assert k.api_keys_int == KAFKA_PRODUCE_KEYS
        assert k.check_api_key_role(0) and k.check_api_key_role(18)
        assert not k.check_api_key_role(1)
        k = PortRuleKafka(role="consume")
        k.sanitize()
        assert k.api_keys_int == KAFKA_CONSUME_KEYS
        k = PortRuleKafka(api_key="fetch")
        k.sanitize()
        assert k.api_keys_int == (1,)
        k = PortRuleKafka()
        k.sanitize()
        assert k.check_api_key_role(33)  # wildcard

    def test_invalid_regex_rejected(self):
        with pytest.raises(PolicyValidationError):
            PortRuleHTTP(path="([unclosed").sanitize()


# ---------------------------------------------------------------------------
# CIDR set computation


class TestCIDR:
    def test_resultant_cidr_set(self):
        out = compute_resultant_cidr_set(
            [CIDRRule("10.0.0.0/24", ("10.0.0.0/25",))]
        )
        assert out == ["10.0.0.128/25"]

    def test_resultant_no_exceptions(self):
        assert compute_resultant_cidr_set([CIDRRule("10.0.0.0/8")]) == ["10.0.0.0/8"]


# ---------------------------------------------------------------------------
# repository basics (reference: repository_test.go TestAddSearchDelete)


class TestRepository:
    def test_add_search_delete_revision(self):
        repo = Repository()
        lbls1 = LabelArray.parse("tag1", "tag2")
        r1 = Rule(endpoint_selector=sel("foo"), labels=lbls1)
        rev0 = repo.get_revision()
        rev = repo.add(r1)
        assert rev > rev0
        assert repo.search(LabelArray.parse("tag1")) == [r1]
        rev2, deleted = repo.delete_by_labels(LabelArray.parse("tag1"))
        assert deleted == 1 and rev2 > rev
        assert repo.num_rules() == 0
        # deleting nothing does not bump
        rev3, deleted = repo.delete_by_labels(LabelArray.parse("tag1"))
        assert deleted == 0 and rev3 == rev2

    def test_can_reach_ingress(self):
        repo = Repository()
        repo.add(
            Rule(
                endpoint_selector=sel("bar"),
                ingress=[IngressRule(from_endpoints=[sel("foo")])],
            )
        )
        ctx = SearchContext(
            from_labels=LabelArray.parse_select("foo"),
            to_labels=LabelArray.parse_select("bar"),
        )
        assert repo.allows_ingress(ctx) == Decision.ALLOWED
        ctx_bad = SearchContext(
            from_labels=LabelArray.parse_select("baz"),
            to_labels=LabelArray.parse_select("bar"),
        )
        assert repo.allows_ingress(ctx_bad) == Decision.DENIED

    def test_from_requires_denies(self):
        # reference: repository_test.go TestCanReachIngress requires cases
        repo = Repository()
        repo.add(
            Rule(
                endpoint_selector=sel("bar"),
                ingress=[IngressRule(from_endpoints=[sel("foo")])],
            )
        )
        repo.add(
            Rule(
                endpoint_selector=sel("bar"),
                ingress=[IngressRule(from_requires=[sel("team=A")])],
            )
        )
        ok = SearchContext(
            from_labels=LabelArray.parse_select("foo", "team=A"),
            to_labels=LabelArray.parse_select("bar"),
        )
        assert repo.allows_ingress(ok) == Decision.ALLOWED
        bad = SearchContext(
            from_labels=LabelArray.parse_select("foo"),
            to_labels=LabelArray.parse_select("bar"),
        )
        assert repo.allows_ingress(bad) == Decision.DENIED

    def test_egress_requires(self):
        repo = Repository()
        repo.add(
            Rule(
                endpoint_selector=sel("foo"),
                egress=[EgressRule(to_endpoints=[sel("bar")])],
            )
        )
        repo.add(
            Rule(
                endpoint_selector=sel("foo"),
                egress=[EgressRule(to_requires=[sel("zone=pci")])],
            )
        )
        ok = SearchContext(
            from_labels=LabelArray.parse_select("foo"),
            to_labels=LabelArray.parse_select("bar", "zone=pci"),
        )
        assert repo.allows_egress(ok) == Decision.ALLOWED
        bad = SearchContext(
            from_labels=LabelArray.parse_select("foo"),
            to_labels=LabelArray.parse_select("bar"),
        )
        assert repo.allows_egress(bad) == Decision.DENIED


# ---------------------------------------------------------------------------
# L4 resolution & merge (reference: l4Filter_test.go case table)


def http_port_rule(port="80", path="/"):
    return PortRule(
        ports=[PortProtocol(port, "TCP")],
        rules=L7Rules(http=[PortRuleHTTP(method="GET", path=path)]),
    )


def plain_port_rule(port="80", proto="TCP"):
    return PortRule(ports=[PortProtocol(port, proto)])


class TestL4Resolution:
    def test_case1_allow_all_l3_l4_merge(self):
        # Two identical wildcard-L3 rules on 80/TCP merge to one filter.
        repo = Repository()
        repo.add(
            Rule(
                endpoint_selector=sel("a"),
                ingress=[
                    IngressRule(
                        from_endpoints=[WILDCARD_SELECTOR],
                        to_ports=[plain_port_rule()],
                    ),
                    IngressRule(
                        from_endpoints=[WILDCARD_SELECTOR],
                        to_ports=[plain_port_rule()],
                    ),
                ],
            )
        )
        l4 = repo.resolve_l4_ingress_policy(ctx_to("a"))
        assert set(l4) == {"80/TCP"}
        f = l4["80/TCP"]
        assert f.allows_all_at_l3()
        assert f.l7_parser == ""
        assert not f.is_redirect()

    def test_case2_l7_shadowed_by_allow_all(self):
        # Rule 1 wildcard L7, rule 2 restricted L7 on same port: merged filter
        # keeps HTTP parser; the wildcard selector's rules include both.
        repo = Repository()
        repo.add(
            Rule(
                endpoint_selector=sel("a"),
                ingress=[
                    IngressRule(
                        from_endpoints=[WILDCARD_SELECTOR],
                        to_ports=[plain_port_rule()],
                    ),
                    IngressRule(
                        from_endpoints=[WILDCARD_SELECTOR],
                        to_ports=[http_port_rule()],
                    ),
                ],
            )
        )
        l4 = repo.resolve_l4_ingress_policy(ctx_to("a"))
        f = l4["80/TCP"]
        assert f.l7_parser == PARSER_TYPE_HTTP
        assert f.is_redirect()
        # wildcardL3L4Rules wildcards L7 for L3/L4-only allows on this port:
        wild_rules = f.l7_rules_per_ep[WILDCARD_SELECTOR]
        assert any(h.path == "" and h.method == "" for h in wild_rules.http)

    def test_case3_duplicate_http_rules_dedup(self):
        repo = Repository()
        repo.add(
            Rule(
                endpoint_selector=sel("a"),
                ingress=[
                    IngressRule(
                        from_endpoints=[WILDCARD_SELECTOR],
                        to_ports=[http_port_rule()],
                    ),
                    IngressRule(
                        from_endpoints=[WILDCARD_SELECTOR],
                        to_ports=[http_port_rule()],
                    ),
                ],
            )
        )
        l4 = repo.resolve_l4_ingress_policy(ctx_to("a"))
        f = l4["80/TCP"]
        assert len(f.l7_rules_per_ep[WILDCARD_SELECTOR].http) == 1

    def test_case5_conflicting_parsers(self):
        repo = Repository()
        repo.add(
            Rule(
                endpoint_selector=sel("a"),
                ingress=[
                    IngressRule(
                        from_endpoints=[WILDCARD_SELECTOR],
                        to_ports=[
                            PortRule(
                                ports=[PortProtocol("80", "TCP")],
                                rules=L7Rules(
                                    l7proto="testing", l7=[PortRuleL7({"cmd": "X"})]
                                ),
                            )
                        ],
                    ),
                    IngressRule(
                        from_endpoints=[WILDCARD_SELECTOR],
                        to_ports=[http_port_rule()],
                    ),
                ],
            )
        )
        with pytest.raises(PolicyMergeError, match="parsers"):
            repo.resolve_l4_ingress_policy(ctx_to("a"))

    def test_case6_superset_collapses_to_wildcard(self):
        repo = Repository()
        repo.add(
            Rule(
                endpoint_selector=sel("a"),
                ingress=[
                    IngressRule(
                        from_endpoints=[sel("id=a")],
                        to_ports=[plain_port_rule()],
                    ),
                    IngressRule(
                        from_endpoints=[WILDCARD_SELECTOR],
                        to_ports=[plain_port_rule()],
                    ),
                ],
            )
        )
        l4 = repo.resolve_l4_ingress_policy(ctx_to("a"))
        f = l4["80/TCP"]
        assert f.endpoints == [WILDCARD_SELECTOR]

    def test_case10_distinct_l3_same_l7(self):
        repo = Repository()
        repo.add(
            Rule(
                endpoint_selector=sel("a"),
                ingress=[
                    IngressRule(
                        from_endpoints=[sel("id=a")], to_ports=[http_port_rule()]
                    ),
                    IngressRule(
                        from_endpoints=[sel("id=c")], to_ports=[http_port_rule()]
                    ),
                ],
            )
        )
        l4 = repo.resolve_l4_ingress_policy(ctx_to("a"))
        f = l4["80/TCP"]
        assert len(f.l7_rules_per_ep) == 2
        assert not f.allows_all_at_l3()
        assert len(f.endpoints) == 2

    def test_proto_any_expands(self):
        repo = Repository()
        repo.add(
            Rule(
                endpoint_selector=sel("a"),
                ingress=[
                    IngressRule(
                        from_endpoints=[WILDCARD_SELECTOR],
                        to_ports=[plain_port_rule("53", "ANY")],
                    )
                ],
            )
        )
        l4 = repo.resolve_l4_ingress_policy(ctx_to("a"))
        assert set(l4) == {"53/TCP", "53/UDP"}

    def test_l3_only_rule_wildcards_l7(self):
        # reference: repository_test.go TestWildcardL3RulesIngress — an
        # L3-only allow for id=a wildcards the L7 rules of the redirect.
        repo = Repository()
        repo.add(
            Rule(
                endpoint_selector=sel("a"),
                ingress=[IngressRule(from_endpoints=[sel("id=a")])],
            )
        )
        repo.add(
            Rule(
                endpoint_selector=sel("a"),
                ingress=[
                    IngressRule(
                        from_endpoints=[sel("id=b")], to_ports=[http_port_rule()]
                    )
                ],
            )
        )
        l4 = repo.resolve_l4_ingress_policy(ctx_to("a"))
        f = l4["80/TCP"]
        a_rules = f.l7_rules_per_ep.get(sel("id=a"))
        assert a_rules is not None
        assert any(h.path == "" for h in a_rules.http)

    def test_egress_resolution(self):
        repo = Repository()
        repo.add(
            Rule(
                endpoint_selector=sel("foo"),
                egress=[
                    EgressRule(
                        to_endpoints=[sel("db")],
                        to_ports=[plain_port_rule("5432")],
                    )
                ],
            )
        )
        ctx = SearchContext(from_labels=LabelArray.parse_select("foo"))
        l4 = repo.resolve_l4_egress_policy(ctx)
        assert set(l4) == {"5432/TCP"}
        assert not l4["5432/TCP"].ingress

    def test_from_requires_folded_into_l4(self):
        # reference: repository_test.go TestL3DependentL4IngressFromRequires
        repo = Repository()
        repo.add(
            Rule(
                endpoint_selector=sel("a"),
                ingress=[
                    IngressRule(
                        from_endpoints=[sel("id=b")],
                        to_ports=[plain_port_rule()],
                    ),
                    IngressRule(from_requires=[sel("zone=z")]),
                ],
            )
        )
        l4 = repo.resolve_l4_ingress_policy(ctx_to("a"))
        f = l4["80/TCP"]
        assert len(f.endpoints) == 1
        ep = f.endpoints[0]
        # selector must now require both id=b and zone=z
        assert ep.matches(LabelArray.parse_select("id=b", "zone=z"))
        assert not ep.matches(LabelArray.parse_select("id=b"))


# ---------------------------------------------------------------------------
# CIDR policy resolution


class TestCIDRResolution:
    def test_resolve_cidr_policy(self):
        repo = Repository()
        repo.add(
            Rule(
                endpoint_selector=sel("a"),
                ingress=[IngressRule(from_cidr=["10.0.0.0/8"])],
                egress=[
                    EgressRule(
                        to_cidr_set=[CIDRRule("192.168.0.0/16", ("192.168.1.0/24",))]
                    )
                ],
            )
        )
        cp = repo.resolve_cidr_policy(ctx_to("a"))
        assert "10.0.0.0/8" in cp.ingress.map
        assert cp.ingress.ipv4_prefix_count[8] == 1
        # exception carved out of egress set
        assert "192.168.1.0/24" not in cp.egress.map
        assert len(cp.egress.map) > 0
        s6, s4 = cp.to_lpm_data()
        assert s4 == sorted(s4, reverse=True)
        assert 0 in s4 and 32 in s4

    def test_ingress_cidr_l4_skipped(self):
        # CIDR+L4 ingress is handled via L4 resolution, not CIDR policy.
        repo = Repository()
        r = Rule(
            endpoint_selector=sel("a"),
            egress=[
                EgressRule(
                    to_cidr=["10.0.0.0/8"],
                    to_ports=[plain_port_rule()],
                )
            ],
        )
        repo.add(r)
        cp = repo.resolve_cidr_policy(ctx_to("a"))
        # egress CIDR+L4 still counted for prefix lengths
        assert "10.0.0.0/8" in cp.egress.map


# ---------------------------------------------------------------------------
# proxy ID


class TestProxyID:
    def test_round_trip(self):
        pid = proxy_id(42, True, "TCP", 80)
        assert parse_proxy_id(pid) == (42, True, "TCP", 80)
        pid = proxy_id(7, False, "UDP", 53)
        assert parse_proxy_id(pid) == (7, False, "UDP", 53)
        with pytest.raises(ValueError):
            parse_proxy_id("bogus")


# ---------------------------------------------------------------------------
# JSON serialization round trip (reference policy document schema)


SAMPLE_POLICY = """
[{
  "endpointSelector": {"matchLabels": {"role": "backend"}},
  "labels": ["k8s:io.cilium.k8s.policy.name=rule1"],
  "ingress": [{
    "fromEndpoints": [{"matchLabels": {"role": "frontend"}}],
    "toPorts": [{
      "ports": [{"port": "80", "protocol": "TCP"}],
      "rules": {"http": [{"method": "GET", "path": "/public/.*"}]}
    }]
  }],
  "egress": [{
    "toCIDRSet": [{"cidr": "10.0.0.0/8", "except": ["10.96.0.0/12"]}]
  }]
}]
"""


class TestSerialization:
    def test_round_trip(self):
        rules = rules_from_json(SAMPLE_POLICY)
        assert len(rules) == 1
        r = rules[0]
        r.sanitize()
        assert r.endpoint_selector.matches(LabelArray.parse("k8s:role=backend"))
        assert r.ingress[0].to_ports[0].rules.http[0].path == "/public/.*"
        assert r.egress[0].to_cidr_set[0].except_cidrs == ("10.96.0.0/12",)
        # round trip preserves resolution behavior
        text = rules_to_json(rules)
        rules2 = rules_from_json(text)
        repo = Repository()
        repo.add(rules2[0])
        ctx = SearchContext(
            from_labels=LabelArray.parse_select("role=frontend"),
            to_labels=LabelArray.parse_select("role=backend"),
            dports=[DPort(80, "TCP")],
        )
        l4 = repo.resolve_l4_ingress_policy(ctx)
        assert "80/TCP" in l4
        assert l4["80/TCP"].l7_parser == PARSER_TYPE_HTTP
