"""cilium-health prober, fqdn DNS->CIDR generation, and the bugtool
support bundle (reference: pkg/health/server/prober.go:40, pkg/fqdn,
bugtool/cmd/root.go:159)."""

import json
import tarfile
import time

import pytest

from cilium_tpu.fqdn import DnsCache, DnsPoller
from cilium_tpu.health import HealthResponder, Prober
from cilium_tpu.policy.api import EgressRule, EndpointSelector, FQDNSelector, Rule
from cilium_tpu.policy.repository import Repository


# --- health ----------------------------------------------------------------

def test_prober_healthy_and_degraded_nodes():
    """Two live nodes + one dead address: the prober reports exactly the
    dead one degraded, with latency recorded for the live ones."""
    r1, r2 = HealthResponder(), HealthResponder()
    p = Prober(node_name="n0")
    try:
        p.add_node("n1", r1.address)
        p.add_node("n2", r2.address)
        p.add_node("n3", "127.0.0.1:1")  # closed port
        p.probe_all()
        st = p.get_status()
        assert st["probed_nodes"] == 3
        assert st["degraded"] == ["n3"]
        assert st["healthy"] == 2
        assert st["nodes"]["n1"]["reachable"]
        assert st["nodes"]["n1"]["latency_ms"] > 0
        assert st["nodes"]["n3"]["failures"] == 1
        # a node coming back after death recovers
        p.probe_all()
        assert p.get_status()["nodes"]["n3"]["failures"] == 2
    finally:
        r1.close()
        r2.close()
        p.close()


def test_prober_detects_node_death():
    r = HealthResponder()
    p = Prober()
    try:
        p.add_node("n1", r.address)
        p.probe_all()
        assert p.get_status()["degraded"] == []
        r.close()
        p.probe_all()
        st = p.get_status()
        assert st["degraded"] == ["n1"]
        assert st["nodes"]["n1"]["failures"] >= 1
    finally:
        p.close()


def test_daemon_wires_health(tmp_path):
    from cilium_tpu.daemon.daemon import Daemon
    from cilium_tpu.utils.option import DaemonConfig

    d = Daemon(DaemonConfig(state_dir=str(tmp_path), dry_mode=True))
    try:
        assert d.health_prober is not None
        d.health_prober.probe_all()
        st = d.health_prober.get_status()
        assert st["probed_nodes"] == 1 and st["degraded"] == []
    finally:
        d.close()


# --- fqdn ------------------------------------------------------------------

def _fqdn_rule(name="svc.example.com"):
    f = FQDNSelector(match_name=name)
    f.sanitize()
    r = Rule(
        endpoint_selector=EndpointSelector.from_dict({"app": "client"}),
        egress=[EgressRule(to_fqdns=[f])],
    )
    r.sanitize()
    return r


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_dns_cache_ttl():
    clock = FakeClock()
    c = DnsCache(clock=clock)
    c.update("a.com", ["1.1.1.1"], ttl=10)
    assert c.lookup("a.com") == ("1.1.1.1",)
    clock.t += 11
    assert c.lookup("a.com") == ()
    assert c.expired("a.com")


def test_poller_generates_and_refreshes_cidrs():
    repo = Repository()
    repo.add(_fqdn_rule())
    clock = FakeClock()
    answers = {"svc.example.com": (["10.1.1.1", "10.1.1.2"], 30.0)}
    changes = []
    poller = DnsPoller(
        repo, lambda name: answers[name],
        on_change=lambda: changes.append(1), clock=clock,
    )
    poller.lookup_update_dns()
    cidrs = {c.cidr for c in repo.rules[0].egress[0].to_cidr_set}
    assert cidrs == {"10.1.1.1/32", "10.1.1.2/32"}
    assert all(c.generated for c in repo.rules[0].egress[0].to_cidr_set)
    assert changes == [1]
    rev = repo.revision

    # within TTL: no re-resolution, no change
    poller.lookup_update_dns()
    assert changes == [1] and repo.revision == rev

    # TTL lapses and the answer set changes -> regenerated + notified
    clock.t += 31
    answers["svc.example.com"] = (["10.9.9.9"], 30.0)
    poller.lookup_update_dns()
    cidrs = {c.cidr for c in repo.rules[0].egress[0].to_cidr_set}
    assert cidrs == {"10.9.9.9/32"}
    assert changes == [1, 1] and repo.revision > rev


def test_poller_detects_shrink_to_empty_and_skips_no_op_refresh():
    """Change detection compares against the last known (possibly
    expired) answer: a name whose records disappear must drop its
    generated CIDRs, and an unchanged answer re-resolved after TTL
    expiry must NOT trigger a spurious regeneration."""
    repo = Repository()
    repo.add(_fqdn_rule())
    clock = FakeClock()
    answers = {"svc.example.com": (["10.5.5.5"], 30.0)}
    changes = []
    poller = DnsPoller(
        repo, lambda name: answers[name],
        on_change=lambda: changes.append(1), clock=clock,
    )
    poller.lookup_update_dns()
    assert changes == [1]
    rev = repo.revision

    # same answer after expiry: re-resolved, but no change event
    clock.t += 31
    poller.lookup_update_dns()
    assert changes == [1] and repo.revision == rev

    # records removed after expiry: generated CIDRs must go away
    clock.t += 31
    answers["svc.example.com"] = ([], 30.0)
    poller.lookup_update_dns()
    assert changes == [1, 1] and repo.revision > rev
    assert repo.rules[0].egress[0].to_cidr_set == []


def test_poller_survives_resolver_failure():
    """A failing resolver keeps serving the last good answer (the
    reference keeps cached IPs until a successful re-resolution)."""
    repo = Repository()
    repo.add(_fqdn_rule())
    clock = FakeClock()
    state = {"fail": False}

    def resolver(name):
        if state["fail"]:
            raise OSError("dns down")
        return ["10.2.2.2"], 5.0

    poller = DnsPoller(repo, resolver, clock=clock)
    poller.lookup_update_dns()
    cidrs = {c.cidr for c in repo.rules[0].egress[0].to_cidr_set}
    assert cidrs == {"10.2.2.2/32"}
    # resolver failure after expiry: the generated entry survives
    clock.t += 6
    state["fail"] = True
    poller.lookup_update_dns()
    cidrs = {c.cidr for c in repo.rules[0].egress[0].to_cidr_set}
    assert cidrs == {"10.2.2.2/32"}


def test_daemon_dns_poller_triggers_regeneration(tmp_path):
    from cilium_tpu.daemon.daemon import Daemon
    from cilium_tpu.utils.option import DaemonConfig

    d = Daemon(DaemonConfig(state_dir=str(tmp_path), dry_mode=True))
    try:
        d.policy_add([_fqdn_rule()])
        answers = {"svc.example.com": (["10.3.3.3"], 1.0)}
        poller = d.start_dns_poller(lambda n: answers[n], interval=3600)
        poller.lookup_update_dns()
        with d.policy.mutex:
            cidrs = {
                c.cidr for r in d.policy.rules
                for e in r.egress for c in e.to_cidr_set
            }
        assert cidrs == {"10.3.3.3/32"}
    finally:
        d.close()


# --- bugtool ---------------------------------------------------------------

def test_bugtool_bundle(tmp_path):
    """One command produces a tar with every section (reference:
    bugtool support bundle)."""
    from cilium_tpu.api.server import ApiClient, ApiServer
    from cilium_tpu.bugtool import SECTIONS, collect
    from cilium_tpu.daemon.daemon import Daemon
    from cilium_tpu.utils.option import DaemonConfig

    sock = str(tmp_path / "api.sock")
    d = Daemon(DaemonConfig(state_dir=str(tmp_path / "s"), dry_mode=True))
    srv = ApiServer(d, sock)
    try:
        d.endpoint_create(3, ipv4="10.44.0.3", labels=["k8s:app=bt"])
        out = str(tmp_path / "bundle.tar.gz")
        manifest = collect(ApiClient(sock), out)
        assert all(v["ok"] for v in manifest["sections"].values()), manifest
        with tarfile.open(out) as tar:
            names = {m.name for m in tar.getmembers()}
            for section, _ in SECTIONS:
                assert f"cilium-tpu-bugtool/{section}" in names
            status = json.load(
                tar.extractfile("cilium-tpu-bugtool/status.json")
            )
            assert "cilium" in str(status).lower() or status
            eps = json.load(
                tar.extractfile("cilium-tpu-bugtool/endpoints.json")
            )
            assert any(e.get("id") == 3 for e in eps)
    finally:
        srv.close()
        d.close()


def test_bugtool_native_sections(tmp_path):
    """The beyond-the-agent captures (reference: bugtool/cmd/root.go:159
    tc/ip/bpffs dumps): device platform, verdict-service counters over
    its own wire, kvstore failure counters, CNI interface records, and
    the latest BENCH/MULTICHIP artifacts."""
    from cilium_tpu.api.server import ApiClient, ApiServer
    from cilium_tpu.bugtool import collect
    from cilium_tpu.daemon.daemon import Daemon
    from cilium_tpu.k8s.cni import CniPlugin
    from cilium_tpu.k8s.ipam import IpamAllocator
    from cilium_tpu.proxylib import instance as inst
    from cilium_tpu.sidecar.service import VerdictService
    from cilium_tpu.utils.option import DaemonConfig

    inst.reset_module_registry()
    sock = str(tmp_path / "api.sock")
    vsock = str(tmp_path / "vs.sock")
    d = Daemon(DaemonConfig(state_dir=str(tmp_path / "s"), dry_mode=True))
    srv = ApiServer(d, sock)
    vs = VerdictService(vsock, DaemonConfig(batch_timeout_ms=2.0)).start()
    cni = CniPlugin(d, IpamAllocator("10.45.0.0/24"))
    cni.cni_add("bt-cont", "ns1", "pod-bt")
    # A fake BENCH artifact in the "repo root".
    root = str(tmp_path / "root")
    import os

    os.makedirs(root)
    with open(f"{root}/BENCH_r99.json", "w") as f:
        json.dump({"parsed": {"metric": "x", "value": 1}}, f)
    try:
        out = str(tmp_path / "bundle2.tar.gz")
        manifest = collect(
            ApiClient(sock), out, verdict_socket=vsock, cni=cni,
            repo_root=root,
        )
        with tarfile.open(out) as tar:
            names = {m.name for m in tar.getmembers()}
            for extra in (
                "cilium-tpu-bugtool/device.json",
                "cilium-tpu-bugtool/kvstore-counters.json",
                "cilium-tpu-bugtool/verdict-service.json",
                "cilium-tpu-bugtool/cni-interfaces.json",
                "cilium-tpu-bugtool/artifacts/BENCH_r99.json",
            ):
                assert extra in names, names
            dev = json.load(tar.extractfile("cilium-tpu-bugtool/device.json"))
            assert dev["device_count"] >= 1
            vsj = json.load(
                tar.extractfile("cilium-tpu-bugtool/verdict-service.json")
            )
            assert "dispatcher" in vsj
            cnij = json.load(
                tar.extractfile("cilium-tpu-bugtool/cni-interfaces.json")
            )
            assert any(v["container_ifname"] == "eth0" for v in cnij.values())
        assert manifest["sections"]["device.json"]["ok"]
        assert manifest["sections"]["verdict-service.json"]["ok"]
    finally:
        vs.stop()
        srv.close()
        d.close()
        inst.reset_module_registry()
