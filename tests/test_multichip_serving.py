"""Multi-chip sharded verdict serving on the LIVE dispatch path.

The service builds mesh-resident models (parallel/rulesharding.py
ShardedVerdictModel: rule rows split-balanced across RULE_AXIS, flow
batches sharded across FLOW_AXIS) and serves every lane — vec, fast
entry, columnar reassembly — through the sharded steps.  Contracts
pinned here, on the conftest 8-device CPU mesh:

- **Bit-identity.**  A mesh service answers byte-identically to the
  single-chip service for the same traffic, including denials with
  injected error replies, and its flow records carry the SAME global
  rule ids and match kinds the host oracle walk names (shard-local
  argmax + cross-shard min-index reduction).
- **Columnar lane.**  The reassembler's bucket issue routes through
  the sharded step with no new jit shapes (fixed power-of-two buckets
  divide the flow axis by construction).
- **Fail-closed degradation.**  A lost/erroring mesh device demotes
  the service to the single-chip fallback executable — typed
  (mesh_demotions_total{reason}), counted, status-surfaced — with
  zero silent loss and bit-identical verdicts after the flip.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from cilium_tpu.parallel.rulesharding import ShardedVerdictModel
from cilium_tpu.proxylib import (
    FilterResult,
    NetworkPolicy,
    PortNetworkPolicy,
    PortNetworkPolicyRule,
)
from cilium_tpu.proxylib import instance as inst
from cilium_tpu.sidecar import SidecarClient, VerdictService
from cilium_tpu.utils.option import DaemonConfig

POLICY_RULES = [
    {"cmd": "READ", "file": "/public/.*"},
    {"cmd": "HALT"},
    {"cmd": "WRITE", "file": "^/tmp/"},
    {"file": "\\.txt$"},
]


def _policy(name="mesh-pol"):
    return NetworkPolicy(
        name=name,
        policy=2,
        ingress_per_port_policies=[
            PortNetworkPolicy(
                port=80,
                rules=[
                    PortNetworkPolicyRule(
                        remote_policies=[1, 3],
                        l7_proto="r2d2",
                        l7_rules=POLICY_RULES[:2],
                    ),
                    PortNetworkPolicyRule(
                        l7_proto="r2d2", l7_rules=POLICY_RULES[2:]
                    ),
                ],
            )
        ],
    )


# (frame, remote) -> allowed under the policy above.
TRAFFIC = [
    (b"READ /public/a.txt\r\n", 1, True),
    (b"READ /secret\r\n", 1, False),
    (b"HALT\r\n", 3, True),
    (b"HALT\r\n", 9, False),     # remote 9 not in [1, 3]
    (b"WRITE /tmp/x\r\n", 9, True),
    (b"WRITE /etc/x\r\n", 1, False),
    (b"READ notes.txt\r\n", 5, True),
]


def _start(tmp_path, name, **cfg_kw):
    defaults = dict(
        batch_flows=64, dispatch_mode="jit",
        mesh="on", mesh_rule_shards=2,
        device_reprobe_interval_s=1e9,
    )
    defaults.update(cfg_kw)
    cfg = DaemonConfig(**defaults)
    svc = VerdictService(str(tmp_path / f"{name}.sock"), cfg).start()
    client = SidecarClient(svc.socket_path, timeout=120.0)
    mod = client.open_module([])
    assert mod != 0
    assert client.policy_update(mod, [_policy()]) == int(FilterResult.OK)
    return svc, client, mod


def _conn(client, mod, conn_id, remote):
    res, shim = client.new_connection(
        mod, "r2d2", conn_id, True, remote, 2,
        f"1.1.1.{conn_id}:{1000 + conn_id}", "2.2.2.2:80", "mesh-pol",
    )
    assert res == int(FilterResult.OK)
    return shim


def _drive(client, mod, base_cid=1):
    """Serve TRAFFIC one conn per (frame, remote); returns outputs."""
    outs = []
    for i, (frame, remote, _want) in enumerate(TRAFFIC):
        shim = _conn(client, mod, base_cid + i, remote)
        res, out = shim.on_io(False, frame)
        assert res == int(FilterResult.OK)
        outs.append(out)
        shim.close()
    return outs


def test_mesh_service_serves_sharded_bit_identical(tmp_path):
    """Greedy mesh service: engine model IS the sharded wrapper, the
    status surface names the layout, and every verdict matches both
    the policy truth and a single-chip control service byte-for-byte."""
    inst.reset_module_registry()
    svc = client = ctrl = cctl = None
    try:
        svc, client, mod = _start(tmp_path, "mesh",
                                  batch_timeout_ms=0.0)
        mesh_outs = _drive(client, mod)
        eng = next(iter(svc._engines.values()))
        assert isinstance(eng.model, ShardedVerdictModel)
        assert eng.model.n_shards == 2
        st = svc.status()["mesh"]
        assert st == {
            "devices": 8, "flow_shards": 4, "rule_shards": 2,
            "active": True, "demoted": None, "demotions": {},
            "repromotions": 0, "rebind_rebuilds": 0,
        }
        # Single-chip control, same traffic.
        inst.reset_module_registry()
        ctrl, cctl, cmod = _start(tmp_path, "ctrl",
                                  batch_timeout_ms=0.0, mesh="off")
        ctrl_outs = _drive(cctl, cmod)
        assert ctrl.status()["mesh"] is None
        assert mesh_outs == ctrl_outs
        for out, (frame, _r, want) in zip(mesh_outs, TRAFFIC):
            assert (out == frame) == want, (frame, out)
    finally:
        for c in (client, cctl):
            if c is not None:
                c.close()
        for s in (svc, ctrl):
            if s is not None:
                s.stop()
        inst.reset_module_registry()


def test_mesh_columnar_lane_parity(tmp_path):
    """Pipelined mesh service: split frames + multi-entry rounds ride
    the columnar reassembly lane, whose bucket issue dispatches the
    SHARDED step (fixed power-of-two buckets shard the batch axis with
    no new jit shapes).  Verdicts match the policy truth and the lane
    actually ran (rounds > 0 — a silent scalar fallback cannot pass)."""
    inst.reset_module_registry()
    svc = client = None
    try:
        svc, client, mod = _start(
            tmp_path, "mesh-col", batch_timeout_ms=2.0,
            batch_width=64, reasm_min_entries=1,
        )
        shims = {
            cid: _conn(client, mod, cid, 1) for cid in (1, 2, 3, 4)
        }
        got: dict = {}
        evt = threading.Event()

        def cb(vb):
            got[vb.seq] = [vb.entry(i) for i in range(vb.count)]
            evt.set()

        client.verdict_callback = cb

        def send(seq, entries):
            cids = np.array([e[0] for e in entries], np.uint64)
            fl = np.array([e[1] for e in entries], np.uint8)
            lens = np.array([len(e[2]) for e in entries], np.uint32)
            client.send_batch(
                seq, cids, fl, lens, b"".join(e[2] for e in entries)
            )

        def wait_for(seq):
            deadline = time.monotonic() + 90
            while seq not in got and time.monotonic() < deadline:
                evt.wait(0.5)
                evt.clear()
            assert seq in got, sorted(got)

        # Round 1: four split-frame heads (buffered, no verdict yet).
        # Answered before round 2 is sent — two batches racing into
        # ONE dispatcher round would make every conn a duplicate,
        # which (correctly) routes the round scalar.
        send(1, [(1, 0, b"READ /pub"), (2, 0, b"READ /sec"),
                 (3, 0, b"HALT"), (4, 0, b"WRITE /tm")])
        wait_for(1)
        # Round 2: the tails complete all four frames.
        send(2, [(1, 0, b"lic/a.txt\r\n"), (2, 0, b"ret\r\n"),
                 (3, 0, b"\r\n"), (4, 0, b"p/x\r\n")])
        wait_for(2)
        # Tail round: PASS/DROP per conn in the oracle's op shapes.
        by_cid = {e[0]: e for e in got[2]}
        from cilium_tpu.proxylib.types import DROP, PASS

        def first_op(cid):
            return by_cid[cid][2][0][0]

        assert first_op(1) == int(PASS)
        assert first_op(2) == int(DROP)
        assert first_op(3) == int(PASS)
        assert first_op(4) == int(PASS)
        st = svc.status()
        assert st["reasm"] is not None and st["reasm"]["rounds"] > 0, (
            st["reasm"]
        )
        assert st["mesh"]["active"]
        eng = next(iter(svc._engines.values()))
        assert isinstance(eng.model, ShardedVerdictModel)
    finally:
        if client is not None:
            client.close()
        if svc is not None:
            svc.stop()
        inst.reset_module_registry()


def test_mesh_flowlog_attribution_matches_host_walk(tmp_path):
    """Flow records from the mesh path carry GLOBAL rule ids: each
    allowed record's (rule_id, match_kind) equals the host oracle
    walk's first match over the same frame."""
    from cilium_tpu.proxylib.parsers.r2d2 import R2d2RequestData

    inst.reset_module_registry()
    svc = client = None
    try:
        svc, client, mod = _start(tmp_path, "mesh-attr",
                                  batch_timeout_ms=0.0)
        _drive(client, mod)
        ins = inst.find_instance(mod)
        pi = ins.policy_map()["mesh-pol"]
        eng = next(iter(svc._engines.values()))
        kinds = eng.model.match_kinds
        # Record emission is asynchronous to the verdict reply; poll
        # until the allowed rows all landed (bounded).
        want_allowed = sum(1 for _f, _r, w in TRAFFIC if w)
        deadline = time.monotonic() + 10
        recs = []
        while time.monotonic() < deadline:
            recs = [
                r for r in svc.flowlog.query(n=10000)
                if r.get("rule_id", -1) >= 0
            ]
            if len(recs) >= want_allowed:
                break
            time.sleep(0.05)
        assert len(recs) >= want_allowed, recs
        frames = {
            i + 1: (f, r) for i, (f, r, _w) in enumerate(TRAFFIC)
        }
        checked = 0
        for rec in recs:
            frame, remote = frames[rec["conn_id"]]
            parts = frame[:-2].decode().split(" ")
            l7 = R2d2RequestData(
                parts[0], parts[1] if len(parts) > 1 else ""
            )
            hok, hrule = pi.matches_at(True, 80, remote, l7)
            assert hok
            assert rec["rule_id"] == hrule, (frame, rec, hrule)
            assert rec["match_kind"] == kinds[hrule], (frame, rec)
            checked += 1
        assert checked >= want_allowed
    finally:
        if client is not None:
            client.close()
        if svc is not None:
            svc.stop()
        inst.reset_module_registry()


def test_http_sidecar_lane_serves_sharded_and_demotes(tmp_path):
    """The l7 (HTTP) judge routes through the service's dispatch —
    shared jit caches AND the mesh rung: a raising sharded dispatch
    demotes typed and the round is answered from the single-chip
    fallback, not host-judged forever through crash containment."""
    inst.reset_module_registry()
    svc = client = None
    try:
        pol = NetworkPolicy(
            name="http-mesh", policy=2,
            ingress_per_port_policies=[
                PortNetworkPolicy(port=80, rules=[
                    PortNetworkPolicyRule(http_rules=[
                        {"method": "GET", "path": "/public/.*"},
                        {"method": "POST", "path": "/api/.*"},
                    ])
                ])
            ],
        )
        cfg = DaemonConfig(
            batch_flows=64, batch_timeout_ms=0.0, dispatch_mode="jit",
            mesh="on", mesh_rule_shards=2,
            device_reprobe_interval_s=1e9,
        )
        svc = VerdictService(
            str(tmp_path / "http-mesh.sock"), cfg
        ).start()
        client = SidecarClient(svc.socket_path, timeout=120.0)
        mod = client.open_module([])
        assert client.policy_update(mod, [pol]) == int(FilterResult.OK)
        res, shim = client.new_connection(
            mod, "http", 9, True, 1, 2, "1.1.1.9:1009", "2.2.2.2:80",
            "http-mesh",
        )
        assert res == int(FilterResult.OK)
        ok_req = b"GET /public/a HTTP/1.1\r\n\r\n"
        res, out = shim.on_io(False, ok_req)
        assert res == int(FilterResult.OK) and out == ok_req
        eng = next(
            e for k, e in svc._engines.items() if k[4] == "http"
        )
        assert isinstance(eng.model, ShardedVerdictModel)

        orig = svc._jit_for

        def lost_device(cache, model, trace_fn, arg_fn=None):
            if isinstance(model, ShardedVerdictModel):
                def boom(*_a, **_k):
                    raise RuntimeError("PJRT_Error: device lost")

                return boom
            return orig(cache, model, trace_fn, arg_fn)

        svc._jit_for = lost_device
        res, out = shim.on_io(False, b"POST /api/x HTTP/1.1\r\n\r\n")
        assert res == int(FilterResult.OK)
        assert out == b"POST /api/x HTTP/1.1\r\n\r\n"
        st = svc.status()
        assert st["mesh"]["demoted"] == "device-call"
        assert not isinstance(eng.model, ShardedVerdictModel)
        res, out = shim.on_io(False, b"DELETE /x HTTP/1.1\r\n\r\n")
        assert out != b"DELETE /x HTTP/1.1\r\n\r\n"  # still denying
        assert st["containment"]["batch_crashes"] == 0
        assert svc.fallback_entries == 0  # never host-judged rounds
    finally:
        if client is not None:
            client.close()
        if svc is not None:
            svc.stop()
        inst.reset_module_registry()


def test_daemon_engine_factory_builds_sharded_and_demotes():
    """The daemon-side factory path: build_model_for_filter with a
    mesh returns the sharded wrappers (http + kafka), the runtime
    engines serve them bit-identically, and the engine-level judge
    rung demotes a dead sharded model to its fallback typed instead
    of crashing the step."""
    import jax

    from cilium_tpu.labels import Labels
    from cilium_tpu.models.builder import build_model_for_filter
    from cilium_tpu.parallel.mesh import RULE_AXIS, serving_mesh
    from cilium_tpu.parallel.rulesharding import ShardedKafkaModel
    from cilium_tpu.policy.api import (
        EndpointSelector,
        L7Rules,
        PortRuleHTTP,
        PortRuleKafka,
    )
    from cilium_tpu.policy.l4 import (
        L4Filter,
        L7DataMap,
        PARSER_TYPE_HTTP,
        PARSER_TYPE_KAFKA,
    )
    from cilium_tpu.proxylib.types import DROP, PASS
    from cilium_tpu.runtime.engines import (
        HttpBatchEngine,
        KafkaBatchEngine,
        _daemon_mesh,
    )

    mesh = serving_mesh("on", rule_shards=2, devices=jax.devices())
    assert mesh is not None and mesh.shape[RULE_AXIS] == 2

    # _daemon_mesh resolves from config once and caches on the daemon.
    class _Daemon:
        config = DaemonConfig(mesh="on", mesh_rule_shards=2)

    d = _Daemon()
    got = _daemon_mesh(d)
    assert got is not None and got.shape[RULE_AXIS] == 2
    assert d.verdict_mesh is got
    assert _daemon_mesh(d) is got  # cached

    identity_cache = {7: Labels.from_model(["k8s:app=web"])}
    sel = EndpointSelector.from_dict({"k8s:app": "web"})
    dm = L7DataMap()
    dm[sel] = L7Rules(http=[PortRuleHTTP(method="GET", path="/ok/.*")])
    f = L4Filter(port=80, protocol="TCP", l7_parser=PARSER_TYPE_HTTP,
                 l7_rules_per_ep=dm)
    model = build_model_for_filter(f, identity_cache, mesh=mesh)
    assert isinstance(model, ShardedVerdictModel)
    eng = HttpBatchEngine(model)
    req = b"GET /ok/x HTTP/1.1\r\n\r\n"
    eng.feed(1, req, remote_id=7)
    eng.feed(2, req, remote_id=99)
    eng.pump()
    assert eng.take_ops(1)[0] == [(PASS, len(req))]
    assert eng.take_ops(2)[0][0][0] == int(DROP)

    # Engine-level mesh rung: a dead sharded model demotes in-step.
    class _DeadSharded:
        def __init__(self, fallback):
            self.fallback = fallback

        def __call__(self, *_a, **_k):
            raise RuntimeError("PJRT_Error: device lost")

        def verdicts_attr(self, *_a, **_k):
            raise RuntimeError("PJRT_Error: device lost")

    eng.model = _DeadSharded(model.fallback)
    eng.feed(3, req, remote_id=7)
    eng.pump()
    assert eng.take_ops(3)[0] == [(PASS, len(req))]
    assert eng.model is model.fallback  # demoted, typed, serving

    # Kafka wrapper through the same factory.
    kr = PortRuleKafka(topic="orders", role="produce")
    kr.sanitize()
    dmk = L7DataMap()
    dmk[sel] = L7Rules(kafka=[kr])
    fk = L4Filter(port=9092, protocol="TCP",
                  l7_parser=PARSER_TYPE_KAFKA, l7_rules_per_ep=dmk)
    kmodel = build_model_for_filter(fk, identity_cache, mesh=mesh)
    assert isinstance(kmodel, ShardedKafkaModel)
    from test_kafka import produce_request

    keng = KafkaBatchEngine(kmodel)
    ok = produce_request(["orders"])
    bad = produce_request(["secret"])
    keng.feed(1, ok, remote_id=7)
    keng.feed(2, bad, remote_id=7)
    keng.pump()
    assert keng.take_ops(1)[0] == [(PASS, len(ok))]
    assert keng.take_ops(2)[0][0][0] == int(DROP)


def test_device_loss_demotes_typed_zero_silent_loss(tmp_path):
    """Fault injection at the executable layer (how a lost mesh device
    actually surfaces: the compiled sharded dispatch raises): the
    in-flight round is answered from the single-chip fallback in the
    SAME round, the demotion is typed and status-surfaced, subsequent
    traffic serves bit-identically, and nothing is shed, crashed, or
    left unanswered."""
    inst.reset_module_registry()
    svc = client = None
    try:
        svc, client, mod = _start(tmp_path, "mesh-loss",
                                  batch_timeout_ms=0.0)
        shim = _conn(client, mod, 50, 1)
        res, out = shim.on_io(False, b"READ /public/a.txt\r\n")
        assert out == b"READ /public/a.txt\r\n"

        orig = svc._jit_for

        def lost_device(cache, model, trace_fn, arg_fn=None):
            if isinstance(model, ShardedVerdictModel):
                def boom(*_a, **_k):
                    raise RuntimeError("PJRT_Error: device lost")

                return boom
            return orig(cache, model, trace_fn, arg_fn)

        svc._jit_for = lost_device
        # The round that hits the dead mesh is still answered — with
        # the CORRECT verdict, from the fallback executable.
        res, out = shim.on_io(False, b"HALT\r\n")
        assert res == int(FilterResult.OK) and out == b"HALT\r\n"
        st = svc.status()
        assert st["mesh"]["demoted"] == "device-call"
        assert st["mesh"]["demotions"] == {"device-call": 1}
        assert st["mesh"]["active"] is False
        # Engines flipped to the single-chip executable.
        eng = next(iter(svc._engines.values()))
        assert not isinstance(eng.model, ShardedVerdictModel)
        # Still serving, still bit-identical, nothing lost.
        for frame, remote, want in TRAFFIC:
            s2 = _conn(client, mod, 60 + remote, remote)
            res, out = s2.on_io(False, frame)
            assert res == int(FilterResult.OK)
            assert (out == frame) == want, (frame, out)
            s2.close()
        st = svc.status()
        assert st["containment"]["shed_entries"] == 0
        assert st["containment"]["batch_crashes"] == 0
        assert st["containment"]["error_entries"] == 0
        # Sticky: one demotion, not one per round.
        assert st["mesh"]["demotions"] == {"device-call": 1}
        # New engine builds while demoted are single-chip.
        assert svc._serving_mesh() is None
    finally:
        if client is not None:
            client.close()
        if svc is not None:
            svc.stop()
        inst.reset_module_registry()


def test_mesh_repromotes_after_heal_bit_identical(tmp_path):
    """Guarded re-promotion (ROADMAP 1b): after a demotion, the timed
    off-path re-probe rebuilds a sharded executable, parity-probes it
    against the single-chip fallback, and flips the retained sharded
    wrappers back — typed (mesh_repromotions_total), traffic-driven
    pacing, and the healed mesh serves bit-identically."""
    inst.reset_module_registry()
    svc = client = None
    try:
        svc, client, mod = _start(
            tmp_path, "mesh-heal", batch_timeout_ms=0.0,
            mesh_reprobe_interval_s=0.05,
        )
        shim = _conn(client, mod, 50, 1)
        res, out = shim.on_io(False, b"READ /public/a.txt\r\n")
        assert out == b"READ /public/a.txt\r\n"

        orig = svc._jit_for

        def lost_device(cache, model, trace_fn, arg_fn=None):
            if isinstance(model, ShardedVerdictModel):
                def boom(*_a, **_k):
                    raise RuntimeError("PJRT_Error: device lost")

                return boom
            return orig(cache, model, trace_fn, arg_fn)

        svc._jit_for = lost_device
        res, out = shim.on_io(False, b"HALT\r\n")
        assert res == int(FilterResult.OK) and out == b"HALT\r\n"
        assert svc.status()["mesh"]["demoted"] == "device-call"
        # Device heals: the fault injection is removed.  The next
        # paced re-probe (traffic-driven, like the quarantine heal)
        # must rebuild + parity-probe off-path and flip back.
        svc._jit_for = orig
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            res, out = shim.on_io(False, b"HALT\r\n")
            assert res == int(FilterResult.OK) and out == b"HALT\r\n"
            if svc.status()["mesh"]["active"]:
                break
            time.sleep(0.05)
        st = svc.status()
        assert st["mesh"]["active"] is True, st["mesh"]
        assert st["mesh"]["demoted"] is None
        assert st["mesh"]["repromotions"] == 1
        # Engines flipped BACK to the sharded wrappers.
        eng = next(iter(svc._engines.values()))
        assert isinstance(eng.model, ShardedVerdictModel)
        # New builds shard again.
        assert svc._serving_mesh() is not None
        # Bit-identical service on the re-promoted mesh, nothing lost.
        for i, (frame, remote, want) in enumerate(TRAFFIC):
            s2 = _conn(client, mod, 70 + i, remote)
            res, out = s2.on_io(False, frame)
            assert res == int(FilterResult.OK)
            assert (out == frame) == want, (frame, out)
            s2.close()
        st = svc.status()
        assert st["containment"]["shed_entries"] == 0
        assert st["containment"]["batch_crashes"] == 0
        assert st["containment"]["error_entries"] == 0
        # A second loss after the heal demotes AGAIN, typed — the
        # rung stays re-entrant, never a crashed round.
        svc._jit_for = lost_device
        res, out = shim.on_io(False, b"HALT\r\n")
        assert res == int(FilterResult.OK) and out == b"HALT\r\n"
        assert svc.status()["mesh"]["demotions"]["device-call"] == 2
    finally:
        if client is not None:
            client.close()
        if svc is not None:
            svc.stop()
        inst.reset_module_registry()
