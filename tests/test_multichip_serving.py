"""Multi-chip sharded verdict serving on the LIVE dispatch path.

The service builds mesh-resident models (parallel/rulesharding.py
ShardedVerdictModel: rule rows split-balanced across RULE_AXIS, flow
batches sharded across FLOW_AXIS) and serves every lane — vec, fast
entry, columnar reassembly — through the sharded steps.  Contracts
pinned here, on the conftest 8-device CPU mesh:

- **Bit-identity.**  A mesh service answers byte-identically to the
  single-chip service for the same traffic, including denials with
  injected error replies, and its flow records carry the SAME global
  rule ids and match kinds the host oracle walk names (shard-local
  argmax + cross-shard min-index reduction).
- **Columnar lane.**  The reassembler's bucket issue routes through
  the sharded step with no new jit shapes (fixed power-of-two buckets
  divide the flow axis by construction).
- **Fail-closed degradation.**  A lost/erroring mesh device demotes
  the service to the single-chip fallback executable — typed
  (mesh_demotions_total{reason}), counted, status-surfaced — with
  zero silent loss and bit-identical verdicts after the flip.
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np
import pytest

from cilium_tpu.parallel.rulesharding import ShardedVerdictModel
from cilium_tpu.proxylib import (
    FilterResult,
    NetworkPolicy,
    PortNetworkPolicy,
    PortNetworkPolicyRule,
)
from cilium_tpu.proxylib import instance as inst
from cilium_tpu.sidecar import SidecarClient, VerdictService
from cilium_tpu.utils.option import DaemonConfig

POLICY_RULES = [
    {"cmd": "READ", "file": "/public/.*"},
    {"cmd": "HALT"},
    {"cmd": "WRITE", "file": "^/tmp/"},
    {"file": "\\.txt$"},
]


def _policy(name="mesh-pol"):
    return NetworkPolicy(
        name=name,
        policy=2,
        ingress_per_port_policies=[
            PortNetworkPolicy(
                port=80,
                rules=[
                    PortNetworkPolicyRule(
                        remote_policies=[1, 3],
                        l7_proto="r2d2",
                        l7_rules=POLICY_RULES[:2],
                    ),
                    PortNetworkPolicyRule(
                        l7_proto="r2d2", l7_rules=POLICY_RULES[2:]
                    ),
                ],
            )
        ],
    )


# (frame, remote) -> allowed under the policy above.
TRAFFIC = [
    (b"READ /public/a.txt\r\n", 1, True),
    (b"READ /secret\r\n", 1, False),
    (b"HALT\r\n", 3, True),
    (b"HALT\r\n", 9, False),     # remote 9 not in [1, 3]
    (b"WRITE /tmp/x\r\n", 9, True),
    (b"WRITE /etc/x\r\n", 1, False),
    (b"READ notes.txt\r\n", 5, True),
]


def _start(tmp_path, name, **cfg_kw):
    defaults = dict(
        batch_flows=64, dispatch_mode="jit",
        mesh="on", mesh_rule_shards=2,
        device_reprobe_interval_s=1e9,
    )
    defaults.update(cfg_kw)
    cfg = DaemonConfig(**defaults)
    svc = VerdictService(str(tmp_path / f"{name}.sock"), cfg).start()
    client = SidecarClient(svc.socket_path, timeout=120.0)
    mod = client.open_module([])
    assert mod != 0
    assert client.policy_update(mod, [_policy()]) == int(FilterResult.OK)
    return svc, client, mod


def _conn(client, mod, conn_id, remote):
    res, shim = client.new_connection(
        mod, "r2d2", conn_id, True, remote, 2,
        f"1.1.1.{conn_id}:{1000 + conn_id}", "2.2.2.2:80", "mesh-pol",
    )
    assert res == int(FilterResult.OK)
    return shim


def _drive(client, mod, base_cid=1):
    """Serve TRAFFIC one conn per (frame, remote); returns outputs."""
    outs = []
    for i, (frame, remote, _want) in enumerate(TRAFFIC):
        shim = _conn(client, mod, base_cid + i, remote)
        res, out = shim.on_io(False, frame)
        assert res == int(FilterResult.OK)
        outs.append(out)
        shim.close()
    return outs


def test_mesh_service_serves_sharded_bit_identical(tmp_path):
    """Greedy mesh service: engine model IS the sharded wrapper, the
    status surface names the layout, and every verdict matches both
    the policy truth and a single-chip control service byte-for-byte."""
    inst.reset_module_registry()
    svc = client = ctrl = cctl = None
    try:
        svc, client, mod = _start(tmp_path, "mesh",
                                  batch_timeout_ms=0.0)
        mesh_outs = _drive(client, mod)
        eng = next(iter(svc._engines.values()))
        assert isinstance(eng.model, ShardedVerdictModel)
        assert eng.model.n_shards == 2
        st = svc.status()["mesh"]
        assert st == {
            "devices": 8, "flow_shards": 4, "rule_shards": 2,
            "active": True, "demoted": None, "demotions": {},
            "repromotions": 0, "rebind_rebuilds": 0,
            # Width-ladder surface (PR 17): full rung, nothing lost.
            "rung": "full", "serving_devices": 8, "lost_devices": [],
            "reshapes": 0, "reshape_failures": {},
            "capacity_frac": 1.0, "reshape_window_ms": 0.0,
        }
        # Single-chip control, same traffic.
        inst.reset_module_registry()
        ctrl, cctl, cmod = _start(tmp_path, "ctrl",
                                  batch_timeout_ms=0.0, mesh="off")
        ctrl_outs = _drive(cctl, cmod)
        assert ctrl.status()["mesh"] is None
        assert mesh_outs == ctrl_outs
        for out, (frame, _r, want) in zip(mesh_outs, TRAFFIC):
            assert (out == frame) == want, (frame, out)
    finally:
        for c in (client, cctl):
            if c is not None:
                c.close()
        for s in (svc, ctrl):
            if s is not None:
                s.stop()
        inst.reset_module_registry()


def test_mesh_columnar_lane_parity(tmp_path):
    """Pipelined mesh service: split frames + multi-entry rounds ride
    the columnar reassembly lane, whose bucket issue dispatches the
    SHARDED step (fixed power-of-two buckets shard the batch axis with
    no new jit shapes).  Verdicts match the policy truth and the lane
    actually ran (rounds > 0 — a silent scalar fallback cannot pass)."""
    inst.reset_module_registry()
    svc = client = None
    try:
        svc, client, mod = _start(
            tmp_path, "mesh-col", batch_timeout_ms=2.0,
            batch_width=64, reasm_min_entries=1,
        )
        shims = {
            cid: _conn(client, mod, cid, 1) for cid in (1, 2, 3, 4)
        }
        got: dict = {}
        evt = threading.Event()

        def cb(vb):
            got[vb.seq] = [vb.entry(i) for i in range(vb.count)]
            evt.set()

        client.verdict_callback = cb

        def send(seq, entries):
            cids = np.array([e[0] for e in entries], np.uint64)
            fl = np.array([e[1] for e in entries], np.uint8)
            lens = np.array([len(e[2]) for e in entries], np.uint32)
            client.send_batch(
                seq, cids, fl, lens, b"".join(e[2] for e in entries)
            )

        def wait_for(seq):
            deadline = time.monotonic() + 90
            while seq not in got and time.monotonic() < deadline:
                evt.wait(0.5)
                evt.clear()
            assert seq in got, sorted(got)

        # Round 1: four split-frame heads (buffered, no verdict yet).
        # Answered before round 2 is sent — two batches racing into
        # ONE dispatcher round would make every conn a duplicate,
        # which (correctly) routes the round scalar.
        send(1, [(1, 0, b"READ /pub"), (2, 0, b"READ /sec"),
                 (3, 0, b"HALT"), (4, 0, b"WRITE /tm")])
        wait_for(1)
        # Round 2: the tails complete all four frames.
        send(2, [(1, 0, b"lic/a.txt\r\n"), (2, 0, b"ret\r\n"),
                 (3, 0, b"\r\n"), (4, 0, b"p/x\r\n")])
        wait_for(2)
        # Tail round: PASS/DROP per conn in the oracle's op shapes.
        by_cid = {e[0]: e for e in got[2]}
        from cilium_tpu.proxylib.types import DROP, PASS

        def first_op(cid):
            return by_cid[cid][2][0][0]

        assert first_op(1) == int(PASS)
        assert first_op(2) == int(DROP)
        assert first_op(3) == int(PASS)
        assert first_op(4) == int(PASS)
        st = svc.status()
        assert st["reasm"] is not None and st["reasm"]["rounds"] > 0, (
            st["reasm"]
        )
        assert st["mesh"]["active"]
        eng = next(iter(svc._engines.values()))
        assert isinstance(eng.model, ShardedVerdictModel)
    finally:
        if client is not None:
            client.close()
        if svc is not None:
            svc.stop()
        inst.reset_module_registry()


def test_mesh_flowlog_attribution_matches_host_walk(tmp_path):
    """Flow records from the mesh path carry GLOBAL rule ids: each
    allowed record's (rule_id, match_kind) equals the host oracle
    walk's first match over the same frame."""
    from cilium_tpu.proxylib.parsers.r2d2 import R2d2RequestData

    inst.reset_module_registry()
    svc = client = None
    try:
        svc, client, mod = _start(tmp_path, "mesh-attr",
                                  batch_timeout_ms=0.0)
        _drive(client, mod)
        ins = inst.find_instance(mod)
        pi = ins.policy_map()["mesh-pol"]
        eng = next(iter(svc._engines.values()))
        kinds = eng.model.match_kinds
        # Record emission is asynchronous to the verdict reply; poll
        # until the allowed rows all landed (bounded).
        want_allowed = sum(1 for _f, _r, w in TRAFFIC if w)
        deadline = time.monotonic() + 10
        recs = []
        while time.monotonic() < deadline:
            recs = [
                r for r in svc.flowlog.query(n=10000)
                if r.get("rule_id", -1) >= 0
            ]
            if len(recs) >= want_allowed:
                break
            time.sleep(0.05)
        assert len(recs) >= want_allowed, recs
        frames = {
            i + 1: (f, r) for i, (f, r, _w) in enumerate(TRAFFIC)
        }
        checked = 0
        for rec in recs:
            frame, remote = frames[rec["conn_id"]]
            parts = frame[:-2].decode().split(" ")
            l7 = R2d2RequestData(
                parts[0], parts[1] if len(parts) > 1 else ""
            )
            hok, hrule = pi.matches_at(True, 80, remote, l7)
            assert hok
            assert rec["rule_id"] == hrule, (frame, rec, hrule)
            assert rec["match_kind"] == kinds[hrule], (frame, rec)
            checked += 1
        assert checked >= want_allowed
    finally:
        if client is not None:
            client.close()
        if svc is not None:
            svc.stop()
        inst.reset_module_registry()


def test_http_sidecar_lane_serves_sharded_and_demotes(tmp_path):
    """The l7 (HTTP) judge routes through the service's dispatch —
    shared jit caches AND the mesh rung: a raising sharded dispatch
    demotes typed and the round is answered from the single-chip
    fallback, not host-judged forever through crash containment."""
    inst.reset_module_registry()
    svc = client = None
    try:
        pol = NetworkPolicy(
            name="http-mesh", policy=2,
            ingress_per_port_policies=[
                PortNetworkPolicy(port=80, rules=[
                    PortNetworkPolicyRule(http_rules=[
                        {"method": "GET", "path": "/public/.*"},
                        {"method": "POST", "path": "/api/.*"},
                    ])
                ])
            ],
        )
        cfg = DaemonConfig(
            batch_flows=64, batch_timeout_ms=0.0, dispatch_mode="jit",
            mesh="on", mesh_rule_shards=2,
            device_reprobe_interval_s=1e9,
        )
        svc = VerdictService(
            str(tmp_path / "http-mesh.sock"), cfg
        ).start()
        client = SidecarClient(svc.socket_path, timeout=120.0)
        mod = client.open_module([])
        assert client.policy_update(mod, [pol]) == int(FilterResult.OK)
        res, shim = client.new_connection(
            mod, "http", 9, True, 1, 2, "1.1.1.9:1009", "2.2.2.2:80",
            "http-mesh",
        )
        assert res == int(FilterResult.OK)
        ok_req = b"GET /public/a HTTP/1.1\r\n\r\n"
        res, out = shim.on_io(False, ok_req)
        assert res == int(FilterResult.OK) and out == ok_req
        eng = next(
            e for k, e in svc._engines.items() if k[4] == "http"
        )
        assert isinstance(eng.model, ShardedVerdictModel)

        orig = svc._jit_for

        def lost_device(cache, model, trace_fn, arg_fn=None):
            if isinstance(model, ShardedVerdictModel):
                def boom(*_a, **_k):
                    raise RuntimeError("PJRT_Error: device lost")

                return boom
            return orig(cache, model, trace_fn, arg_fn)

        svc._jit_for = lost_device
        res, out = shim.on_io(False, b"POST /api/x HTTP/1.1\r\n\r\n")
        assert res == int(FilterResult.OK)
        assert out == b"POST /api/x HTTP/1.1\r\n\r\n"
        st = svc.status()
        assert st["mesh"]["demoted"] == "device-call"
        assert not isinstance(eng.model, ShardedVerdictModel)
        res, out = shim.on_io(False, b"DELETE /x HTTP/1.1\r\n\r\n")
        assert out != b"DELETE /x HTTP/1.1\r\n\r\n"  # still denying
        assert st["containment"]["batch_crashes"] == 0
        assert svc.fallback_entries == 0  # never host-judged rounds
    finally:
        if client is not None:
            client.close()
        if svc is not None:
            svc.stop()
        inst.reset_module_registry()


def test_daemon_engine_factory_builds_sharded_and_demotes():
    """The daemon-side factory path: build_model_for_filter with a
    mesh returns the sharded wrappers (http + kafka), the runtime
    engines serve them bit-identically, and the engine-level judge
    rung demotes a dead sharded model to its fallback typed instead
    of crashing the step."""
    import jax

    from cilium_tpu.labels import Labels
    from cilium_tpu.models.builder import build_model_for_filter
    from cilium_tpu.parallel.mesh import RULE_AXIS, serving_mesh
    from cilium_tpu.parallel.rulesharding import ShardedKafkaModel
    from cilium_tpu.policy.api import (
        EndpointSelector,
        L7Rules,
        PortRuleHTTP,
        PortRuleKafka,
    )
    from cilium_tpu.policy.l4 import (
        L4Filter,
        L7DataMap,
        PARSER_TYPE_HTTP,
        PARSER_TYPE_KAFKA,
    )
    from cilium_tpu.proxylib.types import DROP, PASS
    from cilium_tpu.runtime.engines import (
        HttpBatchEngine,
        KafkaBatchEngine,
        _daemon_mesh,
    )

    mesh = serving_mesh("on", rule_shards=2, devices=jax.devices())
    assert mesh is not None and mesh.shape[RULE_AXIS] == 2

    # _daemon_mesh resolves from config once and caches on the daemon.
    class _Daemon:
        config = DaemonConfig(mesh="on", mesh_rule_shards=2)

    d = _Daemon()
    got = _daemon_mesh(d)
    assert got is not None and got.shape[RULE_AXIS] == 2
    assert d.verdict_mesh is got
    assert _daemon_mesh(d) is got  # cached

    identity_cache = {7: Labels.from_model(["k8s:app=web"])}
    sel = EndpointSelector.from_dict({"k8s:app": "web"})
    dm = L7DataMap()
    dm[sel] = L7Rules(http=[PortRuleHTTP(method="GET", path="/ok/.*")])
    f = L4Filter(port=80, protocol="TCP", l7_parser=PARSER_TYPE_HTTP,
                 l7_rules_per_ep=dm)
    model = build_model_for_filter(f, identity_cache, mesh=mesh)
    assert isinstance(model, ShardedVerdictModel)
    eng = HttpBatchEngine(model)
    req = b"GET /ok/x HTTP/1.1\r\n\r\n"
    eng.feed(1, req, remote_id=7)
    eng.feed(2, req, remote_id=99)
    eng.pump()
    assert eng.take_ops(1)[0] == [(PASS, len(req))]
    assert eng.take_ops(2)[0][0][0] == int(DROP)

    # Engine-level mesh rung: a dead sharded model demotes in-step.
    class _DeadSharded:
        def __init__(self, fallback):
            self.fallback = fallback

        def __call__(self, *_a, **_k):
            raise RuntimeError("PJRT_Error: device lost")

        def verdicts_attr(self, *_a, **_k):
            raise RuntimeError("PJRT_Error: device lost")

    eng.model = _DeadSharded(model.fallback)
    eng.feed(3, req, remote_id=7)
    eng.pump()
    assert eng.take_ops(3)[0] == [(PASS, len(req))]
    assert eng.model is model.fallback  # demoted, typed, serving

    # Kafka wrapper through the same factory.
    kr = PortRuleKafka(topic="orders", role="produce")
    kr.sanitize()
    dmk = L7DataMap()
    dmk[sel] = L7Rules(kafka=[kr])
    fk = L4Filter(port=9092, protocol="TCP",
                  l7_parser=PARSER_TYPE_KAFKA, l7_rules_per_ep=dmk)
    kmodel = build_model_for_filter(fk, identity_cache, mesh=mesh)
    assert isinstance(kmodel, ShardedKafkaModel)
    from test_kafka import produce_request

    keng = KafkaBatchEngine(kmodel)
    ok = produce_request(["orders"])
    bad = produce_request(["secret"])
    keng.feed(1, ok, remote_id=7)
    keng.feed(2, bad, remote_id=7)
    keng.pump()
    assert keng.take_ops(1)[0] == [(PASS, len(ok))]
    assert keng.take_ops(2)[0][0][0] == int(DROP)


def test_device_loss_demotes_typed_zero_silent_loss(tmp_path):
    """Fault injection at the executable layer (how a lost mesh device
    actually surfaces: the compiled sharded dispatch raises): the
    in-flight round is answered from the single-chip fallback in the
    SAME round, the demotion is typed and status-surfaced, subsequent
    traffic serves bit-identically, and nothing is shed, crashed, or
    left unanswered."""
    inst.reset_module_registry()
    svc = client = None
    try:
        svc, client, mod = _start(tmp_path, "mesh-loss",
                                  batch_timeout_ms=0.0)
        shim = _conn(client, mod, 50, 1)
        res, out = shim.on_io(False, b"READ /public/a.txt\r\n")
        assert out == b"READ /public/a.txt\r\n"

        orig = svc._jit_for

        def lost_device(cache, model, trace_fn, arg_fn=None):
            if isinstance(model, ShardedVerdictModel):
                def boom(*_a, **_k):
                    raise RuntimeError("PJRT_Error: device lost")

                return boom
            return orig(cache, model, trace_fn, arg_fn)

        svc._jit_for = lost_device
        # The round that hits the dead mesh is still answered — with
        # the CORRECT verdict, from the fallback executable.
        res, out = shim.on_io(False, b"HALT\r\n")
        assert res == int(FilterResult.OK) and out == b"HALT\r\n"
        st = svc.status()
        assert st["mesh"]["demoted"] == "device-call"
        assert st["mesh"]["demotions"] == {"device-call": 1}
        assert st["mesh"]["active"] is False
        # Engines flipped to the single-chip executable.
        eng = next(iter(svc._engines.values()))
        assert not isinstance(eng.model, ShardedVerdictModel)
        # Still serving, still bit-identical, nothing lost.
        for frame, remote, want in TRAFFIC:
            s2 = _conn(client, mod, 60 + remote, remote)
            res, out = s2.on_io(False, frame)
            assert res == int(FilterResult.OK)
            assert (out == frame) == want, (frame, out)
            s2.close()
        st = svc.status()
        assert st["containment"]["shed_entries"] == 0
        assert st["containment"]["batch_crashes"] == 0
        assert st["containment"]["error_entries"] == 0
        # Sticky: one demotion, not one per round.
        assert st["mesh"]["demotions"] == {"device-call": 1}
        # New engine builds while demoted are single-chip.
        assert svc._serving_mesh() is None
    finally:
        if client is not None:
            client.close()
        if svc is not None:
            svc.stop()
        inst.reset_module_registry()


def test_mesh_repromotes_after_heal_bit_identical(tmp_path):
    """Guarded re-promotion (ROADMAP 1b): after a demotion, the timed
    off-path re-probe rebuilds a sharded executable, parity-probes it
    against the single-chip fallback, and flips the retained sharded
    wrappers back — typed (mesh_repromotions_total), traffic-driven
    pacing, and the healed mesh serves bit-identically."""
    inst.reset_module_registry()
    svc = client = None
    try:
        svc, client, mod = _start(
            tmp_path, "mesh-heal", batch_timeout_ms=0.0,
            mesh_reprobe_interval_s=0.05,
        )
        shim = _conn(client, mod, 50, 1)
        res, out = shim.on_io(False, b"READ /public/a.txt\r\n")
        assert out == b"READ /public/a.txt\r\n"

        orig = svc._jit_for

        def lost_device(cache, model, trace_fn, arg_fn=None):
            if isinstance(model, ShardedVerdictModel):
                def boom(*_a, **_k):
                    raise RuntimeError("PJRT_Error: device lost")

                return boom
            return orig(cache, model, trace_fn, arg_fn)

        svc._jit_for = lost_device
        res, out = shim.on_io(False, b"HALT\r\n")
        assert res == int(FilterResult.OK) and out == b"HALT\r\n"
        assert svc.status()["mesh"]["demoted"] == "device-call"
        # Device heals: the fault injection is removed.  The next
        # paced re-probe (traffic-driven, like the quarantine heal)
        # must rebuild + parity-probe off-path and flip back.
        svc._jit_for = orig
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            res, out = shim.on_io(False, b"HALT\r\n")
            assert res == int(FilterResult.OK) and out == b"HALT\r\n"
            if svc.status()["mesh"]["active"]:
                break
            time.sleep(0.05)
        st = svc.status()
        assert st["mesh"]["active"] is True, st["mesh"]
        assert st["mesh"]["demoted"] is None
        assert st["mesh"]["repromotions"] == 1
        # Engines flipped BACK to the sharded wrappers.
        eng = next(iter(svc._engines.values()))
        assert isinstance(eng.model, ShardedVerdictModel)
        # New builds shard again.
        assert svc._serving_mesh() is not None
        # Bit-identical service on the re-promoted mesh, nothing lost.
        for i, (frame, remote, want) in enumerate(TRAFFIC):
            s2 = _conn(client, mod, 70 + i, remote)
            res, out = s2.on_io(False, frame)
            assert res == int(FilterResult.OK)
            assert (out == frame) == want, (frame, out)
            s2.close()
        st = svc.status()
        assert st["containment"]["shed_entries"] == 0
        assert st["containment"]["batch_crashes"] == 0
        assert st["containment"]["error_entries"] == 0
        # A second loss after the heal demotes AGAIN, typed — the
        # rung stays re-entrant, never a crashed round.
        svc._jit_for = lost_device
        res, out = shim.on_io(False, b"HALT\r\n")
        assert res == int(FilterResult.OK) and out == b"HALT\r\n"
        assert svc.status()["mesh"]["demotions"]["device-call"] == 2
    finally:
        if client is not None:
            client.close()
        if svc is not None:
            svc.stop()
        inst.reset_module_registry()


# --- width ladder (PR 17): shard-loss reshape --------------------------------

def _arm_named_loss(svc, dev_id):
    """One-shot sharded-dispatch fault NAMING a device (the ladder's
    attribution source) plus a probe seam marking that device dead.
    Self-disarming: the reshaped wrappers (still ShardedVerdictModel)
    must serve cleanly after the fault, so the injector restores the
    real _jit_for the moment it fires."""
    orig = svc.__class__._jit_for.__get__(svc)

    def lost_device(cache, model, trace_fn, arg_fn=None):
        if isinstance(model, ShardedVerdictModel):
            def boom(*_a, **_k):
                svc._jit_for = orig
                raise RuntimeError(
                    f"PJRT_Error: transfer to device {dev_id} failed"
                )

            return boom
        return orig(cache, model, trace_fn, arg_fn)

    svc._jit_for = lost_device
    svc._device_probe_fn = lambda dev, _d=dev_id: dev.id != _d


def _await_rung(svc, rung, client=None, mod=None, timeout=60.0,
                drive=False):
    """Wait for the builder thread's ladder walk to land on ``rung``;
    optionally keep traffic flowing (the paced re-probe is
    traffic-driven)."""
    deadline = time.monotonic() + timeout
    i = 0
    while time.monotonic() < deadline:
        st = svc.status()["mesh"]
        if st["rung"] == rung:
            return st
        if drive:
            s = _conn(client, mod, 9000 + (i % 50), 3)
            res, out = s.on_io(False, b"HALT\r\n")
            assert res == int(FilterResult.OK) and out == b"HALT\r\n"
            s.close()
            i += 1
        time.sleep(0.05)
    raise AssertionError(
        f"rung {rung!r} never reached: {svc.status()['mesh']}"
    )


def test_mesh_reshape_serves_degraded_then_repromotes(tmp_path):
    """The tentpole walk, fast-entry lane: an attributed device loss
    demotes typed, the IMMEDIATE off-path reshape flips every engine
    onto a survivor mesh (fallback covers only the rebuild window),
    the reshaped rung serves bit-identically at a published capacity
    fraction that scales admission, and the paced re-probe walks back
    UP to full width when the device heals — all counted, zero loss."""
    inst.reset_module_registry()
    svc = client = None
    try:
        svc, client, mod = _start(
            tmp_path, "mesh-reshape", batch_timeout_ms=0.0,
            mesh_reprobe_interval_s=0.05,
        )
        shim = _conn(client, mod, 50, 1)
        res, out = shim.on_io(False, b"READ /public/a.txt\r\n")
        assert out == b"READ /public/a.txt\r\n"
        full_share = svc._drr_share()

        _arm_named_loss(svc, 3)
        # The faulting round is still answered from the fallback twin
        # in the SAME round (PR 11 contract: no round waits on the
        # rebuild).
        res, out = shim.on_io(False, b"HALT\r\n")
        assert res == int(FilterResult.OK) and out == b"HALT\r\n"
        assert svc.status()["mesh"]["demotions"] == {"device-call": 1}

        st = _await_rung(svc, "reshaped")
        assert st["active"] is True and st["demoted"] is None
        assert st["lost_devices"] == [3]
        assert st["reshapes"] == 1
        assert 1 <= st["serving_devices"] < 8
        assert 0.0 < st["capacity_frac"] < 1.0
        assert st["reshape_window_ms"] > 0.0
        # Engines flipped onto the SURVIVOR mesh (sharded again, and
        # the dead device is not in the serving layout).
        eng = next(iter(svc._engines.values()))
        assert isinstance(eng.model, ShardedVerdictModel)
        serving_ids = {d.id for d in svc._mesh_serving.devices.flat}
        assert 3 not in serving_ids
        assert len(serving_ids) == st["serving_devices"]
        # Capacity-aware admission: queue cap and DRR credit windows
        # shrink to the degraded fraction.
        assert svc.dispatcher.max_pending < svc.config.shed_queue_entries
        assert svc._drr_share() <= full_share
        # Guard health table attributes the chip, typed by reason.
        table = svc.guard.device_table()
        assert table["3"]["state"] == "lost"
        assert table["3"]["faults"].get("device-call", 0) >= 1
        # Bit-identical service on the reshaped rung, nothing lost.
        for i, (frame, remote, want) in enumerate(TRAFFIC):
            s2 = _conn(client, mod, 100 + i, remote)
            res, out = s2.on_io(False, frame)
            assert res == int(FilterResult.OK)
            assert (out == frame) == want, (frame, out)
            s2.close()
        # New engine builds while reshaped shard onto the survivors.
        assert svc._serving_mesh() is svc._mesh_serving
        # Device-economics ledger (PR 20): the reshape fan-out's
        # survivor-mesh rebuilds booked under the mesh-reshape cause —
        # off-path engine builds, each stamped with the mesh layout it
        # was built against.
        reshape_evs = svc.ledger.events(n=1000, cause="mesh-reshape")
        assert reshape_evs, svc.ledger.events(n=1000)
        for ev in reshape_evs:
            assert ev["kind"] == "engine-build", ev
            assert not ev["on_dispatch_path"], ev
            assert ev["mesh"], ev

        # Heal: the paced re-probe walks back up to full width.
        svc._device_probe_fn = lambda dev: True
        st = _await_rung(svc, "full", client, mod, drive=True)
        assert st["repromotions"] == 1
        assert st["lost_devices"] == []
        assert st["capacity_frac"] == 1.0
        assert st["serving_devices"] == 8
        assert svc.dispatcher.max_pending == svc.config.shed_queue_entries
        table = svc.guard.device_table()
        assert table["3"]["state"] == "ok"
        assert table["3"]["heals"] >= 1
        # The walk back up booked its full-width rebuilds under the
        # repromotion cause — distinct in the census from both the
        # demotion-era reshape and any cold start, so the ledger alone
        # answers "what did that incident cost on-device?".
        repro_evs = svc.ledger.events(n=1000, cause="repromotion")
        assert repro_evs, svc.ledger.events(n=1000)
        for ev in repro_evs:
            assert ev["kind"] == "engine-build", ev
            assert not ev["on_dispatch_path"], ev
        by_cause = svc.ledger.status()["by_cause"]
        assert by_cause.get("mesh-reshape", 0) >= 1, by_cause
        assert by_cause.get("repromotion", 0) >= 1, by_cause
        # Full-width mesh serves bit-identically again.
        eng = next(iter(svc._engines.values()))
        assert isinstance(eng.model, ShardedVerdictModel)
        for i, (frame, remote, want) in enumerate(TRAFFIC):
            s2 = _conn(client, mod, 200 + i, remote)
            res, out = s2.on_io(False, frame)
            assert res == int(FilterResult.OK)
            assert (out == frame) == want, (frame, out)
            s2.close()
        st = svc.status()
        assert st["containment"]["shed_entries"] == 0
        assert st["containment"]["batch_crashes"] == 0
        assert st["containment"]["error_entries"] == 0
    finally:
        if client is not None:
            client.close()
        if svc is not None:
            svc.stop()
        inst.reset_module_registry()


def test_capacity_scaling_never_raises_a_small_cap(tmp_path):
    """The session_share_min floor under the capacity coupling guards
    deep degradation from starving admission — it must never RAISE an
    operator's small shed_queue_entries above its configured value
    (regression: mesh resolution at frac=1.0 once floored an 8-entry
    cap up to 64, so the overload test's queue never shed)."""
    inst.reset_module_registry()
    svc = client = None
    try:
        svc, client, mod = _start(
            tmp_path, "small-cap", shed_queue_entries=8,
        )
        shim = _conn(client, mod, 1, 3)
        res, out = shim.on_io(False, b"HALT\r\n")  # resolves the mesh
        assert res == int(FilterResult.OK) and out == b"HALT\r\n"
        assert svc.status()["mesh"]["rung"] == "full"
        assert svc.dispatcher.max_pending == 8
        # Degraded: the scaled cap floors at min(entries, share_min)
        # — bounded by the configured cap on every rung.
        _arm_named_loss(svc, 3)
        res, out = shim.on_io(False, b"HALT\r\n")
        assert res == int(FilterResult.OK) and out == b"HALT\r\n"
        _await_rung(svc, "reshaped", client, mod, drive=True)
        assert 1 <= svc.dispatcher.max_pending <= 8
    finally:
        if client is not None:
            client.close()
        if svc is not None:
            svc.stop()
        inst.reset_module_registry()


# Dispatch lanes the device-loss injection must cover (satellite 3):
# vec (pipelined single complete frames), fast-entry (greedy inline),
# columnar (_process_columnar: split frames through the reassembler),
# slow-async (engine slow path, reassembler off).  The HTTP-judge lane
# has its own test below (different protocol plumbing).
LANE_CONFIGS = {
    "vec": (dict(batch_timeout_ms=2.0), False),
    "fast-entry": (dict(batch_timeout_ms=0.0), False),
    "columnar": (
        dict(batch_timeout_ms=2.0, reasm_min_entries=1), True,
    ),
    "slow-async": (dict(batch_timeout_ms=2.0, reasm=False), True),
}


# The reassembler lanes carry two full chaos+control service pairs
# each (~10s apiece on the CPU smoke); keep tier-1 on the two cheap
# lanes and run the split-frame lanes in the slow suite.
@pytest.mark.parametrize(
    "lane",
    [
        pytest.param("columnar", marks=pytest.mark.slow),
        "fast-entry",
        pytest.param("slow-async", marks=pytest.mark.slow),
        "vec",
    ],
)
def test_mesh_reshape_per_lane_bit_identical(tmp_path, lane):
    """Every dispatch lane drives fault -> reshape -> bit-identical
    continued service -> re-promotion.  Outputs are compared against a
    single-chip control service fed the identical byte sequence — the
    ladder must be invisible in the reply stream."""
    cfg_kw, split = LANE_CONFIGS[lane]

    def run(name, mesh_mode, fault):
        inst.reset_module_registry()
        svc = client = None
        try:
            svc, client, mod = _start(
                tmp_path, name, mesh=mesh_mode,
                mesh_reprobe_interval_s=0.05, **cfg_kw,
            )
            outs = []

            def burst(base):
                for i, (frame, remote, _w) in enumerate(TRAFFIC):
                    shim = _conn(client, mod, base + i, remote)
                    if split and len(frame) > 6:
                        r1, o1 = shim.on_io(False, frame[:6])
                        assert r1 == int(FilterResult.OK)
                        r2, o2 = shim.on_io(False, frame[6:])
                        assert r2 == int(FilterResult.OK)
                        outs.append((o1, o2))
                    else:
                        r1, o1 = shim.on_io(False, frame)
                        assert r1 == int(FilterResult.OK)
                        outs.append(o1)
                    shim.close()

            burst(100)
            if fault:
                _arm_named_loss(svc, 5)
            burst(200)  # fault fires mid-burst; answered via fallback
            if fault:
                _await_rung(svc, "reshaped")
                st = svc.status()["mesh"]
                assert st["lost_devices"] == [5]
                assert st["reshapes"] == 1
            burst(300)  # reshaped rung (or full, for the control)
            if fault:
                svc._device_probe_fn = lambda dev: True
                _await_rung(svc, "full", client, mod, drive=True)
                assert svc.status()["mesh"]["repromotions"] == 1
            burst(400)  # re-promoted full width
            st = svc.status()
            if fault:
                assert st["containment"]["batch_crashes"] == 0
                assert st["containment"]["error_entries"] == 0
                assert st["containment"]["shed_entries"] == 0
            return outs
        finally:
            if client is not None:
                client.close()
            if svc is not None:
                svc.stop()
            inst.reset_module_registry()

    chaos = run(f"lane-{lane}", "on", fault=True)
    control = run(f"lane-{lane}-ctrl", "off", fault=False)
    assert chaos == control


def test_http_judge_lane_reshapes_and_repromotes(tmp_path):
    """The HTTP-judge lane walks the full ladder too: a named device
    loss mid-request demotes typed, the off-path reshape restores a
    SHARDED judge over the survivors, and the heal promotes back to
    full width — verdicts correct at every rung."""
    inst.reset_module_registry()
    svc = client = None
    try:
        pol = NetworkPolicy(
            name="http-mesh", policy=2,
            ingress_per_port_policies=[
                PortNetworkPolicy(port=80, rules=[
                    PortNetworkPolicyRule(http_rules=[
                        {"method": "GET", "path": "/public/.*"},
                        {"method": "POST", "path": "/api/.*"},
                    ])
                ])
            ],
        )
        cfg = DaemonConfig(
            batch_flows=64, batch_timeout_ms=0.0, dispatch_mode="jit",
            mesh="on", mesh_rule_shards=2,
            device_reprobe_interval_s=1e9,
            mesh_reprobe_interval_s=0.05,
        )
        svc = VerdictService(
            str(tmp_path / "http-mesh-l.sock"), cfg
        ).start()
        client = SidecarClient(svc.socket_path, timeout=120.0)
        mod = client.open_module([])
        assert client.policy_update(mod, [pol]) == int(FilterResult.OK)

        def req(cid, frame):
            res, shim = client.new_connection(
                mod, "http", cid, True, 1, 2,
                f"1.1.1.{cid}:{1000 + cid}", "2.2.2.2:80", "http-mesh",
            )
            assert res == int(FilterResult.OK)
            res, out = shim.on_io(False, frame)
            assert res == int(FilterResult.OK)
            shim.close()
            return out

        ok_req = b"GET /public/a HTTP/1.1\r\n\r\n"
        bad_req = b"DELETE /x HTTP/1.1\r\n\r\n"
        assert req(9, ok_req) == ok_req

        _arm_named_loss(svc, 2)
        # Faulting round still answered (fallback twin, same round).
        assert req(10, ok_req) == ok_req
        assert svc.status()["mesh"]["demotions"] == {"device-call": 1}
        st = _await_rung(svc, "reshaped")
        assert st["lost_devices"] == [2]
        eng = next(
            e for k, e in svc._engines.items() if k[4] == "http"
        )
        assert isinstance(eng.model, ShardedVerdictModel)
        assert req(11, ok_req) == ok_req
        assert req(12, bad_req) != bad_req  # still denying, reshaped

        svc._device_probe_fn = lambda dev: True
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if svc.status()["mesh"]["rung"] == "full":
                break
            assert req(13, ok_req) == ok_req
            time.sleep(0.05)
        st = svc.status()["mesh"]
        assert st["rung"] == "full" and st["repromotions"] == 1
        assert req(14, ok_req) == ok_req
        assert req(15, bad_req) != bad_req
        assert svc.status()["containment"]["batch_crashes"] == 0
        assert svc.fallback_entries == 0  # never host-judged rounds
    finally:
        if client is not None:
            client.close()
        if svc is not None:
            svc.stop()
        inst.reset_module_registry()


# --- chaos soak: repeated device loss under churn ------------------------

def _churn_policy(j):
    """Policy-churn payload under its OWN name — forces builder-thread
    rebuild load without changing the truth table traffic asserts."""
    return NetworkPolicy(
        name="churn-pol", policy=2,
        ingress_per_port_policies=[
            PortNetworkPolicy(port=80, rules=[
                PortNetworkPolicyRule(
                    l7_proto="r2d2",
                    l7_rules=POLICY_RULES[: 1 + (j % len(POLICY_RULES))],
                )
            ])
        ],
    )


def _chaos_soak(tmp_path, name, cycles, n_threads):
    """Kill a different shard device each cycle, mid-burst, under
    policy churn; every frame must still be answered exactly once with
    the policy-truth verdict, and the ladder must end back at full."""
    from test_sidecar import CORPUS, assert_parity, oracle_ops, \
        r2d2_policy
    from test_sidecar_faults import _open_conn, _shim_run

    inst.reset_module_registry()
    svc = None
    clients = []
    try:
        svc, client, _mod = _start(
            tmp_path, name, batch_timeout_ms=2.0,
            mesh_reprobe_interval_s=0.05,
        )
        clients.append(client)
        stop = threading.Event()
        errors = []
        counts = [0] * n_threads

        # Sessions, modules, policies, and conns are set up
        # SEQUENTIALLY (the contract under test is verdict serving
        # during device loss, not control-plane races); the threads
        # then drive persistent conns concurrently, each asserting
        # bit-identical ops vs its HOST-ORACLE walk every pass.
        def _slice(tid):
            return CORPUS + [
                f"READ /public/pod{tid}.txt\r\n".encode(),
                b"HALT\r\n",
            ]

        shims, oracles = [], []
        for tid in range(n_threads):
            c = SidecarClient(svc.socket_path, timeout=120.0,
                              identity=f"pod-{tid}")
            clients.append(c)
            _m, shim = _open_conn(c, 5000 + tid)
            shims.append(shim)
            oracles.append(oracle_ops(r2d2_policy(), _slice(tid)))
        churn_c = SidecarClient(svc.socket_path, timeout=120.0,
                                identity="pod-churn")
        clients.append(churn_c)
        churn_m = churn_c.open_module([])

        def traffic(tid):
            try:
                while not stop.is_set():
                    out = _shim_run(clients[tid + 1], shims[tid],
                                    _slice(tid))
                    assert_parity(out, oracles[tid])
                    counts[tid] += 1
            except Exception as exc:  # noqa: BLE001 - soak collector
                errors.append((tid, "exc", repr(exc)))

        def churn():
            try:
                j = 0
                while not stop.is_set():
                    # Full policy set each push (policy_update
                    # REPLACES the instance's map, like an xDS
                    # snapshot): churn-pol varies, the serving
                    # policies ride along unchanged.
                    res = churn_c.policy_update(
                        churn_m,
                        [r2d2_policy(), _policy(), _churn_policy(j)],
                    )
                    if res != int(FilterResult.OK):
                        errors.append(("churn", j, res))
                        return
                    j += 1
                    time.sleep(0.01)
            except Exception as exc:  # noqa: BLE001 - soak collector
                errors.append(("churn", "exc", repr(exc)))

        threads = [
            threading.Thread(target=traffic, args=(t,), daemon=True)
            for t in range(n_threads)
        ]
        threads.append(threading.Thread(target=churn, daemon=True))
        for t in threads:
            t.start()
        try:
            for cyc in range(cycles):
                # Let the full-width mesh serve a burst first.
                base = list(counts)
                deadline = time.monotonic() + 60.0
                while (time.monotonic() < deadline and not errors
                       and any(c - b < 1
                               for c, b in zip(counts, base))):
                    time.sleep(0.02)
                assert not errors, errors
                dev = 1 + (cyc % 7)
                _arm_named_loss(svc, dev)
                st = _await_rung(svc, "reshaped", timeout=60.0)
                assert st["lost_devices"] == [dev], st
                assert not errors, errors
                # Heal: traffic threads drive the paced re-probe.
                svc._device_probe_fn = lambda d: True
                st = _await_rung(svc, "full", timeout=60.0)
                assert not errors, errors
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=60.0)
        assert not errors, errors
        assert all(c > 0 for c in counts), counts
        st = svc.status()
        assert st["mesh"]["rung"] == "full"
        assert st["mesh"]["reshapes"] == cycles
        assert st["mesh"]["repromotions"] == cycles
        assert st["containment"]["batch_crashes"] == 0
        assert st["containment"]["error_entries"] == 0
        assert st["containment"]["shed_entries"] == 0
        # Exactly-once across every fault cycle: no session lost a
        # round to the ladder (zero silent loss, zero double replies).
        rows = {
            r["identity"]: r for r in st["sessions"]["live"]
        }
        for tid in range(n_threads):
            row = rows[f"pod-{tid}"]
            assert row["submitted"] == row["answered"], row
            assert row["shed"] == {}, row
    finally:
        for c in clients:
            c.close()
        if svc is not None:
            svc.stop()
        inst.reset_module_registry()


def test_mesh_device_loss_chaos_soak_fast(tmp_path):
    """Tier-1 chaos soak: two fault->reshape->heal->full cycles under
    concurrent traffic and policy churn, zero silent loss, zero double
    replies, every verdict policy-true."""
    _chaos_soak(tmp_path, "soak-fast", cycles=1, n_threads=2)


@pytest.mark.slow
def test_mesh_device_loss_chaos_soak_long(tmp_path):
    """Longer soak (BENCH_FULL tier): five cycles, four traffic
    threads — walks the ladder through most of the device set."""
    _chaos_soak(tmp_path, "soak-long", cycles=5, n_threads=4)


# --- flight recorder: the incident timeline through the device walk -------

def _await_bundles(rec, n, timeout=30.0):
    """Postmortem enrichment rides its own daemon thread (the
    fail-closed edge fires under service locks); wait for the
    written-bundle counter to land."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if rec.bundles_written >= n:
            return
        time.sleep(0.01)
    raise AssertionError(f"{n} bundle(s) never written: {rec.status()}")


def test_flight_recorder_records_device_loss_cascade(tmp_path):
    """The device-loss walk — descent, heal, re-promotion, second
    descent — lands in the flight recorder as an ORDERED declared-edge
    sequence: every recorded transition is an edge its protocols.py
    table declares, the fail-closed flags match the declared
    FAIL_CLOSED surface exactly, each descent yields exactly ONE
    postmortem bundle whose LAST event is the triggering edge, and the
    heal re-arms the latch so the second descent gets its own bundle."""
    from cilium_tpu.analysis import protocols as proto

    inst.reset_module_registry()
    svc = client = None
    try:
        svc, client, mod = _start(
            tmp_path, "recorder-walk", batch_timeout_ms=0.0,
            mesh_reprobe_interval_s=0.05,
            timeline_bundle_dir=str(tmp_path / "bundles"),
        )
        rec = svc.recorder
        shim = _conn(client, mod, 60, 1)
        res, out = shim.on_io(False, b"READ /public/a.txt\r\n")
        assert res == int(FilterResult.OK)
        seq0 = rec.status()["seq"]
        assert rec.status()["armed"] is True

        # -- first descent -------------------------------------------
        _arm_named_loss(svc, 3)
        res, out = shim.on_io(False, b"HALT\r\n")
        assert res == int(FilterResult.OK) and out == b"HALT\r\n"
        _await_rung(svc, "reshaped")
        _await_bundles(rec, 1)

        evs = rec.events(n=512, since=seq0)
        assert evs, "descent recorded nothing"
        # Edge-for-edge validation against the DECLARED tables: every
        # recorded typestate transition is one of its table's edges,
        # and its fail-closed flag matches the declared surface.
        for ev in evs:
            if ev["table"] in ("mark", "overload"):
                continue
            table = proto._PROTOCOLS_BY_NAME[ev["table"]]
            edge = tuple(ev["edge"])
            assert edge in table.edges, ev
            want_fc = (ev["table"],) + edge in proto.FAIL_CLOSED_EDGES
            assert bool(ev.get("fail_closed")) == want_fc, ev
        # The cascade's shape, in ring order: the faulting round's
        # off-path demotion, then the guard attributing the chip, then
        # the builder's reshape onto the survivors.
        ladder = [tuple(e["edge"]) for e in evs
                  if e["table"] == "mesh_ladder"]
        assert ladder == [("full", "fallback"), ("fallback", "reshaped")]
        # The guard attributes the chip once typed ("device-call");
        # the paced re-probe may re-attribute it ("probe-failed",
        # the declared lost->lost self-edge) before the reshape lands.
        dev_evs = [e for e in evs if e["table"] == "mesh_device"]
        assert [tuple(e["edge"]) for e in dev_evs][0] == ("ok", "lost")
        assert all(tuple(e["edge"]) == ("lost", "lost")
                   for e in dev_evs[1:])
        # Correlation ids rode the thread-local annotation in.
        assert dev_evs[0]["device"] == "3"
        assert dev_evs[0]["reason"] == "device-call"
        assert all(e["device"] == "3" and e["reason"] == "probe-failed"
                   for e in dev_evs[1:])
        demote = next(e for e in evs if e["table"] == "mesh_ladder"
                      and e["edge"] == ["full", "fallback"])
        assert demote["reason"] == "device-call"
        reshape = next(e for e in evs
                       if e["edge"] == ["fallback", "reshaped"])
        assert reshape["reason"] == "reshape"
        assert demote["seq"] < dev_evs[0]["seq"] < reshape["seq"]

        # Exactly ONE bundle for the whole cascade: several fail-closed
        # edges fired; the latch folded them into one postmortem.
        st = rec.status()
        assert st["fail_closed_events"] >= 2
        assert st["postmortems"] == 1 and len(rec.postmortems) == 1
        assert st["armed"] is False
        assert st["tiers"]["mesh"] == 1  # reshaped rung on the gauge
        pm = rec.postmortems[0]
        assert pm["trigger"] == "mesh_ladder:full->fallback"
        assert pm["seq"] == demote["seq"]
        assert pm["reason"] == "device-call"
        # The bundle file: the triggering edge lands LAST (the ring is
        # snapshotted under the latch, before the cascade's later
        # edges append).
        assert pm["path"] is not None
        with open(pm["path"], encoding="utf-8") as f:
            bundle = json.load(f)
        assert bundle["trigger"] == pm["trigger"]
        assert bundle["events"][-1]["seq"] == demote["seq"]
        assert bundle["events"][-1]["edge"] == ["full", "fallback"]
        assert bundle["status"] is not None  # enrichment providers ran
        # The wire surface serves the same ring (MSG_TIMELINE RPC).
        reply = client.timeline(n=512, since=seq0, table="mesh_ladder")
        assert [tuple(e["edge"]) for e in reply["events"]] == ladder
        assert reply["timeline"]["postmortems"] == 1

        # -- heal: the ascent re-arms the latch ----------------------
        seq1 = rec.status()["seq"]
        svc._device_probe_fn = lambda dev: True
        _await_rung(svc, "full", client, mod, drive=True)
        evs = rec.events(n=512, since=seq1)
        # A probe already in flight may land one more lost->lost row;
        # the heal itself is exactly one lost->ok, probe-attributed.
        heal = [e for e in evs if e["table"] == "mesh_device"
                and tuple(e["edge"]) == ("lost", "ok")]
        assert len(heal) == 1 and heal[0]["reason"] == "probe-heal"
        assert heal[0]["device"] == "3"
        promote = [e for e in evs if e["table"] == "mesh_ladder"]
        assert [tuple(e["edge"]) for e in promote] == [
            ("reshaped", "full")
        ]
        assert promote[0]["reason"] == "repromote"
        # No NEW descent on the way up: the only fail-closed rows an
        # ascent may carry are straggler lost->lost re-attributions.
        assert all(tuple(e["edge"]) == ("lost", "lost")
                   for e in evs if e.get("fail_closed"))
        st = rec.status()
        assert st["armed"] is True
        assert st["tiers"]["mesh"] == 0

        # -- second descent: one bundle PER descent ------------------
        seq2 = rec.status()["seq"]
        _arm_named_loss(svc, 5)
        s2 = _conn(client, mod, 61, 3)
        res, out = s2.on_io(False, b"HALT\r\n")
        assert res == int(FilterResult.OK) and out == b"HALT\r\n"
        s2.close()
        _await_rung(svc, "reshaped")
        _await_bundles(rec, 2)
        assert rec.status()["postmortems"] == 2
        pm2 = rec.postmortems[-1]
        assert pm2["trigger"] == "mesh_ladder:full->fallback"
        assert pm2["seq"] > seq2
        dev2 = [e for e in rec.events(n=512, since=seq2)
                if e["table"] == "mesh_device"]
        assert {e["device"] for e in dev2} == {"5"}
        assert tuple(dev2[0]["edge"]) == ("ok", "lost")
    finally:
        if client is not None:
            client.close()
        if svc is not None:
            svc.stop()
        inst.reset_module_registry()


# --- ladder state across hitless restart (satellite 2) --------------------

def test_mesh_ladder_survives_hitless_restart(tmp_path):
    """snapshot_handoff carries the per-device health table and the
    degraded width; a restored successor starts DIRECTLY on the
    reshaped rung (no re-discovery outage) and can still walk back up
    once the device heals."""
    inst.reset_module_registry()
    svc = client = fresh = client2 = None
    path = str(tmp_path / "handoff-mesh.sock")
    try:
        cfg_kw = dict(
            batch_flows=64, dispatch_mode="jit", batch_timeout_ms=0.0,
            mesh="on", mesh_rule_shards=2,
            device_reprobe_interval_s=1e9,
            mesh_reprobe_interval_s=0.05,
        )
        svc = VerdictService(path, DaemonConfig(**cfg_kw)).start()
        client = SidecarClient(svc.socket_path, timeout=120.0)
        mod = client.open_module([])
        assert client.policy_update(mod, [_policy()]) == int(
            FilterResult.OK
        )
        shim = _conn(client, mod, 1, 3)
        res, out = shim.on_io(False, b"HALT\r\n")
        assert out == b"HALT\r\n"
        _arm_named_loss(svc, 3)
        res, out = shim.on_io(False, b"HALT\r\n")
        assert res == int(FilterResult.OK) and out == b"HALT\r\n"
        _await_rung(svc, "reshaped")

        snap = svc.snapshot_handoff()
        assert snap["mesh"] == {"lost": [3], "reshapes": 1}
        assert snap["guard"]["devices"]["3"]["state"] == "lost"
        client.close()
        client = None
        svc.stop()
        svc = None

        fresh = VerdictService(path, DaemonConfig(**cfg_kw))
        assert fresh.restore_handoff(snap) is True
        # Device 3 is STILL dead across the restart.
        fresh._device_probe_fn = lambda dev: dev.id != 3
        fresh.start()
        client2 = SidecarClient(fresh.socket_path, timeout=120.0)
        mod2 = client2.open_module([])
        assert client2.policy_update(mod2, [_policy()]) == int(
            FilterResult.OK
        )
        # Mesh resolution is lazy (first engine build): drive a frame
        # before inspecting the inherited rung.
        s1 = _conn(client2, mod2, 1, 3)
        res, out = s1.on_io(False, b"HALT\r\n")
        assert res == int(FilterResult.OK) and out == b"HALT\r\n"
        s1.close()
        st = fresh.status()["mesh"]
        assert st["rung"] == "reshaped", st
        assert st["lost_devices"] == [3]
        assert st["reshapes"] == 1
        assert 0.0 < st["capacity_frac"] < 1.0
        assert 3 not in {d.id for d in fresh._mesh_serving.devices.flat}
        assert fresh.guard.device_table()["3"]["state"] == "lost"
        # Bit-identical service on the inherited reshaped rung.
        for i, (frame, remote, want) in enumerate(TRAFFIC):
            s2 = _conn(client2, mod2, 100 + i, remote)
            res, out = s2.on_io(False, frame)
            assert res == int(FilterResult.OK)
            assert (out == frame) == want, (frame, out)
            s2.close()
        # Heal walks back up — inherited degradation is not sticky.
        fresh._device_probe_fn = lambda dev: True
        st = _await_rung(fresh, "full", client2, mod2, drive=True)
        assert st["repromotions"] == 1
        assert fresh.guard.device_table()["3"]["state"] == "ok"
    finally:
        for c in (client, client2):
            if c is not None:
                c.close()
        for s in (svc, fresh):
            if s is not None:
                s.stop()
        inst.reset_module_registry()


# --- >32-wide layouts: degenerate shapes (satellite 1, ROADMAP 5b) --------

def test_mesh_extents_64_wide_and_auto_cap():
    from cilium_tpu.parallel import mesh_extents

    # Explicit 64-wide flow split is honored (no max_flow cap).
    assert mesh_extents("on", flow_shards=64, n_devices=64) == (64, 1)
    assert mesh_extents("on", rule_shards=2, flow_shards=64,
                        n_devices=128) == (64, 2)
    # AUTO derivation still caps at max_flow.
    assert mesh_extents("on", n_devices=128) == (32, 1)
    assert mesh_extents("on", n_devices=128, max_flow=64) == (64, 1)
    # pow2 floor; infeasible explicit extents resolve to None.
    assert mesh_extents("on", flow_shards=48, n_devices=64) == (32, 1)
    assert mesh_extents("on", flow_shards=64, n_devices=32) is None
    assert mesh_extents("off") is None


def test_reshape_mesh_rungs_on_real_devices():
    import jax

    from cilium_tpu.parallel import (
        FLOW_AXIS, RULE_AXIS, reshape_mesh,
    )

    devs = jax.devices()
    assert len(devs) == 8  # conftest forces 8 virtual CPU devices
    # 7 survivors, rule extent 2 preserved: pow2 floor -> 2x2.
    m = reshape_mesh(devs[:3] + devs[4:], rule_shards=2, max_flow=4)
    assert (m.shape[FLOW_AXIS], m.shape[RULE_AXIS]) == (2, 2)
    assert devs[3] not in set(m.devices.flat)
    # 3 survivors still fill rule extent 2 -> 1x2.
    m = reshape_mesh(devs[:3], rule_shards=2)
    assert (m.shape[FLOW_AXIS], m.shape[RULE_AXIS]) == (1, 2)
    # 2 survivors cannot fill rule extent 4 -> halved to 2.
    m = reshape_mesh(devs[:2], rule_shards=4)
    assert (m.shape[FLOW_AXIS], m.shape[RULE_AXIS]) == (1, 2)
    # A lone survivor is below the minimum mesh width.
    assert reshape_mesh(devs[:1], rule_shards=2) is None
    assert reshape_mesh([], rule_shards=1) is None


def test_sharded_split_64_wide_degenerate_shapes():
    """64-way splits of tiny row sets: empty shards get never-matching
    NFA rows, offsets stay monotone, and the stacked model's leading
    shard dim is the full 64 — the shapes a >32-device pod builds."""
    import jax

    from cilium_tpu.parallel.rulesharding import (
        build_sharded_r2d2_from_rows, shard_offsets, split_balanced,
    )

    rows = [
        ([1, 3], "READ", "/public/.*"),
        ([], "HALT", ""),
        ([9], "WRITE", "^/tmp/"),
        ([], "", "\\.txt$"),
    ]
    chunks = split_balanced(rows, 64)
    assert len(chunks) == 64
    assert [len(c) for c in chunks[:4]] == [1, 1, 1, 1]
    assert all(not c for c in chunks[4:])
    offs = np.asarray(shard_offsets(len(rows), 64))
    assert offs.shape == (64,)
    assert list(offs[:4]) == [0, 1, 2, 3]
    assert all(int(o) == 4 for o in offs[4:])
    assert all(b >= a for a, b in zip(offs, offs[1:]))

    model = build_sharded_r2d2_from_rows(rows, 64, bucket=True)
    lead = {
        int(x.shape[0])
        for x in jax.tree_util.tree_leaves(model)
        if hasattr(x, "shape") and x.shape
    }
    assert lead == {64}
