"""Shared-memory transport tests (sidecar/shm.py, sidecar/transport.py).

The contract under test (ISSUE 8): the shm fast path is bit-identical
to the socket path (verdicts, op sequences, flowlog attribution), and
every ring fault degrades TYPED to the socket rung — torn slot, stale
generation, service restart — with zero silent loss even at 2×
capacity with ring-fault injection.
"""

from __future__ import annotations

import json
import struct
import threading
import time

import numpy as np
import pytest

from cilium_tpu.proxylib import FilterResult
from cilium_tpu.proxylib import instance as inst
from cilium_tpu.sidecar import (
    SidecarClient,
    VerdictService,
    wire,
)
from cilium_tpu.sidecar.shm import (
    SLOT_HEADER_BYTES,
    GenerationMismatch,
    ShmRing,
    TornSlot,
)
from cilium_tpu.sidecar.transport import (
    REASON_TORN_SLOT,
    TRANSPORT_SHM,
    TRANSPORT_SOCKET,
    ShmSession,
)
from cilium_tpu.utils.option import DaemonConfig

from test_sidecar import CORPUS, assert_parity, oracle_ops, r2d2_policy
from test_sidecar_faults import _open_conn, _shim_run, _wait


def _service(tmp_path, name, **cfg_kw):
    inst.reset_module_registry()
    defaults = dict(
        batch_timeout_ms=2.0,
        batch_flows=256,
        dispatch_mode="eager",
    )
    defaults.update(cfg_kw)
    cfg = DaemonConfig(**defaults)
    return VerdictService(str(tmp_path / f"{name}.sock"), cfg).start()


SHM_KW = dict(
    transport=TRANSPORT_SHM,
    shm_data_slots=16,
    shm_slot_bytes=1 << 16,
    shm_verdict_slots=16,
    shm_verdict_slot_bytes=1 << 16,
)


# --- ring unit behavior ----------------------------------------------------

def test_ring_roundtrip_and_full_refusal():
    ring = ShmRing.create("test", 3, slots=4, slot_bytes=256)
    try:
        assert ring.try_push(5, b"abc", 0)
        assert ring.try_push(6, b"defg", 0)
        # 4-slot ring with zero credit: two more fit, the fifth refuses
        assert ring.try_push(5, b"x", 0)
        assert ring.try_push(5, b"y", 0)
        assert not ring.try_push(5, b"z", 0), "full ring must refuse"
        got = [ring.read(i)[:2] for i in range(3)]
        assert got == [(5, b"abc"), (6, b"defg"), (5, b"x")]
        assert ring.try_push(5, b"z", 1), "credit frees the slot"
        assert ring.read(4)[:2] == (5, b"z")  # wrapped into slot 0
        assert not ring.fits(257)
        assert not ring.try_push(5, b"q" * 500, 5), "oversize refuses"
    finally:
        ring.close()
        ring.unlink()


def test_ring_torn_slot_and_stale_generation():
    ring = ShmRing.create("test", 9, slots=4, slot_bytes=256)
    try:
        assert ring.try_push(5, b"abc", 0)
        # Tear the slot: zero the commit word (producer died mid-write).
        struct.pack_into("<Q", ring.seg.buf, 64, 0)
        with pytest.raises(TornSlot):
            ring.read(0)
        # Attach validates generation against the segment header.
        with pytest.raises(GenerationMismatch):
            ShmRing.attach(ring.seg.name, 10)
        peer = ShmRing.attach(ring.seg.name, 9)
        peer.close()
    finally:
        ring.close()
        ring.unlink()


def test_wire_shm_roundtrips():
    g, t, vh = 7, 123456789, 42
    assert wire.unpack_shm_doorbell(
        wire.pack_shm_doorbell(g, t, vh)
    ) == (g, t, vh)
    assert wire.unpack_shm_credit(
        wire.pack_shm_credit(g, 1, t, vh)
    ) == (g, 1, t, vh)
    assert wire.unpack_shm_detach(wire.pack_shm_detach(g)) == (g, 0)
    assert wire.unpack_shm_detach(
        wire.pack_shm_detach(g, wire.DETACH_FLAG_NO_ACK)
    ) == (g, wire.DETACH_FLAG_NO_ACK)


# --- bit-identical parity across transports --------------------------------

def _flow_records(svc):
    """Flowlog extract for parity: the attribution-relevant columns as
    a sorted multiset.  Seqs/timestamps are transport noise, and
    CROSS-round emission order is thread-interleave noise (vec rounds
    record on the send thread, entrywise rounds on the dispatcher) —
    the contract is that every flow gets the same verdict with the
    same rule attribution on both transports."""
    recs = svc.flowlog.query(n=10_000)
    return sorted(
        (r["conn_id"], r["verdict"], r["rule_id"], r["match_kind"])
        for r in recs
    )


PARITY_MSGS = CORPUS + [
    b"READ /pub",                 # partial frame...
    b"lic/tail.txt\r\n",          # ...completed next entry
    b"READ /public/a.txt\r\nHALT\r\n",  # pipelined pair
]


def _settle_flows(svc, timeout_s: float = 5.0) -> None:
    """Record emission may lag the RPC reply (vec-round records are
    appended on the send thread after the verdict frame is written):
    wait until the record count is quiescent before comparing."""
    deadline = time.monotonic() + timeout_s
    last, stable = -1, 0
    while time.monotonic() < deadline and stable < 3:
        n = svc.flowlog.stats().get("records", 0)
        stable = stable + 1 if n == last else 0
        last = n
        time.sleep(0.05)


def _run_transport(tmp_path, name, **client_kw):
    svc = _service(tmp_path, name)
    client = SidecarClient(svc.socket_path, timeout=30.0, **client_kw)
    try:
        _, shim = _open_conn(client, 4100)
        got = _shim_run(client, shim, PARITY_MSGS)
        _settle_flows(svc)
        flows = _flow_records(svc)
        return got, flows, client.transport_status(), svc.status()
    finally:
        client.close()
        svc.stop()
        inst.reset_module_registry()


def test_shm_socket_parity_verdicts_and_flowlog(tmp_path):
    """The acceptance gate: identical traffic through both transports
    produces bit-identical op sequences, injects, AND flow-record
    attribution — and the shm run really rode the ring."""
    got_sock, flows_sock, _, _ = _run_transport(tmp_path, "par_sock")
    got_shm, flows_shm, tstat, sstat = _run_transport(
        tmp_path, "par_shm", **SHM_KW
    )
    assert_parity(got_shm, got_sock)
    # Both also match the in-process oracle (the definition of exact).
    assert_parity(got_sock, oracle_ops(r2d2_policy(), PARITY_MSGS))
    assert flows_shm == flows_sock
    assert tstat["mode"] == TRANSPORT_SHM
    assert tstat["session"]["data_frames"] == len(PARITY_MSGS)
    assert tstat["session"]["verdict_frames"] > 0, (
        "verdicts must ride the verdict ring, not the socket"
    )
    assert tstat["fallbacks"] == {}
    sess = sstat["transport"]["sessions"][0]
    assert sess["mode"] == TRANSPORT_SHM
    assert sstat["transport"]["shm_entries"] == len(PARITY_MSGS)
    # Ring-stage observability: shm rounds carve STAGE_RING out of the
    # queue wait in the latency decomposition.
    stages = sstat["latency"]["stages"]
    assert any("ring" in per_path for per_path in stages.values())


def test_oversize_batch_falls_back_per_batch(tmp_path):
    """A frame larger than a slot rides the socket (typed, counted) —
    the session itself stays on the shm rung."""
    svc = _service(tmp_path, "oversize")
    client = SidecarClient(
        svc.socket_path, timeout=30.0, transport=TRANSPORT_SHM,
        shm_data_slots=4, shm_slot_bytes=SLOT_HEADER_BYTES + 64,
    )
    try:
        _, shim = _open_conn(client, 4200)
        big = b"READ /public/" + b"a" * 200 + b"\r\n"
        exp = oracle_ops(r2d2_policy(), [big])
        got = _shim_run(client, shim, [big])
        assert_parity(got, exp)
        assert client.transport_mode == TRANSPORT_SHM
        assert client.transport_fallbacks.get("oversize", 0) >= 1
    finally:
        client.close()
        svc.stop()
        inst.reset_module_registry()


# --- fault injection -------------------------------------------------------

def test_torn_slot_quarantines_and_demotes_typed(tmp_path):
    """Shim dies mid-write (simulated: a claimed-but-uncommitted slot
    behind an inflated doorbell): the service quarantines the ring and
    demotes the session; the never-admitted frame is answered with a
    client-synthesized typed SHED — zero silent loss — and the session
    keeps serving over the socket."""
    svc = _service(tmp_path, "torn")
    client = SidecarClient(svc.socket_path, timeout=30.0, **SHM_KW)
    try:
        _, shim = _open_conn(client, 4300)
        _shim_run(client, shim, [b"HALT\r\n"])  # shm path warm
        sess = client._shm
        assert sess is not None and sess.active

        got: dict[int, wire.VerdictBatch] = {}
        client.verdict_callback = lambda vb: got.setdefault(vb.seq, vb)

        with client._wlock:
            pos = sess.data.tail
            payload = wire.pack_data_batch(
                991, [shim.conn_id], [0], [6], b"HALT\r\n"
            )
            assert sess.data.try_push(
                wire.MSG_DATA_BATCH, payload, sess.credit_head
            )
            sess.inflight[991] = (
                pos, np.array([shim.conn_id], np.uint64)
            )
            # Tear the slot the doorbell is about to claim.
            off = 64 + (pos % sess.data.slots) * sess.data.slot_bytes
            struct.pack_into("<Q", sess.data.seg.buf, off, 0)
            client._doorbell_send(sess, sess.data.tail)

        _wait(
            lambda: client.transport_mode == TRANSPORT_SOCKET,
            10.0, "session demotion to socket",
        )
        _wait(lambda: 991 in got, 5.0, "typed SHED for the torn frame")
        vb = got[991]
        assert list(vb.results) == [int(FilterResult.SHED)]
        assert client.transport_fallbacks.get(REASON_TORN_SLOT, 0) == 1
        st = svc.status()
        sess_st = st["transport"]["sessions"][0]
        assert sess_st["mode"] == TRANSPORT_SOCKET
        assert sess_st["quarantine_reason"] == REASON_TORN_SLOT

        # Fallback serves, same bit-exact verdicts, on the SAME shim.
        client.verdict_callback = None
        got2 = _shim_run(client, shim, CORPUS)
        assert_parity(got2, oracle_ops(r2d2_policy(), CORPUS))
    finally:
        client.verdict_callback = None
        client.close()
        svc.stop()
        inst.reset_module_registry()


def test_partial_drain_before_torn_slot_is_submitted(tmp_path):
    """Frames drained BEFORE the torn slot in the same doorbell are
    admitted work: they must be submitted (real verdicts over the
    socket after quarantine), while the torn frame and beyond get the
    client's synthesized SHED.  Discarding the partial drain would
    strand its callers below the credit's data_head watermark — silent
    loss by timeout."""
    svc = _service(tmp_path, "partial_torn")
    client = SidecarClient(svc.socket_path, timeout=30.0, **SHM_KW)
    try:
        _, shim = _open_conn(client, 4350)
        _shim_run(client, shim, [b"HALT\r\n"])  # shm path warm
        sess = client._shm

        got: dict[int, wire.VerdictBatch] = {}
        client.verdict_callback = lambda vb: got.setdefault(vb.seq, vb)

        with client._wlock:
            msg = b"HALT\r\n"
            # Good frame at pos, torn frame at pos+1, ONE doorbell.
            for seq in (990, 991):
                pos = sess.data.tail
                payload = wire.pack_data_batch(
                    seq, [shim.conn_id], [0], [len(msg)], msg
                )
                assert sess.data.try_push(
                    wire.MSG_DATA_BATCH, payload, sess.credit_head
                )
                sess.inflight[seq] = (
                    pos, np.array([shim.conn_id], np.uint64)
                )
                if seq == 991:
                    off = (
                        64 + (pos % sess.data.slots) * sess.data.slot_bytes
                    )
                    struct.pack_into("<Q", sess.data.seg.buf, off, 0)
            client._doorbell_send(sess, sess.data.tail)

        _wait(lambda: 990 in got and 991 in got, 10.0,
              "both frames answered")
        assert list(got[990].results) == [int(FilterResult.OK)], (
            "the pre-torn frame must get its REAL verdict"
        )
        assert list(got[991].results) == [int(FilterResult.SHED)]
        assert client.transport_mode == TRANSPORT_SOCKET
    finally:
        client.verdict_callback = None
        client.close()
        svc.stop()
        inst.reset_module_registry()


def test_client_fault_demotion_notifies_service(tmp_path):
    """A CLIENT-detected ring fault (torn verdict slot) must latch the
    SERVICE off the rings too — otherwise the service keeps writing
    verdicts into a ring nobody drains and admitted in-flight RPCs
    time out instead of getting their promised socket verdicts."""
    svc = _service(tmp_path, "clientfault")
    client = SidecarClient(svc.socket_path, timeout=30.0, **SHM_KW)
    try:
        _, shim = _open_conn(client, 4360)
        _shim_run(client, shim, [b"HALT\r\n"])
        assert client.transport_mode == TRANSPORT_SHM
        client._demote_shm(REASON_TORN_SLOT)
        assert client.transport_mode == TRANSPORT_SOCKET
        _wait(
            lambda: svc.status()["transport"]["sessions"][0]["mode"]
            == TRANSPORT_SOCKET,
            5.0, "service latched off the rings",
        )
        # Verdicts keep flowing — over the socket, bit-identical.
        got = _shim_run(client, shim, CORPUS)
        assert_parity(got, oracle_ops(r2d2_policy(), CORPUS))
    finally:
        client.close()
        svc.stop()
        inst.reset_module_registry()


def test_stale_generation_attach_rejected_fallback_serves(tmp_path):
    """Service restart with a stale segment: an attach whose negotiated
    generation mismatches the segment header is rejected TYPED, and the
    session serves on the socket rung."""
    svc = _service(tmp_path, "stalegen")
    client = SidecarClient(svc.socket_path, timeout=30.0)
    sess = ShmSession.create(5, 4, 4096, 4, 4096)
    try:
        req = sess.attach_request()
        req["generation"] = 6  # stale: segment headers say 5
        got = client._control_rpc(
            lambda: (wire.MSG_SHM_ATTACH, json.dumps(req).encode()),
            wire.MSG_SHM_ATTACH_REPLY,
            retry=False,
        )
        rep = json.loads(got.decode())
        assert rep["status"] != int(FilterResult.OK)
        assert "generation" in rep["error"]
        assert svc.transport_rejects.get("generation_mismatch", 0) == 1
        # Fallback serves: the same session keeps verdicting.
        _, shim = _open_conn(client, 4400)
        got2 = _shim_run(client, shim, CORPUS)
        assert_parity(got2, oracle_ops(r2d2_policy(), CORPUS))
        assert client.transport_mode == TRANSPORT_SOCKET
    finally:
        sess.destroy()
        client.close()
        svc.stop()
        inst.reset_module_registry()


def test_shm_disabled_by_config_rejects_typed(tmp_path):
    svc = _service(tmp_path, "disabled", shm_transport=False)
    client = SidecarClient(svc.socket_path, timeout=30.0, **SHM_KW)
    try:
        assert client.transport_mode == TRANSPORT_SOCKET
        assert client.transport_fallbacks.get("attach_rejected", 0) == 1
        assert svc.transport_rejects.get("disabled", 0) == 1
        _, shim = _open_conn(client, 4500)
        got = _shim_run(client, shim, [b"HALT\r\n"])
        assert_parity(got, oracle_ops(r2d2_policy(), [b"HALT\r\n"]))
    finally:
        client.close()
        svc.stop()
        inst.reset_module_registry()


def test_reconnect_renegotiates_fresh_rings(tmp_path):
    """auto_reconnect replays the session AND re-negotiates fresh rings
    (bumped generation, new segments) — a restarted service never
    attaches a stale segment."""
    svc = _service(tmp_path, "renegotiate")
    path = svc.socket_path
    client = SidecarClient(
        path, timeout=8.0, auto_reconnect=True, **SHM_KW
    )
    try:
        _, shim = _open_conn(client, 4600)
        assert client.transport_mode == TRANSPORT_SHM
        gen1 = client._shm.generation
        name1 = client._shm.data.seg.name
        _shim_run(client, shim, [b"HALT\r\n"])

        svc.stop()
        res, out = shim.on_io(False, b"READ /public/a.txt\r\n")
        assert res == int(FilterResult.SERVICE_UNAVAILABLE)

        inst.reset_module_registry()
        svc2 = VerdictService(path, DaemonConfig(
            batch_timeout_ms=2.0, batch_flows=256, dispatch_mode="eager",
        )).start()
        try:
            _wait(
                lambda: client.connected
                and client.reconnects >= 1
                and client.transport_mode == TRANSPORT_SHM,
                10.0, "reconnect with fresh shm rings",
            )
            assert client._shm.generation > gen1
            assert client._shm.data.seg.name != name1

            def verdict_ok():
                res, out = shim.on_io(False, b"READ /public/a.txt\r\n")
                return res == int(FilterResult.OK) and out
            _wait(verdict_ok, 10.0, "verdicts over the fresh rings")
            got = _shim_run(client, shim, CORPUS)
            assert_parity(got, oracle_ops(r2d2_policy(), CORPUS))
            assert client._shm.counters.data_frames > 0
        finally:
            svc2.stop()
    finally:
        client.close()
        svc.stop()
        inst.reset_module_registry()


def test_overload_2x_capacity_zero_silent_loss_with_ring_fault(tmp_path):
    """The acceptance gate: a burst past 2× the admission cap over the
    shm transport, with a ring fault injected mid-burst.  EVERY seq is
    answered — real verdict, service-side typed SHED, or the client's
    demotion-synthesized SHED.  Zero silent loss, zero double replies."""
    svc = _service(
        tmp_path, "overload_shm",
        shed_queue_entries=8,
        shed_queue_age_ms=0.0,
        batch_timeout_ms=20.0,  # slow cadence: the queue really builds
    )
    client = SidecarClient(svc.socket_path, timeout=30.0, **SHM_KW)
    try:
        _, shim = _open_conn(client, 4700)
        _shim_run(client, shim, [b"HALT\r\n"])  # engine + shm warm

        answered: dict[int, int] = {}
        double = []
        done = threading.Event()
        N = 64  # 8× the 8-entry cap; 16 data slots → ring full too

        def cb(vb):
            if vb.seq in answered:
                double.append(vb.seq)
            answered[vb.seq] = int(vb.results[0]) if vb.count else -1
            if len(answered) >= N:
                done.set()

        client.verdict_callback = cb
        msg = b"READ /public/a.txt\r\n"

        def inject_fault() -> bool:
            """Tear the NEXT slot the producer claims and doorbell it
            (only once there is ring space — a full ring would route
            the frame to the socket and inject nothing)."""
            sess = client._shm
            if sess is None or not sess.active:
                return False
            with client._wlock:
                pos = sess.data.tail
                payload = wire.pack_data_batch(
                    3000, [shim.conn_id], [0], [len(msg)], msg
                )
                if not sess.data.try_push(
                    wire.MSG_DATA_BATCH, payload, sess.credit_head
                ):
                    return False  # ring full right now; retry
                sess.inflight[3000] = (
                    pos, np.array([shim.conn_id], np.uint64)
                )
                off = (
                    64 + (pos % sess.data.slots) * sess.data.slot_bytes
                )
                struct.pack_into("<Q", sess.data.seg.buf, off, 0)
                client._doorbell_send(sess, sess.data.tail)
            return True

        injected = False
        for k in range(N):
            client.send_batch(2000 + k, [shim.conn_id], [0], [len(msg)], msg)
            if not injected and k >= N // 2:
                injected = inject_fault()
        if not injected:
            # The burst kept the ring saturated: inject as it drains.
            _wait(inject_fault, 10.0, "ring space for fault injection")

        assert done.wait(30.0), (
            f"silent loss: {N - len(answered)} of {N} entries never "
            f"answered (got {len(answered)})"
        )
        assert not double, f"double replies for seqs {sorted(set(double))}"
        results = set(answered.values())
        assert results <= {
            int(FilterResult.OK),
            int(FilterResult.SHED),
        }, results
        # The fault really demoted the session (and the burst continued
        # on the socket rung afterwards).
        assert client.transport_mode == TRANSPORT_SOCKET
        assert client.transport_fallbacks.get(REASON_TORN_SLOT, 0) == 1
        # The torn frame itself was answered typed too.
        _wait(lambda: 3000 in answered, 5.0, "torn frame typed answer")
        assert answered[3000] == int(FilterResult.SHED)
    finally:
        client.verdict_callback = None
        client.close()
        svc.stop()
        inst.reset_module_registry()


def test_graceful_detach_returns_to_socket(tmp_path):
    svc = _service(tmp_path, "detach")
    client = SidecarClient(svc.socket_path, timeout=30.0, **SHM_KW)
    try:
        _, shim = _open_conn(client, 4800)
        _shim_run(client, shim, [b"HALT\r\n"])
        assert client.transport_mode == TRANSPORT_SHM
        client.detach_shm()
        assert client.transport_mode == TRANSPORT_SOCKET
        _wait(
            lambda: svc.status()["transport"]["sessions"][0]["mode"]
            == TRANSPORT_SOCKET,
            5.0, "service side detach",
        )
        got = _shim_run(client, shim, CORPUS)
        assert_parity(got, oracle_ops(r2d2_policy(), CORPUS))
    finally:
        client.close()
        svc.stop()
        inst.reset_module_registry()


def test_credit_piggybacked_verdict_polling(tmp_path):
    """ISSUE-10 satellite (ROADMAP item 3 remainder): verdicts already
    COMMITTED to the verdict ring are consumable without the credit
    socket frame — the next data push piggybacks a mirror drain, and
    poll_shm_verdicts() exposes the same sweep explicitly.  Proven by
    DROPPING the service's credit frames entirely: verdicts still
    arrive, through the mirror, with zero spinning (every drain rides
    an event the client performed anyway)."""
    svc = _service(tmp_path, "piggy")
    client = SidecarClient(svc.socket_path, timeout=30.0, **SHM_KW)
    got: dict = {}
    evt = threading.Event()
    try:
        assert client.transport_mode == TRANSPORT_SHM
        handler = svc._clients[0]
        assert handler.shm is not None

        def cb(vb):
            got[vb.seq] = vb
            evt.set()

        client.verdict_callback = cb
        # Kill the credit channel: verdict frames land in the ring but
        # the socket never tells the client.
        handler._send_credit_locked = lambda flags=0: None
        ids = np.array([990001], np.uint64)
        fl = np.zeros(1, np.uint8)
        lens = np.array([3], np.uint32)
        client.send_batch(1, ids, fl, lens, b"x\r\n")
        time.sleep(1.0)
        assert 1 not in got, "no credit frame should mean no delivery"
        # A second push piggybacks the drain — no explicit poll, no
        # credit frame, the verdict for seq 1 arrives anyway.
        deadline = time.monotonic() + 10
        seq = 2
        while 1 not in got and time.monotonic() < deadline and seq < 8:
            client.send_batch(seq, ids, fl, lens, b"x\r\n")
            seq += 1
            evt.wait(0.5)
            evt.clear()
        assert 1 in got, "push-time piggyback drain never delivered"
        assert got[1].entry(0)[1] == int(FilterResult.UNKNOWN_CONNECTION)
        # Explicit mirror polling drains the rest (bounded loop on the
        # WALL CLOCK, not on the mirror — R2.2 stays clean).
        deadline = time.monotonic() + 10
        while len(got) < seq - 1 and time.monotonic() < deadline:
            client.poll_shm_verdicts()
            time.sleep(0.05)
        assert len(got) == seq - 1, (sorted(got), seq)
        sess = client.transport_status()["session"]
        assert sess["mirror_drains"] > 0
        assert sess["mirror_frames"] == seq - 1, (
            "every verdict must have been consumed via the mirror"
        )
        assert client.transport_mode == TRANSPORT_SHM
    finally:
        client.verdict_callback = None
        client.close()
        svc.stop()
        inst.reset_module_registry()
