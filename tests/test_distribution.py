"""Distribution (xDS analog) + access log tests.

reference test strategy: pkg/envoy/xds/server_e2e_test.go (ACK/NACK/version
races over a fake stream), accesslog server tests.
"""

import threading
import time

import pytest

from cilium_tpu.accesslog import (
    AccessLogClient,
    AccessLogServer,
    AccessLogger,
    HttpLogEntry,
    LogRecord,
    VERDICT_DENIED,
)
from cilium_tpu.distribution import (
    AckingMutator,
    Cache,
    DistributionServer,
    TYPE_NETWORK_POLICY,
)
from cilium_tpu.distribution.sock import (
    SocketDistributionServer,
    recv_frame,
    send_frame,
)
from cilium_tpu.utils.completion import Completion, CompletionError, WaitGroup


class TestCache:
    def test_versioning(self):
        c = Cache()
        v0 = c.version
        v1, updated, _ = c.upsert(TYPE_NETWORK_POLICY, "ep1", {"p": 1})
        assert updated and v1 > v0
        # identical upsert: no version bump
        v2, updated, _ = c.upsert(TYPE_NETWORK_POLICY, "ep1", {"p": 1})
        assert not updated and v2 == v1
        # changed resource bumps
        v3, updated, _ = c.upsert(TYPE_NETWORK_POLICY, "ep1", {"p": 2})
        assert updated and v3 > v1
        assert c.lookup(TYPE_NETWORK_POLICY, "ep1") == {"p": 2}

    def test_get_resources_since(self):
        c = Cache()
        c.upsert(TYPE_NETWORK_POLICY, "a", 1)
        v, _, _ = c.upsert(TYPE_NETWORK_POLICY, "b", 2)
        assert c.get_resources(TYPE_NETWORK_POLICY, since_version=v) is None
        vr = c.get_resources(TYPE_NETWORK_POLICY, since_version=v - 1)
        assert vr is not None and set(vr.resources) == {"a", "b"}

    def test_revert(self):
        c = Cache()
        c.upsert(TYPE_NETWORK_POLICY, "a", 1)
        _, _, revert = c.upsert(TYPE_NETWORK_POLICY, "a", 2)
        revert()
        assert c.lookup(TYPE_NETWORK_POLICY, "a") == 1
        _, _, revert = c.delete(TYPE_NETWORK_POLICY, "a")
        assert c.lookup(TYPE_NETWORK_POLICY, "a") is None
        revert()
        assert c.lookup(TYPE_NETWORK_POLICY, "a") == 1


class TestServer:
    def test_subscribe_initial_and_updates(self):
        c = Cache()
        c.upsert(TYPE_NETWORK_POLICY, "ep1", {"rules": []})
        s = DistributionServer(c)
        sub = s.subscribe("node1", TYPE_NETWORK_POLICY)
        vr = sub.next(1)
        assert vr is not None and "ep1" in vr.resources
        c.upsert(TYPE_NETWORK_POLICY, "ep2", {"rules": [1]})
        vr = sub.next(1)
        assert vr is not None and set(vr.resources) == {"ep1", "ep2"}

    def test_ack_tracking(self):
        c = Cache()
        s = DistributionServer(c)
        sub = s.subscribe("node1", TYPE_NETWORK_POLICY)
        v, _, _ = c.upsert(TYPE_NETWORK_POLICY, "ep1", 1)
        s.ack(sub, v)
        assert s.node_acked_version("node1", TYPE_NETWORK_POLICY) == v
        # NACK does not advance
        v2, _, _ = c.upsert(TYPE_NETWORK_POLICY, "ep1", 2)
        s.ack(sub, v2, nack=True)
        assert s.node_acked_version("node1", TYPE_NETWORK_POLICY) == v


class TestAckingMutator:
    def test_completion_on_all_acks(self):
        c = Cache()
        s = DistributionServer(c)
        m = AckingMutator(c, s)
        sub1 = s.subscribe("n1", TYPE_NETWORK_POLICY)
        sub2 = s.subscribe("n2", TYPE_NETWORK_POLICY)
        comp = Completion()
        m.upsert(TYPE_NETWORK_POLICY, "ep1", {"r": 1}, ["n1", "n2"], comp)
        vr1 = sub1.next(1)
        s.ack(sub1, vr1.version)
        assert not comp.completed  # n2 still pending
        vr2 = sub2.next(1)
        s.ack(sub2, vr2.version)
        assert comp.wait(1)
        assert m.pending_count() == 0

    def test_nack_leaves_pending(self):
        c = Cache()
        s = DistributionServer(c)
        m = AckingMutator(c, s)
        sub = s.subscribe("n1", TYPE_NETWORK_POLICY)
        comp = Completion()
        m.upsert(TYPE_NETWORK_POLICY, "ep1", {"r": 1}, ["n1"], comp)
        vr = sub.next(1)
        s.ack(sub, vr.version, nack=True)
        assert not comp.completed
        wg = WaitGroup()
        with pytest.raises(CompletionError):
            # policy application would time out and revert here
            # (reference: pkg/endpoint/bpf.go:555)
            _wait(comp, 0.05)

    def test_already_acked_completes_immediately(self):
        c = Cache()
        s = DistributionServer(c)
        m = AckingMutator(c, s)
        sub = s.subscribe("n1", TYPE_NETWORK_POLICY)
        v, _, _ = c.upsert(TYPE_NETWORK_POLICY, "x", 1)
        s.ack(sub, v + 10)  # node ahead of anything we'll push
        comp = Completion()
        m.upsert(TYPE_NETWORK_POLICY, "x", 1, ["n1"], comp)
        assert comp.completed

    def test_later_version_ack_completes_older_pending(self):
        c = Cache()
        s = DistributionServer(c)
        m = AckingMutator(c, s)
        sub = s.subscribe("n1", TYPE_NETWORK_POLICY)
        c1 = Completion()
        c2 = Completion()
        m.upsert(TYPE_NETWORK_POLICY, "a", 1, ["n1"], c1)
        m.upsert(TYPE_NETWORK_POLICY, "b", 2, ["n1"], c2)
        # drain stream; ack only the final version
        last = None
        while True:
            vr = sub.next(0.2)
            if vr is None:
                break
            last = vr
        s.ack(sub, last.version)
        assert c1.wait(1) and c2.wait(1)


def _wait(comp, timeout):
    if not comp.wait(timeout):
        raise CompletionError("deadline")


class TestSocketTransport:
    def test_sidecar_subscription_roundtrip(self, tmp_path):
        import socket as socketlib

        c = Cache()
        s = DistributionServer(c)
        sock_path = str(tmp_path / "dist.sock")
        srv = SocketDistributionServer(s, sock_path)
        try:
            c.upsert(TYPE_NETWORK_POLICY, "ep1", {"rules": ["a"]})
            client = socketlib.socket(socketlib.AF_UNIX,
                                      socketlib.SOCK_STREAM)
            client.connect(sock_path)
            send_frame(client, {
                "subscribe": {"node": "sidecar1",
                              "type_url": TYPE_NETWORK_POLICY}
            })
            msg = recv_frame(client)
            assert msg["resources"] == {"ep1": {"rules": ["a"]}}
            # ack flows back into the server
            send_frame(client, {"ack": {"version": msg["version"]}})
            deadline = time.monotonic() + 2
            while (s.node_acked_version("sidecar1", TYPE_NETWORK_POLICY)
                   != msg["version"] and time.monotonic() < deadline):
                time.sleep(0.01)
            assert (s.node_acked_version("sidecar1", TYPE_NETWORK_POLICY)
                    == msg["version"])
            # live update
            c.upsert(TYPE_NETWORK_POLICY, "ep2", {"rules": ["b"]})
            msg2 = recv_frame(client)
            assert "ep2" in msg2["resources"]
            client.close()
        finally:
            srv.close()


class TestAccessLog:
    def test_client_server_roundtrip(self, tmp_path):
        path = str(tmp_path / "access.sock")
        got = []
        srv = AccessLogServer(path, on_record=got.append)
        try:
            client = AccessLogClient(path)
            rec = LogRecord(
                verdict=VERDICT_DENIED,
                http=HttpLogEntry(code=403, method="GET", url="/private"),
            )
            assert client.log(rec)
            deadline = time.monotonic() + 2
            while not got and time.monotonic() < deadline:
                time.sleep(0.01)
            assert got and got[0].verdict == VERDICT_DENIED
            assert got[0].http.code == 403
            client.close()
        finally:
            srv.close()

    def test_logger_enrichment_and_file(self, tmp_path):
        import json

        from cilium_tpu.endpoint import Endpoint
        from cilium_tpu.identity import Identity
        from cilium_tpu.labels import Labels

        ep = Endpoint(7, ipv4="10.0.0.7")
        ep.set_identity(Identity(id=555, labels=Labels.from_model(
            ["k8s:app=x"])))
        logfile = str(tmp_path / "access.log")
        notified = []
        logger = AccessLogger(
            endpoint_lookup=lambda eid: ep if eid == 7 else None,
            notify=notified.append,
            logfile_path=logfile,
        )
        rec = LogRecord()
        rec.destination.id = 7
        logger.log(rec)
        assert rec.destination.identity == 555
        assert rec.destination.labels == ["k8s:app=x"]
        assert notified
        with open(logfile) as f:
            line = json.loads(f.readline())
        assert line["destination"]["identity"] == 555
