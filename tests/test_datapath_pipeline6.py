"""Composed IPv6 datapath pipeline vs host oracle (reference:
bpf/bpf_lxc.c:418 tail_handle_ipv6 / handle_ipv6_from_lxc)."""

import ipaddress
import random

import numpy as np

from cilium_tpu.datapath.pipeline6 import (
    DROP,
    FORWARD,
    TO_PROXY,
    build_tables6,
    datapath_verdicts6,
    host_oracle6,
)
from cilium_tpu.maps.ctmap import CtKey6, CtMap, PROTO_TCP, PROTO_UDP
from cilium_tpu.maps.ipcache import IpcacheMap
from cilium_tpu.maps.lbmap import LbMap
from cilium_tpu.maps.policymap import DIR_EGRESS, PolicyMap
from cilium_tpu.ops.lpm import ipv6_to_words


def ip6(s: str) -> int:
    return int(ipaddress.IPv6Address(s))


def build_world(rng):
    lb = LbMap()
    for s in range(4):
        vip = ip6(f"fd00:aa::{s + 1}")
        backends = [
            (ip6(f"fd00:be::{s * 8 + b + 1}"), 8000 + b)
            for b in range(rng.randrange(1, 4))
        ]
        lb.upsert_service6(vip, 80, backends, rev_nat_index=s + 1)
    ipc = IpcacheMap()
    for i in range(8):
        ipc.upsert(f"fd00:{i:x}::/64", sec_label=200 + i)
    ipc.upsert("fd00:be::/64", sec_label=600)
    ipc.upsert("fd00:3::7/128", sec_label=777)
    pol = PolicyMap()
    for ident in (200, 201, 600, 777):
        if rng.random() < 0.7:
            pol.allow(ident, 8000, PROTO_TCP, DIR_EGRESS,
                      proxy_port=16000 if rng.random() < 0.4 else 0)
    pol.allow(0, 53, PROTO_UDP, DIR_EGRESS)
    ct = CtMap()
    # some established v6 flows
    for k in range(3):
        ct.create(
            CtKey6(
                daddr=ip6(f"fd00:be::{k + 1}"), saddr=ip6("fd00:1::5"),
                dport=8000, sport=42000 + k, nexthdr=PROTO_TCP,
            ),
            src_sec_id=201,
        )
    return ct, lb, ipc, pol


def gen(rng, f):
    saddr = np.zeros((f,), object)
    daddr = np.zeros((f,), object)
    sport = np.zeros((f,), np.int64)
    dport = np.zeros((f,), np.int64)
    proto = np.zeros((f,), np.int64)
    for i in range(f):
        saddr[i] = ip6(f"fd00:{rng.randrange(8):x}::{rng.randrange(1, 200):x}")
        roll = rng.random()
        if roll < 0.4:  # VIP traffic
            daddr[i] = ip6(f"fd00:aa::{rng.randrange(1, 6)}")
            dport[i] = 80 if rng.random() < 0.8 else 8080
        elif roll < 0.7:  # backend / pod
            daddr[i] = ip6(f"fd00:be::{rng.randrange(1, 30):x}")
            dport[i] = rng.choice([8000, 53, 9999])
        elif roll < 0.85:  # the /128 entry
            daddr[i] = ip6("fd00:3::7")
            dport[i] = 8000
        else:  # unknown -> world
            daddr[i] = ip6("2001:db8::9")
            dport[i] = 8000
        if rng.random() < 0.2:  # sometimes the established tuples
            saddr[i] = ip6("fd00:1::5")
            daddr[i] = ip6(f"fd00:be::{rng.randrange(1, 4)}")
            sport[i] = 42000 + rng.randrange(0, 4)
            dport[i] = 8000
        else:
            sport[i] = rng.randrange(1024, 60000)
        proto[i] = PROTO_TCP if rng.random() < 0.8 else PROTO_UDP
    sw = ipv6_to_words(list(saddr))
    dw = ipv6_to_words(list(daddr))
    return saddr, daddr, sw, dw, sport.astype(np.int32), \
        dport.astype(np.int32), proto.astype(np.int32)


def test_v6_fuzz_matches_host_oracle():
    rng = random.Random(31)
    ct, lb, ipc, pol = build_world(rng)
    tables = build_tables6(ct, lb, ipc, pol)
    f = 512
    saddr, daddr, sw, dw, sport, dport, proto = gen(rng, f)
    out = datapath_verdicts6(tables, sw, dw, sport, dport, proto)
    dev = {
        k: (tuple(np.asarray(w) for w in v) if k == "new_daddr_words"
            else np.asarray(v))
        for k, v in out.items()
    }
    for i in range(f):
        want = host_oracle6(
            ct, lb, ipc, pol, int(saddr[i]), int(daddr[i]),
            int(sport[i]), int(dport[i]), int(proto[i]),
        )
        for fld in ("verdict", "new_dport", "dst_identity", "proxy_port",
                    "rev_nat", "established", "needs_ct_create"):
            assert int(dev[fld][i]) == int(want[fld]), (
                f"pkt {i} field {fld}: {int(dev[fld][i])} != "
                f"{int(want[fld])} ({want})"
            )
        got_daddr = 0
        for w in range(4):
            got_daddr = (got_daddr << 32) | int(
                np.uint32(np.int64(dev["new_daddr_words"][w][i]) & 0xFFFFFFFF)
            )
        assert got_daddr == want["new_daddr"], f"pkt {i} daddr"


def test_v6_established_skips_policy():
    rng = random.Random(32)
    ct, lb, ipc, pol = build_world(rng)
    empty = PolicyMap()
    tables = build_tables6(ct, lb, ipc, empty)
    sw = ipv6_to_words([ip6("fd00:1::5")])
    dw = ipv6_to_words([ip6("fd00:be::1")])
    out = datapath_verdicts6(
        tables, sw, dw,
        np.array([42000], np.int32), np.array([8000], np.int32),
        np.array([PROTO_TCP], np.int32),
    )
    assert int(np.asarray(out["verdict"])[0]) == FORWARD
    assert bool(np.asarray(out["established"])[0])


def test_v6_ct_create_promotes_to_established():
    """apply_ct_creates6 records allowed new flows; the next pass sees
    them established (reference: ct_create6 after the verdict)."""
    from cilium_tpu.datapath.pipeline6 import apply_ct_creates6

    rng = random.Random(33)
    ct, lb, ipc, pol = build_world(rng)
    pol.allow(600, 9100, PROTO_TCP, DIR_EGRESS)
    tables = build_tables6(ct, lb, ipc, pol)
    sw = ipv6_to_words([ip6("fd00:1::9")])
    dw = ipv6_to_words([ip6("fd00:be::5")])
    args = (np.array([5123], np.int32), np.array([9100], np.int32),
            np.array([PROTO_TCP], np.int32))
    out = datapath_verdicts6(tables, sw, dw, *args)
    assert int(np.asarray(out["verdict"])[0]) == FORWARD
    assert bool(np.asarray(out["needs_ct_create"])[0])
    assert apply_ct_creates6(ct, out, sw, args[0], args[2]) == 1
    # rebuild tables (pinned-map snapshot) -> established now
    tables2 = build_tables6(ct, lb, ipc, PolicyMap())  # even with no policy
    out2 = datapath_verdicts6(tables2, sw, dw, *args)
    assert bool(np.asarray(out2["established"])[0])
    assert int(np.asarray(out2["verdict"])[0]) == FORWARD
