"""Device-assisted engine parity: cassandra/memcached through the
sidecar seam must produce the same op/inject streams as the in-process
oracle, with the decisions actually rendered on the device path.
"""

from __future__ import annotations

import random
import struct

import pytest

from cilium_tpu.proxylib import (
    FilterResult,
    NetworkPolicy,
    PortNetworkPolicy,
    PortNetworkPolicyRule,
)
from cilium_tpu.proxylib import instance as inst
from cilium_tpu.proxylib.types import DROP, MORE, PASS
from cilium_tpu.sidecar import SidecarClient, VerdictService
from cilium_tpu.utils.option import DaemonConfig

from proxylib_harness import new_connection


@pytest.fixture
def service(tmp_path):
    inst.reset_module_registry()
    svc = VerdictService(
        str(tmp_path / "l7.sock"), DaemonConfig(batch_timeout_ms=2.0)
    ).start()
    yield svc
    svc.stop()
    inst.reset_module_registry()


@pytest.fixture
def client(service):
    c = SidecarClient(service.socket_path)
    yield c
    c.close()


def cass_policy():
    return NetworkPolicy(
        name="l7e",
        policy=2,
        ingress_per_port_policies=[
            PortNetworkPolicy(
                port=9042,
                rules=[
                    PortNetworkPolicyRule(
                        l7_proto="cassandra",
                        l7_rules=[
                            {"query_action": "select",
                             "query_table": "^public\\."},
                            {"query_action": "use"},
                        ],
                    )
                ],
            )
        ],
    )


def mc_policy():
    return NetworkPolicy(
        name="l7e",
        policy=2,
        ingress_per_port_policies=[
            PortNetworkPolicy(
                port=11211,
                rules=[
                    PortNetworkPolicyRule(
                        l7_proto="memcache",
                        l7_rules=[
                            {"command": "get", "keyPrefix": "user:"},
                            {"command": "set"},
                        ],
                    )
                ],
            )
        ],
    )


def cass_query(cql: str, stream: int = 0) -> bytes:
    q = cql.encode()
    body = struct.pack(">I", len(q)) + q + b"\x00\x01\x00"
    return (
        bytes([4, 0]) + struct.pack(">H", stream) + bytes([0x07])
        + struct.pack(">I", len(body)) + body
    )


def oracle_stream(policy, proto, port, msgs):
    """[(reply, bytes)] through the in-process oracle ->
    [(ops, inject_reply)] per message."""
    mod = inst.open_module([], True)
    ins = inst.find_instance(mod)
    ins.policy_update([policy])
    res, conn = new_connection(
        mod, proto, True, 1, 2, "1.1.1.1:1", f"2.2.2.2:{port}", policy.name
    )
    assert res == FilterResult.OK
    out = []
    bufs = {False: b"", True: b""}
    skip = {False: 0, True: 0}
    for reply, m in msgs:
        if skip[reply]:
            take = min(skip[reply], len(m))
            skip[reply] -= take
            m = m[take:]
        bufs[reply] += m
        ops = []
        conn.on_data(reply, False, [bufs[reply]], ops)
        consumed = 0
        for op, n in ops:
            if op in (PASS, DROP):
                take = min(n, len(bufs[reply]) - consumed)
                consumed += take
                skip[reply] += n - take
        bufs[reply] = bufs[reply][consumed:]
        out.append((
            [(int(o), int(n)) for o, n in ops],
            conn.reply_buf.take(),
        ))
    inst.close_module(mod)
    return out


def sidecar_stream(client, policy, proto, port, msgs, conn_id=7000):
    mod = client.open_module([])
    assert client.policy_update(mod, [policy]) == int(FilterResult.OK)
    res, shim = client.new_connection(
        mod, proto, conn_id, True, 1, 2, "1.1.1.1:1", f"2.2.2.2:{port}",
        policy.name,
    )
    assert res == int(FilterResult.OK)
    out = []
    for reply, m in msgs:
        _, entries = client._on_data_rpc(conn_id, reply, False, m)
        ops, inj = [], b""
        for _, r, eops, _io, ir in entries:
            ops.extend(eops)
            inj += ir
        out.append((ops, inj))
    shim.close()
    return out


def assert_stream_parity(got, exp):
    assert len(got) == len(exp)
    for i, ((gops, ginj), (eops, einj)) in enumerate(zip(got, exp)):
        assert gops == eops, f"msg {i}: ops {gops} != {eops}"
        assert ginj == einj, f"msg {i}: inject {ginj!r} != {einj!r}"


def test_cassandra_sidecar_parity(service, client):
    msgs = [
        (False, cass_query("SELECT * FROM public.users")),
        (False, cass_query("SELECT * FROM secret.creds", stream=3)),
        (False, cass_query("USE public")),
        (False, cass_query("SELECT * FROM t1")),  # -> public.t1, allowed
        (False, cass_query("INSERT INTO public.x (a) VALUES (1)")),
    ]
    exp = oracle_stream(cass_policy(), "cassandra", 9042, msgs)
    got = sidecar_stream(client, cass_policy(), "cassandra", 9042, msgs)
    assert_stream_parity(got, exp)
    # the decisions actually came from the device model
    eng = next(
        e for e in service._engines.values()
        if type(e).__name__ == "CassandraBatchEngine"
    )
    assert eng.device_judged >= 4


def test_cassandra_sidecar_split_frames(service, client):
    f = cass_query("SELECT * FROM public.users")
    msgs = [(False, f[:5]), (False, f[5:20]), (False, f[20:])]
    exp = oracle_stream(cass_policy(), "cassandra", 9042, msgs)
    got = sidecar_stream(client, cass_policy(), "cassandra", 9042, msgs)
    assert_stream_parity(got, exp)


def test_memcache_text_sidecar_parity(service, client):
    msgs = [
        (False, b"get user:1\r\n"),
        (False, b"get admin:1\r\n"),  # denied, queued behind reply 1
        (False, b"set anything 0 0 2\r\nhi\r\n"),
        (True, b"VALUE user:1 0 1\r\nx\r\nEND\r\n"),
        (True, b"STORED\r\n"),
    ]
    exp = oracle_stream(mc_policy(), "memcache", 11211, msgs)
    got = sidecar_stream(client, mc_policy(), "memcache", 11211, msgs)
    assert_stream_parity(got, exp)
    eng = next(
        e for e in service._engines.values()
        if type(e).__name__ == "MemcacheBatchEngine"
    )
    assert eng.device_judged >= 3


def test_memcache_binary_sidecar_parity(service, client):
    def bin_req(opcode, key=b"", extras=b"", value=b""):
        body = extras + key + value
        return (
            bytes([0x80, opcode]) + struct.pack(">H", len(key))
            + bytes([len(extras), 0]) + b"\x00\x00"
            + struct.pack(">I", len(body)) + b"\x00" * 12 + body
        )

    msgs = [
        (False, bin_req(0x00, key=b"user:9")),
        (False, bin_req(0x00, key=b"nope")),
        (False, bin_req(0x01, key=b"k", extras=b"\x00" * 8, value=b"v")),
    ]
    exp = oracle_stream(mc_policy(), "memcache", 11211, msgs)
    got = sidecar_stream(client, mc_policy(), "memcache", 11211, msgs)
    assert_stream_parity(got, exp)


def test_memcache_fuzz_chunked(service, client):
    rng = random.Random(5)
    raw = b"".join(
        [
            b"get user:1\r\n",
            b"get admin:1\r\n",
            b"set k 0 0 4\r\nabcd\r\n",
            b"get user:2 user:3\r\n",  # multi-key -> host fallback
            b"delete user:1\r\n",  # not allowed by policy
            b"get user:4\r\n",
        ]
    )
    msgs = []
    i = 0
    while i < len(raw):
        n = rng.randrange(1, 16)
        msgs.append((False, raw[i : i + n]))
        i += n
    exp = oracle_stream(mc_policy(), "memcache", 11211, msgs)
    got = sidecar_stream(client, mc_policy(), "memcache", 11211, msgs)
    assert_stream_parity(got, exp)


def test_cassandra_fuzz_chunked(service, client):
    rng = random.Random(11)
    frames = [
        cass_query("SELECT * FROM public.users"),
        cass_query("SELECT * FROM secret.x"),
        cass_query("USE public"),
        cass_query("SELECT * FROM y"),
        cass_query("UPDATE public.z SET a=1"),
    ]
    raw = b"".join(frames)
    msgs = []
    i = 0
    while i < len(raw):
        n = rng.randrange(1, 24)
        msgs.append((False, raw[i : i + n]))
        i += n
    exp = oracle_stream(cass_policy(), "cassandra", 9042, msgs)
    got = sidecar_stream(client, cass_policy(), "cassandra", 9042, msgs)
    assert_stream_parity(got, exp)
