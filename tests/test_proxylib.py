"""proxylib framework tests — op/byte-exact oracle scenarios.

Each test replicates a reference scenario from proxylib/proxylib_test.go or
proxylib/r2d2/r2d2parser_test.go with identical expected op sequences and
inject-buffer contents.
"""

import pytest

from cilium_tpu.proxylib import (
    DROP,
    ERROR,
    INJECT,
    MORE,
    NOP,
    PASS,
    FilterResult,
    MemoryAccessLogger,
    NetworkPolicy,
    PolicyParseError,
    PortNetworkPolicy,
    PortNetworkPolicyRule,
    find_instance,
    open_module,
    register_parser_factory,
    reset_module_registry,
)
from cilium_tpu.proxylib.types import OpError

from proxylib_harness import check_on_data, new_connection


@pytest.fixture(autouse=True)
def fresh_registry():
    reset_module_registry()
    yield
    reset_module_registry()


def _mod(**kwargs):
    mod = open_module([], True)
    assert mod != 0
    return mod


def _logger(mod) -> MemoryAccessLogger:
    return find_instance(mod).access_logger


# --- module lifecycle (reference: proxylib_test.go TestOpenModule) -------

def test_open_module_dedup():
    mod1 = open_module([], True)
    mod2 = open_module([], True)
    assert mod1 != 0 and mod2 == mod1
    assert open_module([("dummy-key", "v")], True) == 0
    mod4 = open_module([("access-log-path", "/tmp/x.sock")], True)
    assert mod4 != 0 and mod4 != mod1
    mod5 = open_module(
        [("access-log-path", "/tmp/x.sock"), ("node-id", "host~1~libcilium~dom")], True
    )
    assert mod5 not in (0, mod1, mod4)


# --- connection errors (reference: proxylib_test.go TestOnNewConnection) -

def test_on_new_connection_errors():
    mod = _mod()
    res, _ = new_connection(mod, "invalid-parser", True, 1, 2, "1.1.1.1:34567", "2.2.2.2:80", "policy-1")
    assert res == FilterResult.UNKNOWN_PARSER
    res, _ = new_connection(mod, "test.passer", True, 1, 2, "1.1.1.1:34567", "2.2.2.2:XYZ", "policy-1")
    assert res == FilterResult.INVALID_ADDRESS
    res, _ = new_connection(mod, "test.passer", True, 1, 2, "1.1.1.1:34567", "2.2.2.2", "policy-1")
    assert res == FilterResult.INVALID_ADDRESS
    res, _ = new_connection(mod, "test.passer", True, 1, 2, "1.1.1.1:34567", "2.2.2.2:0", "policy-1")
    assert res == FilterResult.INVALID_ADDRESS
    res, _ = new_connection(mod, "test.passer", True, 1, 2, "1.1.1.1:34567", "2.2.2.2:80", "invalid-policy")
    assert res == FilterResult.POLICY_DROP
    res, conn = new_connection(mod, "test.passer", True, 1, 2, "1.1.1.1:34567", "2.2.2.2:80", "policy-1")
    assert res == FilterResult.OK and conn is not None


# --- no policy: headerparser drops (reference: TestOnDataNoPolicy) -------

def test_on_data_no_policy():
    mod = _mod()
    res, conn = new_connection(
        mod, "test.headerparser", True, 1, 2, "1.1.1.1:34567", "2.2.2.2:80", "policy-1", buf_size=30
    )
    assert res == FilterResult.OK
    line1, line2, line3 = b"No policy\n", b"Dropped\n", b"foo"
    check_on_data(
        conn, False, False, [line1, line2 + line3],
        [(DROP, len(line1)), (DROP, len(line2)), (MORE, 1)],
        exp_reply_buf=b"Line dropped: " + line1 + b"Line dropped: " + line2,
    )
    check_on_data(conn, False, False, [line3], [(MORE, 1)])
    check_on_data(conn, False, False, [], [])
    assert _logger(mod).counts() == (0, 2)


# --- parser panic recovery (reference: TestOnDataPanic) ------------------

class _PanicParser:
    def on_data(self, reply, end_stream, data):
        if not reply:
            raise RuntimeError("panicing...")
        return NOP, 0


class _PanicParserFactory:
    def create(self, connection):
        return _PanicParser()


def test_on_data_panic():
    register_parser_factory("test.panicparser", _PanicParserFactory())
    mod = _mod()
    res, conn = new_connection(
        mod, "test.panicparser", True, 1, 2, "1.1.1.1:34567", "2.2.2.2:80", "policy-1", buf_size=30
    )
    assert res == FilterResult.OK
    check_on_data(conn, False, False, [b"foo"], [], exp_result=FilterResult.PARSER_ERROR)
    assert _logger(mod).counts() == (0, 1)


# --- policies ------------------------------------------------------------

def _policy(name, rules, port=80):
    return NetworkPolicy(
        name=name,
        policy=2,
        ingress_per_port_policies=[PortNetworkPolicy(port=port, rules=rules)],
    )


HEADER_LINES = [b"Beginning----\n", b"foo\n", b"----End\n", b"\n"]


def _header_conn(mod, policy_name="FooBar"):
    res, conn = new_connection(
        mod, "test.headerparser", True, 1, 2, "1.1.1.1:34567", "2.2.2.2:80", policy_name, buf_size=80
    )
    assert res == FilterResult.OK
    return conn


def test_unsupported_l7_drops():
    """Unknown l7 parser => drop all on the port (reference:
    TestUnsupportedL7Drops)."""
    mod = _mod()
    find_instance(mod).policy_update(
        [_policy("FooBar", [PortNetworkPolicyRule(remote_policies=[1, 3], l7_proto="unknown-l7", l7_rules=[])])]
    )
    conn = _header_conn(mod)
    l1, l2, l3, l4 = HEADER_LINES
    check_on_data(
        conn, False, False, [l1 + l2 + l3 + l4],
        [(DROP, len(l1)), (DROP, len(l2)), (DROP, len(l3)), (DROP, len(l4))],
        exp_reply_buf=b"".join(b"Line dropped: " + l for l in HEADER_LINES),
    )
    assert _logger(mod).counts() == (0, 4)


def test_two_rules_same_port_first_no_l7():
    """First rule has no L7 (remote 11 only); second has header rules for
    remotes 1,3,4 (reference: TestTwoRulesOnSamePortFirstNoL7Generic)."""
    mod = _mod()
    find_instance(mod).policy_update(
        [
            _policy(
                "FooBar",
                [
                    PortNetworkPolicyRule(remote_policies=[11]),
                    PortNetworkPolicyRule(
                        remote_policies=[1, 3, 4],
                        l7_proto="test.headerparser",
                        l7_rules=[{"prefix": "Beginning"}, {"suffix": "End"}],
                    ),
                ],
            )
        ]
    )
    conn = _header_conn(mod)
    l1, l2, l3, l4 = HEADER_LINES
    # srcId=1 matches rule 2; prefix/suffix rules pass lines 1 and 3.
    check_on_data(
        conn, False, False, [l1 + l2 + l3 + l4],
        [(PASS, len(l1)), (DROP, len(l2)), (PASS, len(l3)), (DROP, len(l4))],
        exp_reply_buf=b"Line dropped: " + l2 + b"Line dropped: " + l4,
    )
    assert _logger(mod).counts() == (2, 2)


def test_mismatching_l7_types_rejected():
    """Two L7 types on one port => policy update fails atomically
    (reference: TestTwoRulesOnSamePortMismatchingL7, which likewise
    registers a dummy HTTP rule parser first)."""
    from cilium_tpu.proxylib import register_l7_rule_parser

    register_l7_rule_parser("http", lambda rule_config: [])
    mod = _mod()
    ins = find_instance(mod)
    with pytest.raises(PolicyParseError):
        ins.policy_update(
            [
                _policy(
                    "FooBar",
                    [
                        PortNetworkPolicyRule(
                            remote_policies=[11],
                            http_rules=[{"headers": [{"name": ":path", "exact_match": "/allowed"}]}],
                        ),
                        PortNetworkPolicyRule(
                            remote_policies=[1],
                            l7_proto="test.headerparser",
                            l7_rules=[{"prefix": "Beginning"}],
                        ),
                    ],
                )
            ]
        )
    assert not ins.has_policy("FooBar")  # old map untouched


def test_simple_policy_pass_drop():
    """(reference: TestSimplePolicy)."""
    mod = _mod()
    find_instance(mod).policy_update(
        [
            _policy(
                "FooBar",
                [
                    PortNetworkPolicyRule(
                        remote_policies=[1, 3, 4],
                        l7_proto="test.headerparser",
                        l7_rules=[{"prefix": "Beginning"}, {"suffix": "End"}],
                    )
                ],
            )
        ]
    )
    conn = _header_conn(mod)
    l1, l2, l3, l4 = HEADER_LINES
    check_on_data(
        conn, False, False, [l1 + l2 + l3 + l4],
        [(PASS, len(l1)), (DROP, len(l2)), (PASS, len(l3)), (DROP, len(l4))],
        exp_reply_buf=b"Line dropped: " + l2 + b"Line dropped: " + l4,
    )
    assert _logger(mod).counts() == (2, 2)


def test_allow_all_policy():
    """Rule with remotes but no L7 rules => allow all payloads
    (reference: TestAllowAllPolicy)."""
    mod = _mod()
    find_instance(mod).policy_update(
        [
            _policy(
                "FooBar",
                [PortNetworkPolicyRule(remote_policies=[1, 3, 4], l7_proto="test.headerparser", l7_rules=[])],
            )
        ]
    )
    conn = _header_conn(mod)
    l1, l2, l3, l4 = HEADER_LINES
    check_on_data(
        conn, False, False, [l1 + l2 + l3 + l4],
        [(PASS, len(l1)), (PASS, len(l2)), (PASS, len(l3)), (PASS, len(l4))],
    )
    assert _logger(mod).counts() == (4, 0)


def test_wrong_remote_id_drops():
    """Remote not in allowed set => deny."""
    mod = _mod()
    find_instance(mod).policy_update(
        [
            _policy(
                "FooBar",
                [PortNetworkPolicyRule(remote_policies=[11], l7_proto="test.headerparser", l7_rules=[{"prefix": "B"}])],
            )
        ]
    )
    conn = _header_conn(mod)  # srcId=1, not 11
    l1 = HEADER_LINES[0]
    check_on_data(
        conn, False, False, [l1], [(DROP, len(l1))],
        exp_reply_buf=b"Line dropped: " + l1,
    )


# --- line/block parsers (reference: lineparser/blockparser scenarios) ----

def test_line_parser_ops():
    mod = _mod()
    res, conn = new_connection(
        mod, "test.lineparser", True, 1, 2, "1.1.1.1:34567", "2.2.2.2:80", "p", buf_size=80
    )
    assert res == FilterResult.OK
    check_on_data(
        conn, False, False, [b"PASS line\n", b"DROP this\n", b"partial"],
        [(PASS, 10), (DROP, 10), (MORE, 1)],
    )
    # INJECT into reverse direction, then INSERT into current
    check_on_data(
        conn, False, False, [b"INJECT me\n"],
        [(DROP, 10)],
        exp_reply_buf=b"INJECT me\n",
    )
    ops = []
    res = conn.on_data(False, False, [b"INSERT x\n"], ops)
    assert res == FilterResult.OK
    assert ops == [(INJECT, 9), (DROP, 9)]
    assert conn.orig_buf.take() == b"INSERT x\n"


def test_block_parser_ops():
    mod = _mod()
    res, conn = new_connection(
        mod, "test.blockparser", True, 1, 2, "1.1.1.1:34567", "2.2.2.2:80", "p", buf_size=80
    )
    assert res == FilterResult.OK
    # "7:PASS" -> block is '7:PASS' (7 bytes incl. prefix)
    check_on_data(conn, False, False, [b"7:PASS!9:DROP1234"], [(PASS, 7), (DROP, 9), (MORE, 1)])
    check_on_data(conn, False, False, [b"2"], [(MORE, 1)])
    check_on_data(conn, False, False, [], [])
    # Invalid length prefix: the parser yields ERROR; the OnData loop has no
    # ERROR break (reference: connection.go:141-172 breaks only on
    # NOP/MORE/full-inject), so the op repeats to capacity and the datapath
    # closes the connection on the first ERROR it applies.
    ops = []
    res = conn.on_data(False, False, [b"XYZ:foo"], ops)
    assert res == FilterResult.OK
    assert ops == [(ERROR, int(OpError.ERROR_INVALID_FRAME_LENGTH))] * 16


# --- r2d2 (reference: r2d2parser_test.go) --------------------------------

def _r2d2_policy(name, l7_rules):
    return _policy(
        name,
        [PortNetworkPolicyRule(remote_policies=[], l7_proto="r2d2", l7_rules=l7_rules)],
    )


def _r2d2_conn(mod, policy_name):
    res, conn = new_connection(
        mod, "r2d2", True, 1, 2, "1.1.1.1:34567", "2.2.2.2:80", policy_name
    )
    assert res == FilterResult.OK
    return conn


def test_r2d2_incomplete():
    mod = _mod()
    conn = _r2d2_conn(mod, "no-policy")
    check_on_data(conn, False, False, [b"READ xssss"], [(MORE, 1)])


def test_r2d2_basic_pass():
    mod = _mod()
    find_instance(mod).policy_update([_r2d2_policy("cp1", None)])
    conn = _r2d2_conn(mod, "cp1")
    msgs = [b"READ sssss\r\n", b"WRITE sssss\r\n", b"HALT\r\n", b"RESET\r\n"]
    check_on_data(
        conn, False, False, [b"".join(msgs)],
        [(PASS, len(m)) for m in msgs] + [(MORE, 1)],
    )


def test_r2d2_split_message():
    mod = _mod()
    find_instance(mod).policy_update([_r2d2_policy("cp1", None)])
    conn = _r2d2_conn(mod, "cp1")
    check_on_data(
        conn, False, False, [b"RE", b"SET\r\n"],
        [(PASS, 7), (MORE, 1)],
    )


def test_r2d2_allow_deny_cmd():
    mod = _mod()
    find_instance(mod).policy_update([_r2d2_policy("cp2", [{"cmd": "READ"}])])
    conn = _r2d2_conn(mod, "cp2")
    msg1, msg2 = b"READ xssss\r\n", b"WRITE xssss\r\n"
    check_on_data(
        conn, False, False, [msg1 + msg2],
        [(PASS, len(msg1)), (DROP, len(msg2)), (MORE, 1)],
        exp_reply_buf=b"ERROR\r\n",
    )
    assert _logger(mod).counts() == (1, 1)


def test_r2d2_allow_deny_regex():
    mod = _mod()
    find_instance(mod).policy_update([_r2d2_policy("cp3", [{"file": "s.*"}])])
    conn = _r2d2_conn(mod, "cp3")
    msg1, msg2 = b"READ ssss\r\n", b"WRITE yyyyy\r\n"
    check_on_data(
        conn, False, False, [msg1 + msg2],
        [(PASS, len(msg1)), (DROP, len(msg2)), (MORE, 1)],
        exp_reply_buf=b"ERROR\r\n",
    )


def test_r2d2_reply_passes():
    mod = _mod()
    find_instance(mod).policy_update([_r2d2_policy("cp1", [{"cmd": "READ"}])])
    conn = _r2d2_conn(mod, "cp1")
    check_on_data(conn, True, False, [b"OK data\r\n"], [(PASS, 9), (MORE, 1)])


def test_r2d2_rule_validation():
    mod = _mod()
    ins = find_instance(mod)
    with pytest.raises(PolicyParseError):
        ins.policy_update([_r2d2_policy("bad1", [{"cmd": "FLY"}])])
    with pytest.raises(PolicyParseError):
        ins.policy_update([_r2d2_policy("bad2", [{"cmd": "HALT", "file": "x"}])])
    with pytest.raises(PolicyParseError):
        ins.policy_update([_r2d2_policy("bad3", [{"bogus": "x"}])])


# --- wildcard port (reference: policymap.go:216-223) ---------------------

def test_wildcard_port():
    mod = _mod()
    find_instance(mod).policy_update(
        [_policy("wc", [PortNetworkPolicyRule(l7_proto="r2d2", l7_rules=[{"cmd": "READ"}])], port=0)]
    )
    conn = _r2d2_conn(mod, "wc")  # port 80, policy only has port 0
    check_on_data(conn, False, False, [b"READ f\r\n"], [(PASS, 8), (MORE, 1)])
    check_on_data(
        conn, False, False, [b"HALT\r\n"], [(DROP, 6), (MORE, 1)],
        exp_reply_buf=b"ERROR\r\n",
    )


def test_no_policy_for_port_drops():
    mod = _mod()
    find_instance(mod).policy_update(
        [_policy("p90", [PortNetworkPolicyRule(l7_proto="r2d2", l7_rules=[{"cmd": "READ"}])], port=90)]
    )
    conn = _r2d2_conn(mod, "p90")  # port 80; policy has only port 90, no wildcard
    check_on_data(
        conn, False, False, [b"READ f\r\n"], [(DROP, 8), (MORE, 1)],
        exp_reply_buf=b"ERROR\r\n",
    )
