"""Services / load-balancer control plane end-to-end
(reference: pkg/service/id_kvstore.go, daemon/loadbalancer.go,
daemon/k8s_watcher.go:822,945 service+endpoints informers).

Covers: kvstore service-ID allocation (cluster-wide convergence),
ServiceManager map programming, k8s Service+Endpoints -> lb_map sync,
the datapath pipeline DNATing a flow to a programmed backend, and the
REST + CLI round trips.
"""

import ipaddress
import json
import time

import numpy as np
import pytest

from cilium_tpu.api import ApiClient, ApiError, ApiServer
from cilium_tpu.cli import main as cli_main
from cilium_tpu.daemon.daemon import Daemon
from cilium_tpu.datapath.pipeline import (
    FORWARD,
    build_tables,
    datapath_verdicts,
)
from cilium_tpu.k8s import FakeApiServer, K8sWatcher
from cilium_tpu.k8s.apiserver import KIND_ENDPOINTS, KIND_SERVICE
from cilium_tpu.kvstore import LocalBackend
from cilium_tpu.maps.ctmap import PROTO_TCP
from cilium_tpu.maps.ipcache import IpcacheMap
from cilium_tpu.maps.lbmap import LbKey, LbMap
from cilium_tpu.maps.policymap import DIR_EGRESS, PolicyMap
from cilium_tpu.service import (
    L3n4Addr,
    ServiceError,
    ServiceIDAllocator,
    ServiceManager,
)
from cilium_tpu.utils.option import DaemonConfig


@pytest.fixture
def daemon(tmp_path):
    cfg = DaemonConfig(
        run_dir=str(tmp_path),
        socket_path=str(tmp_path / "agent.sock"),
        monitor_socket_path=str(tmp_path / "monitor.sock"),
        dry_mode=True,
    )
    d = Daemon(cfg, node_name="test-node")
    yield d
    d.close()


def ip4(s: str) -> int:
    return int(ipaddress.IPv4Address(s))


# --- service-ID allocation (reference: pkg/service/id_kvstore.go) --------

def test_id_allocator_acquire_reuse_delete():
    be = LocalBackend()
    alloc = ServiceIDAllocator(be)
    fe = L3n4Addr("172.16.0.1", 80)
    id1 = alloc.acquire_id(fe)
    assert id1 >= 1
    # Same frontend -> same ID (cluster-wide convergence).
    assert alloc.acquire_id(fe) == id1
    # Different frontend -> different ID.
    id2 = alloc.acquire_id(L3n4Addr("172.16.0.2", 80))
    assert id2 != id1
    assert alloc.get_id(id1) == fe
    assert alloc.delete_id(id1)
    assert alloc.get_id(id1) is None
    assert not alloc.delete_id(id1)


def test_id_allocator_two_agents_converge():
    """Two managers over one kvstore allocate the same ID for the same
    frontend (reference: AcquireID reuse across agents)."""
    be = LocalBackend()
    a1 = ServiceIDAllocator(be)
    a2 = ServiceIDAllocator(be)
    fe = L3n4Addr("10.96.0.10", 53)
    assert a1.acquire_id(fe) == a2.acquire_id(fe)


def test_id_allocator_desired_id_conflicts():
    be = LocalBackend()
    alloc = ServiceIDAllocator(be)
    fe = L3n4Addr("172.16.0.1", 80)
    assert alloc.acquire_id(fe, desired=7) == 7
    # Same frontend, different desired ID -> error (SVCAdd contract).
    with pytest.raises(ServiceError):
        alloc.acquire_id(fe, desired=9)
    # Different frontend, taken ID -> error.
    with pytest.raises(ServiceError):
        alloc.acquire_id(L3n4Addr("172.16.0.2", 80), desired=7)
    # Matching desired is idempotent.
    assert alloc.acquire_id(fe, desired=7) == 7


# --- ServiceManager map programming (reference: addSVC2BPFMap) -----------

def test_service_manager_programs_lbmap():
    lb = LbMap()
    mgr = ServiceManager(lb, LocalBackend())
    fe = L3n4Addr("172.16.0.1", 80)
    svc_id, created = mgr.upsert(
        fe, [L3n4Addr("10.0.0.1", 8080), L3n4Addr("10.0.0.2", 8080)]
    )
    assert created
    master = lb.services[LbKey(ip4("172.16.0.1"), 80, 0)]
    assert master.count == 2 and master.rev_nat_index == svc_id
    assert lb.revnat[svc_id] == (ip4("172.16.0.1"), 80)
    assert lb.services[LbKey(ip4("172.16.0.1"), 80, 1)].target == ip4("10.0.0.1")

    # Update backends in place: same ID, new slave set.
    svc_id2, created2 = mgr.upsert(fe, [L3n4Addr("10.0.0.9", 9090)])
    assert svc_id2 == svc_id and not created2
    master = lb.services[LbKey(ip4("172.16.0.1"), 80, 0)]
    assert master.count == 1
    assert LbKey(ip4("172.16.0.1"), 80, 2) not in lb.services
    assert mgr.get(svc_id).backends[0].port == 9090

    assert mgr.delete_by_id(svc_id)
    assert LbKey(ip4("172.16.0.1"), 80, 0) not in lb.services
    assert svc_id not in lb.revnat
    assert mgr.get(svc_id) is None
    assert not mgr.delete_by_id(svc_id)


def test_service_manager_resync_converges_under_churn():
    """k8s→lbmap resync (PR 9): after a burst of missed add/update/
    delete events, resync with the full desired set converges the maps
    — stale frontends pruned, surviving IDs stable, new ones
    programmed."""
    lb = LbMap()
    mgr = ServiceManager(lb, LocalBackend())
    fes = [L3n4Addr(f"172.16.0.{i}", 80) for i in range(1, 6)]
    ids = {}
    for fe in fes:
        ids[fe.key()], _ = mgr.upsert(fe, [L3n4Addr("10.0.0.1", 8080)])
    # Churn the apiserver's world while this agent missed the events:
    # fe[0], fe[1] deleted; fe[2] rebackended; a new fe appears.
    new_fe = L3n4Addr("172.16.0.9", 443)
    desired = [
        (fes[2], [L3n4Addr("10.0.9.9", 9999)]),
        (fes[3], [L3n4Addr("10.0.0.1", 8080)]),
        (fes[4], [L3n4Addr("10.0.0.1", 8080)]),
        (new_fe, [L3n4Addr("10.0.4.4", 8443)]),
    ]
    out = mgr.resync(desired)
    assert out["pruned"] == 2 and out["created"] == 1
    assert out["upserted"] == 4
    # Stale frontends gone from manager AND map.
    assert mgr.get_by_frontend(fes[0]) is None
    assert LbKey(ip4("172.16.0.1"), 80, 0) not in lb.services
    # Survivors keep their service IDs (RevNAT stability under churn).
    assert mgr.get_by_frontend(fes[3]).id == ids[fes[3].key()]
    # Rebackended service reprogrammed.
    assert mgr.get_by_frontend(fes[2]).backends[0].port == 9999
    # New service programmed.
    assert mgr.get_by_frontend(new_fe) is not None
    assert len(mgr) == 4
    # Idempotent: a second resync with the same desired set is a no-op.
    out2 = mgr.resync(desired)
    assert out2["pruned"] == 0 and out2["created"] == 0


def test_service_manager_rejects_protocol_only_collision():
    """The LB map key is (vip, port) without protocol (reference:
    bpf lb4_key) — a second service differing only in protocol would
    silently share the slot, so it is rejected."""
    lb = LbMap()
    mgr = ServiceManager(lb, LocalBackend())
    mgr.upsert(L3n4Addr("10.0.0.1", 53, "TCP"), [L3n4Addr("10.1.0.1", 53)])
    with pytest.raises(ServiceError):
        mgr.upsert(L3n4Addr("10.0.0.1", 53, "UDP"), [L3n4Addr("10.1.0.2", 53)])
    # Same protocol re-upsert still fine.
    mgr.upsert(L3n4Addr("10.0.0.1", 53, "TCP"), [L3n4Addr("10.1.0.3", 53)])


def test_service_manager_v6_and_family_mismatch():
    lb = LbMap()
    mgr = ServiceManager(lb, LocalBackend())
    fe6 = L3n4Addr("fd00::1", 443)
    svc_id, _ = mgr.upsert(fe6, [L3n4Addr("fd00::10", 8443)])
    assert lb.services6[LbKey(int(ipaddress.IPv6Address("fd00::1")), 443, 0)].count == 1
    assert lb.revnat6[svc_id] == (int(ipaddress.IPv6Address("fd00::1")), 443)
    with pytest.raises(ServiceError):
        mgr.upsert(L3n4Addr("172.16.0.1", 80), [L3n4Addr("fd00::10", 8443)])
    assert mgr.delete_by_frontend(fe6)
    assert not lb.services6


# --- k8s Service+Endpoints -> lb_map (reference: addK8sSVCs) -------------

def svc_obj(name="svc1", ns="default", cluster_ip="10.96.0.1", ports=None):
    return {
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "clusterIP": cluster_ip,
            "ports": ports or [
                {"name": "http", "port": 80, "protocol": "TCP"}
            ],
        },
    }


def eps_obj(name="svc1", ns="default", ips=("10.0.1.1", "10.0.1.2"),
            ports=None):
    return {
        "metadata": {"name": name, "namespace": ns},
        "subsets": [{
            "addresses": [{"ip": ip} for ip in ips],
            "ports": ports or [
                {"name": "http", "port": 8080, "protocol": "TCP"}
            ],
        }],
    }


@pytest.fixture
def watched(daemon):
    apisrv = FakeApiServer()
    w = K8sWatcher(daemon, apisrv).start()
    yield daemon, apisrv, w
    w.stop()


def test_k8s_service_sync_programs_lb(watched):
    d, apisrv, w = watched
    apisrv.upsert(KIND_SERVICE, svc_obj())
    apisrv.upsert(KIND_ENDPOINTS, eps_obj())
    w.sync()
    svc = d.service_manager.get_by_frontend(L3n4Addr("10.96.0.1", 80))
    assert svc is not None
    assert sorted(b.ip for b in svc.backends) == ["10.0.1.1", "10.0.1.2"]
    assert all(b.port == 8080 for b in svc.backends)
    master = d.lb_map.services[LbKey(ip4("10.96.0.1"), 80, 0)]
    assert master.count == 2 and master.rev_nat_index == svc.id

    # Endpoint churn: backend set follows (reference: addK8sEndpointV1).
    apisrv.upsert(KIND_ENDPOINTS, eps_obj(ips=("10.0.1.3",)))
    w.sync()
    svc = d.service_manager.get_by_frontend(L3n4Addr("10.96.0.1", 80))
    assert [b.ip for b in svc.backends] == ["10.0.1.3"]
    assert d.lb_map.services[LbKey(ip4("10.96.0.1"), 80, 0)].count == 1

    # Service delete tears everything down (reference: delK8sSVCs).
    apisrv.delete(KIND_SERVICE, "default", "svc1")
    w.sync()
    assert d.service_manager.get_by_frontend(L3n4Addr("10.96.0.1", 80)) is None
    assert LbKey(ip4("10.96.0.1"), 80, 0) not in d.lb_map.services


def test_k8s_headless_service_programs_nothing(watched):
    d, apisrv, w = watched
    apisrv.upsert(KIND_SERVICE, svc_obj(name="hl", cluster_ip="None"))
    apisrv.upsert(KIND_ENDPOINTS, eps_obj(name="hl"))
    w.sync()
    assert len(d.service_manager) == 0
    assert not d.lb_map.services


def test_k8s_service_port_removal_prunes_frontend(watched):
    d, apisrv, w = watched
    apisrv.upsert(KIND_SERVICE, svc_obj(ports=[
        {"name": "http", "port": 80, "protocol": "TCP"},
        {"name": "https", "port": 443, "protocol": "TCP"},
    ]))
    apisrv.upsert(KIND_ENDPOINTS, eps_obj(ports=[
        {"name": "http", "port": 8080, "protocol": "TCP"},
        {"name": "https", "port": 8443, "protocol": "TCP"},
    ]))
    w.sync()
    assert d.service_manager.get_by_frontend(L3n4Addr("10.96.0.1", 443)) is not None
    apisrv.upsert(KIND_SERVICE, svc_obj())  # https port gone
    w.sync()
    assert d.service_manager.get_by_frontend(L3n4Addr("10.96.0.1", 443)) is None
    assert d.service_manager.get_by_frontend(L3n4Addr("10.96.0.1", 80)) is not None
    assert LbKey(ip4("10.96.0.1"), 443, 0) not in d.lb_map.services


def test_k8s_service_without_endpoints_has_empty_backends(watched):
    """reference: addK8sSVCs installs the frontend with no backends when
    the Endpoints object has not arrived yet."""
    d, apisrv, w = watched
    apisrv.upsert(KIND_SERVICE, svc_obj())
    w.sync()
    svc = d.service_manager.get_by_frontend(L3n4Addr("10.96.0.1", 80))
    assert svc is not None and svc.backends == []


# --- datapath e2e: k8s manifest -> watcher -> lb_map -> DNAT -------------

def test_k8s_service_to_datapath_dnat(watched):
    """The full vertical the VERDICT asked for: Service manifest ->
    watcher -> lb_map -> the device pipeline DNATs a flow to a backend
    (reference: lb4_lookup_service from handle_ipv4_from_lxc,
    bpf_lxc.c:684)."""
    d, apisrv, w = watched
    apisrv.upsert(KIND_SERVICE, svc_obj())
    apisrv.upsert(KIND_ENDPOINTS, eps_obj())
    w.sync()
    svc = d.service_manager.get_by_frontend(L3n4Addr("10.96.0.1", 80))

    ipc = IpcacheMap()
    ipc.upsert("10.0.1.0/24", sec_label=300)
    pol = PolicyMap()
    pol.allow(300, 8080, PROTO_TCP, DIR_EGRESS)
    tables = build_tables(d.ct_map, d.lb_map, ipc, pol)

    as_i32 = lambda v: np.asarray([v], np.int64).astype(np.uint32).view(np.int32)
    out = datapath_verdicts(
        tables,
        as_i32(ip4("10.0.9.9")), as_i32(ip4("10.96.0.1")),
        np.asarray([40000], np.int32), np.asarray([80], np.int32),
        np.asarray([PROTO_TCP], np.int32),
    )
    out = {k: np.asarray(v) for k, v in out.items()}
    assert int(out["verdict"][0]) == FORWARD
    new_daddr = int(out["new_daddr"][0]) & 0xFFFFFFFF
    assert new_daddr in (ip4("10.0.1.1"), ip4("10.0.1.2"))
    assert int(out["new_dport"][0]) == 8080
    # RevNAT index carried for the reply path = the kvstore service ID.
    assert int(out["rev_nat"][0]) == svc.id


# --- REST + CLI (reference: PUT/GET/DELETE /service, cilium service) -----

@pytest.fixture
def api(daemon, tmp_path):
    server = ApiServer(daemon, str(tmp_path / "agent.sock"))
    client = ApiClient(str(tmp_path / "agent.sock"))
    yield client
    server.close()


def test_service_rest_roundtrip(api):
    body = {
        "frontend-address": {"ip": "172.16.9.1", "port": 80,
                             "protocol": "TCP"},
        "backend-addresses": [
            {"ip": "10.0.0.1", "port": 8080},
            {"ip": "10.0.0.2", "port": 8080},
        ],
    }
    out = api.put("/v1/service/5", body)
    assert out["id"] == 5
    assert len(out["backend-addresses"]) == 2

    got = api.get("/v1/service/5")
    assert got["frontend-address"]["ip"] == "172.16.9.1"
    assert [s["id"] for s in api.get("/v1/service")] == [5]

    # Conflicting PUT: same frontend under another ID -> 460 (reference:
    # PutServiceIDInvalidFrontendCode family).
    with pytest.raises(ApiError):
        api.put("/v1/service/6", body)

    api.delete("/v1/service/5")
    assert api.get("/v1/service") == []
    with pytest.raises(ApiError):
        api.get("/v1/service/5")


def test_service_cli(api, daemon, tmp_path, capsys):
    sock = str(tmp_path / "agent.sock")
    assert cli_main([
        "--socket", sock, "service", "update", "--id", "3",
        "--frontend", "172.16.9.2:443",
        "--backends", "10.0.0.5:8443,10.0.0.6:8443",
    ]) == 0
    assert cli_main(["--socket", sock, "service", "list"]) == 0
    out = capsys.readouterr().out
    assert "172.16.9.2:443/TCP" in out and "10.0.0.5:8443" in out
    assert cli_main(["--socket", sock, "service", "get", "3"]) == 0
    assert cli_main(["--socket", sock, "service", "delete", "3"]) == 0
    assert len(daemon.service_manager) == 0
