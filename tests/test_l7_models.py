"""Device-model fuzz tests: cassandra + memcached batch models must be
bit-identical to the host oracle rule cascade (the ported proxylib
matchers) over randomized policies and request batches.
"""

import random

import numpy as np
import pytest

from cilium_tpu.models.cassandra import (
    build_cassandra_model,
    cassandra_verdicts,
    encode_cassandra_batch,
)
from cilium_tpu.models.memcached import (
    TEXT_COMMANDS,
    build_memcache_model,
    encode_memcache_batch,
    memcache_verdicts,
)
from cilium_tpu.models.base import ConstVerdict
from cilium_tpu.proxylib import (
    NetworkPolicy,
    PortNetworkPolicy,
    PortNetworkPolicyRule,
)
from cilium_tpu.proxylib.parsers.memcached import MemcacheMeta
from cilium_tpu.proxylib.policy import compile_policy

ACTIONS = ["select", "insert", "update", "delete", "use", "create-table"]
TABLES = [
    "system.local", "ks1.users", "ks1.orders", "secret.creds",
    "public.data", "a.b",
]
TABLE_PATTERNS = [
    "^system\\.", "^ks1\\.", "users", "^public\\.data$", ".*", "^a\\.",
]


def cass_policy(rng, n_rules):
    rules = []
    for _ in range(n_rules):
        kv = {}
        if rng.random() < 0.7:
            kv["query_action"] = rng.choice(ACTIONS)
        if rng.random() < 0.7:
            kv["query_table"] = rng.choice(TABLE_PATTERNS)
        rules.append(kv)
    remotes = sorted(rng.sample(range(1, 8), rng.randrange(0, 3)))
    return NetworkPolicy(
        name="fz",
        policy=2,
        ingress_per_port_policies=[
            PortNetworkPolicy(
                port=9042,
                rules=[
                    PortNetworkPolicyRule(
                        remote_policies=remotes,
                        l7_proto="cassandra",
                        l7_rules=rules,
                    )
                ],
            )
        ],
    )


@pytest.mark.parametrize("seed", range(6))
def test_cassandra_model_matches_oracle(seed):
    rng = random.Random(seed)
    policy = compile_policy(cass_policy(rng, rng.randrange(1, 4)))
    model = build_cassandra_model(policy, ingress=True, port=9042)

    reqs, paths, remotes = [], [], []
    for _ in range(128):
        if rng.random() < 0.15:
            op = rng.choice(["options", "startup", "register"])
            reqs.append((op, "", True))
            paths.append(f"/{op}")
        else:
            action = rng.choice(ACTIONS)
            table = rng.choice(TABLES)
            reqs.append((action, table, False))
            paths.append(f"/query/{action}/{table}")
        remotes.append(rng.randrange(1, 8))

    expected = [
        policy.matches(True, 9042, r, p) for r, p in zip(remotes, paths)
    ]
    if isinstance(model, ConstVerdict):
        assert all(e == model.allow for e in expected)
        return
    data, alen, tlen, nq, overflow = encode_cassandra_batch(reqs)
    assert not overflow.any()
    allow = np.asarray(
        cassandra_verdicts(
            model, data, alen, tlen, nq, np.asarray(remotes, np.int32)
        )
    )
    for i in range(len(reqs)):
        assert bool(allow[i]) == expected[i], (
            f"req {reqs[i]} remote {remotes[i]}: device {bool(allow[i])} "
            f"!= oracle {expected[i]}"
        )


MC_COMMANDS = ["get", "set", "delete", "incr", "stats", "touch", "flush_all"]
MC_GROUPS = ["get", "set", "storage", "writeGroup", "delete", "stats", "touch"]
MC_KEYS = [b"user:1", b"user:2", b"admin:1", b"k42", b"x", b""]


def mc_policy(rng, n_rules):
    rules = []
    for _ in range(n_rules):
        kv = {}
        if rng.random() < 0.85:
            kv["command"] = rng.choice(MC_GROUPS)
            mode = rng.randrange(4)
            if mode == 1:
                kv["keyExact"] = rng.choice(["user:1", "k42"])
            elif mode == 2:
                kv["keyPrefix"] = rng.choice(["user:", "k"])
            elif mode == 3:
                kv["keyRegex"] = rng.choice(["^user:[0-9]+$", "k[0-9]+", "^x"])
        rules.append(kv)
    remotes = sorted(rng.sample(range(1, 8), rng.randrange(0, 3)))
    return NetworkPolicy(
        name="fz",
        policy=2,
        ingress_per_port_policies=[
            PortNetworkPolicy(
                port=11211,
                rules=[
                    PortNetworkPolicyRule(
                        remote_policies=remotes,
                        l7_proto="memcache",
                        l7_rules=rules,
                    )
                ],
            )
        ],
    )


BIN_OPCODES = [0, 1, 2, 4, 5, 16, 20, 28, 10, 11]


@pytest.mark.parametrize("seed", range(6))
def test_memcache_model_matches_oracle(seed):
    rng = random.Random(100 + seed)
    policy = compile_policy(mc_policy(rng, rng.randrange(1, 4)))
    model = build_memcache_model(policy, ingress=True, port=11211)

    frames, metas, remotes = [], [], []
    for _ in range(128):
        if rng.random() < 0.5:  # binary
            op = rng.choice(BIN_OPCODES)
            key = rng.choice(MC_KEYS)
            frames.append((True, op, "", [key]))
            metas.append(MemcacheMeta(opcode=op, keys=[key]))
        else:  # text
            cmd = rng.choice(MC_COMMANDS)
            nkeys = 0 if cmd in ("stats", "flush_all") else 1
            keys = [rng.choice(MC_KEYS[:-1]) for _ in range(nkeys)]
            frames.append((False, 0, cmd, keys))
            metas.append(MemcacheMeta(command=cmd, keys=keys))
        remotes.append(rng.randrange(1, 8))

    expected = [
        policy.matches(True, 11211, r, m) for r, m in zip(remotes, metas)
    ]
    if isinstance(model, ConstVerdict):
        assert all(e == model.allow for e in expected)
        return
    key_data, key_len, has_key, is_bin, opcode, cmd_id, overflow = (
        encode_memcache_batch(frames)
    )
    assert not overflow.any()
    allow = np.asarray(
        memcache_verdicts(
            model, key_data, key_len, has_key, is_bin, opcode, cmd_id,
            np.asarray(remotes, np.int32),
        )
    )
    for i in range(len(frames)):
        assert bool(allow[i]) == expected[i], (
            f"frame {frames[i]} remote {remotes[i]}: device "
            f"{bool(allow[i])} != oracle {expected[i]}"
        )


def test_memcache_multikey_overflow_flagged():
    frames = [(False, 0, "get", [b"a", b"b"]), (False, 0, "get", [b"a"])]
    *_, overflow = encode_memcache_batch(frames)
    assert overflow.tolist() == [True, False]


def test_cassandra_oversize_table_overflow_flagged():
    reqs = [("select", "x" * 200, False), ("select", "ks.t", False)]
    *_, overflow = encode_cassandra_batch(reqs)
    assert overflow.tolist() == [True, False]
