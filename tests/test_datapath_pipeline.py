"""Composed L3/L4 datapath pipeline vs host oracle: bit-identical
verdicts for the CT -> LB -> ipcache -> policy composition
(reference: bpf/bpf_lxc.c:684-760 handle_ipv4_from_lxc)."""

import ipaddress
import random

import numpy as np

from cilium_tpu.datapath.pipeline import (
    DROP,
    FORWARD,
    TO_PROXY,
    apply_ct_creates,
    build_tables,
    datapath_verdicts,
    host_oracle,
)
from cilium_tpu.maps.ctmap import CtKey4, CtMap, PROTO_TCP, PROTO_UDP
from cilium_tpu.maps.ipcache import IpcacheMap
from cilium_tpu.maps.lbmap import LbMap
from cilium_tpu.maps.policymap import DIR_EGRESS, PolicyMap


def ip(i: int) -> int:
    return int(ipaddress.IPv4Address(f"10.{(i >> 8) & 255}.{i & 255}.{i % 250 + 1}"))


def build_world(rng):
    lb = LbMap()
    for s in range(8):
        vip = int(ipaddress.IPv4Address(f"172.16.0.{s + 1}"))
        n_be = rng.randrange(1, 4)
        backends = [
            (ip(1000 + s * 10 + b), 8000 + b) for b in range(n_be)
        ]
        lb.upsert_service(vip, 80, backends, rev_nat_index=s + 1)
    ipc = IpcacheMap()
    for i in range(20):
        ipc.upsert(f"10.0.{i}.0/24", sec_label=100 + i)
    ipc.upsert("10.1.0.0/16", sec_label=500)
    ipc.upsert("10.0.3.7/32", sec_label=777)
    pol = PolicyMap()
    for ident in (100, 101, 102, 500, 777):
        if rng.random() < 0.7:
            pol.allow(ident, 8000, PROTO_TCP, DIR_EGRESS,
                      proxy_port=15000 if rng.random() < 0.4 else 0)
        if rng.random() < 0.3:
            pol.allow(ident, 0, 0, DIR_EGRESS)  # L3-only allow
    pol.allow(0, 53, PROTO_UDP, DIR_EGRESS)  # wildcard-identity rule
    ct = CtMap()
    return ct, lb, ipc, pol


def gen_packets(rng, f):
    saddr = np.zeros((f,), np.int64)
    daddr = np.zeros((f,), np.int64)
    sport = np.zeros((f,), np.int64)
    dport = np.zeros((f,), np.int64)
    proto = np.zeros((f,), np.int64)
    for i in range(f):
        saddr[i] = ip(rng.randrange(64))
        roll = rng.random()
        if roll < 0.5:  # service VIP traffic
            daddr[i] = int(ipaddress.IPv4Address(f"172.16.0.{rng.randrange(1, 10)}"))
            dport[i] = 80 if rng.random() < 0.8 else 8080
        elif roll < 0.9:  # direct pod/world traffic
            daddr[i] = ip(rng.randrange(2000))
            dport[i] = rng.choice([8000, 53, 9999])
        else:  # unknown destination -> world identity
            daddr[i] = int(ipaddress.IPv4Address("192.168.9.9"))
            dport[i] = 8000
        sport[i] = rng.randrange(1024, 60000)
        proto[i] = PROTO_TCP if rng.random() < 0.8 else PROTO_UDP
    as_i32 = lambda a: (a & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
    return as_i32(saddr), as_i32(daddr), sport.astype(np.int32), \
        dport.astype(np.int32), proto.astype(np.int32)


def check_batch(ct, lb, ipc, pol, pkts):
    saddr, daddr, sport, dport, proto = pkts
    tables = build_tables(ct, lb, ipc, pol)
    out = datapath_verdicts(tables, saddr, daddr, sport, dport, proto)
    out = {k: np.asarray(v) for k, v in out.items()}
    for i in range(len(saddr)):
        want = host_oracle(
            ct, lb, ipc, pol,
            int(saddr[i]) & 0xFFFFFFFF, int(daddr[i]) & 0xFFFFFFFF,
            int(sport[i]), int(dport[i]), int(proto[i]),
        )
        for field in ("verdict", "new_dport", "dst_identity",
                      "proxy_port", "rev_nat", "established",
                      "needs_ct_create"):
            got = out[field][i]
            assert bool(got) == bool(want[field]) if field in (
                "established", "needs_ct_create"
            ) else int(got) == int(want[field]), (
                f"pkt {i} field {field}: device={got} oracle={want[field]}"
            )
        assert int(out["new_daddr"][i]) & 0xFFFFFFFF == want["new_daddr"]
    return out


def test_fuzz_matches_host_oracle():
    rng = random.Random(7)
    ct, lb, ipc, pol = build_world(rng)
    pkts = gen_packets(rng, 128)
    out = check_batch(ct, lb, ipc, pol, pkts)
    got = np.asarray(out["verdict"])
    # the corpus must exercise every verdict
    assert (got == FORWARD).any() and (got == DROP).any() and (
        got == TO_PROXY
    ).any(), got


def test_established_skips_policy():
    """A CT hit forwards even when policy would deny
    (reference: handle_ipv4 CT_ESTABLISHED path)."""
    rng = random.Random(8)
    ct, lb, ipc, pol = build_world(rng)
    pol.flush()  # deny-all policy
    saddr = int(ipaddress.IPv4Address("10.0.0.1"))
    daddr = ip(5)
    ct.create(CtKey4(daddr=daddr, saddr=saddr, dport=8000, sport=4242,
                     nexthdr=PROTO_TCP))
    as32 = lambda v: np.asarray([v], np.int64).astype(np.uint32).view(np.int32)
    tables = build_tables(ct, lb, ipc, pol)
    out = datapath_verdicts(
        tables, as32(saddr), as32(daddr),
        np.asarray([4242], np.int32), np.asarray([8000], np.int32),
        np.asarray([PROTO_TCP], np.int32),
    )
    assert int(np.asarray(out["verdict"])[0]) == FORWARD
    assert bool(np.asarray(out["established"])[0])
    # the same packet from a different sport is policy-checked -> DROP
    out2 = datapath_verdicts(
        tables, as32(saddr), as32(daddr),
        np.asarray([4243], np.int32), np.asarray([8000], np.int32),
        np.asarray([PROTO_TCP], np.int32),
    )
    assert int(np.asarray(out2["verdict"])[0]) == DROP


def test_ct_create_roundtrip():
    """Allowed new flows report needs_ct_create; applying them makes the
    next batch see the flows as established (the kernel ct_create4
    analog crossing the device boundary)."""
    rng = random.Random(9)
    ct, lb, ipc, pol = build_world(rng)
    pol.flush()
    pol.allow(100, 8000, PROTO_TCP, DIR_EGRESS)
    saddr = np.asarray([ip(1)], np.int64).astype(np.uint32).view(np.int32)
    daddr_i = int(ipaddress.IPv4Address("10.0.0.9"))  # identity 100
    daddr = np.asarray([daddr_i], np.int64).astype(np.uint32).view(np.int32)
    sport = np.asarray([5000], np.int32)
    dport = np.asarray([8000], np.int32)
    proto = np.asarray([PROTO_TCP], np.int32)
    tables = build_tables(ct, lb, ipc, pol)
    out = datapath_verdicts(tables, saddr, daddr, sport, dport, proto)
    assert bool(np.asarray(out["needs_ct_create"])[0])
    n = apply_ct_creates(ct, {k: np.asarray(v) for k, v in out.items()},
                         saddr, sport, proto)
    assert n == 1
    tables2 = build_tables(ct, lb, ipc, pol)
    out2 = datapath_verdicts(tables2, saddr, daddr, sport, dport, proto)
    assert bool(np.asarray(out2["established"])[0])
    assert not bool(np.asarray(out2["needs_ct_create"])[0])


def test_service_dnat_and_revnat():
    """VIP traffic is DNATed to a backend with the service's rev_nat
    index recorded (reference: lb.h lb4_local)."""
    rng = random.Random(10)
    ct, lb, ipc, pol = build_world(rng)
    pol.allow(0, 0, 0, DIR_EGRESS)  # wildcard L3 allow-all... identity 0
    vip = int(ipaddress.IPv4Address("172.16.0.1"))
    as32 = lambda v: np.asarray([v], np.int64).astype(np.uint32).view(np.int32)
    tables = build_tables(ct, lb, ipc, pol)
    out = datapath_verdicts(
        tables, as32(ip(3)), as32(vip), np.asarray([1234], np.int32),
        np.asarray([80], np.int32), np.asarray([PROTO_TCP], np.int32),
    )
    assert int(np.asarray(out["rev_nat"])[0]) == 1
    nd = int(np.asarray(out["new_daddr"])[0]) & 0xFFFFFFFF
    assert nd != vip  # DNATed to a backend
    assert int(np.asarray(out["new_dport"])[0]) >= 8000
    # device backend pick agrees with the host pick (same hash fn)
    want = host_oracle(ct, lb, ipc, pol, ip(3), vip, 1234, 80, PROTO_TCP)
    assert nd == want["new_daddr"]

def test_verdict_accounting_metrics_and_drop_notifications():
    """Batched metrics + bounded drop notifications from one pipeline
    output (reference: bpf/lib/metrics.h update_metrics +
    drop.h send_drop_notify -> perf ring -> monitor)."""
    from cilium_tpu.datapath.notify import (
        DROP_POLICY_REASON,
        MAX_DROP_NOTIFICATIONS,
        account_verdicts,
    )
    from cilium_tpu.maps.metricsmap import (
        METRIC_DIR_EGRESS,
        MetricsMap,
        REASON_FORWARDED,
    )
    from cilium_tpu.monitor import MSG_TYPE_DROP, Monitor

    rng = random.Random(41)
    ct, lb, ipc, pol = build_world(rng)
    tables = build_tables(ct, lb, ipc, pol)
    pkts = gen_packets(rng, 512)
    out = datapath_verdicts(tables, *pkts)

    metrics = MetricsMap()
    monitor = Monitor(4096)
    lengths = np.full((512,), 100, np.int64)
    counts = account_verdicts(
        out, metrics, monitor=monitor, lengths=lengths,
        dports=pkts[3], proto=pkts[4],
    )
    verdict = np.asarray(out["verdict"])
    assert counts["dropped"] == int((verdict == 1).sum())
    assert counts["forwarded"] == int((verdict == 0).sum())
    assert counts["proxied"] == int((verdict == 2).sum())

    fwd = metrics.get(REASON_FORWARDED, METRIC_DIR_EGRESS)
    assert fwd.count == counts["forwarded"] + counts["proxied"]
    assert fwd.bytes == 100 * fwd.count
    drp = metrics.get(DROP_POLICY_REASON, METRIC_DIR_EGRESS)
    assert drp.count == counts["dropped"]
    assert drp.bytes == 100 * counts["dropped"]

    # Drop notifications are emitted (bounded) with packet context.
    drops = [e for e in monitor.recent(4096) if e.type == MSG_TYPE_DROP]
    assert len(drops) == min(counts["dropped"], MAX_DROP_NOTIFICATIONS)
    if drops:
        assert drops[0].payload["dport"] in (80, 8080, 8000, 53, 9999)
