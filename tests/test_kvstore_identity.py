"""kvstore, allocator, identity, ipcache, node discovery tests.

Multi-node convergence is exercised by running several allocator/store
clients against one shared backend — the same strategy as the reference's
kvstore tests against a real etcd (reference: pkg/kvstore/*_test.go,
Makefile:88 start-kvstores), without the external process.
"""

import time

import pytest

from cilium_tpu.identity import (
    Identity,
    IdentityAllocator,
    MIN_USER_IDENTITY,
    RESERVED_HOST,
    RESERVED_WORLD,
    ReservedIdentities,
    look_up_reserved_identity,
)
from cilium_tpu.ipcache import (
    IPIdentityCache,
    IPIdentityPair,
    KvstoreIPSync,
    datapath_listener,
)
from cilium_tpu.kvstore import LocalBackend, FileBackend
from cilium_tpu.kvstore.allocator import Allocator, AllocatorError
from cilium_tpu.kvstore.backend import EventType
from cilium_tpu.kvstore.store import SharedStore
from cilium_tpu.labels import Labels
from cilium_tpu.maps.ipcache import IpcacheMap
from cilium_tpu.node import Node, NodeDiscovery


def wait_for(cond, timeout=3.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


class TestLocalBackend:
    def test_crud(self):
        b = LocalBackend()
        assert b.get("k") is None
        b.set("a/k1", b"v1")
        b.set("a/k2", b"v2")
        assert b.get("a/k1") == b"v1"
        assert b.get_prefix("a/") == b"v1"
        assert set(b.list_prefix("a/")) == {"a/k1", "a/k2"}
        b.delete("a/k1")
        assert b.get("a/k1") is None
        b.delete_prefix("a/")
        assert b.list_prefix("a/") == {}

    def test_create_only_atomic(self):
        b = LocalBackend()
        assert b.create_only("k", b"1")
        assert not b.create_only("k", b"2")
        assert b.get("k") == b"1"

    def test_create_if_exists(self):
        b = LocalBackend()
        assert not b.create_if_exists("cond", "k", b"v")
        b.set("cond", b"x")
        assert b.create_if_exists("cond", "k", b"v")
        assert b.get("k") == b"v"

    def test_watch_list_then_live(self):
        b = LocalBackend()
        b.set("p/a", b"1")
        w = b.list_and_watch("t", "p/")
        ev = w.next_event(1)
        assert ev.typ == EventType.CREATE and ev.key == "p/a"
        assert w.next_event(1).typ == EventType.LIST_DONE
        b.set("p/b", b"2")
        b.delete("p/b")
        assert w.next_event(1).typ == EventType.CREATE
        assert w.next_event(1).typ == EventType.DELETE
        # outside prefix: not delivered
        b.set("q/x", b"3")
        assert w.next_event(0.05) is None
        w.stop()

    def test_lease_revoked_on_close(self):
        b = LocalBackend()
        b.set("leased", b"1", lease=True)
        b.set("durable", b"2")
        b.close()
        assert b.get("leased") is None
        assert b.get("durable") == b"2"

    def test_lock_path(self):
        b = LocalBackend()
        l1 = b.lock_path("x")
        with pytest.raises(Exception):
            b.lock_path("x", timeout=0.05)
        l1.unlock()
        b.lock_path("x", timeout=0.5).unlock()

    def test_file_backend_persists(self, tmp_path):
        path = str(tmp_path / "kv.json")
        b1 = FileBackend(path)
        b1.set("persist/me", b"hello")
        b1.set("lease/me", b"bye", lease=True)
        b1._persist()
        b2 = FileBackend(path)
        assert b2.get("persist/me") == b"hello"
        assert b2.get("lease/me") is None  # leases don't survive restart


class TestAllocator:
    def test_allocate_reuse_and_refcount(self):
        b = LocalBackend()
        a = Allocator(b, "test/ids", "node1", min_id=10, max_id=20)
        id1, new1 = a.allocate("key-a")
        assert new1 and 10 <= id1 <= 20
        id2, new2 = a.allocate("key-a")
        assert id2 == id1 and not new2
        id3, _ = a.allocate("key-b")
        assert id3 != id1
        # release: refcount 2 -> 1 keeps the value key
        assert a.release("key-a")
        assert b.list_prefix(a._value_prefix("key-a") + "/")
        assert a.release("key-a")
        assert not b.list_prefix(a._value_prefix("key-a") + "/")

    def test_cross_node_convergence(self):
        b = LocalBackend()
        a1 = Allocator(b, "test/ids", "node1", min_id=10, max_id=1000)
        a2 = Allocator(b, "test/ids", "node2", min_id=10, max_id=1000)
        id1, new1 = a1.allocate("shared-key")
        id2, new2 = a2.allocate("shared-key")
        assert id1 == id2
        assert new1 and not new2

    def test_gc_removes_unreferenced(self):
        b = LocalBackend()
        a = Allocator(b, "test/ids", "node1", min_id=10, max_id=20)
        id1, _ = a.allocate("k")
        a.release("k")
        assert a.run_gc() == 1
        assert b.get(a._id_path(id1)) is None
        # ID is reusable again
        id2, _ = a.allocate("k2")
        a.release("k2")

    def test_exhaustion(self):
        b = LocalBackend()
        a = Allocator(b, "test/ids", "n", min_id=1, max_id=2)
        a.allocate("x")
        a.allocate("y")
        with pytest.raises(AllocatorError):
            a.allocate("z")

    def test_watch_updates_cache(self):
        b = LocalBackend()
        a1 = Allocator(b, "test/ids", "node1", min_id=10, max_id=99)
        a1.start_watch()
        a2 = Allocator(b, "test/ids", "node2", min_id=10, max_id=99)
        id_, _ = a2.allocate("remote-key")
        assert wait_for(lambda: a1.get_by_id(id_) == "remote-key")

    def test_restart_syncs_existing(self):
        b = LocalBackend()
        a1 = Allocator(b, "test/ids", "node1", min_id=10, max_id=99)
        id_, _ = a1.allocate("persisted")
        a3 = Allocator(b, "test/ids", "node1-restarted", min_id=10, max_id=99)
        assert a3.get_by_id(id_) == "persisted"
        # restarted node reuses, not reallocates
        id2, new = a3.allocate("persisted")
        assert id2 == id_ and not new


class TestIdentity:
    def test_reserved(self):
        assert ReservedIdentities["host"].id == RESERVED_HOST
        assert look_up_reserved_identity(RESERVED_WORLD).labels.get_model() == [
            "reserved:world"
        ]

    def test_allocate_reserved_labels(self):
        alloc = IdentityAllocator(backend=LocalBackend())
        lbls = Labels.from_model(["reserved:host"])
        ident, new = alloc.allocate(lbls)
        assert ident.id == RESERVED_HOST and not new

    def test_allocate_user_identity_round_trip(self):
        b = LocalBackend()
        alloc = IdentityAllocator(backend=b)
        lbls = Labels.from_model(["k8s:app=web", "k8s:env=prod"])
        ident, new = alloc.allocate(lbls)
        assert new and ident.id >= MIN_USER_IDENTITY
        # same labels, same identity
        ident2, new2 = alloc.allocate(lbls)
        assert ident2.id == ident.id and not new2
        # lookup by id recovers the labels
        got = alloc.lookup_by_id(ident.id)
        assert got is not None and got.labels.equals(lbls)
        assert alloc.lookup(lbls).id == ident.id
        # cache includes reserved + allocated
        cache = alloc.get_identity_cache()
        assert RESERVED_HOST in cache and ident.id in cache

    def test_cross_node_identity(self):
        b = LocalBackend()
        a1 = IdentityAllocator(backend=b, node_name="n1")
        a2 = IdentityAllocator(backend=b, node_name="n2")
        lbls = Labels.from_model(["k8s:app=db"])
        i1, _ = a1.allocate(lbls)
        i2, _ = a2.allocate(lbls)
        assert i1.id == i2.id

    def test_owner_notified_on_remote_change(self):
        b = LocalBackend()
        notified = []
        a1 = IdentityAllocator(backend=b, node_name="n1",
                               owner_notify=lambda: notified.append(1))
        a2 = IdentityAllocator(backend=b, node_name="n2")
        a2.allocate(Labels.from_model(["k8s:app=x"]))
        assert wait_for(lambda: len(notified) > 0)


class TestIPCache:
    def test_upsert_delete_listeners(self):
        c = IPIdentityCache()
        events = []
        c.add_listener(lambda e, ip, p: events.append((e, ip)))
        assert c.upsert("10.0.0.1", 100)
        assert not c.upsert("10.0.0.1", 100)  # unchanged
        assert c.upsert("10.0.0.1", 200)  # identity change
        assert c.lookup_by_ip("10.0.0.1") == 200
        assert c.lookup_by_identity(200) == ["10.0.0.1"]
        assert c.delete("10.0.0.1")
        assert not c.delete("10.0.0.1")
        assert events == [
            ("upsert", "10.0.0.1"), ("upsert", "10.0.0.1"),
            ("delete", "10.0.0.1"),
        ]

    def test_listener_replays_existing(self):
        c = IPIdentityCache()
        c.upsert("10.0.0.2", 7)
        seen = []
        c.add_listener(lambda e, ip, p: seen.append((e, ip, p.identity)))
        assert seen == [("upsert", "10.0.0.2", 7)]

    def test_datapath_listener_mirrors_map(self):
        c = IPIdentityCache()
        m = IpcacheMap()
        c.add_listener(datapath_listener(m))
        c.upsert("10.0.0.3", 55)
        assert m.lookup("10.0.0.3").sec_label == 55
        c.delete("10.0.0.3")
        assert m.lookup("10.0.0.3") is None

    def test_kvstore_sync_two_nodes(self):
        b = LocalBackend()
        c1 = IPIdentityCache()
        c2 = IPIdentityCache()
        s1 = KvstoreIPSync(c1, backend=b)
        s2 = KvstoreIPSync(c2, backend=b)
        s2.start_watcher()
        s1.upsert_to_kvstore(IPIdentityPair("10.1.0.1", 321))
        assert wait_for(lambda: c2.lookup_by_ip("10.1.0.1") == 321)
        s1.delete_from_kvstore("10.1.0.1")
        assert wait_for(lambda: c2.lookup_by_ip("10.1.0.1") is None)
        s2.stop()


class TestSharedStoreAndNodes:
    def test_store_sync(self):
        b = LocalBackend()
        seen = {}
        s1 = SharedStore(b, "test/store", "n1")
        s2 = SharedStore(b, "test/store", "n2",
                         on_update=lambda n, v: seen.update({n: v}))
        s1.update_local_key_sync("n1", {"x": 1})
        assert wait_for(lambda: s2.get("n1") == {"x": 1})
        assert seen["n1"] == {"x": 1}
        s1.delete_local_key("n1")
        assert wait_for(lambda: s2.get("n1") is None)

    def test_node_discovery(self):
        b = LocalBackend()
        n1 = NodeDiscovery(Node(name="node1", ipv4_address="192.168.0.1",
                                ipv4_alloc_cidr="10.1.0.0/16"), backend=b)
        updates = []
        n2 = NodeDiscovery(Node(name="node2", ipv4_address="192.168.0.2"),
                           backend=b, on_node_update=lambda n: updates.append(n.name))
        assert wait_for(lambda: "default/node1" in n2.get_nodes())
        got = n2.get_nodes()["default/node1"]
        assert got.ipv4_alloc_cidr == "10.1.0.0/16"
        # local update propagates
        n1.update_local(ipv4_health_ip="10.1.0.4")
        assert wait_for(
            lambda: n2.get_nodes()["default/node1"].ipv4_health_ip == "10.1.0.4"
        )
        n1.close()
        assert wait_for(lambda: "default/node1" not in n2.get_nodes())
        n2.close()
