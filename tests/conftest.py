"""Test configuration: force a virtual 8-device CPU mesh for sharding tests.

The axon TPU plugin (sitecustomize) overrides JAX_PLATFORMS at import time,
so env vars alone don't stick — the config must be updated programmatically
before the first backend use.  Benchmarks (bench.py) do NOT use this and run
on the real TPU chip.
"""

import os

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax (< 0.5) spells it via XLA_FLAGS; the flag is read at
    # backend initialization, which no test has triggered yet.
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long soak/chaos tests excluded from the tier-1 run "
        "(-m 'not slow')",
    )


# --- thread-leak guard -----------------------------------------------------
#
# A hung BatchDispatcher worker or a leaked non-daemon thread used to eat
# the whole tier-1 timeout before anything failed.  This fixture makes the
# hang fail FAST and NAMED: after each test module, any surviving
# dispatcher worker or module-spawned non-daemon thread fails that module
# with the thread list in the message.

import threading

import pytest


@pytest.fixture(scope="module", autouse=True)
def _no_leaked_threads():
    baseline = set(threading.enumerate())
    yield
    GRACE_S = 5.0
    deadline = None

    def _offenders():
        dispatchers = [
            t for t in threading.enumerate()
            if t.is_alive() and t.name.startswith("verdict-dispatch")
            and not t.name.endswith("-watchdog")
        ]
        nondaemon = [
            t for t in threading.enumerate()
            if t.is_alive() and not t.daemon
            and t is not threading.main_thread()
            and t not in baseline
        ]
        return dispatchers, nondaemon

    import time as _time

    deadline = _time.monotonic() + GRACE_S
    dispatchers, nondaemon = _offenders()
    while (dispatchers or nondaemon) and _time.monotonic() < deadline:
        for t in dispatchers + nondaemon:
            t.join(timeout=0.25)
        dispatchers, nondaemon = _offenders()
    assert not dispatchers, (
        "stuck BatchDispatcher worker(s) survived the module: "
        f"{[t.name for t in dispatchers]} — a service was not stopped or "
        "a dispatch round is hung"
    )
    assert not nondaemon, (
        "leaked non-daemon thread(s) survived the module: "
        f"{[t.name for t in nondaemon]}"
    )
