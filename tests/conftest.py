"""Test configuration: force a virtual 8-device CPU mesh for sharding tests.

The axon TPU plugin (sitecustomize) overrides JAX_PLATFORMS at import time,
so env vars alone don't stick — the config must be updated programmatically
before the first backend use.  Benchmarks (bench.py) do NOT use this and run
on the real TPU chip.
"""

import os

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax (< 0.5) spells it via XLA_FLAGS; the flag is read at
    # backend initialization, which no test has triggered yet.
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )


# The lint corpus holds deliberately-broken KNOWN-BAD snippets for the
# analyzer's regression suite — some (the R21 landing-bar twins) are
# named test_*.py because the rule checks parity-test file naming.
# They are analyzer INPUT, never runnable tests.
collect_ignore = ["lint_corpus"]


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long soak/chaos tests excluded from the tier-1 run "
        "(-m 'not slow')",
    )


# --- thread-leak guard -----------------------------------------------------
#
# A hung BatchDispatcher worker or a leaked non-daemon thread used to eat
# the whole tier-1 timeout before anything failed.  This fixture makes the
# hang fail FAST and NAMED: after each test module, any surviving
# dispatcher worker or module-spawned non-daemon thread fails that module
# with the thread list in the message.

import socket as _socket
import threading
import weakref

import pytest

# --- listening-socket leak guard (complements lint rules R3/R6) ------------
#
# A server that a test never close()s keeps its LISTENING socket alive for
# the rest of the run: the port/path keeps accepting into a dead object
# (the exact zombie-listener shape rule R3 flags in production code).
# Track every socket that listen()s; at module teardown any socket that
# started listening during the module and is still open fails the module,
# named by address.

_listening: "weakref.WeakSet[_socket.socket]" = weakref.WeakSet()
_orig_listen = _socket.socket.listen


def _tracking_listen(self, *args):
    _listening.add(self)
    return _orig_listen(self, *args)


_socket.socket.listen = _tracking_listen


def _open_listeners():
    out = []
    for s in list(_listening):
        try:
            if s.fileno() != -1:
                out.append(s)
        except OSError:
            pass
    return out


def _describe_sock(s):
    try:
        return repr(s.getsockname())
    except OSError:
        return "<unknown addr>"


@pytest.fixture(scope="module", autouse=True)
def _no_leaked_listening_sockets():
    baseline = set(_open_listeners())
    yield
    import time as _time

    deadline = _time.monotonic() + 2.0
    leaked = [s for s in _open_listeners() if s not in baseline]
    while leaked and _time.monotonic() < deadline:
        _time.sleep(0.05)  # teardown threads may still be closing
        leaked = [s for s in _open_listeners() if s not in baseline]
    assert not leaked, (
        "leaked LISTENING socket(s) survived the module (a server was "
        "not close()d — the zombie-listener shape lint rule R3 flags): "
        f"{[_describe_sock(s) for s in leaked]}"
    )


# --- shared-memory segment leak guard --------------------------------------
#
# The shm transport (sidecar/shm.py) creates /dev/shm segments per
# session.  A test that forgets close()/unlink() leaks a mapping (and a
# backing file) for the rest of the run — invisible until /dev/shm
# fills or the resource tracker spams at exit.  Weakref-track every
# SharedMemory create/attach; at module teardown, any handle opened
# during the module that is still mapped — or a segment created during
# the module and never unlinked — fails the module, named.

from multiprocessing import shared_memory as _shared_memory

_shm_handles: "weakref.WeakSet" = weakref.WeakSet()
_shm_created: dict[str, bool] = {}  # name -> unlinked yet?

_orig_shm_init = _shared_memory.SharedMemory.__init__
_orig_shm_unlink = _shared_memory.SharedMemory.unlink


def _tracking_shm_init(self, *args, **kwargs):
    _orig_shm_init(self, *args, **kwargs)
    _shm_handles.add(self)
    created = kwargs.get("create", args[1] if len(args) > 1 else False)
    if created:
        _shm_created[self.name] = False


def _tracking_shm_unlink(self):
    _shm_created[self.name] = True
    return _orig_shm_unlink(self)


_shared_memory.SharedMemory.__init__ = _tracking_shm_init
_shared_memory.SharedMemory.unlink = _tracking_shm_unlink


def _open_shm_handles():
    out = []
    for s in list(_shm_handles):
        if getattr(s, "_buf", None) is not None:  # not yet close()d
            out.append(s)
    return out


@pytest.fixture(scope="module", autouse=True)
def _no_leaked_shm_segments():
    baseline_handles = set(_open_shm_handles())
    baseline_names = set(_shm_created)
    yield
    import time as _time

    def _leaks():
        handles = [
            s for s in _open_shm_handles() if s not in baseline_handles
        ]
        names = [
            n for n, unlinked in _shm_created.items()
            if n not in baseline_names and not unlinked
        ]
        return handles, names

    deadline = _time.monotonic() + 2.0
    handles, names = _leaks()
    while (handles or names) and _time.monotonic() < deadline:
        _time.sleep(0.05)  # teardown threads may still be releasing
        handles, names = _leaks()
    assert not handles, (
        "leaked SharedMemory handle(s) survived the module (a ring/"
        "segment was not close()d): "
        f"{sorted({s.name for s in handles})}"
    )
    assert not names, (
        "SharedMemory segment(s) created during the module were never "
        f"unlink()ed (backing /dev/shm files leak): {sorted(names)}"
    )


@pytest.fixture(scope="module", autouse=True)
def _no_leaked_threads():
    baseline = set(threading.enumerate())
    yield
    GRACE_S = 5.0
    deadline = None

    def _offenders():
        dispatchers = [
            t for t in threading.enumerate()
            if t.is_alive() and t.name.startswith("verdict-dispatch")
            and not t.name.endswith("-watchdog")
        ]
        nondaemon = [
            t for t in threading.enumerate()
            if t.is_alive() and not t.daemon
            and t is not threading.main_thread()
            and t not in baseline
        ]
        return dispatchers, nondaemon

    import time as _time

    deadline = _time.monotonic() + GRACE_S
    dispatchers, nondaemon = _offenders()
    while (dispatchers or nondaemon) and _time.monotonic() < deadline:
        for t in dispatchers + nondaemon:
            t.join(timeout=0.25)
        dispatchers, nondaemon = _offenders()
    assert not dispatchers, (
        "stuck BatchDispatcher worker(s) survived the module: "
        f"{[t.name for t in dispatchers]} — a service was not stopped or "
        "a dispatch round is hung"
    )
    assert not nondaemon, (
        "leaked non-daemon thread(s) survived the module: "
        f"{[t.name for t in nondaemon]}"
    )
