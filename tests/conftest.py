"""Test configuration: force a virtual 8-device CPU mesh for sharding tests.

The axon TPU plugin (sitecustomize) overrides JAX_PLATFORMS at import time,
so env vars alone don't stick — the config must be updated programmatically
before the first backend use.  Benchmarks (bench.py) do NOT use this and run
on the real TPU chip.
"""

import os

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax (< 0.5) spells it via XLA_FLAGS; the flag is read at
    # backend initialization, which no test has triggered yet.
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long soak/chaos tests excluded from the tier-1 run "
        "(-m 'not slow')",
    )


# --- thread-leak guard -----------------------------------------------------
#
# A hung BatchDispatcher worker or a leaked non-daemon thread used to eat
# the whole tier-1 timeout before anything failed.  This fixture makes the
# hang fail FAST and NAMED: after each test module, any surviving
# dispatcher worker or module-spawned non-daemon thread fails that module
# with the thread list in the message.

import socket as _socket
import threading
import weakref

import pytest

# --- listening-socket leak guard (complements lint rules R3/R6) ------------
#
# A server that a test never close()s keeps its LISTENING socket alive for
# the rest of the run: the port/path keeps accepting into a dead object
# (the exact zombie-listener shape rule R3 flags in production code).
# Track every socket that listen()s; at module teardown any socket that
# started listening during the module and is still open fails the module,
# named by address.

_listening: "weakref.WeakSet[_socket.socket]" = weakref.WeakSet()
_orig_listen = _socket.socket.listen


def _tracking_listen(self, *args):
    _listening.add(self)
    return _orig_listen(self, *args)


_socket.socket.listen = _tracking_listen


def _open_listeners():
    out = []
    for s in list(_listening):
        try:
            if s.fileno() != -1:
                out.append(s)
        except OSError:
            pass
    return out


def _describe_sock(s):
    try:
        return repr(s.getsockname())
    except OSError:
        return "<unknown addr>"


@pytest.fixture(scope="module", autouse=True)
def _no_leaked_listening_sockets():
    baseline = set(_open_listeners())
    yield
    import time as _time

    deadline = _time.monotonic() + 2.0
    leaked = [s for s in _open_listeners() if s not in baseline]
    while leaked and _time.monotonic() < deadline:
        _time.sleep(0.05)  # teardown threads may still be closing
        leaked = [s for s in _open_listeners() if s not in baseline]
    assert not leaked, (
        "leaked LISTENING socket(s) survived the module (a server was "
        "not close()d — the zombie-listener shape lint rule R3 flags): "
        f"{[_describe_sock(s) for s in leaked]}"
    )


@pytest.fixture(scope="module", autouse=True)
def _no_leaked_threads():
    baseline = set(threading.enumerate())
    yield
    GRACE_S = 5.0
    deadline = None

    def _offenders():
        dispatchers = [
            t for t in threading.enumerate()
            if t.is_alive() and t.name.startswith("verdict-dispatch")
            and not t.name.endswith("-watchdog")
        ]
        nondaemon = [
            t for t in threading.enumerate()
            if t.is_alive() and not t.daemon
            and t is not threading.main_thread()
            and t not in baseline
        ]
        return dispatchers, nondaemon

    import time as _time

    deadline = _time.monotonic() + GRACE_S
    dispatchers, nondaemon = _offenders()
    while (dispatchers or nondaemon) and _time.monotonic() < deadline:
        for t in dispatchers + nondaemon:
            t.join(timeout=0.25)
        dispatchers, nondaemon = _offenders()
    assert not dispatchers, (
        "stuck BatchDispatcher worker(s) survived the module: "
        f"{[t.name for t in dispatchers]} — a service was not stopped or "
        "a dispatch round is hung"
    )
    assert not nondaemon, (
        "leaked non-daemon thread(s) survived the module: "
        f"{[t.name for t in nondaemon]}"
    )
