"""Test configuration: force a virtual 8-device CPU mesh for sharding tests.

The axon TPU plugin (sitecustomize) overrides JAX_PLATFORMS at import time,
so env vars alone don't stick — the config must be updated programmatically
before the first backend use.  Benchmarks (bench.py) do NOT use this and run
on the real TPU chip.
"""

import os

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax (< 0.5) spells it via XLA_FLAGS; the flag is read at
    # backend initialization, which no test has triggered yet.
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long soak/chaos tests excluded from the tier-1 run "
        "(-m 'not slow')",
    )
