"""Test configuration: force a virtual 8-device CPU mesh for sharding tests.

The axon TPU plugin (sitecustomize) overrides JAX_PLATFORMS at import time,
so env vars alone don't stick — the config must be updated programmatically
before the first backend use.  Benchmarks (bench.py) do NOT use this and run
on the real TPU chip.
"""

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
