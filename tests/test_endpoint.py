"""Endpoint lifecycle tests: state machine, regeneration pipeline, policy
map sync, redirects, restore, manager, build queue.

Modeled on the reference's endpoint + daemon policy tests (reference:
pkg/endpoint tests, daemon/policy_test.go:481 — rules in, expected
per-identity policy map entries out).
"""

import threading
import time

import pytest

from cilium_tpu.endpoint import (
    BuildQueue,
    Endpoint,
    EndpointManager,
    EndpointState,
)
from cilium_tpu.endpoint.endpoint import LOCALHOST_KEY
from cilium_tpu.identity import Identity, RESERVED_HOST
from cilium_tpu.labels import Labels
from cilium_tpu.maps.policymap import DIR_EGRESS, DIR_INGRESS, PolicyKey
from cilium_tpu.policy import (
    EndpointSelector,
    IngressRule,
    L7Rules,
    PortProtocol,
    PortRule,
    PortRuleL7,
    Repository,
    Rule,
    set_policy_enabled,
)
from cilium_tpu.proxy import ProxyManager
from cilium_tpu.utils import option
from cilium_tpu.utils.option import DaemonConfig
from cilium_tpu.labels import parse_select_label


def sel(*lbls):
    return EndpointSelector.from_labels(*(parse_select_label(l) for l in lbls))


class FakeOwner:
    def __init__(self):
        self.repo = Repository()
        self.identity_cache = {}
        self.proxy = ProxyManager()

    def get_policy_repository(self):
        return self.repo

    def get_identity_cache(self):
        return dict(self.identity_cache)

    def get_proxy_manager(self):
        return self.proxy

    def update_network_policy(self, ep):
        return True  # no proxy layer attached — vacuous ACK


@pytest.fixture(autouse=True)
def _default_enforcement():
    # Fresh global config: daemon tests install their own (dry-mode) one.
    option.config = DaemonConfig()
    set_policy_enabled("default")
    yield
    set_policy_enabled("default")


def make_endpoint(ep_id=100, identity_id=1000, labels=("k8s:app=server",)):
    ep = Endpoint(ep_id, ipv4="10.0.0.10")
    ep.set_identity(Identity(id=identity_id, labels=Labels.from_model(labels)))
    ep.state = EndpointState.WAITING_TO_REGENERATE
    return ep


class TestStateMachine:
    def test_valid_lifecycle(self):
        ep = Endpoint(1)
        assert ep.set_state(EndpointState.WAITING_FOR_IDENTITY)
        assert ep.set_state(EndpointState.READY)
        assert ep.set_state(EndpointState.WAITING_TO_REGENERATE)
        assert ep.set_state(EndpointState.REGENERATING)
        assert ep.set_state(EndpointState.READY)
        assert ep.set_state(EndpointState.DISCONNECTING)
        assert ep.set_state(EndpointState.DISCONNECTED)

    def test_invalid_transitions_rejected(self):
        ep = Endpoint(1)
        assert not ep.set_state(EndpointState.READY)  # creating -> ready
        ep.state = EndpointState.DISCONNECTED
        assert not ep.set_state(EndpointState.READY)
        assert not ep.set_state(EndpointState.DISCONNECTED)  # same state


class TestRegeneration:
    def test_l3_l4_map_entries(self):
        owner = FakeOwner()
        server_lbls = Labels.from_model(["k8s:app=server"])
        client_lbls = Labels.from_model(["k8s:app=client"])
        owner.identity_cache = {1000: server_lbls, 2000: client_lbls}
        # L4 rule: client -> server on 80/TCP; plus L3-only from client.
        owner.repo.add(
            Rule(
                endpoint_selector=sel("app=server"),
                ingress=[
                    IngressRule(
                        from_endpoints=[sel("app=client")],
                        to_ports=[
                            PortRule(ports=[PortProtocol("80", "TCP")])
                        ],
                    )
                ],
            )
        )
        ep = make_endpoint()
        assert ep.regenerate(owner)
        assert ep.state == EndpointState.READY
        # desired state contains the L4 key for the client identity
        assert PolicyKey(2000, 80, 6, DIR_INGRESS) in ep.desired_map_state
        # no entry for the server identity itself (rule doesn't allow it)
        assert PolicyKey(1000, 80, 6, DIR_INGRESS) not in ep.desired_map_state
        # egress not enforced (no egress rules select the ep) -> allow-all
        # entries for all identities
        assert PolicyKey(2000, 0, 0, DIR_EGRESS) in ep.desired_map_state
        # realized matches desired after sync
        assert set(ep.realized_map_state) == set(ep.desired_map_state)
        # the host policy map answers the datapath question
        allowed, port = ep.policy_map.lookup(2000, 80, 6, DIR_INGRESS)
        assert allowed and port == 0
        allowed, _ = ep.policy_map.lookup(3000, 80, 6, DIR_INGRESS)
        assert not allowed
        # device export present
        assert ep.device_policy_map is not None

    def test_redirect_allocates_proxy_port(self):
        owner = FakeOwner()
        owner.identity_cache = {
            1000: Labels.from_model(["k8s:app=server"]),
            2000: Labels.from_model(["k8s:app=client"]),
        }
        owner.repo.add(
            Rule(
                endpoint_selector=sel("app=server"),
                ingress=[
                    IngressRule(
                        from_endpoints=[sel("app=client")],
                        to_ports=[
                            PortRule(
                                ports=[PortProtocol("80", "TCP")],
                                rules=L7Rules(
                                    l7proto="r2d2",
                                    l7=[PortRuleL7({"cmd": "READ"})],
                                ),
                            )
                        ],
                    )
                ],
            )
        )
        ep = make_endpoint()
        assert ep.regenerate(owner)
        key = PolicyKey(2000, 80, 6, DIR_INGRESS)
        assert key in ep.desired_map_state
        port = ep.desired_map_state[key].proxy_port
        assert 10000 <= port < 20000
        # redirect registered under the endpoint's proxy ID
        pid = f"{ep.id}:ingress:TCP:80"
        assert owner.proxy.get(pid).proxy_port == port
        # localhost allowed because a redirect exists (policy.go:262)
        assert LOCALHOST_KEY in ep.desired_map_state
        # datapath lookup returns the proxy port
        allowed, got = ep.policy_map.lookup(2000, 80, 6, DIR_INGRESS)
        assert allowed and got == port
        # second regeneration reuses the same port
        ep.force_policy_compute = True
        ep.set_state(EndpointState.WAITING_TO_REGENERATE)
        assert ep.regenerate(owner)
        assert ep.desired_map_state[key].proxy_port == port

    def test_redirect_removed_when_rule_deleted(self):
        owner = FakeOwner()
        owner.identity_cache = {
            1000: Labels.from_model(["k8s:app=server"]),
        }
        from cilium_tpu.labels import LabelArray

        owner.repo.add(
            Rule(
                endpoint_selector=sel("app=server"),
                labels=LabelArray.parse("rule=l7"),
                ingress=[
                    IngressRule(
                        from_endpoints=[sel("app=server")],
                        to_ports=[
                            PortRule(
                                ports=[PortProtocol("80", "TCP")],
                                rules=L7Rules(
                                    l7proto="r2d2",
                                    l7=[PortRuleL7({"cmd": "READ"})],
                                ),
                            )
                        ],
                    )
                ],
            )
        )
        ep = make_endpoint()
        assert ep.regenerate(owner)
        pid = f"{ep.id}:ingress:TCP:80"
        assert owner.proxy.get(pid) is not None
        owner.repo.delete_by_labels(LabelArray.parse("rule=l7"))
        ep.set_state(EndpointState.WAITING_TO_REGENERATE)
        assert ep.regenerate(owner)
        assert owner.proxy.get(pid) is None
        assert pid not in ep.realized_redirects

    def test_enforcement_modes(self):
        owner = FakeOwner()
        owner.identity_cache = {2000: Labels.from_model(["k8s:app=client"])}
        ep = make_endpoint()
        # never: no enforcement, allow-all entries both directions
        set_policy_enabled("never")
        assert ep.regenerate(owner)
        assert PolicyKey(2000, 0, 0, DIR_INGRESS) in ep.desired_map_state
        assert PolicyKey(2000, 0, 0, DIR_EGRESS) in ep.desired_map_state
        # always: enforcement with no rules -> no L3 allows
        set_policy_enabled("always")
        ep.force_policy_compute = True
        ep.set_state(EndpointState.WAITING_TO_REGENERATE)
        assert ep.regenerate(owner)
        assert PolicyKey(2000, 0, 0, DIR_INGRESS) not in ep.desired_map_state

    def test_revision_skip(self):
        owner = FakeOwner()
        owner.identity_cache = {1000: Labels.from_model(["k8s:app=server"])}
        ep = make_endpoint()
        assert ep.regenerate_policy(owner)
        # same revision, same identity cache: skipped
        assert not ep.regenerate_policy(owner)
        owner.repo.bump_revision()
        assert ep.regenerate_policy(owner)
        # identity cache change forces recompute
        owner.identity_cache[2000] = Labels.from_model(["k8s:app=client"])
        assert ep.regenerate_policy(owner)

    def test_sync_deletes_stale_keys(self):
        owner = FakeOwner()
        owner.identity_cache = {2000: Labels.from_model(["k8s:app=client"])}
        set_policy_enabled("never")
        ep = make_endpoint()
        assert ep.regenerate(owner)
        assert ep.policy_map.exists(2000, 0, 0, DIR_INGRESS)
        # drop the identity: its keys must be deleted on next sync
        owner.identity_cache = {}
        ep.force_policy_compute = True
        ep.set_state(EndpointState.WAITING_TO_REGENERATE)
        assert ep.regenerate(owner)
        assert not ep.policy_map.exists(2000, 0, 0, DIR_INGRESS)


class TestRestore:
    def test_round_trip(self, tmp_path):
        ep = make_endpoint(ep_id=42)
        ep.policy_revision = 7
        path = ep.write_state(str(tmp_path))
        assert path.endswith("42/ep_config.json")
        restored = Endpoint.restore_from_dir(str(tmp_path))
        assert len(restored) == 1
        r = restored[0]
        assert r.id == 42
        assert r.ipv4 == "10.0.0.10"
        assert r.security_identity.id == 1000
        assert r.policy_revision == 7
        assert r.state == EndpointState.RESTORING

    def test_corrupt_state_skipped(self, tmp_path):
        d = tmp_path / "13"
        d.mkdir()
        (d / "ep_config.json").write_text("{nope")
        ep = make_endpoint(ep_id=14)
        ep.write_state(str(tmp_path))
        restored = Endpoint.restore_from_dir(str(tmp_path))
        assert [e.id for e in restored] == [14]


class TestManager:
    def test_indexes(self):
        mgr = EndpointManager()
        ep = make_endpoint(ep_id=5)
        ep.container_name = "web-1"
        mgr.insert(ep)
        assert mgr.lookup(5) is ep
        assert mgr.lookup_container("web-1") is ep
        assert mgr.lookup_ipv4("10.0.0.10") is ep
        assert len(mgr) == 1
        assert mgr.remove(ep)
        assert mgr.lookup(5) is None
        assert not mgr.remove(ep)

    def test_trigger_policy_updates(self):
        mgr = EndpointManager()
        for i in range(3):
            e = make_endpoint(ep_id=i + 1)
            e.ipv4 = f"10.0.0.{i+1}"
            mgr.insert(e)
        seen = []
        assert mgr.trigger_policy_updates(lambda ep: seen.append(ep.id)) == 3
        assert seen == [1, 2, 3]


class TestBuildQueue:
    def test_builds_run(self):
        built = []
        q = BuildQueue(lambda x: built.append(x), workers=2)
        for i in range(10):
            q.enqueue(i)
        assert q.wait_idle(5)
        assert sorted(built) == list(range(10))
        q.stop()

    def test_duplicate_folding(self):
        started = threading.Event()
        release = threading.Event()
        built = []

        def build(x):
            built.append(x)
            started.set()
            release.wait(5)

        q = BuildQueue(build, workers=1)
        q.enqueue("ep1", key="ep1")
        assert started.wait(2)
        # while ep1 is building, repeated enqueues fold into one rebuild
        q.enqueue("ep1", key="ep1")
        q.enqueue("ep1", key="ep1")
        q.enqueue("ep1", key="ep1")
        release.set()
        assert q.wait_idle(5)
        assert built == ["ep1", "ep1"]  # initial + one folded rebuild
        q.stop()

    def test_build_errors_do_not_kill_workers(self):
        built = []

        def build(x):
            if x == "bad":
                raise RuntimeError("boom")
            built.append(x)

        q = BuildQueue(build, workers=1)
        q.enqueue("bad")
        q.enqueue("good")
        assert q.wait_idle(5)
        assert built == ["good"]
        q.stop()
