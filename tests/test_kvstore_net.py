"""Networked kvstore: the TCP backend must honor the full Backend
contract (CRUD, CAS, locks, leases, watch) across a real socket, and two
daemons sharing one server must converge on identities and ipcache state
— including lease revocation when a daemon dies
(reference: pkg/kvstore/etcd.go leases/CAS/watch, two-node convergence)."""

import json
import time

import pytest

from cilium_tpu.kvstore import (
    EventType,
    KvstoreServer,
    LockError,
    NetBackend,
)


@pytest.fixture
def server():
    srv = KvstoreServer()
    yield srv
    srv.close()


@pytest.fixture
def client(server):
    c = NetBackend(server.address)
    yield c
    c.close()


def _drain_until(w, typ, key, timeout=3.0):
    deadline = time.monotonic() + timeout
    seen = []
    while time.monotonic() < deadline:
        ev = w.next_event(timeout=0.2)
        if ev is None:
            continue
        seen.append(ev)
        if ev.typ == typ and ev.key == key:
            return ev
    raise AssertionError(f"no {typ} for {key}; saw {seen}")


class TestNetBackend:
    def test_crud_roundtrip(self, client):
        assert client.get("a/b") is None
        client.set("a/b", b"v1")
        assert client.get("a/b") == b"v1"
        client.set("a/c", b"v2")
        assert client.list_prefix("a/") == {"a/b": b"v1", "a/c": b"v2"}
        assert client.get_prefix("a/") == b"v1"
        client.delete("a/b")
        assert client.get("a/b") is None
        client.delete_prefix("a/")
        assert client.list_prefix("a/") == {}

    def test_cas_across_clients(self, server, client):
        c2 = NetBackend(server.address)
        try:
            assert client.create_only("id/5", b"x")
            assert not c2.create_only("id/5", b"y")  # atomic on the server
            assert client.get("id/5") == b"x"
            assert c2.create_if_exists("id/5", "val/5/n2", b"1")
            assert not c2.create_if_exists("id/9", "val/9/n2", b"1")
        finally:
            c2.close()

    def test_watch_snapshot_then_live(self, server, client):
        client.set("w/a", b"1")
        c2 = NetBackend(server.address)
        try:
            w = c2.list_and_watch("t", "w/")
            ev = w.next_event(timeout=2)
            assert ev.typ == EventType.CREATE and ev.key == "w/a"
            assert w.next_event(timeout=2).typ == EventType.LIST_DONE
            client.set("w/b", b"2")
            _drain_until(w, EventType.CREATE, "w/b")
            client.delete("w/b")
            _drain_until(w, EventType.DELETE, "w/b")
            w.stop()
        finally:
            c2.close()

    def test_lock_exclusion_across_clients(self, server, client):
        c2 = NetBackend(server.address)
        try:
            lock = client.lock_path("locks/x", timeout=1.0)
            with pytest.raises(LockError):
                c2.lock_path("locks/x", timeout=0.3)
            lock.unlock()
            c2.lock_path("locks/x", timeout=2.0).unlock()
        finally:
            c2.close()

    def test_lease_revoked_on_close(self, server, client):
        c2 = NetBackend(server.address)
        c2.set("lease/k", b"v", lease=True)
        c2.set("plain/k", b"v")
        w = client.list_and_watch("t", "lease/")
        _drain_until(w, EventType.CREATE, "lease/k")
        c2.close()
        # the server revokes the dead session's leases -> DELETE event
        _drain_until(w, EventType.DELETE, "lease/k")
        assert client.get("lease/k") is None
        assert client.get("plain/k") == b"v"  # non-leased survives

    def test_lock_released_on_session_death(self, server, client):
        c2 = NetBackend(server.address)
        c2.lock_path("locks/dead", timeout=1.0)
        c2.close()  # never unlocked explicitly
        # lock must become available once the session is gone
        deadline = time.monotonic() + 3
        while True:
            try:
                client.lock_path("locks/dead", timeout=0.3).unlock()
                break
            except LockError:
                assert time.monotonic() < deadline, "lock never released"

    def test_status(self, client):
        assert "connected" in client.status()

    def test_lease_reregistration_survives_old_session_death(self, server, client):
        """etcd semantics: the latest PUT's lease wins.  A restarted
        daemon re-registering its key must not lose it when the OLD
        session's death is finally noticed."""
        c_old = NetBackend(server.address)
        c_old.set("nodes/A", b"v1", lease=True)
        c_new = NetBackend(server.address)
        try:
            c_new.set("nodes/A", b"v2", lease=True)
            c_old.close()
            time.sleep(0.3)
            assert client.get("nodes/A") == b"v2"  # survived old death
            c_new.close()
            deadline = time.monotonic() + 3
            while time.monotonic() < deadline:
                if client.get("nodes/A") is None:
                    break
                time.sleep(0.05)
            assert client.get("nodes/A") is None  # dies with NEW session
        finally:
            c_new.close()

    def test_nonlease_overwrite_clears_lease(self, server, client):
        """A non-leased PUT over a leased key detaches the lease."""
        c2 = NetBackend(server.address)
        c2.set("cfg/x", b"v1", lease=True)
        client.set("cfg/x", b"v2")  # plain set from another session
        c2.close()
        time.sleep(0.3)
        assert client.get("cfg/x") == b"v2"


class TestClusterMesh:
    def test_remote_cluster_merge_and_purge(self, tmp_path):
        """Cluster A meshes with cluster B: B's endpoint IPs become
        resolvable in A's ipcache; dropping the mesh config purges them
        (reference: pkg/clustermesh remote_cluster onRemove)."""
        from cilium_tpu.clustermesh import ClusterMesh
        from cilium_tpu.daemon.daemon import Daemon
        from cilium_tpu.utils.option import DaemonConfig

        srv_b = KvstoreServer()
        db = Daemon(
            DaemonConfig(
                state_dir=str(tmp_path / "b"), dry_mode=True,
                kvstore="tcp", kvstore_opts={"address": srv_b.address},
                cluster_name="cluster-b",
            ),
            node_name="b0",
        )
        # Local side: just an ipcache + a mesh config dir (cluster A's
        # agent state, no full daemon needed).
        from cilium_tpu.ipcache import IPIdentityCache

        cache_a = IPIdentityCache("cluster-a")
        cfg_dir = str(tmp_path / "mesh")
        mesh = ClusterMesh(cfg_dir, cache_a, interval=0.05)
        try:
            with open(f"{cfg_dir}/cluster-b", "w") as f:
                json.dump({"address": srv_b.address}, f)
            db.endpoint_create(31, ipv4="10.60.0.31", labels=["k8s:app=remote"])
            id_b = db.endpoint_manager.lookup(31).security_identity.id
            deadline = time.monotonic() + 3
            while time.monotonic() < deadline:
                if cache_a.lookup_by_ip("10.60.0.31") == id_b:
                    break
                time.sleep(0.05)
            assert cache_a.lookup_by_ip("10.60.0.31") == id_b
            assert mesh.status()[0]["connected"]
            # drop the config: learned entries purge
            import os

            os.unlink(f"{cfg_dir}/cluster-b")
            deadline = time.monotonic() + 3
            while time.monotonic() < deadline:
                if cache_a.lookup_by_ip("10.60.0.31") is None:
                    break
                time.sleep(0.05)
            assert cache_a.lookup_by_ip("10.60.0.31") is None
            assert mesh.num_connected() == 0
        finally:
            mesh.close()
            db.close()
            srv_b.close()


class TestTwoDaemonConvergence:
    def test_identity_and_ipcache_converge(self, server, tmp_path):
        """Identity allocated on node A resolves on node B (same numeric
        id for the same labels), ipcache syncs both ways, and A's death
        revokes its ipcache entries on B."""
        from cilium_tpu.daemon.daemon import Daemon
        from cilium_tpu.utils.option import DaemonConfig

        def mk(node):
            return Daemon(
                DaemonConfig(
                    state_dir=str(tmp_path / node), dry_mode=True,
                    kvstore="tcp",
                    kvstore_opts={"address": server.address},
                ),
                node_name=node,
            )

        da = mk("node-a")
        db = mk("node-b")
        try:
            ep = da.endpoint_create(
                11, ipv4="10.50.0.11", labels=["k8s:app=web"]
            )
            id_a = ep.security_identity.id
            # B allocating the same labels converges on the same id
            from cilium_tpu.labels import Labels

            ident_b, _ = db.identity_allocator.allocate(
                Labels.from_model(["k8s:app=web"])
            )
            assert ident_b.id == id_a
            # B's identity cache learns A's allocation via watch
            deadline = time.monotonic() + 3
            while time.monotonic() < deadline:
                if db.identity_allocator.lookup_by_id(id_a) is not None:
                    break
                time.sleep(0.05)
            assert db.identity_allocator.lookup_by_id(id_a) is not None
            # ipcache converges A -> B
            deadline = time.monotonic() + 3
            while time.monotonic() < deadline:
                if db.ipcache.lookup_by_ip("10.50.0.11") == id_a:
                    break
                time.sleep(0.05)
            assert db.ipcache.lookup_by_ip("10.50.0.11") == id_a
            # and B -> A
            db.endpoint_create(22, ipv4="10.50.0.22", labels=["k8s:app=db"])
            id_b = db.endpoint_manager.lookup(22).security_identity.id
            deadline = time.monotonic() + 3
            while time.monotonic() < deadline:
                if da.ipcache.lookup_by_ip("10.50.0.22") == id_b:
                    break
                time.sleep(0.05)
            assert da.ipcache.lookup_by_ip("10.50.0.22") == id_b

            # node A dies: its leased ipcache entry disappears on B
            da.close()
            da = None
            deadline = time.monotonic() + 3
            while time.monotonic() < deadline:
                if db.ipcache.lookup_by_ip("10.50.0.11") is None:
                    break
                time.sleep(0.05)
            assert db.ipcache.lookup_by_ip("10.50.0.11") is None
        finally:
            if da is not None:
                da.close()
            db.close()


class TestReconnect:
    """Server-restart resilience (reference: pkg/kvstore reconnect with
    pkg/backoff + lease keepalive re-registration)."""

    def test_client_survives_server_restart(self, tmp_path):
        import time

        from cilium_tpu.kvstore.net import KvstoreServer, NetBackend

        srv = KvstoreServer("127.0.0.1", 0)
        addr = srv.address
        port = int(addr.rpartition(":")[2])
        c = NetBackend(addr, timeout=8.0)
        try:
            c.set("persist/a", b"1")
            c.set("lease/mine", b"owned", lease=True)
            w = c.list_and_watch("t", "persist/")
            # drain the initial snapshot
            ev = w.events.get(timeout=2)
            assert ev.key == "persist/a"

            srv.close()
            srv2 = None
            for _ in range(80):  # the old listener may linger briefly
                try:
                    srv2 = KvstoreServer("127.0.0.1", port)
                    break
                except OSError:
                    time.sleep(0.05)
            assert srv2 is not None, "could not rebind kvstore port"
            try:
                # Requests transparently reconnect + retry.
                assert c.get("persist/a") is None  # fresh empty server
                c.set("persist/b", b"2")
                assert c.get("persist/b") == b"2"
                assert c.reconnects == 1

                # The leased key was replayed on the new session.
                assert c.get("lease/mine") == b"owned"

                # The watcher survived and re-subscribed: it sees the
                # new-session events for its prefix.
                seen = {}
                t0 = time.monotonic()
                while time.monotonic() - t0 < 4:
                    try:
                        ev = w.events.get(timeout=0.2)
                        seen[ev.key] = ev
                    except Exception:
                        pass
                    if "persist/b" in seen:
                        break
                assert "persist/b" in seen and not w.stopped
            finally:
                srv2.close()
        finally:
            c.close()

    def test_lock_loss_is_surfaced_after_reconnect(self, tmp_path):
        import time

        from cilium_tpu.kvstore.backend import LockError
        from cilium_tpu.kvstore.net import KvstoreServer, NetBackend

        srv = KvstoreServer("127.0.0.1", 0)
        port = int(srv.address.rpartition(":")[2])
        c = NetBackend(srv.address, timeout=8.0)
        try:
            lock = c.lock_path("locks/critical")
            srv.close()
            srv2 = None
            for _ in range(80):
                try:
                    srv2 = KvstoreServer("127.0.0.1", port)
                    break
                except OSError:
                    time.sleep(0.05)
            assert srv2 is not None
            try:
                c.set("x", b"1")  # triggers reconnect
                # The server-side session death released the lock; the
                # holder must be TOLD, not silently "succeed".
                import pytest as _pytest

                with _pytest.raises(LockError, match="lost"):
                    lock.unlock()
            finally:
                srv2.close()
        finally:
            c.close()

    def test_lease_replay_never_clobbers_new_claimant(self, tmp_path):
        import time

        from cilium_tpu.kvstore.net import KvstoreServer, NetBackend

        srv = KvstoreServer("127.0.0.1", 0)
        port = int(srv.address.rpartition(":")[2])
        a = NetBackend(srv.address, timeout=8.0)
        try:
            a.set("claim/id", b"owner-a", lease=True)
            srv.close()
            srv2 = None
            for _ in range(80):
                try:
                    srv2 = KvstoreServer("127.0.0.1", port)
                    break
                except OSError:
                    time.sleep(0.05)
            assert srv2 is not None
            try:
                # B races A's background replay for the key on the
                # fresh server.  Either may win — the invariant is that
                # the FIRST claimant keeps it (replay never clobbers).
                b = NetBackend(srv2.address, timeout=8.0)
                try:
                    created_b = b.create_only(
                        "claim/id", b"owner-b", lease=True
                    )
                    a.set("other", b"1")  # ensure A reconnected+replayed
                    winner = b"owner-b" if created_b else b"owner-a"
                    assert a.get("claim/id") == winner
                    assert b.get("claim/id") == winner
                finally:
                    b.close()
            finally:
                srv2.close()
        finally:
            a.close()
