"""Sidecar seam tests: wire protocol, dispatcher, and op/byte parity of
the service+shim path against the in-process oracle.

The service+shim pair must reproduce the exact FilterOp sequences the
in-process proxylib oracle produces (the reference's bit-exactness
contract, proxylib/proxylib/test_util.go) — including partial frames,
pipelined frames, reply traffic, denials with injected error replies,
and policy swaps.
"""

from __future__ import annotations

import random
import threading
import time

import numpy as np
import pytest

from cilium_tpu.proxylib import (
    NetworkPolicy,
    PortNetworkPolicy,
    PortNetworkPolicyRule,
    FilterResult,
)
from cilium_tpu.proxylib import instance as inst
from cilium_tpu.proxylib.types import DROP, MORE, PASS
from cilium_tpu.sidecar import BatchDispatcher, SidecarClient, VerdictService
from cilium_tpu.sidecar import wire
from cilium_tpu.utils.option import DaemonConfig

from proxylib_harness import new_connection


def r2d2_policy(name="sidecar-pol"):
    return NetworkPolicy(
        name=name,
        policy=2,
        ingress_per_port_policies=[
            PortNetworkPolicy(
                port=80,
                rules=[
                    PortNetworkPolicyRule(
                        remote_policies=[1, 3],
                        l7_proto="r2d2",
                        l7_rules=[
                            {"cmd": "READ", "file": "/public/.*"},
                            {"cmd": "HALT"},
                        ],
                    )
                ],
            )
        ],
    )


@pytest.fixture
def service(tmp_path):
    inst.reset_module_registry()
    cfg = DaemonConfig(batch_timeout_ms=2.0, batch_flows=512)
    svc = VerdictService(str(tmp_path / "verdict.sock"), cfg).start()
    yield svc
    svc.stop()
    inst.reset_module_registry()


@pytest.fixture
def client(service):
    # The service's first engine prewarm runs the dispatch-mode probe
    # (eager AND jit compiles) lazily inside whichever RPC triggers it;
    # compile wall-time late in a long pytest process can exceed the
    # default 10s RPC timeout and flake the test that got unlucky.
    c = SidecarClient(service.socket_path, timeout=60.0)
    yield c
    c.close()


def open_with_policy(client, policies=None):
    mod = client.open_module([])
    assert mod != 0
    assert client.policy_update(mod, policies or [r2d2_policy()]) == int(
        FilterResult.OK
    )
    return mod


# --- wire round trips ----------------------------------------------------

def test_wire_data_batch_roundtrip():
    blob = b"helloworldxy"
    payload = wire.pack_data_batch(7, [1, 2, 3], [0, 1, 2], [5, 5, 2], blob)
    b = wire.unpack_data_batch(payload)
    assert b.seq == 7 and b.count == 3
    assert b.entry(0) == (1, False, False, b"hello")
    assert b.entry(1) == (2, True, False, b"world")
    assert b.entry(2) == (3, False, True, b"xy")


def test_wire_verdict_batch_roundtrip():
    ops = np.zeros(3, wire.FILTER_OP)
    ops["op"] = [1, 2, 0]
    ops["n_bytes"] = [10, 4, 1]
    payload = wire.pack_verdict_batch(
        9, [5, 6], [0, 0], [2, 1], [1, 0], [3, 2], ops, b"XabcYZ"
    )
    v = wire.unpack_verdict_batch(payload)
    assert v.seq == 9 and v.count == 2
    assert v.entry(0) == (5, 0, [(1, 10), (2, 4)], b"X", b"abc")
    assert v.entry(1) == (6, 0, [(0, 1)], b"", b"YZ")


# --- dispatcher ----------------------------------------------------------

def test_dispatcher_fill_trigger():
    batches = []
    done = threading.Event()

    def proc(items):
        batches.append(list(items))
        done.set()

    d = BatchDispatcher(proc, max_batch=4, timeout_ms=10_000).start()
    try:
        for i in range(4):
            d.submit(i)
        assert done.wait(2)
        assert batches and len(batches[0]) == 4
        assert d.fill_dispatches == 1 and d.deadline_dispatches == 0
    finally:
        d.stop()


def test_dispatcher_deadline_trigger():
    got = threading.Event()
    latency = {}

    def proc(items):
        latency["t"] = time.perf_counter()
        got.set()

    d = BatchDispatcher(proc, max_batch=1_000_000, timeout_ms=5.0).start()
    try:
        t0 = time.perf_counter()
        d.submit("x")
        assert got.wait(2)
        waited = latency["t"] - t0
        assert 0.004 <= waited < 0.5, waited
        assert d.deadline_dispatches == 1
    finally:
        d.stop()


# --- service parity vs in-process oracle ---------------------------------

CORPUS = [
    b"READ /public/a.txt\r\n",
    b"READ /private/x\r\n",
    b"HALT\r\n",
    b"WRITE /public/b\r\n",
    b"RESET\r\n",
    b"READ /public/deep/path/c.dat\r\n",
]


def oracle_ops(policy, msgs, remote_id=1, reply_flags=None):
    """Run msgs through the in-process oracle, one on_data per msg,
    returning [(ops, reply_inject)]"""
    mod = inst.open_module([], True)
    ins = inst.find_instance(mod)
    ins.policy_update([policy])
    res, conn = new_connection(
        mod, "r2d2", True, remote_id, 2, "1.1.1.1:1", "2.2.2.2:80",
        policy.name,
    )
    assert res == FilterResult.OK
    out = []
    buf = {False: b"", True: b""}
    for i, m in enumerate(msgs):
        reply = bool(reply_flags[i]) if reply_flags else False
        buf[reply] += m
        ops = []
        conn.on_data(reply, False, [buf[reply]], ops)
        consumed = sum(n for op, n in ops if op in (PASS, DROP))
        buf[reply] = buf[reply][consumed:]
        out.append((list(ops), conn.reply_buf.take()))
    inst.close_module(mod)
    return out


def shim_ops(client, msgs, remote_id=1, reply_flags=None, conn_id=1000):
    mod = open_with_policy(client)
    res, shim = client.new_connection(
        mod, "r2d2", conn_id, True, remote_id, 2, "1.1.1.1:1",
        "2.2.2.2:80", "sidecar-pol",
    )
    assert res == int(FilterResult.OK)
    out = []
    for i, m in enumerate(msgs):
        reply = bool(reply_flags[i]) if reply_flags else False
        result, entries = client._on_data_rpc(shim.conn_id, reply, False, m)
        ops = []
        inj_reply = b""
        for _, r, eops, io, ir in entries:
            assert r == int(FilterResult.OK)
            ops.extend(eops)
            inj_reply += ir
        out.append((ops, inj_reply))
    shim.close()
    return out


def assert_parity(got, exp):
    assert len(got) == len(exp)
    for i, ((gops, ginj), (eops, einj)) in enumerate(zip(got, exp)):
        gops = [(int(o), int(n)) for o, n in gops]
        eops = [(int(o), int(n)) for o, n in eops]
        assert gops == eops, f"msg {i}: ops {gops} != {eops}"
        assert ginj == einj, f"msg {i}: inject {ginj!r} != {einj!r}"


def test_sidecar_parity_single_frames(client):
    exp = oracle_ops(r2d2_policy(), CORPUS)
    got = shim_ops(client, CORPUS)
    assert_parity(got, exp)


def test_sidecar_parity_denied_remote(client):
    # remote 9 not in remote_policies -> everything denied
    exp = oracle_ops(r2d2_policy(), CORPUS, remote_id=9)
    got = shim_ops(client, CORPUS, remote_id=9)
    assert_parity(got, exp)


def test_sidecar_parity_split_and_pipelined(client):
    msgs = [
        b"READ /pub",  # partial
        b"lic/a.txt\r\nHALT\r\nREAD /private/x\r\n",  # completes + 2 more
        b"WRI",
        b"TE /public/b\r\n",
    ]
    exp = oracle_ops(r2d2_policy(), msgs)
    got = shim_ops(client, msgs)
    assert_parity(got, exp)


def test_sidecar_parity_reply_direction(client):
    msgs = [b"READ /public/a.txt\r\n", b"OK data\r\n", b"HALT\r\n"]
    flags = [0, 1, 0]
    exp = oracle_ops(r2d2_policy(), msgs, reply_flags=flags)
    got = shim_ops(client, msgs, reply_flags=flags)
    assert_parity(got, exp)


def test_sidecar_parity_fuzz(client):
    rng = random.Random(42)
    msgs = []
    raw = b"".join(
        CORPUS[rng.randrange(len(CORPUS))] for _ in range(60)
    )
    # random re-chunking: partial/pipelined mix
    i = 0
    while i < len(raw):
        n = rng.randrange(1, 40)
        msgs.append(raw[i : i + n])
        i += n
    exp = oracle_ops(r2d2_policy(), msgs)
    got = shim_ops(client, msgs)
    assert_parity(got, exp)


def test_sidecar_shim_on_io_output_bytes(client):
    """End-to-end byte semantics: allowed frames forwarded, denied frames
    removed with the error reply injected into the reply direction."""
    mod = open_with_policy(client)
    res, shim = client.new_connection(
        mod, "r2d2", 2000, True, 1, 2, "1.1.1.1:1", "2.2.2.2:80",
        "sidecar-pol",
    )
    assert res == int(FilterResult.OK)
    res, out = shim.on_io(False, b"READ /public/a.txt\r\nREAD /private/x\r\n")
    assert res == int(FilterResult.OK)
    assert out == b"READ /public/a.txt\r\n"  # denied frame removed
    # The denial error surfaces at the head of the next reply-direction IO.
    res, out = shim.on_io(True, b"SERVED\r\n")
    assert res == int(FilterResult.OK)
    assert out == b"ERROR\r\nSERVED\r\n"
    shim.close()


def test_sidecar_oracle_drains_large_backlog(client):
    """A single entry carrying thousands of buffered frames is fully
    verdicted in one response: the oracle drain loop has no fixed
    iteration cap (a quiescent peer would stall tail frames forever),
    and the backlog exceeds the 64KB drain window so the windowed
    re-parse path is exercised too."""
    mod = open_with_policy(client)
    res, shim = client.new_connection(
        mod, "r2d2", 4242, True, 1, 2, "1.1.1.1:1", "2.2.2.2:80",
        "sidecar-pol",
    )
    assert res == int(FilterResult.OK)
    line = b"OK 0123456\r\n"
    n = 10_000  # ~120KB of reply frames, > the 64KB window
    burst = line * n
    result, out = shim.on_io(True, burst)
    assert result == int(FilterResult.OK)
    assert out == burst  # every reply frame passed, none stalled
    shim.close()


def test_sidecar_policy_swap(client):
    mod = open_with_policy(client)
    res, shim = client.new_connection(
        mod, "r2d2", 3000, True, 1, 2, "1.1.1.1:1", "2.2.2.2:80",
        "sidecar-pol",
    )
    assert res == int(FilterResult.OK)
    _, out = shim.on_io(False, b"READ /public/a.txt\r\n")
    assert out == b"READ /public/a.txt\r\n"
    # Swap to a policy denying READ /public
    pol = r2d2_policy()
    pol.ingress_per_port_policies[0].rules[0].l7_rules = [{"cmd": "RESET"}]
    assert client.policy_update(mod, [pol]) == int(FilterResult.OK)
    _, out = shim.on_io(False, b"READ /public/a.txt\r\n")
    assert out == b""
    shim.close()


def test_sidecar_unknown_parser(client):
    mod = client.open_module([])
    res, shim = client.new_connection(
        mod, "no-such-proto", 4000, True, 1, 2, "1.1.1.1:1", "2.2.2.2:80", "p",
    )
    assert res == int(FilterResult.UNKNOWN_PARSER)
    assert shim is None


def test_sidecar_unknown_connection(client):
    open_with_policy(client)
    result, entries = client._on_data_rpc(99999, False, False, b"HALT\r\n")
    assert result == int(FilterResult.UNKNOWN_CONNECTION)


def test_sidecar_fast_path_used(service, client):
    """Single complete frames from fresh flows ride the vectorized fast
    path (columnar access log records them)."""
    mod = open_with_policy(client)
    for cid in range(5000, 5008):
        res, shim = client.new_connection(
            mod, "r2d2", cid, True, 1, 2, "1.1.1.1:1", "2.2.2.2:80",
            "sidecar-pol",
        )
        assert res == int(FilterResult.OK)
        _, out = shim.on_io(False, b"READ /public/a.txt\r\n")
        assert out == b"READ /public/a.txt\r\n"
    assert service.fast_log.requests >= 8


# --- dispatch mode / verdict device (measured config) ---------------------

@pytest.mark.parametrize(
    "mode,device",
    [("eager", "default"), ("jit", "default"), ("eager", "cpu")],
)
def test_sidecar_dispatch_modes_bit_identical(tmp_path, mode, device):
    """Eager and jitted dispatch (and the cpu-backed verdict device the
    co-located latbench mode uses) render identical verdicts vs the
    oracle — the dispatch choice is performance config, never policy."""
    inst.reset_module_registry()
    cfg = DaemonConfig(
        batch_timeout_ms=2.0, batch_flows=512,
        dispatch_mode=mode, verdict_device=device,
    )
    svc = VerdictService(
        str(tmp_path / f"verdict-{mode}-{device}.sock"), cfg
    ).start()
    try:
        c = SidecarClient(svc.socket_path, timeout=60.0)
        try:
            exp = oracle_ops(r2d2_policy(), CORPUS)
            got = shim_ops(c, CORPUS)
            assert_parity(got, exp)
            assert svc.dispatch_mode_chosen == mode
        finally:
            c.close()
    finally:
        svc.stop()
        inst.reset_module_registry()


def test_sidecar_dispatch_auto_resolves_by_measurement(tmp_path):
    """dispatch_mode='auto' must resolve to a concrete measured choice
    at first engine prewarm."""
    inst.reset_module_registry()
    cfg = DaemonConfig(
        batch_timeout_ms=2.0, batch_flows=512, dispatch_mode="auto"
    )
    svc = VerdictService(str(tmp_path / "verdict-auto.sock"), cfg).start()
    try:
        assert svc.dispatch_mode_chosen is None
        c = SidecarClient(svc.socket_path, timeout=60.0)
        try:
            exp = oracle_ops(r2d2_policy(), CORPUS)
            got = shim_ops(c, CORPUS)
            assert_parity(got, exp)
            assert svc.dispatch_mode_chosen in ("eager", "jit")
        finally:
            c.close()
    finally:
        svc.stop()
        inst.reset_module_registry()


# --- grouped matrix rounds + VERDICT_MULTI --------------------------------

def test_wire_verdict_multi_roundtrip():
    """One MULTI frame answers several seqs with one columnar body."""
    ops = np.zeros((4,), wire.FILTER_OP)
    ops["op"] = [PASS, MORE, DROP, MORE]
    ops["n_bytes"] = [10, 1, 5, 1]
    body = wire.pack_verdict_body(
        [7, 8], [0, 0], [2, 2], [0, 0], [0, 7], ops, b"ERROR\r\n"
    )
    payload = wire.pack_verdict_multi([21, 22], [1, 1], 2, body)
    vbs = wire.unpack_verdict_multi(payload)
    assert [vb.seq for vb in vbs] == [21, 22]
    assert vbs[0].entry(0) == (7, 0, [(PASS, 10), (MORE, 1)], b"", b"")
    assert vbs[1].entry(0) == (8, 0, [(DROP, 5), (MORE, 1)], b"", b"ERROR\r\n")


def test_grouped_matrix_round_multi_verdicts(tmp_path):
    """A greedy service aggregates several complete-flag matrix batches
    into ONE group round and answers each client with one frame; the
    verdicts stay bit-identical to the oracle."""
    inst.reset_module_registry()
    cfg = DaemonConfig(batch_timeout_ms=0.0, batch_flows=512)
    svc = VerdictService(str(tmp_path / "v2.sock"), cfg).start()
    c = SidecarClient(svc.socket_path, timeout=60.0)
    try:
        mod = open_with_policy(c)
        width = cfg.batch_width
        n_conns = 12
        for cid in range(1, n_conns + 1):
            res, _ = c.new_connection(
                mod, "r2d2", cid, True, 1, 2, "1.1.1.1:1", "2.2.2.2:80",
                "sidecar-pol",
            )
            assert res == int(FilterResult.OK)

        msgs = [CORPUS[i % len(CORPUS)] for i in range(n_conns)]
        got: dict[int, object] = {}
        evt = threading.Event()

        def cb(vb):
            got[vb.seq] = vb
            if len(got) == 3:
                evt.set()

        c.verdict_callback = cb
        # Three matrix batches back to back: the first may cut through,
        # the rest aggregate behind the in-flight round.
        for b in range(3):
            ids = np.arange(
                1 + b * 4, 5 + b * 4, dtype=np.uint64
            )
            lens = np.array(
                [len(msgs[int(i) - 1]) for i in ids], np.uint32
            )
            rows = np.zeros((4, width), np.uint8)
            for j, i in enumerate(ids):
                m = msgs[int(i) - 1]
                rows[j, : len(m)] = np.frombuffer(m, np.uint8)
            c.send_matrix(100 + b, width, ids, lens, rows.tobytes(),
                          complete=True)
        assert evt.wait(10), f"verdicts missing: {sorted(got)}"

        exp = oracle_ops(r2d2_policy(), msgs)
        for b in range(3):
            vb = got[100 + b]
            for j in range(vb.count):
                cid, res, ops, _io, ir = vb.entry(j)
                eops, einj = exp[cid - 1]
                assert [(int(o), int(n)) for o, n in ops] == [
                    (int(o), int(n)) for o, n in eops
                ], (cid, ops, eops)
                assert ir == einj, (cid, ir, einj)
    finally:
        c.close()
        svc.stop()
        inst.reset_module_registry()
